"""Concurrent query executor: bounded workers, read admission, rulers.

The write path got its front door in PR 7 (`AdmissionController`,
sources/manager.py): budgets, a cached decision, a counted structured
429. This is the same contract generalized to READS — hundreds of
dashboard pollers must not be able to convoy the analytics path into
unbounded queueing, and a shed dashboard poll must be a cheap, visible
429, not a 30 s hang:

  * a bounded worker pool (`workers` threads) runs every query; callers
    block on a future, never on the engine;
  * per-tenant admission: a tenant whose queued+running reads exceed
    `queue_depth_budget`, or whose recent latency breaches
    `latency_budget_ms`, gets :class:`QueryShedError` (HTTP 429) at
    submit — counted under `query.shed`;
  * scans are snapshot-isolated by construction: the eventlog hands the
    cache a sealed-segment snapshot under one lock acquisition
    (`sealed_snapshot`) and the monolithic path's `scan()` does the
    same, so a query NEVER holds a lock that an ingest append or the
    step loop waits on;
  * rulers: `query.latency_seconds{tenant}` histogram, the
    `analytics_query` edge on the ingest->effect age waterfall
    (`pipeline.event_age_seconds{engine="serving"}`), `query.shed` /
    `query.cache_hit` / `query.cache_miss` counters, and a bounded ring
    of per-query spans (admit -> start -> done, route + cache
    attribution) exported by :meth:`report` — the flight-plane analog
    for reads.
"""

from __future__ import annotations

import threading
import time
from collections import deque
from concurrent.futures import Future, ThreadPoolExecutor
from typing import Any, Deque, Dict, Optional

from sitewhere_tpu.errors import SiteWhereError
from sitewhere_tpu.runtime.eventage import (
    AgeSidecar, age_histogram, observe_summary)
from sitewhere_tpu.runtime.metrics import GLOBAL_METRICS
from sitewhere_tpu.serving.planner import QueryPlanner, WindowQuery
from sitewhere_tpu.serving.wincache import WindowGridCache

AGE_EDGE = "analytics_query"
AGE_ENGINE = "serving"


class QueryShedError(SiteWhereError):
    """Client-visible NACK for a read shed under overload — HTTP 429,
    the read-side sibling of IngestShedError."""

    def __init__(self, message: str = "query shed: serving over budget"):
        super().__init__(message, http_status=429)


class QueryExecutor:
    """Bounded concurrent serving over one analytics engine."""

    def __init__(self, engine, planner: Optional[QueryPlanner] = None,
                 cache: Optional[WindowGridCache] = None, *,
                 workers: int = 4, queue_depth_budget: int = 64,
                 latency_budget_ms: float = 0.0,
                 latency_window: int = 128, registry=None):
        self.engine = engine
        self.planner = planner or QueryPlanner(engine.event_log)
        self.cache = cache if cache is not None else WindowGridCache()
        self.workers = max(1, int(workers))
        self.queue_depth_budget = int(queue_depth_budget)
        self.latency_budget_ms = float(latency_budget_ms)
        self._pool = ThreadPoolExecutor(
            max_workers=self.workers, thread_name_prefix="serving")
        self._lock = threading.Lock()
        self._inflight: Dict[str, int] = {}
        self._latencies: Deque[float] = deque(maxlen=max(8, latency_window))
        self._spans: Deque[Dict[str, Any]] = deque(maxlen=256)
        self._queries = 0
        m = registry or GLOBAL_METRICS
        self.latency_hist = m.histogram(
            "query.latency_seconds",
            buckets=(0.001, 0.005, 0.01, 0.025, 0.05, 0.1, 0.25, 0.5,
                     1.0, 2.5, 5.0))
        self.shed_counter = m.counter("query.shed")
        self.mesh_counter = m.counter("query.mesh_routed")
        self._age_hist = age_histogram(m)

    # -- admission ---------------------------------------------------------

    def _recent_p99_ms(self) -> float:
        with self._lock:
            if not self._latencies:
                return 0.0
            ordered = sorted(self._latencies)
        return ordered[min(len(ordered) - 1,
                           int(0.99 * len(ordered)))] * 1e3

    def _admit(self, tenant: str) -> None:
        """One read-admission decision; raises the structured 429. Depth
        is checked per tenant (a greedy dashboard cannot starve the
        rest); the latency budget is global — when the pool itself is
        over budget everyone sheds."""
        if self.queue_depth_budget > 0:
            with self._lock:
                depth = self._inflight.get(tenant, 0)
            if depth >= self.queue_depth_budget:
                self.shed_counter.inc()
                raise QueryShedError(
                    f"query shed: tenant {tenant} read depth {depth} over "
                    f"budget {self.queue_depth_budget}")
        if self.latency_budget_ms > 0.0:
            p99 = self._recent_p99_ms()
            if p99 > self.latency_budget_ms:
                self.shed_counter.inc()
                raise QueryShedError(
                    f"query shed: recent p99 {p99:.1f} ms over budget "
                    f"{self.latency_budget_ms:.1f} ms")

    # -- execution ---------------------------------------------------------

    def _run(self, query: WindowQuery, admitted_s: float) -> Dict[str, Any]:
        started_s = time.perf_counter()
        plan = self.planner.plan(query)
        report = None
        info: Dict[str, Any] = {"cache_hit": False}
        route = plan.route
        if plan.cacheable and self.cache is not None:
            tlog = self.engine.event_log.tenant_if_exists(query.tenant)
            if tlog is not None and hasattr(tlog, "sealed_snapshot"):
                served = self.cache.query(
                    tlog, tenant=query.tenant, flt=query.filter(),
                    window_ms=query.window_ms, start_ms=query.start_ms,
                    end_ms=query.end_ms, max_windows=query.max_windows)
                if served is not None:
                    report, info = served
                    route = "cache"
        if report is None:
            if plan.mesh is not None:
                self.mesh_counter.inc()
            report = self.engine.measurement_windows(
                query.tenant, window_ms=query.window_ms,
                mm_name=query.mm_name, start_ms=query.start_ms,
                end_ms=query.end_ms, area_id=query.area_id,
                max_windows=query.max_windows,
                with_type_histogram=query.with_type_histogram,
                mesh=plan.mesh, combine=query.combine)
        done_s = time.perf_counter()
        total_s = done_s - admitted_s
        self.latency_hist.observe(total_s, tenant=query.tenant)
        sidecar = AgeSidecar()
        sidecar.add(admitted_s, 1)
        observe_summary(self._age_hist, sidecar.close(done_s),
                        engine=AGE_ENGINE, edge=AGE_EDGE)
        span = {
            "tenant": query.tenant, "route": route,
            "cache_hit": bool(info.get("cache_hit")),
            "est_rows": plan.est_rows,
            "wait_ms": round((started_s - admitted_s) * 1e3, 3),
            "exec_ms": round((done_s - started_s) * 1e3, 3),
            "total_ms": round(total_s * 1e3, 3),
        }
        if "delta_rows" in info:
            span["delta_rows"] = info["delta_rows"]
        with self._lock:
            self._latencies.append(total_s)
            self._spans.append(span)
        return {"report": report, "plan": plan, "info": info, "span": span}

    def submit(self, query: WindowQuery) -> Future:
        """Admit + enqueue one query; the returned future resolves to
        `{"report": WindowReport, "plan": QueryPlan, "info": ..,
        "span": ..}`."""
        self._admit(query.tenant)
        admitted_s = time.perf_counter()
        with self._lock:
            self._inflight[query.tenant] = \
                self._inflight.get(query.tenant, 0) + 1
            self._queries += 1
        future = self._pool.submit(self._run, query, admitted_s)

        def _done(_f, tenant=query.tenant):
            with self._lock:
                left = self._inflight.get(tenant, 1) - 1
                if left <= 0:
                    self._inflight.pop(tenant, None)
                else:
                    self._inflight[tenant] = left

        future.add_done_callback(_done)
        return future

    def query(self, query: WindowQuery,
              timeout: Optional[float] = None) -> Dict[str, Any]:
        """Synchronous convenience wrapper around :meth:`submit`."""
        return self.submit(query).result(timeout=timeout)

    # -- telemetry ---------------------------------------------------------

    def report(self) -> Dict[str, Any]:
        with self._lock:
            spans = list(self._spans)
            inflight = dict(self._inflight)
            queries = self._queries
        return {
            "workers": self.workers,
            "queries": queries,
            "inflight": inflight,
            "queue_depth_budget": self.queue_depth_budget,
            "latency_budget_ms": self.latency_budget_ms,
            "recent_p99_ms": round(self._recent_p99_ms(), 3),
            "shed_total": self.shed_counter.value,
            "mesh_routed_total": self.mesh_counter.value,
            "cache": {
                "entries": len(self.cache),
                "resident_bytes": self.cache.resident_bytes,
                "max_bytes": self.cache.max_bytes,
                "hits": self.cache.hit_counter.value,
                "misses": self.cache.miss_counter.value,
                "evictions": self.cache.evict_counter.value,
            },
            "spans": spans[-64:],
        }

    def stop(self) -> None:
        self._pool.shutdown(wait=True)
