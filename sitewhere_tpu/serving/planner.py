"""Query planner: normalize windowed-read requests, route host vs mesh.

Every dashboard read is normalized into a :class:`WindowQuery` — the
`measurement_windows` parameter surface plus the tenant — which yields
(a) the canonical `EventFilter` the scan runs under, (b) the cache
identity `(tenant, filter, window_ms, range)` the incremental grid cache
keys on (serving/wincache.py), and (c) a routing decision:

  * **small scans** stay on the host `windowed_stats` kernel — one
    compiled plan per padded `[K, W]` shape, no device round-trip;
  * **large scans** default onto `sharded_windowed_stats`
    (parallel/distributed.py) over the live mesh — replay rows split
    across the shard axis, partial grids combined on-device. The old
    `mesh=None` call sites flip to planner-decided the moment an engine
    is built with a planner: mesh-sharded replay is the DEFAULT query
    engine for large windows (ROADMAP item 3), not opt-in plumbing.

The routing estimate is the eventlog's per-segment skip index
(`estimate_rows` — O(segments), no column reads), so planning cost is
noise even at high poll rates. Both routes sit behind the same
`_pad_pow2` static-shape bucketing, so compiled plans are reused across
queries of similar size.
"""

from __future__ import annotations

from dataclasses import dataclass
from typing import Optional

from sitewhere_tpu.model.event import DeviceEventType
from sitewhere_tpu.persist.eventlog import EventFilter

# Below this many scanned rows the host kernel wins: one device dispatch
# plus the shard-pad overhead costs more than the host fold. Measured on
# the bench serving tier; overridable per planner.
DEFAULT_MESH_ROW_THRESHOLD = 200_000


@dataclass(frozen=True)
class WindowQuery:
    """One normalized windowed read (the `measurement_windows` surface)."""

    tenant: str
    window_ms: int = 60_000
    mm_name: Optional[str] = None
    start_ms: Optional[int] = None
    end_ms: Optional[int] = None
    area_id: Optional[str] = None
    max_windows: int = 4096
    with_type_histogram: bool = False
    combine: str = "psum"

    def filter(self) -> EventFilter:
        return EventFilter(event_type=DeviceEventType.MEASUREMENT,
                           mm_name=self.mm_name, area_id=self.area_id,
                           start_date=self.start_ms, end_date=self.end_ms)

    @property
    def cacheable(self) -> bool:
        """Only explicit-range, histogram-free queries are cacheable: an
        open range derives the grid origin from data min/max, which moves
        with every append — there is no stable grid to cache."""
        return (self.start_ms is not None and self.end_ms is not None
                and not self.with_type_histogram)


@dataclass
class QueryPlan:
    route: str              # "host" | "mesh"
    cacheable: bool
    est_rows: int
    mesh: object = None     # live mesh when route == "mesh"


class QueryPlanner:
    """Routes normalized queries over one event log + optional mesh.

    `mesh_provider` is a zero-arg callable returning the live mesh (or
    None when the process runs single-chip) — typically
    `parallel.distributed.live_mesh` or a lambda closing over the
    instance's pipeline mesh. Row estimates come from the log's segment
    skip index; stores without `estimate_rows` (wide-row datastores)
    degrade to host routing and no caching."""

    def __init__(self, event_log, *, mesh_provider=None,
                 mesh_row_threshold: int = DEFAULT_MESH_ROW_THRESHOLD,
                 combine: str = "psum"):
        self.event_log = event_log
        self.mesh_provider = mesh_provider
        self.mesh_row_threshold = int(mesh_row_threshold)
        self.combine = combine

    def estimate_rows(self, tenant: str, flt: EventFilter) -> int:
        est = getattr(self.event_log, "estimate_rows", None)
        if est is None:
            return 0
        try:
            return int(est(tenant, flt))
        except Exception:
            return 0

    def choose_mesh(self, tenant: str, flt: EventFilter):
        """The planner-decided `mesh` argument for one scan: the live
        mesh when the estimated scan is large enough to amortize the
        dispatch, else None (host kernel). This is what the engine's
        `mesh=None` default resolves through."""
        if self.mesh_provider is None:
            return None
        est = self.estimate_rows(tenant, flt)
        if est < self.mesh_row_threshold:
            return None
        try:
            return self.mesh_provider()
        except Exception:
            return None

    def plan(self, query: WindowQuery) -> QueryPlan:
        flt = query.filter()
        est = self.estimate_rows(query.tenant, flt)
        mesh = None
        if self.mesh_provider is not None and \
                est >= self.mesh_row_threshold:
            try:
                mesh = self.mesh_provider()
            except Exception:
                mesh = None
        cacheable = query.cacheable and \
            hasattr(self.event_log, "tenant_if_exists")
        return QueryPlan(route="mesh" if mesh is not None else "host",
                         cacheable=cacheable, est_rows=est, mesh=mesh)
