"""Incremental window-grid cache: sealed segments in, `[K, W]` grids out.

tf.data (Murray et al. 2021, PAPERS.md) caches a materialized
intermediate and reuses it across epochs; the dashboard analog is the
finalized window grid reused across polls. A `measurement_windows`-shaped
query with an explicit `[start_ms, end_ms]` range is a pure function of
(filter, grid geometry, log contents) — and the log's sealed segments are
immutable and append-only (persist/eventlog.py), so the grid over sealed
segments `[0, w)` never changes. The cache stores exactly that prefix
grid, keyed by `(retention_epoch, w)`:

  * a repeat query scans only segments sealed since the cached watermark
    plus the unsealed buffer tail, folds the delta with the SAME
    segment-reduction kernels (analytics/windows.py, one compiled plan
    per padded shape), and merges;
  * count and sum compose by addition, min/max by min/max over +-inf
    empty-cell sentinels — exactly; mean is refinalized as
    sum / max(count, 1) (float sums reassociate across the merge, the
    one documented deviation from a monolithic rescan);
  * invalidation is structural: sealing only appends (the watermark
    advances, the cached prefix stays exact) and retention bumps
    `retention_epoch` (every entry over that log dies). No listener
    plumbing — validity is checked against the log's own snapshot at
    lookup time;
  * the buffered (unsealed, still-growing) tail is folded per query and
    NEVER stored.

Resident bytes are LRU-bounded (`max_bytes`) and exported into the
HBM/host ledger as `hbm.wincache_bytes` (instance.extra_gauges).

Cacheability guard: rows appended by the control plane may carry
`device_idx == 0` (no interned index); the engine assigns those synthetic
per-token keys from the WHOLE result set, which an incremental fold
cannot reproduce. Any idx-0 row in a scanned range marks the query
uncacheable and the caller falls back to the monolithic engine path.
"""

from __future__ import annotations

import threading
from collections import OrderedDict
from typing import Dict, List, Optional, Tuple

import numpy as np

from sitewhere_tpu.analytics.windows import WindowedStats, compact_keys, \
    windowed_stats
from sitewhere_tpu.persist.eventlog import EventFilter
from sitewhere_tpu.runtime.metrics import GLOBAL_METRICS

_COLS = ("device_idx", "event_date", "value", "device_token")


def grid_geometry(start_ms: int, end_ms: int, window_ms: int,
                  max_windows: int) -> int:
    """n_windows for an explicit range — must mirror
    WindowedAnalyticsEngine._build_report exactly."""
    return max(1, min(max_windows, (end_ms - start_ms) // window_ms + 1))


class _Fold:
    """One un-finalized grid: union raw keys (sorted) + composable
    per-(key, window) accumulators. `min`/`max` carry +-inf sentinels in
    empty cells so merges stay exact; NaN appears only at finalize."""

    __slots__ = ("key_ids", "tokens", "count", "sum", "min", "max")

    def __init__(self, key_ids: np.ndarray, tokens: List[str],
                 count: np.ndarray, vsum: np.ndarray, vmin: np.ndarray,
                 vmax: np.ndarray):
        self.key_ids = key_ids
        self.tokens = tokens
        self.count = count
        self.sum = vsum
        self.min = vmin
        self.max = vmax

    @property
    def nbytes(self) -> int:
        return int(self.key_ids.nbytes + self.count.nbytes +
                   self.sum.nbytes + self.min.nbytes + self.max.nbytes) + \
            sum(len(t) for t in self.tokens) + 64

    @classmethod
    def empty(cls, n_windows: int) -> "_Fold":
        shape = (0, n_windows)
        return cls(np.array([], np.int64), [],
                   np.zeros(shape, np.int64),
                   np.zeros(shape, np.float32),
                   np.full(shape, np.inf, np.float32),
                   np.full(shape, -np.inf, np.float32))


def _fold_rows(device_idx: np.ndarray, dates: np.ndarray,
               values: np.ndarray, tokens: np.ndarray, *, t0: int,
               window_ms: int, n_windows: int) -> _Fold:
    """Fold filtered raw rows into a `_Fold` via the shared windowed_stats
    kernel (same `_pad_pow2` static-shape bucketing as the engine, so the
    delta fold reuses the engine's compiled plans)."""
    from sitewhere_tpu.analytics.engine import _pad_pow2

    device_idx = device_idx.astype(np.int64, copy=False)
    dense, uniq = compact_keys(device_idx)
    u = len(uniq)
    if u == 0:
        return _Fold.empty(n_windows)
    rel = dates.astype(np.int64) - t0
    buckets = np.where((rel >= 0) & (rel // window_ms < n_windows),
                       rel // window_ms, -1).astype(np.int32)
    K = _pad_pow2(u)
    W = _pad_pow2(int(n_windows))
    stats = windowed_stats(dense, buckets, values.astype(np.float32),
                           np.ones(len(dense), bool), window_ms=1,
                           num_keys=K, n_windows=W)
    count = np.asarray(stats.count)[:u, :n_windows].astype(np.int64)
    vsum = np.asarray(stats.sum)[:u, :n_windows].astype(np.float32)
    # re-sentinel the finalized NaNs: empty cells merge as +-inf
    empty = count == 0
    vmin = np.where(empty, np.inf,
                    np.asarray(stats.min)[:u, :n_windows]).astype(np.float32)
    vmax = np.where(empty, -np.inf,
                    np.asarray(stats.max)[:u, :n_windows]).astype(np.float32)
    # token per unique key from its first-occurrence row
    first = np.full(u, -1, np.int64)
    order = np.argsort(dense, kind="stable")
    pos = dense[order]
    sel = pos >= 0
    # last write wins on reversed order -> first occurrence survives
    first[pos[sel][::-1]] = order[sel][::-1]
    toks = ["" if (r < 0 or tokens[r] is None) else str(tokens[r])
            for r in first.tolist()]
    return _Fold(uniq.astype(np.int64), toks, count, vsum, vmin, vmax)


def _merge(a: _Fold, b: _Fold) -> _Fold:
    """Exact composition of two folds over disjoint row sets."""
    if len(a.key_ids) == 0:
        return b
    if len(b.key_ids) == 0:
        return a
    union = np.union1d(a.key_ids, b.key_ids)
    u, w = len(union), a.count.shape[1]
    pa = np.searchsorted(union, a.key_ids)
    pb = np.searchsorted(union, b.key_ids)
    count = np.zeros((u, w), np.int64)
    vsum = np.zeros((u, w), np.float32)
    vmin = np.full((u, w), np.inf, np.float32)
    vmax = np.full((u, w), -np.inf, np.float32)
    count[pa] = a.count
    vsum[pa] = a.sum
    vmin[pa] = a.min
    vmax[pa] = a.max
    count[pb] += b.count
    vsum[pb] += b.sum
    vmin[pb] = np.minimum(vmin[pb], b.min)
    vmax[pb] = np.maximum(vmax[pb], b.max)
    tokens = [""] * u
    for p, t in zip(pa.tolist(), a.tokens):
        tokens[p] = t
    for p, t in zip(pb.tolist(), b.tokens):
        if not tokens[p]:
            tokens[p] = t
    return _Fold(union.astype(np.int64), tokens, count, vsum, vmin, vmax)


def _finalize(fold: _Fold, *, t0: int, window_ms: int,
              n_windows: int):
    """Fold -> WindowReport, matching the engine's padded-grid layout
    (rows past num_keys unused, mean/min/max NaN where count == 0)."""
    from sitewhere_tpu.analytics.engine import WindowReport, _pad_pow2

    u = len(fold.key_ids)
    if u == 0:
        empty = WindowedStats(*(np.zeros((0, 0), d) for d in
                                (np.int32, np.float32, np.float32,
                                 np.float32, np.float32)))
        return WindowReport(t0_ms=t0, window_ms=window_ms, n_windows=0,
                            key_ids=np.array([], object), key_tokens=[],
                            stats=empty)
    K = _pad_pow2(u)
    W = _pad_pow2(int(n_windows))
    count = np.zeros((K, W), np.int32)
    vsum = np.zeros((K, W), np.float32)
    mean = np.zeros((K, W), np.float32)
    vmin = np.zeros((K, W), np.float32)
    vmax = np.zeros((K, W), np.float32)
    count[:u, :n_windows] = fold.count
    vsum[:u, :n_windows] = fold.sum
    cells = fold.count > 0
    mean[:u, :n_windows] = np.where(
        cells, fold.sum / np.maximum(fold.count, 1), np.nan)
    vmin[:u, :n_windows] = np.where(cells, fold.min, np.nan)
    vmax[:u, :n_windows] = np.where(cells, fold.max, np.nan)
    mean[:u, n_windows:] = np.nan
    vmin[:u, n_windows:] = np.nan
    vmax[:u, n_windows:] = np.nan
    mean[u:] = np.nan
    vmin[u:] = np.nan
    vmax[u:] = np.nan
    stats = WindowedStats(count=count, sum=vsum, mean=mean, min=vmin,
                          max=vmax)
    return WindowReport(t0_ms=t0, window_ms=window_ms,
                        n_windows=int(n_windows),
                        key_ids=fold.key_ids.copy(),
                        key_tokens=list(fold.tokens), stats=stats)


class _Entry:
    __slots__ = ("fold", "epoch", "watermark")

    def __init__(self, fold: _Fold, epoch: int, watermark: int):
        self.fold = fold
        self.epoch = epoch
        self.watermark = watermark


def _gather(segments, flt: EventFilter
            ) -> Optional[Tuple[np.ndarray, ...]]:
    """Concatenated (device_idx, event_date, value, device_token) over the
    given immutable segments — the lock-free half of a snapshot scan.
    Returns None when an idx-0 row makes the range uncacheable."""
    parts: Dict[str, List[np.ndarray]] = {n: [] for n in _COLS}
    for seg in segments:
        if seg is None or seg.n == 0:
            continue
        if flt.start_date is not None and seg.max_date < flt.start_date:
            continue
        if flt.end_date is not None and seg.min_date > flt.end_date:
            continue
        idx = np.nonzero(flt._mask(seg.cols))[0]
        if not len(idx):
            continue
        dev = np.asarray(seg.cols["device_idx"][idx])
        if (dev == 0).any():
            return None
        parts["device_idx"].append(dev)
        for name in _COLS[1:]:
            parts[name].append(np.asarray(seg.cols[name][idx]))
    if not parts["device_idx"]:
        return (np.array([], np.int64), np.array([], np.int64),
                np.array([], np.float32), np.array([], object))
    return tuple(np.concatenate(parts[n]) for n in _COLS)


class WindowGridCache:
    """LRU byte-budgeted store of sealed-prefix window grids.

    One instance serves every tenant (keys embed the tenant); `query()`
    is thread-safe — folds run outside the lock, only the LRU map and
    byte accounting are guarded."""

    def __init__(self, max_bytes: int = 64 << 20, registry=None):
        self.max_bytes = int(max_bytes)
        self._entries: "OrderedDict[Tuple, _Entry]" = OrderedDict()
        self._bytes = 0
        self._lock = threading.Lock()
        m = registry or GLOBAL_METRICS
        self.hit_counter = m.counter("query.cache_hit")
        self.miss_counter = m.counter("query.cache_miss")
        self.evict_counter = m.counter("query.cache_evict")

    @property
    def resident_bytes(self) -> int:
        return self._bytes

    def __len__(self) -> int:
        return len(self._entries)

    def invalidate(self, tenant: Optional[str] = None) -> int:
        """Drop entries (one tenant's, or all). Returns entries dropped."""
        with self._lock:
            if tenant is None:
                n = len(self._entries)
                self._entries.clear()
                self._bytes = 0
                return n
            dead = [k for k in self._entries if k[0] == tenant]
            for k in dead:
                self._bytes -= self._entries.pop(k).fold.nbytes
            return len(dead)

    def _store(self, key: Tuple, entry: _Entry) -> None:
        with self._lock:
            old = self._entries.pop(key, None)
            if old is not None:
                self._bytes -= old.fold.nbytes
            self._entries[key] = entry
            self._bytes += entry.fold.nbytes
            while self._bytes > self.max_bytes and len(self._entries) > 1:
                _, evicted = self._entries.popitem(last=False)
                self._bytes -= evicted.fold.nbytes
                self.evict_counter.inc()

    def query(self, tlog, *, tenant: str, flt: EventFilter, window_ms: int,
              start_ms: int, end_ms: int, max_windows: int):
        """Serve one cacheable windowed query from `tlog`
        (persist/eventlog.py TenantEventLog). Returns
        `(WindowReport, info)` or None when the scanned rows are
        uncacheable (idx-0 rows) — the caller falls back to the
        monolithic engine path."""
        n_windows = grid_geometry(start_ms, end_ms, window_ms, max_windows)
        key = (tenant, int(window_ms), int(start_ms), int(end_ms),
               int(n_windows), flt.event_type, flt.mm_name, flt.area_id,
               flt.device_token, flt.assignment_token, flt.customer_id,
               flt.asset_id)
        epoch, segments, pending = tlog.sealed_snapshot()
        with self._lock:
            entry = self._entries.get(key)
            if entry is not None and (entry.epoch != epoch or
                                      entry.watermark > len(segments)):
                self._bytes -= entry.fold.nbytes
                del self._entries[key]
                entry = None
            if entry is not None:
                self._entries.move_to_end(key)
        hit = entry is not None
        base = entry.watermark if hit else 0
        delta_segments = segments[base:]
        delta = _gather(delta_segments, flt)
        if delta is None:
            return None
        delta_rows = len(delta[0])
        fold = entry.fold if hit else _Fold.empty(n_windows)
        if delta_rows:
            fold = _merge(fold, _fold_rows(
                delta[0], delta[1], delta[2], delta[3], t0=start_ms,
                window_ms=window_ms, n_windows=n_windows))
        if delta_rows or not hit or entry.watermark < len(segments):
            self._store(key, _Entry(fold, epoch, len(segments)))
        # the unsealed tail: folded into the RESULT only, never stored
        tail = _gather([pending], flt)
        if tail is None:
            return None
        tail_rows = len(tail[0])
        result = fold
        if tail_rows:
            result = _merge(result, _fold_rows(
                tail[0], tail[1], tail[2], tail[3], t0=start_ms,
                window_ms=window_ms, n_windows=n_windows))
        (self.hit_counter if hit else self.miss_counter).inc()
        report = _finalize(result, t0=start_ms, window_ms=window_ms,
                           n_windows=n_windows)
        return report, {
            "cache_hit": hit,
            "delta_segments": len(delta_segments),
            "delta_rows": delta_rows + tail_rows,
            "watermark": len(segments),
            "epoch": epoch,
        }
