"""Concurrent query serving tier (docs/SERVING.md).

Reads that never stall ingest: a planner normalizes every
`measurement_windows`-shaped request and routes it host-vs-mesh by
estimated scan size (serving/planner.py), an incremental window cache
reuses finalized `[K, W]` grids across dashboard polls by folding only
the segments sealed since the cached watermark (serving/wincache.py),
and a bounded executor runs it all behind per-tenant read admission
with a structured 429 (serving/executor.py)."""

from sitewhere_tpu.serving.executor import (  # noqa: F401
    QueryExecutor, QueryShedError)
from sitewhere_tpu.serving.planner import (  # noqa: F401
    QueryPlan, QueryPlanner, WindowQuery)
from sitewhere_tpu.serving.wincache import WindowGridCache  # noqa: F401

__all__ = ["QueryExecutor", "QueryShedError", "QueryPlan", "QueryPlanner",
           "WindowQuery", "WindowGridCache"]
