"""Per-tenant datastore configuration: the DatastoreConfigurationParser role.

Reference: tenants choose their event store via configuration
(sitewhere-configuration/src/main/java/com/sitewhere/configuration/datastore/
DatastoreConfigurationParser.java — mongodb/influxdb/cassandra/hbase per
tenant). This framework has ONE storage engine (the columnar Arrow/Parquet
event log — the TPU-first answer to all four reference stores), so the
per-tenant choice becomes: which *instance* of it, where it spills, how it
buffers, and whether it persists at all:

- kind "columnar": dedicated ColumnarEventLog for the tenant with its own
  spill dir / segment size / linger (isolation, per-tenant retention).
- kind "memory": dedicated in-memory log, never touches disk (dev/test or
  data-residency-restricted tenants).
- kind "widerow": the SECOND interchangeable historical backend
  (`persist/widerow.py` — the sitewhere-hbase/cassandra wide-column
  store role): ACID sqlite rows in time buckets, indexed on the
  reference's query axes, whole-bucket retention pruning.
- no override: the tenant shares the instance's default log (the default
  single-store deployment).

Configuration sources, in priority order: explicit overrides passed by the
operator (config model `event_management.tenant_datastore` elements) and
`datastore.*` keys in the tenant's metadata (tenant templates can set them
— the analogue of the reference's per-tenant ZK config).
"""

from __future__ import annotations

import os
import threading
from dataclasses import dataclass
from typing import Dict, Optional

from sitewhere_tpu.persist.eventlog import ColumnarEventLog

_KINDS = ("columnar", "memory", "widerow")


@dataclass
class DatastoreConfig:
    """One tenant's event-store choice."""

    kind: str = "columnar"           # "columnar" | "memory" | "widerow"
    data_dir: Optional[str] = None   # spill dir; relative = under base dir
    segment_rows: int = 65536
    linger_ms: int = 250
    spill: bool = True
    bucket_ms: int = 3_600_000       # widerow time-bucket width

    def __post_init__(self) -> None:
        if self.kind not in _KINDS:
            raise ValueError(
                f"unknown datastore kind {self.kind!r} (one of {_KINDS})")

    @classmethod
    def from_metadata(cls, metadata: Dict[str, str]
                      ) -> Optional["DatastoreConfig"]:
        """Build from `datastore.*` tenant-metadata keys; None when the
        tenant doesn't customize (shares the instance default)."""
        keys = {k: v for k, v in (metadata or {}).items()
                if k.startswith("datastore.")}
        if not keys:
            return None
        return cls(
            kind=keys.get("datastore.kind", "columnar"),
            data_dir=keys.get("datastore.data_dir") or None,
            segment_rows=int(keys.get("datastore.segment_rows", 65536)),
            linger_ms=int(keys.get("datastore.linger_ms", 250)),
            spill=keys.get("datastore.spill", "true").lower()
            in ("1", "true", "yes", "on"),
            bucket_ms=int(keys.get("datastore.bucket_ms", 3_600_000)))


class TenantDatastoreManager:
    """Resolves each tenant to its event log and owns the dedicated ones.

    The instance's shared default log is NOT owned here (the instance
    starts/stops it); dedicated per-tenant logs are created lazily on first
    resolution and lifecycle-managed by this manager.
    """

    def __init__(self, default_log: ColumnarEventLog,
                 base_dir: Optional[str] = None,
                 overrides: Optional[Dict[str, DatastoreConfig]] = None):
        self.default_log = default_log
        self.base_dir = base_dir
        self.overrides: Dict[str, DatastoreConfig] = dict(overrides or {})
        # ColumnarEventLog or WideRowEventStore (duck-compatible surface)
        self._dedicated: Dict[str, object] = {}
        self._lock = threading.Lock()
        self._started = False

    def register_override(self, tenant_token: str,
                          config: DatastoreConfig) -> None:
        """Operator-level override (config model tenant_datastore element).
        Takes effect on the tenant's next resolution (engine restart)."""
        with self._lock:
            self.overrides[tenant_token] = config

    def config_for(self, tenant) -> Optional[DatastoreConfig]:
        """tenant: token string or Tenant model object."""
        token = getattr(tenant, "token", tenant)
        with self._lock:
            if token in self.overrides:
                return self.overrides[token]
        return DatastoreConfig.from_metadata(
            getattr(tenant, "metadata", None) or {})

    def event_log_for(self, tenant):
        """The tenant's event store: the shared default ColumnarEventLog,
        or a dedicated columnar/memory/widerow store (duck-compatible)."""
        token = getattr(tenant, "token", tenant)
        config = self.config_for(tenant)
        if config is None:
            return self.default_log
        with self._lock:
            log = self._dedicated.get(token)
            if log is None:
                log = self._build(token, config)
                self._dedicated[token] = log
                if self._started:
                    log.start()
            return log

    def _build(self, token: str, config: DatastoreConfig):
        from urllib.parse import quote

        if config.kind == "widerow":
            from sitewhere_tpu.persist.widerow import WideRowEventStore

            db_path = config.data_dir
            if db_path is None and self.base_dir:
                stores = os.path.join(self.base_dir, "tenant-stores")
                db_path = os.path.join(
                    stores, quote(token, safe="") + ".widerow.db")
            elif db_path is not None and not os.path.isabs(db_path) \
                    and self.base_dir:
                db_path = os.path.join(self.base_dir, db_path)
            return WideRowEventStore(db_path=db_path,
                                     bucket_ms=config.bucket_ms)
        data_dir = None
        if config.kind == "columnar":
            data_dir = config.data_dir
            if data_dir is None:
                # percent-encode: "a/b" and "a_b" are distinct tenants and
                # must not share a spill directory
                if self.base_dir:
                    stores = os.path.join(self.base_dir, "tenant-stores")
                    data_dir = os.path.join(stores, quote(token, safe=""))
                    # migrate a directory created by the pre-encoding
                    # underscore scheme so its data stays visible
                    legacy = os.path.join(stores, token.replace("/", "_"))
                    if (legacy != data_dir and os.path.isdir(legacy)
                            and not os.path.exists(data_dir)):
                        try:
                            os.rename(legacy, data_dir)
                        except OSError:
                            pass  # fall through: fresh dir
            elif not os.path.isabs(data_dir) and self.base_dir:
                data_dir = os.path.join(self.base_dir, data_dir)
        return ColumnarEventLog(data_dir=data_dir,
                                segment_rows=config.segment_rows,
                                linger_ms=config.linger_ms,
                                spill_parquet=config.spill)

    def dedicated_tenants(self) -> Dict[str, str]:
        """token -> kind, for topology/observability."""
        def kind(log) -> str:
            explicit = getattr(log, "kind", None)
            if explicit:
                return explicit
            return "columnar" if log._data_dir else "memory"

        with self._lock:
            return {tok: kind(log)
                    for tok, log in self._dedicated.items()}

    # -- lifecycle (instance calls these around its own) -------------------
    def start(self) -> None:
        with self._lock:
            self._started = True
            logs = list(self._dedicated.values())
        for log in logs:
            log.start()

    def stop(self) -> None:
        with self._lock:
            self._started = False
            logs = list(self._dedicated.values())
        for log in logs:
            log.stop()

    def flush(self) -> None:
        with self._lock:
            logs = list(self._dedicated.values())
        for log in logs:
            log.flush()
