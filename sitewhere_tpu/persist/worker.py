"""Asynchronous bulk persistence: columnar appends off the ingest thread.

Reference: service-event-management's DeviceEventBuffer
(mongodb/DeviceEventBuffer.java:99-123) — a bounded in-memory queue plus
writer threads so API calls never block on the datastore, with the queue
bound providing backpressure. The TPU rebuild's equivalent moves the
columnar `append_batch` (persist/eventlog.py) onto a writer thread so the
ingest path (pipelined fused-step feeding) overlaps durable persistence
instead of serializing ahead of it — the last serialized host cost on the
bulk lane.

After each append the worker publishes a compact batch marker on the
`inbound-enriched-batches` topic (runtime/bus.py TopicNaming): the bulk
analog of the reference's enriched-events production
(OutboundPayloadEnrichmentLogic), carrying (tenant, rows, event-date span)
so consumers — analytics receivers, outbound fan-out — read the rows back
columnar from the log rather than receiving per-event envelopes.

Failure contract: an append that raises parks the batch's marker on the
`.dead-letter` surface of the marker topic with the error, and the worker
keeps running — persistence failures must never poison the ingest loop
(same isolation pipeline/inbound.py gives the fused step).
"""

from __future__ import annotations

import queue
import threading
import time
from typing import Optional

import msgpack
import numpy as np

from sitewhere_tpu.runtime.bus import EventBus, TopicNaming
from sitewhere_tpu.runtime.lifecycle import LifecycleComponent
from sitewhere_tpu.runtime.metrics import MetricsRegistry

import logging

LOGGER = logging.getLogger("sitewhere.persist.worker")


class AsyncEventPersister(LifecycleComponent):
    """Bounded-queue writer thread for bulk EventBatch persistence.

    `submit(batch)` enqueues and returns immediately; when `depth` batches
    are already queued it blocks — natural backpressure, the ingest loop
    is paced by the datastore exactly when the datastore is the
    bottleneck (the reference blocks API threads on its full queue the
    same way). `flush()` waits until everything queued so far is durable
    in the columnar log and its marker published.
    """

    def __init__(self, eventlog, packer, tenant: str = "default",
                 bus: Optional[EventBus] = None,
                 naming: Optional[TopicNaming] = None,
                 registry=None, depth: int = 8,
                 metrics: Optional[MetricsRegistry] = None):
        super().__init__(f"async-persister:{tenant}")
        self.eventlog = eventlog
        self.packer = packer
        self.tenant = tenant
        self.bus = bus
        self.naming = naming or TopicNaming()
        self.registry = registry
        m = (metrics or MetricsRegistry()).scoped("persist_worker")
        self.persisted_meter = m.meter("events")
        self.failed_counter = m.counter("failed")
        self._q: "queue.Queue" = queue.Queue(maxsize=max(1, depth))
        self._done = threading.Condition()
        self._submitted = 0
        self._completed = 0
        self._stop = threading.Event()
        # atomic submit-vs-stop gate (the PipelinedSubmitter pattern):
        # liveness check + enqueue happen under one lock, and stop flips
        # _stop under the same lock — no window where a submit can land
        # in a queue nothing will ever drain
        self._close_lock = threading.Lock()
        self._thread: Optional[threading.Thread] = None

    # -- lifecycle ---------------------------------------------------------
    def on_start(self, monitor) -> None:
        self._stop.clear()
        self._thread = threading.Thread(target=self._run,
                                        name=f"persist-{self.tenant}",
                                        daemon=True)
        self._thread.start()

    def on_stop(self, monitor) -> None:
        self.flush()
        with self._close_lock:
            self._stop.set()
        thread, self._thread = self._thread, None
        if thread is not None:
            thread.join(timeout=10.0)
        # a submit that landed between flush() and the stop flag is still
        # queued with the writer gone: persist stragglers synchronously so
        # nothing is silently lost and no flush() waiter hangs
        while True:
            try:
                batch, tenant = self._q.get_nowait()
            except queue.Empty:
                break
            try:
                self._persist_one(batch, tenant)
            finally:
                with self._done:
                    self._completed += 1
                    self._done.notify_all()

    # -- producer ----------------------------------------------------------
    def submit(self, batch, tenant: Optional[str] = None) -> None:
        """Queue one packed EventBatch for durable append (blocks when
        `depth` batches are pending — backpressure)."""
        item = (batch, tenant or self.tenant)
        while True:
            with self._close_lock:
                if self._stop.is_set() or self._thread is None:
                    raise RuntimeError("persister not running")
                try:
                    self._q.put_nowait(item)
                except queue.Full:
                    pass  # backpressure: retry outside the lock
                else:
                    with self._done:
                        self._submitted += 1
                    return
            time.sleep(0.005)

    def flush(self, timeout: Optional[float] = 60.0) -> None:
        """Block until every batch submitted so far is appended (or failed
        onto the dead-letter surface)."""
        with self._done:
            target = self._submitted
            if not self._done.wait_for(
                    lambda: self._completed >= target, timeout=timeout):
                raise TimeoutError("async persister did not drain in time")

    @property
    def pending(self) -> int:
        with self._done:
            return self._submitted - self._completed

    # -- writer ------------------------------------------------------------
    def _run(self) -> None:
        while True:
            try:
                batch, tenant = self._q.get(timeout=0.1)
            except queue.Empty:
                if self._stop.is_set():
                    return
                continue
            try:
                self._persist_one(batch, tenant)
            finally:
                with self._done:
                    self._completed += 1
                    self._done.notify_all()

    def _persist_one(self, batch, tenant: str) -> None:
        marker_topic = self.naming.inbound_enriched_batches(tenant)
        try:
            valid = np.asarray(batch.valid)
            n = self.eventlog.append_batch(tenant, batch, self.packer,
                                           registry=self.registry)
            self.persisted_meter.mark(n)
            if self.bus is None or n == 0:
                return
            ts = np.asarray(batch.ts)[valid.astype(bool)]
            base = self.packer.epoch_base_ms
            marker = {"tenant": tenant, "n": int(n),
                      "ts_min": int(ts.min()) + base,
                      "ts_max": int(ts.max()) + base}
            self.bus.publish(marker_topic, tenant.encode(),
                             msgpack.packb(marker, use_bin_type=True))
        except Exception as exc:
            self.failed_counter.inc()
            LOGGER.exception("bulk persist failed for tenant '%s'", tenant)
            if self.bus is not None:
                self.bus.publish(
                    marker_topic + ".dead-letter", tenant.encode(),
                    msgpack.packb({"tenant": tenant, "error": str(exc)},
                                  use_bin_type=True))
