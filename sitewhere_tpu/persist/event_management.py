"""Device event management: the persistence API over the columnar log.

Reference surface: IDeviceEventManagement (sitewhere-core-api
spi/device/event/IDeviceEventManagement.java) / the 16 rpcs of
device-event-management.proto:20-93 (AddDeviceEventBatch, GetDeviceEventById,
GetDeviceEventByAlternateId, Add/ListMeasurements, Add/ListLocations,
Add/ListAlerts, Add/ListCommandInvocations, ListCommandResponsesForInvocation,
Add/ListStateChanges, Add/ListStreamData) routed through
EventManagementImpl.java:82 and decorated by KafkaEventPersistenceTriggers.java:50
which forwards every persisted event to the inbound-persisted-events topic.

List rpcs take an *index* (assignment / area / asset / customer) plus ids and
a date range — EventIndex mirrors that.
"""

from __future__ import annotations

import enum
from typing import Callable, Dict, List, Optional, Sequence

import msgpack

from sitewhere_tpu.errors import SiteWhereError
from sitewhere_tpu.model.common import (
    DateRangeCriteria, SearchCriteria, SearchResults, new_id, now_ms)
from sitewhere_tpu.model.event import (
    DeviceAlert, DeviceCommandInvocation, DeviceCommandResponse, DeviceEvent,
    DeviceEventBatch, DeviceEventContext, DeviceEventType, DeviceLocation,
    DeviceMeasurement, DeviceStateChange, DeviceStreamData)
from sitewhere_tpu.persist.eventlog import ColumnarEventLog, EventFilter
from sitewhere_tpu.runtime.lifecycle import LifecycleComponent


class EventIndex(enum.Enum):
    """Which entity field a list query filters on
    (GDeviceEventIndex in device-event-model.proto)."""

    ASSIGNMENT = "assignment"
    AREA = "area"
    ASSET = "asset"
    CUSTOMER = "customer"
    DEVICE = "device"


_INDEX_FIELD = {
    EventIndex.ASSIGNMENT: "assignment_token",
    EventIndex.AREA: "area_id",
    EventIndex.ASSET: "asset_id",
    EventIndex.CUSTOMER: "customer_id",
    EventIndex.DEVICE: "device_token",
}


def context_for_assignment(registry, assignment_token: str,
                           tenant: str) -> DeviceEventContext:
    """Resolve assignment token -> full event context (the lookup the
    reference does over gRPC in both persistence and enrichment). Shared by
    DeviceEventManagement and PayloadEnrichment so their contexts never
    diverge."""
    assignment = registry.get_device_assignment_by_token(assignment_token)
    if assignment is None:
        raise SiteWhereError(f"unknown assignment: {assignment_token}")
    device = registry.get_device(assignment.device_id)
    return DeviceEventContext(
        device_id=device.id, device_token=device.token,
        device_type_id=device.device_type_id, assignment_id=assignment.token,
        customer_id=assignment.customer_id, area_id=assignment.area_id,
        asset_id=assignment.asset_id, tenant_id=tenant)


class DeviceEventManagement(LifecycleComponent):
    """Tenant-scoped event persistence facade.

    `registry` (a DeviceManagement) resolves assignment context so every
    persisted event carries device/customer/area/asset ids, exactly like the
    reference fills GDeviceEventContext during persistence.
    """

    def __init__(self, log: ColumnarEventLog, registry=None,
                 tenant: str = "default", device_interner=None):
        super().__init__(f"event-management:{tenant}")
        self.log = log
        self.registry = registry
        self.tenant = tenant
        self.device_interner = device_interner
        self._listeners: List[Callable[[List[DeviceEvent]], None]] = []

    # -- lifecycle ---------------------------------------------------------
    def on_start(self, monitor) -> None:
        self.log.start()

    def on_stop(self, monitor) -> None:
        # The log is shared across tenants: its lifecycle belongs to whoever
        # constructed it (stop() joins the flusher). Only seal THIS tenant.
        self.log.flush_tenant(self.tenant)

    # -- triggers (KafkaEventPersistenceTriggers equivalent) ---------------
    def add_listener(self, callback: Callable[[List[DeviceEvent]], None]) -> None:
        self._listeners.append(callback)

    def _fire(self, events: List[DeviceEvent]) -> None:
        for cb in self._listeners:
            cb(events)

    # -- context resolution ------------------------------------------------
    def _context_for_assignment(self, assignment_token: str) -> DeviceEventContext:
        if self.registry is None:
            return DeviceEventContext(assignment_id=assignment_token,
                                      tenant_id=self.tenant)
        return context_for_assignment(self.registry, assignment_token,
                                      self.tenant)

    def _stamp(self, ev: DeviceEvent, ctx: DeviceEventContext) -> DeviceEvent:
        if not ev.id:
            ev.id = new_id()
        ev.device_id = ctx.device_token or ev.device_id
        ev.device_assignment_id = ctx.assignment_id
        ev.customer_id = ctx.customer_id
        ev.area_id = ctx.area_id
        ev.asset_id = ctx.asset_id
        ev.received_date = now_ms()
        return ev

    def _persist(self, assignment_token: str,
                 events: Sequence[DeviceEvent]) -> List[DeviceEvent]:
        ctx = self._context_for_assignment(assignment_token)
        stamped = [self._stamp(ev, ctx) for ev in events]
        self.log.append_events(self.tenant, stamped, self.device_interner)
        self._fire(list(stamped))
        return list(stamped)

    # -- add rpcs ----------------------------------------------------------
    def add_measurements(self, assignment_token: str,
                         *events: DeviceMeasurement) -> List[DeviceMeasurement]:
        return self._persist(assignment_token, events)  # type: ignore[return-value]

    def add_locations(self, assignment_token: str,
                      *events: DeviceLocation) -> List[DeviceLocation]:
        return self._persist(assignment_token, events)  # type: ignore[return-value]

    def add_alerts(self, assignment_token: str,
                   *events: DeviceAlert) -> List[DeviceAlert]:
        return self._persist(assignment_token, events)  # type: ignore[return-value]

    def add_command_invocations(self, assignment_token: str,
                                *events: DeviceCommandInvocation
                                ) -> List[DeviceCommandInvocation]:
        return self._persist(assignment_token, events)  # type: ignore[return-value]

    def add_command_responses(self, assignment_token: str,
                              *events: DeviceCommandResponse
                              ) -> List[DeviceCommandResponse]:
        return self._persist(assignment_token, events)  # type: ignore[return-value]

    def add_state_changes(self, assignment_token: str,
                          *events: DeviceStateChange) -> List[DeviceStateChange]:
        return self._persist(assignment_token, events)  # type: ignore[return-value]

    def add_stream_data(self, assignment_token: str,
                        *events: DeviceStreamData) -> List[DeviceStreamData]:
        return self._persist(assignment_token, events)  # type: ignore[return-value]

    def add_device_event_batch(self, device_token: str,
                               batch: DeviceEventBatch) -> List[DeviceEvent]:
        """AddDeviceEventBatch: resolve the device's active assignment, then
        persist every event in the batch (IDeviceEventBatch flow)."""
        if self.registry is None:
            raise SiteWhereError("device event batch requires a registry")
        device = self.registry.get_device_by_token(device_token)
        if device is None:
            raise SiteWhereError(f"unknown device: {device_token}")
        assignment = self.registry.get_active_assignment(device.id)
        if assignment is None:
            raise SiteWhereError(f"device has no active assignment: {device_token}")
        return self._persist(assignment.token, batch.all_events())

    # -- get rpcs ----------------------------------------------------------
    def get_event_by_id(self, event_id: str) -> Optional[DeviceEvent]:
        res = self.log.query(self.tenant, EventFilter(id=event_id),
                             SearchCriteria(page_number=1, page_size=1))
        return res.results[0] if res.results else None

    def get_event_by_alternate_id(self, alternate_id: str
                                  ) -> Optional[DeviceEvent]:
        res = self.log.query(self.tenant, EventFilter(alternate_id=alternate_id),
                             SearchCriteria(page_number=1, page_size=1))
        return res.results[0] if res.results else None

    # -- list rpcs ---------------------------------------------------------
    def _list(self, event_type: DeviceEventType, index: EventIndex,
              token: str, criteria: Optional[SearchCriteria]
              ) -> SearchResults[DeviceEvent]:
        flt = EventFilter(event_type=event_type)
        setattr(flt, _INDEX_FIELD[index], token)
        return self.log.query(self.tenant, flt, criteria)

    def list_measurements(self, index: EventIndex, token: str,
                          criteria: Optional[DateRangeCriteria] = None
                          ) -> SearchResults[DeviceMeasurement]:
        return self._list(DeviceEventType.MEASUREMENT, index, token, criteria)

    def list_locations(self, index: EventIndex, token: str,
                       criteria: Optional[DateRangeCriteria] = None
                       ) -> SearchResults[DeviceLocation]:
        return self._list(DeviceEventType.LOCATION, index, token, criteria)

    def list_alerts(self, index: EventIndex, token: str,
                    criteria: Optional[DateRangeCriteria] = None
                    ) -> SearchResults[DeviceAlert]:
        return self._list(DeviceEventType.ALERT, index, token, criteria)

    def list_command_invocations(self, index: EventIndex, token: str,
                                 criteria: Optional[DateRangeCriteria] = None
                                 ) -> SearchResults[DeviceCommandInvocation]:
        return self._list(DeviceEventType.COMMAND_INVOCATION, index, token,
                          criteria)

    def list_command_responses_for_invocation(
            self, invocation_event_id: str,
            criteria: Optional[SearchCriteria] = None
            ) -> SearchResults[DeviceCommandResponse]:
        return self.log.query(
            self.tenant,
            EventFilter(event_type=DeviceEventType.COMMAND_RESPONSE,
                        originating_event_id=invocation_event_id), criteria)

    def list_state_changes(self, index: EventIndex, token: str,
                           criteria: Optional[DateRangeCriteria] = None
                           ) -> SearchResults[DeviceStateChange]:
        return self._list(DeviceEventType.STATE_CHANGE, index, token, criteria)

    def list_stream_data(self, assignment_token: str, stream_id: str,
                         criteria: Optional[SearchCriteria] = None
                         ) -> SearchResults[DeviceStreamData]:
        return self.log.query(
            self.tenant,
            EventFilter(event_type=DeviceEventType.STREAM_DATA,
                        assignment_token=assignment_token,
                        stream_id=stream_id), criteria,
            order_by="sequence_asc")  # pages align with chunk order

    def list_device_events(self, device_token: str,
                           criteria: Optional[DateRangeCriteria] = None
                           ) -> SearchResults[DeviceEvent]:
        return self.log.query(
            self.tenant, EventFilter(device_token=device_token), criteria)


class EventPersistenceTriggers:
    """Forward persisted events onto the bus — KafkaEventPersistenceTriggers
    (forwardEvents :72): each persisted event goes to inbound-persisted-events,
    keyed by device token for per-device ordering."""

    def __init__(self, bus, naming, tenant: str = "default"):
        self.bus = bus
        self.topic = naming.inbound_persisted_events(tenant)

    def __call__(self, events: List[DeviceEvent]) -> None:
        for ev in events:
            payload = msgpack.packb(ev.to_dict(), use_bin_type=True)
            self.bus.publish(self.topic, ev.device_id.encode(), payload)

    def attach(self, management: DeviceEventManagement) -> None:
        management.add_listener(self)
