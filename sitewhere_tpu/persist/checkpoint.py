"""Checkpoint/restore of HBM device-state + replay recovery.

Reference: SiteWhere has *no* snapshotting — durable truth lives in the
datastores and Kafka offsets, and a restarted service replays from committed
offsets (SURVEY.md §5; offset commit at DecodedEventsConsumer.java:194-199).
Here the HBM DeviceStateTensors are exactly such a rebuildable cache: the
checkpointer snapshots them (plus the interner tables and packer epoch that
give the indices meaning, plus the bus committed offsets) so recovery is
  restore latest checkpoint -> replay bus records past the saved offsets
instead of a full-history replay.

Format: a directory per checkpoint (`ckpt-<n>/`) holding one .npz of all
state arrays + a JSON manifest; written to a temp dir and atomically renamed,
so a crash mid-write never corrupts the latest checkpoint. (orbax serves the
same role for model training; this state is a handful of dense arrays, so a
direct npz keeps restore dependency-free and fast.)
"""

from __future__ import annotations

import dataclasses
import json
import os
import shutil
from typing import Any, Dict, List, Optional

import jax
import numpy as np

from sitewhere_tpu.model.common import _asdict
from sitewhere_tpu.model.event import DeviceAlert
from sitewhere_tpu.pipeline.state_tensors import DeviceStateTensors


def _alert_from_dict(d: Dict[str, Any]) -> DeviceAlert:
    """Manifest dict -> DeviceAlert (enum fields coerced by annotation)."""
    import enum
    import typing

    hints = typing.get_type_hints(DeviceAlert)
    kwargs: Dict[str, Any] = {}
    for f in dataclasses.fields(DeviceAlert):
        if f.name not in d:
            continue
        val = d[f.name]
        t = hints.get(f.name)
        if (isinstance(t, type) and issubclass(t, enum.Enum)
                and val is not None and not isinstance(val, t)):
            val = t(val)
        kwargs[f.name] = val
    return DeviceAlert(**kwargs)


_TENANT_FIELDS = ("tenant_event_count", "tenant_alert_count")
# rebased-int32 timestamp fields (EventPacker.epoch_base_ms); -2^31 = never
_TS_FIELDS = ("last_interaction", "presence_missing_since",
              "last_location_ts", "last_measurement_ts", "last_alert_ts")
_NEG = -(2 ** 31)


def _permute_device_rows(kwargs: Dict[str, np.ndarray],
                         perm: np.ndarray) -> Dict[str, np.ndarray]:
    """Re-index device-major state rows old-index -> perm[old-index]
    (elastic restore across shard-congruent interner layouts). Rows with
    no device (perm 0) fall away; untouched rows keep init sentinels."""
    from sitewhere_tpu.pipeline.state_tensors import init_device_state_np

    sample = kwargs["last_measurement"]
    init = init_device_state_np(sample.shape[0], sample.shape[1],
                                kwargs["tenant_event_count"].shape[0])
    out = {}
    old_idx = np.nonzero(perm)[0]
    new_idx = perm[old_idx]
    for name, array in kwargs.items():
        if name in _TENANT_FIELDS:
            out[name] = array
            continue
        fresh = np.array(getattr(init, name))
        fresh[new_idx] = array[old_idx]
        out[name] = fresh
    return out


def _shift_ts(array: np.ndarray, delta_ms: int) -> np.ndarray:
    """Shift rebased timestamps between epoch bases; the 'never' sentinel
    stays put."""
    if delta_ms == 0:
        return array
    return np.where(array == _NEG, _NEG,
                    array + np.int32(delta_ms)).astype(array.dtype)


# rule-program state fields with a device-major leading axis (the rest —
# gen/fire_count/suppress_count — are program-indexed and move verbatim)
_RULE_STATE_DEVICE_FIELDS = ("slab",)


def _permute_rule_state_rows(kwargs: Dict[str, np.ndarray],
                             perm: np.ndarray) -> Dict[str, np.ndarray]:
    """Re-index the rule state's device-major rows old -> perm[old]
    (elastic restore, mirrors _permute_device_rows): untouched rows keep
    init sentinels so unmapped devices start temporal windows fresh."""
    from sitewhere_tpu.ops.stateful import init_rule_state_np

    sample = kwargs["slab"]
    init = init_rule_state_np(sample.shape[0], sample.shape[1],
                              (sample.shape[2] - 2) // 4)
    out = {}
    old_idx = np.nonzero(perm)[0]
    new_idx = perm[old_idx]
    for name, array in kwargs.items():
        if name not in _RULE_STATE_DEVICE_FIELDS:
            out[name] = array
            continue
        fresh = np.array(getattr(init, name))
        fresh[new_idx] = array[old_idx]
        out[name] = fresh
    return out


# anomaly-model state fields with a device-major leading axis (the rest —
# gen/fire_count/eval_count — are model-indexed and move verbatim)
_MODEL_STATE_DEVICE_FIELDS = ("slab",)


def _permute_model_state_rows(kwargs: Dict[str, np.ndarray],
                              perm: np.ndarray) -> Dict[str, np.ndarray]:
    """Re-index the anomaly-model state's device-major rows old -> perm[old]
    (elastic restore, mirrors _permute_rule_state_rows): untouched rows keep
    init sentinels so unmapped devices start feature windows fresh."""
    from sitewhere_tpu.ops.anomaly import init_model_state_np

    sample = kwargs["slab"]
    init = init_model_state_np(sample.shape[0], sample.shape[1],
                               (sample.shape[2] - 2) // 4)
    out = {}
    old_idx = np.nonzero(perm)[0]
    new_idx = perm[old_idx]
    for name, array in kwargs.items():
        if name not in _MODEL_STATE_DEVICE_FIELDS:
            out[name] = array
            continue
        fresh = np.array(getattr(init, name))
        fresh[new_idx] = array[old_idx]
        out[name] = fresh
    return out


# actuation state fields with a device-major leading axis (the rest —
# gen/fire_count/debounce_count — are policy-indexed and move verbatim)
_ACTUATION_STATE_DEVICE_FIELDS = ("slab",)


def _permute_actuation_state_rows(kwargs: Dict[str, np.ndarray],
                                  perm: np.ndarray) -> Dict[str, np.ndarray]:
    """Re-index the actuation state's device-major rows old -> perm[old]
    (elastic restore, mirrors _permute_model_state_rows): untouched rows
    keep init sentinels so unmapped devices start debounce windows
    fresh."""
    from sitewhere_tpu.ops.actuate import init_actuation_state_np

    sample = kwargs["slab"]
    init = init_actuation_state_np(sample.shape[0], sample.shape[1])
    out = {}
    old_idx = np.nonzero(perm)[0]
    new_idx = perm[old_idx]
    for name, array in kwargs.items():
        if name not in _ACTUATION_STATE_DEVICE_FIELDS:
            out[name] = array
            continue
        fresh = np.array(getattr(init, name))
        fresh[new_idx] = array[old_idx]
        out[name] = fresh
    return out


def _migrate_state_cols(cols: Dict[str, np.ndarray], *, flag_field: str
                        ) -> Dict[str, np.ndarray]:
    """Fuse a pre-slab checkpoint's separate state columns
    (value/aux/ts/counter + flag + row_gen) into the current fused-slab
    layout (ops/stateful.py pack_state_slab_np). Slab-era checkpoints
    (or empty column sets) pass through untouched. float planes travel
    as raw IEEE bits, so restored state is bit-identical."""
    if not cols or "slab" in cols or "value" not in cols:
        return cols
    from sitewhere_tpu.ops.stateful import pack_state_slab_np

    fused = {"slab": pack_state_slab_np(
        cols["value"], cols["aux"], cols["ts"], cols["counter"],
        cols[flag_field], cols["row_gen"])}
    for key, array in cols.items():
        if key not in ("value", "aux", "ts", "counter", flag_field,
                       "row_gen"):
            fused[key] = array
    return fused


def _install_overflow(engine, overflow_cols: Dict[str, np.ndarray]) -> None:
    """Hand a restored overflow backlog to the engine: engines with a
    pending-overflow slot park it (drained before the next checkpoint);
    others fold it immediately in batch-size chunks, stashing any fired
    alerts on the engine's pending list (never silently lost — the same
    contract as ShardedPipelineEngine.drain_pending)."""
    from sitewhere_tpu.ops.pack import EventBatch

    batch = EventBatch(**overflow_cols)
    setter = getattr(engine, "set_pending_overflow_batch", None)
    if setter is not None:
        setter(batch)
        return
    n = batch.device_idx.shape[0]
    B = engine.batch_size
    for start in range(0, n, B):
        chunk = {}
        for field in dataclasses.fields(EventBatch):
            col = getattr(batch, field.name)[start:start + B]
            if col.shape[0] < B:
                pad = np.zeros((B - col.shape[0],) + col.shape[1:],
                               col.dtype)
                col = np.concatenate([col, pad])
            chunk[field.name] = col
        fold = EventBatch(**chunk)
        routed, outputs = engine.submit_routed(fold)
        engine._pending_alerts.extend(
            engine.materialize_alerts(routed, outputs))


def _write_checkpoint_dir(directory: str, arrays: Dict[str, np.ndarray],
                          manifest: Dict[str, Any]) -> str:
    """Write one `ckpt-<seq>/` directory (state.npz + manifest.json +
    digest.json) with the next sequence number, atomically via fsync +
    tmp-dir rename — the single writer behind PipelineCheckpointer.save
    and write_assembled. The digest lets restore verify completeness and
    fall back to the last good checkpoint instead of trusting the rename
    alone (a torn write inside a renamed dir is the failure the
    `checkpoint_torn_write` drill injects)."""
    from sitewhere_tpu.persist.atomic import (
        fsync_dir, write_digest_manifest)
    from sitewhere_tpu.runtime.faults import FaultError, fault_point

    existing = [int(n.split("-")[1]) for n in os.listdir(directory)
                if n.startswith("ckpt-") and not n.endswith(".tmp")
                and not n.endswith(".quarantine")]
    seq = (max(existing) + 1) if existing else 0
    final = os.path.join(directory, f"ckpt-{seq:08d}")
    tmp = final + ".tmp"
    os.makedirs(tmp, exist_ok=True)
    np.savez_compressed(os.path.join(tmp, "state.npz"), **arrays)
    with open(os.path.join(tmp, "manifest.json"), "w",
              encoding="utf-8") as fh:
        json.dump(manifest, fh)
    write_digest_manifest(tmp)
    try:
        fault_point("checkpoint_torn_write")
    except FaultError:
        # simulate the dangerous case: the rename lands but the payload
        # is torn — digest verification is what must catch this
        state_path = os.path.join(tmp, "state.npz")
        size = os.path.getsize(state_path)
        with open(state_path, "r+b") as fh:
            fh.truncate(max(1, size // 2))
        os.replace(tmp, final)
        return final
    os.replace(tmp, final)
    fsync_dir(directory)
    return final


def _union_tokens(per_host: List[List[Optional[str]]]):
    """Union sequential interner snapshots by token; returns the merged
    table plus one old-index -> merged-index array per host."""
    tokens: List[Optional[str]] = [None]
    index: Dict[str, int] = {}
    remaps = []
    for snapshot in per_host:
        snapshot = snapshot or [None]
        remap = np.zeros(max(len(snapshot), 1), np.int32)
        for i, token in enumerate(snapshot):
            if i == 0 or token is None:
                continue
            if token not in index:
                index[token] = len(tokens)
                tokens.append(token)
            remap[i] = index[token]
        remaps.append(remap)
    return tokens, remaps


def _merge_congruent_tokens(per_host: List[List[Optional[str]]]):
    """Merge shard-congruent DEVICE tables: the index of a token is a pure
    function of the token, so hosts must agree wherever they overlap."""
    size = max(len(s) for s in per_host)
    out: List[Optional[str]] = [None] * size
    for snapshot in per_host:
        for i, token in enumerate(snapshot):
            if i == 0 or token is None:
                continue
            if out[i] is None:
                out[i] = token
            elif out[i] != token:
                raise SiteWhereCheckpointError(
                    f"device interner disagreement at index {i}: "
                    f"{out[i]!r} vs {token!r} — per-host checkpoints were "
                    f"not taken from one converged cluster")
    return out


def assemble_canonical(paths: List[str]):
    """Merge one per-host shard checkpoint from EVERY host of a cluster
    into a single canonical (topology-independent) snapshot: returns
    (manifest, state_arrays, overflow_cols-or-None).

    This closes the multi-host elasticity gap: per-host checkpoints alone
    restore only onto the same topology (parallel/engine.py
    load_local_state_shards); the assembled canonical form restores onto
    ANY mesh — other host counts, shard counts, or a single chip —
    via the elastic restore path. Host-local divergences are normalized:
    measurement/alert-type/tenant interner tables union (state columns,
    values, and counter rows remap), and rebased timestamps shift onto
    one epoch base. Bus offsets do NOT travel (they name per-host bus
    logs); a restored instance replays its retained log from the start —
    at-least-once, the reference's recovery semantics.

    The reference gets topology-independent durability from its
    datastores (SURVEY.md §5 checkpoint/resume); this is the explicit
    TPU-cache equivalent."""
    loads = []
    for path in paths:
        with open(os.path.join(path, "manifest.json"),
                  encoding="utf-8") as fh:
            manifest = json.load(fh)
        with np.load(os.path.join(path, "state.npz")) as data:
            arrays = {key: np.asarray(data[key]) for key in data.files}
        loads.append((manifest, arrays))

    for manifest, _ in loads:
        if manifest.get("layout") != "host-shards":
            raise SiteWhereCheckpointError(
                "assemble_canonical expects per-host shard checkpoints "
                "(layout=host-shards); canonical checkpoints already "
                "restore anywhere")
    n_shards = {m["n_shards"] for m, _ in loads}
    if len(n_shards) != 1:
        raise SiteWhereCheckpointError(
            f"checkpoints disagree on n_shards: {sorted(n_shards)}")
    S = n_shards.pop()
    covered: List[int] = []
    for manifest, _ in loads:
        covered.extend(manifest["shard_ids"])
    if sorted(covered) != list(range(S)):
        raise SiteWhereCheckpointError(
            f"shard coverage {sorted(covered)} != 0..{S - 1} — need "
            f"exactly one checkpoint per host of the full cluster")

    base = min(m["epoch_base_ms"] for m, _ in loads)
    device_tokens = _merge_congruent_tokens(
        [m["interners"]["devices"] for m, _ in loads])
    mm_tokens, mm_remaps = _union_tokens(
        [m["interners"]["measurements"] for m, _ in loads])
    at_tokens, at_remaps = _union_tokens(
        [m["interners"]["alert_types"] for m, _ in loads])
    tenant_tokens, tenant_remaps = _union_tokens(
        [m["interners"].get("tenants") or [None] for m, _ in loads])

    from sitewhere_tpu.pipeline.state_tensors import init_device_state_np

    sample = loads[0][1]["state.last_measurement"]
    L, M = sample.shape[1], sample.shape[2]
    T = loads[0][1]["state.tenant_event_count"].shape[-1]
    D = S * L
    init = init_device_state_np(D, M, T)
    canonical = {f.name: np.array(getattr(init, f.name))
                 for f in dataclasses.fields(DeviceStateTensors)}
    overflow_parts: List[Dict[str, np.ndarray]] = []
    pending_alerts: List[Dict] = []

    for host, (manifest, arrays) in enumerate(loads):
        delta = manifest["epoch_base_ms"] - base
        mm_remap, at_remap = mm_remaps[host], at_remaps[host]
        for f in dataclasses.fields(DeviceStateTensors):
            block = np.array(arrays[f"state.{f.name}"])
            if f.name in _TS_FIELDS:
                block = _shift_ts(block, delta)
            if f.name in ("last_measurement", "last_measurement_ts"):
                # slot column = interned measurement index: remap columns
                # host-local -> union (columns past capacity M drop);
                # untouched slots keep init semantics (0 value, NEVER ts)
                remapped = (np.zeros(block.shape, block.dtype)
                            if f.name == "last_measurement"
                            else np.full(block.shape, _NEG, block.dtype))
                for old_col in range(1, min(block.shape[-1],
                                            len(mm_remap))):
                    new_col = mm_remap[old_col]
                    if 0 < new_col < M:
                        remapped[..., new_col] = block[..., old_col]
                block = remapped
            if f.name == "last_alert_type":
                block = np.where(
                    (block > 0) & (block < len(at_remap)),
                    at_remap[np.clip(block, 0, len(at_remap) - 1)],
                    np.where(block > 0, 0, block)).astype(block.dtype)
            if f.name in _TENANT_FIELDS:
                remap = tenant_remaps[host]
                rows = block.sum(0, dtype=block.dtype) \
                    if block.ndim == 2 else block
                for old_row in range(1, min(rows.shape[-1], len(remap))):
                    new_row = remap[old_row]
                    if 0 < new_row < T:
                        canonical[f.name][new_row] += rows[old_row]
                canonical[f.name][0] += rows[0]
                continue
            # global device d lives at (d % S, d // S): shard s's row l is
            # device l*S + s
            for si, shard in enumerate(manifest["shard_ids"]):
                canonical[f.name][shard::S] = block[si]
        part = {key[len("overflow."):]: np.array(val)
                for key, val in arrays.items()
                if key.startswith("overflow.")}
        if part:
            part["ts"] = _shift_ts(part["ts"], delta)

            def _remap_values(col, remap):
                return np.where(
                    col < len(remap),
                    remap[np.clip(col, 0, len(remap) - 1)],
                    0).astype(np.int32)

            part["mm_idx"] = _remap_values(part["mm_idx"], mm_remap)
            part["alert_type_idx"] = _remap_values(part["alert_type_idx"],
                                                   at_remap)
            part["tenant_idx"] = _remap_values(part["tenant_idx"],
                                               tenant_remaps[host])
            overflow_parts.append(part)
        pending_alerts.extend(manifest.get("pending_alerts", []))

    overflow_cols = None
    if overflow_parts:
        overflow_cols = {
            key: np.concatenate([p[key] for p in overflow_parts])
            for key in overflow_parts[0]
        }
    rules: List[Dict] = []
    seen_rules = set()
    for manifest, _ in loads:
        for rule in manifest.get("rules", []):
            if rule.get("token") not in seen_rules:
                seen_rules.add(rule.get("token"))
                rules.append(rule)
    # rule programs union by token with slot/epoch STRIPPED: per-host
    # slot assignment is host-local, so assembled restores re-install
    # fresh (temporal windows restart; the per-host rulestate arrays are
    # intentionally not merged — cross-host slot spaces don't line up)
    rule_programs: List[Dict] = []
    seen_programs = set()
    for manifest, _ in loads:
        for row in manifest.get("rule_programs", []):
            token = (row.get("spec") or {}).get("token")
            if token and token not in seen_programs:
                seen_programs.add(token)
                rule_programs.append({"spec": dict(row["spec"])})
    # anomaly models union the same way (slot/epoch stripped): per-host
    # slot assignment is host-local, so assembled restores re-install
    # fresh and scoring state restarts (modelstate arrays don't merge)
    anomaly_models: List[Dict] = []
    seen_models = set()
    for manifest, _ in loads:
        for row in manifest.get("anomaly_models", []):
            token = (row.get("spec") or {}).get("token")
            if token and token not in seen_models:
                seen_models.add(token)
                anomaly_models.append({"spec": dict(row["spec"])})
    # actuation policies union identically (slot/epoch stripped): the
    # assembled restore re-installs fresh and debounce windows restart
    actuation_policies: List[Dict] = []
    seen_policies = set()
    for manifest, _ in loads:
        for row in manifest.get("actuation_policies", []):
            token = (row.get("spec") or {}).get("token")
            if token and token not in seen_policies:
                seen_policies.add(token)
                actuation_policies.append({"spec": dict(row["spec"])})
    out_manifest: Dict[str, Any] = {
        "epoch_base_ms": base,
        "interners": {"devices": device_tokens,
                      "measurements": mm_tokens,
                      "alert_types": at_tokens,
                      "tenants": tenant_tokens},
        "offsets": {},
        "pending_alerts": pending_alerts,
        "rules": rules,
        "rule_programs": rule_programs,
        "anomaly_models": anomaly_models,
        "actuation_policies": actuation_policies,
        "assembled_from": [os.path.basename(p) for p in paths],
    }
    return out_manifest, canonical, overflow_cols


def write_assembled(paths: List[str], out_dir: str) -> str:
    """assemble_canonical + write the result as a regular canonical
    checkpoint directory under `out_dir` (ready for restore_on_boot /
    PipelineCheckpointer.restore on ANY topology). Returns the path."""
    manifest, canonical, overflow_cols = assemble_canonical(paths)
    os.makedirs(out_dir, exist_ok=True)
    arrays = {f"state.{name}": arr for name, arr in canonical.items()}
    if overflow_cols:
        arrays.update({f"overflow.{name}": arr
                       for name, arr in overflow_cols.items()})
    return _write_checkpoint_dir(out_dir, arrays, manifest)


class PipelineCheckpointer:
    """Snapshot/restore a PipelineEngine's recoverable state."""

    def __init__(self, directory: str, keep: int = 3):
        self.directory = directory
        self.keep = keep
        # save() has multiple callers (periodic thread + REST POST):
        # racing saves would compute the same sequence and interleave
        # writes into one tmp dir, promoting a mixed-snapshot checkpoint
        import threading

        self._save_lock = threading.Lock()
        # recovery epoch of the process that owns this checkpointer;
        # stamped into every manifest so a later incarnation (or a
        # takeover successor) can fence a zombie writer's stale saves
        self.recovery_epoch = 0
        self.last_restore_epoch: Optional[int] = None
        os.makedirs(directory, exist_ok=True)

    # -- save --------------------------------------------------------------
    def save(self, engine, bus=None,
             consumer_groups: Optional[List] = None,
             extra_manifest: Optional[Dict] = None) -> str:
        """Write a new checkpoint; returns its path.

        `consumer_groups` are bus ConsumerGroup objects whose committed
        offsets should be captured (the replay cursor).
        `extra_manifest` merges additional instance-level payloads into
        the manifest (scripts, scripted-rule installs — the
        InstanceCheckpointManager adds them).

        Offsets are captured BEFORE the state arrays: a commit racing the
        snapshot then yields offsets <= state, i.e. at worst a duplicate
        replay (at-least-once, like the reference's Kafka semantics);
        offsets ahead of state would silently LOSE events."""
        with self._save_lock:
            return self._save_locked(engine, consumer_groups,
                                     extra_manifest)

    def _save_locked(self, engine, consumer_groups: Optional[List],
                     extra_manifest: Optional[Dict] = None) -> str:
        self._fence_stale_save()
        captured_offsets = {
            f"{g.topic.name}@{g.group_id}": list(g.committed)
            for g in consumer_groups or []
        }
        multihost = bool(getattr(engine, "is_multiprocess", False))
        layout: Dict[str, Any] = {}
        if multihost:
            # Per-HOST shard layout (gang-restart recovery): draining the
            # overflow would run a host-local number of collective steps
            # (lockstep violation), so the parked overflow batch is saved
            # VERBATIM instead — its bus offsets may already be committed,
            # and restoring it preserves the offsets<=state invariant.
            # Restores onto the SAME cluster topology only.
            shard_ids, blocks = engine.local_state_shards()
            arrays = {f"state.{name}": np.asarray(block)
                      for name, block in blocks.items()}
            rule_blocks = (engine.local_rule_state_blocks()
                           if hasattr(engine, "local_rule_state_blocks")
                           else None)
            if rule_blocks:
                arrays.update({f"rulestate.{name}": np.asarray(block)
                               for name, block in rule_blocks.items()})
            model_blocks = (engine.local_model_state_blocks()
                            if hasattr(engine, "local_model_state_blocks")
                            else None)
            if model_blocks:
                arrays.update({f"modelstate.{name}": np.asarray(block)
                               for name, block in model_blocks.items()})
            act_blocks = (engine.local_actuation_state_blocks()
                          if hasattr(engine, "local_actuation_state_blocks")
                          else None)
            if act_blocks:
                arrays.update({f"actstate.{name}": np.asarray(block)
                               for name, block in act_blocks.items()})
            overflow = engine.pending_overflow_batch()
            if overflow is not None:
                for f in dataclasses.fields(overflow):
                    arrays[f"overflow.{f.name}"] = np.asarray(
                        getattr(overflow, f.name))
            layout = {"layout": "host-shards", "shard_ids": list(shard_ids),
                      "n_shards": engine.n_shards,
                      "process_id": jax.process_index()}
        else:
            # parked shard-overflow rows must fold into state before the
            # snapshot: their bus offsets may already be committed, and a
            # snapshot without them would break the offsets<=state
            # invariant
            drain = getattr(engine, "drain_pending", None)
            if drain is not None:
                drain()
            # canonical flat layout: topology-independent, so a checkpoint
            # taken on an N-shard mesh restores onto any other mesh size
            state = engine.canonical_state()
            arrays = {
                f"state.{f.name}": np.asarray(getattr(state, f.name))
                for f in dataclasses.fields(state)
            }
            # rule-program temporal state travels with the device state
            # (AFTER the drain above — drained rows advance it) so a
            # restart resumes debounce/for-duration/hysteresis windows
            # mid-flight, re-joined to its programs by the manifest's
            # pinned slot/epoch assignment
            rule_state = (engine.canonical_rule_state()
                          if hasattr(engine, "canonical_rule_state")
                          else None)
            if rule_state is not None:
                arrays.update({
                    f"rulestate.{f.name}": np.asarray(
                        getattr(rule_state, f.name))
                    for f in dataclasses.fields(rule_state)})
            # anomaly-model scoring state travels the same way: feature
            # accumulators + rising-edge latches resume mid-flight,
            # re-joined to their models by the manifest's slot/epoch pins
            model_state = (engine.canonical_model_state()
                           if hasattr(engine, "canonical_model_state")
                           else None)
            if model_state is not None:
                arrays.update({
                    f"modelstate.{f.name}": np.asarray(
                        getattr(model_state, f.name))
                    for f in dataclasses.fields(model_state)})
            # per-(device, policy) debounce state rides the same way: a
            # restart must not re-fire a command inside a policy's
            # debounce window, re-joined by the manifest's slot/epoch pins
            act_state = (engine.canonical_actuation_state()
                         if hasattr(engine, "canonical_actuation_state")
                         else None)
            if act_state is not None:
                arrays.update({
                    f"actstate.{f.name}": np.asarray(
                        getattr(act_state, f.name))
                    for f in dataclasses.fields(act_state)})
        packer = engine.packer
        manifest: Dict[str, Any] = {
            "epoch_base_ms": packer.epoch_base_ms,
            "interners": {
                "devices": packer.devices.snapshot(),
                "measurements": packer.measurements.snapshot(),
                "alert_types": packer.alert_types.snapshot(),
                # tenant table gives tenant_* counter rows meaning when a
                # checkpoint moves across hosts/topologies (assemble)
                "tenants": engine.registry.tenants.snapshot(),
            },
            "offsets": captured_offsets,
            # alerts stashed by the pre-snapshot drain (and any earlier
            # internal drain steps) travel WITH the checkpoint: the drained
            # events' offsets are committed, so replay will not re-fire
            # them — without this, a crash before the next
            # materialize_alerts would silently lose them. Not cleared
            # here (a live process still delivers them; a restore may
            # duplicate — at-least-once, like everything else).
            "pending_alerts": [_asdict(a) for a in
                               getattr(engine, "_pending_alerts", [])],
            # rules are config, but REST-added ones exist only in the
            # engine — a restart must not silently drop the operator's
            # alerting (pipeline/engine.py rule management surface)
            "rules": self._rules_manifest(engine),
            # rule programs with their runtime (slot, epoch) assignment:
            # restore re-pins temporal state to its program mid-window
            "rule_programs": (engine.rule_program_manifest()
                              if hasattr(engine, "rule_program_manifest")
                              else []),
            # anomaly models with their runtime (slot, epoch) assignment:
            # restore re-pins scoring state to its model mid-flight
            "anomaly_models": (engine.anomaly_model_manifest()
                               if hasattr(engine, "anomaly_model_manifest")
                               else []),
            # actuation policies with their (slot, epoch) assignment:
            # restore re-pins debounce state to its policy mid-window
            "actuation_policies": (
                engine.actuation_policy_manifest()
                if hasattr(engine, "actuation_policy_manifest") else []),
            # fencing stamp: a successor that took over this shard group
            # minted a higher epoch; its checkpoints outrank ours and
            # _fence_stale_save refuses to let a zombie clobber them
            "recovery_epoch": int(self.recovery_epoch),
            **(extra_manifest or {}),
            **layout,
        }
        final = _write_checkpoint_dir(self.directory, arrays, manifest)
        self._gc()
        return final

    def _fence_stale_save(self) -> None:
        """Refuse to write a checkpoint below the newest on-disk epoch.

        After a takeover the successor restores from this directory and
        saves with a higher recovery_epoch; a paused-then-resumed old
        owner (zombie) that still holds a checkpointer must not promote
        a snapshot of pre-takeover state over the successor's."""
        latest = self.latest()
        if latest is None:
            return
        try:
            with open(os.path.join(latest, "manifest.json"),
                      encoding="utf-8") as fh:
                disk_epoch = int(json.load(fh).get("recovery_epoch", 0))
        except (OSError, ValueError):
            return  # unreadable manifest: latest() already quarantines
        if disk_epoch > int(self.recovery_epoch):
            from sitewhere_tpu.runtime.metrics import GLOBAL_METRICS

            GLOBAL_METRICS.counter("fencing.rejected").inc()
            raise SiteWhereCheckpointError(
                f"checkpoint save fenced: on-disk epoch {disk_epoch} > "
                f"writer epoch {self.recovery_epoch} (stale owner)")

    def _gc(self) -> None:
        ckpts = sorted(n for n in os.listdir(self.directory)
                       if n.startswith("ckpt-") and not n.endswith(".tmp")
                       and not n.endswith(".quarantine"))
        for stale in ckpts[:-self.keep]:
            shutil.rmtree(os.path.join(self.directory, stale),
                          ignore_errors=True)

    # -- restore -----------------------------------------------------------
    def _quarantine(self, path: str) -> None:
        """Move a checkpoint that failed verification aside (never delete
        forensic evidence) so the next latest() scan skips it."""
        import logging

        dest = path + ".quarantine"
        try:
            os.replace(path, dest)
        except OSError:
            dest = path  # couldn't move: the verify gate still skips it
        logging.getLogger("sitewhere.checkpoint").error(
            "checkpoint %s failed digest verification; quarantined at %s",
            path, dest)

    def latest(self) -> Optional[str]:
        """Newest checkpoint that passes digest verification. Corrupt
        ones (torn writes that survived the rename) are quarantined and
        the scan falls back to the previous good checkpoint — restore
        degrades to older state instead of crashing. Pre-digest legacy
        checkpoints (no digest.json) are trusted as before."""
        from sitewhere_tpu.persist.atomic import verify_digest_manifest

        ckpts = sorted(n for n in os.listdir(self.directory)
                       if n.startswith("ckpt-") and not n.endswith(".tmp")
                       and not n.endswith(".quarantine"))
        for name in reversed(ckpts):
            path = os.path.join(self.directory, name)
            if verify_digest_manifest(path) is False:
                self._quarantine(path)
                continue
            return path
        return None

    def restore(self, engine, path: Optional[str] = None) -> Dict[str, List[int]]:
        """Load a checkpoint into the engine; returns the saved bus offsets
        keyed `topic@group` so the caller can seed replay consumers."""
        explicit = path is not None
        path = path or self.latest()
        if path is None:
            return {}
        try:
            with open(os.path.join(path, "manifest.json"),
                      encoding="utf-8") as fh:
                manifest = json.load(fh)
            with np.load(os.path.join(path, "state.npz")) as data:
                kwargs = {
                    f.name: np.asarray(data[f"state.{f.name}"])
                    for f in dataclasses.fields(DeviceStateTensors)
                }
                overflow_cols = {
                    key[len("overflow."):]: np.asarray(data[key])
                    for key in data.files if key.startswith("overflow.")
                }
                rule_state_cols = {
                    key[len("rulestate."):]: np.asarray(data[key])
                    for key in data.files if key.startswith("rulestate.")
                }
                model_state_cols = {
                    key[len("modelstate."):]: np.asarray(data[key])
                    for key in data.files if key.startswith("modelstate.")
                }
                act_state_cols = {
                    key[len("actstate."):]: np.asarray(data[key])
                    for key in data.files if key.startswith("actstate.")
                }
        except (OSError, ValueError, KeyError) as err:
            # a pre-digest checkpoint torn some other way (np.load raises
            # ValueError/BadZipFile subclasses): same treatment as a
            # digest mismatch — quarantine, fall back to last-good.
            # Explicit paths propagate: the operator asked for THAT one.
            if explicit:
                raise SiteWhereCheckpointError(
                    f"checkpoint {path} is unreadable: {err}") from err
            self._quarantine(path)
            return self.restore(engine)
        # pre-slab checkpoints saved the state quads as separate columns;
        # fuse them into the current slab layout in place so old
        # checkpoints restore transparently (no operator migration step).
        # Works uniformly for canonical [D, P, S] arrays and host-shard
        # stacked blocks: the fuse is a last-axis concat of bit planes.
        rule_state_cols = _migrate_state_cols(
            rule_state_cols, flag_field="root_prev")
        model_state_cols = _migrate_state_cols(
            model_state_cols, flag_field="score_prev")
        packer = engine.packer
        # rule programs re-install FIRST (they only mutate host lists):
        # the restored rule state's per-slot generations must meet their
        # matching table epochs on the next compile, or the stale-slot
        # check would wipe the mid-window temporal state it pins
        self._restore_rule_programs(engine, manifest.get("rule_programs"))
        # anomaly models likewise re-install before their state loads so
        # the restored row generations meet matching table epochs
        self._restore_anomaly_models(engine, manifest.get("anomaly_models"))
        # actuation policies too: their debounce rows must meet the same
        # slot/epoch pins or the stale check would re-open closed windows
        self._restore_actuation_policies(engine,
                                         manifest.get("actuation_policies"))
        if manifest.get("layout") == "host-shards":
            # per-host gang-restart checkpoint: same-topology restore of
            # this host's shard blocks + the verbatim overflow batch
            engine.load_local_state_shards(manifest["shard_ids"], kwargs)
            if rule_state_cols and hasattr(engine,
                                           "load_local_rule_state_blocks"):
                engine.load_local_rule_state_blocks(rule_state_cols)
            if model_state_cols and hasattr(
                    engine, "load_local_model_state_blocks"):
                engine.load_local_model_state_blocks(model_state_cols)
            if act_state_cols and hasattr(
                    engine, "load_local_actuation_state_blocks"):
                engine.load_local_actuation_state_blocks(act_state_cols)
            if overflow_cols:
                from sitewhere_tpu.ops.pack import EventBatch

                engine.set_pending_overflow_batch(EventBatch(**overflow_cols))
            packer.devices.restore(manifest["interners"]["devices"])
        else:
            # canonical (topology-independent) restore. The device interner
            # may use a DIFFERENT shard-congruent layout than the saving
            # engine (elastic 4-shard -> 8-shard/single-chip restore):
            # re-intern congruently and permute the device-major rows.
            perm = self._restore_devices_elastic(
                engine, manifest["interners"]["devices"])
            if perm is not None:
                kwargs = _permute_device_rows(kwargs, perm)
                if rule_state_cols:
                    rule_state_cols = _permute_rule_state_rows(
                        rule_state_cols, perm)
                if model_state_cols:
                    model_state_cols = _permute_model_state_rows(
                        model_state_cols, perm)
                if act_state_cols:
                    act_state_cols = _permute_actuation_state_rows(
                        act_state_cols, perm)
                if overflow_cols:
                    valid_rows = overflow_cols["device_idx"] < len(perm)
                    overflow_cols["device_idx"] = np.where(
                        valid_rows,
                        perm[np.clip(overflow_cols["device_idx"], 0,
                                     len(perm) - 1)],
                        0).astype(np.int32)
            engine.load_canonical_state(DeviceStateTensors(**kwargs))
            if rule_state_cols and hasattr(engine,
                                           "load_canonical_rule_state"):
                from sitewhere_tpu.ops.stateful import RuleStateTensors

                try:
                    engine.load_canonical_rule_state(
                        RuleStateTensors(**rule_state_cols))
                except (TypeError, ValueError):
                    import logging

                    logging.getLogger("sitewhere.checkpoint").exception(
                        "rule-program state did not restore (bucket "
                        "mismatch); temporal windows restart fresh")
            if model_state_cols and hasattr(engine,
                                            "load_canonical_model_state"):
                from sitewhere_tpu.ops.anomaly import ModelStateTensors

                try:
                    engine.load_canonical_model_state(
                        ModelStateTensors(**model_state_cols))
                except (TypeError, ValueError):
                    import logging

                    logging.getLogger("sitewhere.checkpoint").exception(
                        "anomaly-model state did not restore (bucket "
                        "mismatch); feature windows restart fresh")
            if act_state_cols and hasattr(
                    engine, "load_canonical_actuation_state"):
                from sitewhere_tpu.ops.actuate import ActuationStateTensors

                try:
                    engine.load_canonical_actuation_state(
                        ActuationStateTensors(**act_state_cols))
                except (TypeError, ValueError):
                    import logging

                    logging.getLogger("sitewhere.checkpoint").exception(
                        "actuation state did not restore (bucket "
                        "mismatch); debounce windows restart fresh")
        packer.epoch_base_ms = manifest["epoch_base_ms"]
        packer.measurements.restore(manifest["interners"]["measurements"])
        packer.alert_types.restore(manifest["interners"]["alert_types"])
        self._remap_tenant_rows(engine,
                                manifest["interners"].get("tenants"))
        pending = manifest.get("pending_alerts", [])
        if pending and hasattr(engine, "_pending_alerts"):
            engine._pending_alerts.extend(
                _alert_from_dict(d) for d in pending)
        self._restore_rules(engine, manifest.get("rules", []))
        if overflow_cols and manifest.get("layout") != "host-shards":
            # fold LAST: the overflow's indices/timestamps only mean
            # something under the restored interners + epoch base, and
            # its events must fire the restored rules, not an empty set
            _install_overflow(engine, overflow_cols)
        self.last_restore_epoch = int(manifest.get("recovery_epoch", 0))
        return manifest.get("offsets", {})

    @staticmethod
    def _restore_devices_elastic(engine, tokens) -> Optional[np.ndarray]:
        """Restore the device interner; when the snapshot's shard-congruent
        layout differs from this engine's (different shard count, or a
        sequential pre-congruent snapshot), re-intern every token into THIS
        layout and return old-index -> new-index (None when the snapshot
        loaded verbatim)."""
        devices = engine.packer.devices
        try:
            devices.restore(tokens)
            return None
        except ValueError:
            pass
        devices.restore([None])  # reset, then allocate congruently
        perm = np.zeros(max(len(tokens), 1), np.int32)
        for i, token in enumerate(tokens):
            if i and token is not None:
                perm[i] = devices.intern(token)
        # the registry mirror's rows were built for the pre-reset index
        # assignment: re-mirror onto the new one
        rebuild = getattr(engine.registry, "rebuild", None)
        if rebuild is not None:
            rebuild()
        return perm

    @staticmethod
    def _remap_tenant_rows(engine, tenant_tokens) -> None:
        """Move tenant_* counter rows from the checkpoint's tenant table to
        the LIVE engine's (tenant interning order differs across
        hosts/boots). Old checkpoints without a tenant table keep rows
        as-is."""
        if not tenant_tokens:
            return
        live = engine.registry.tenants
        mapping = []
        for old_idx, token in enumerate(tenant_tokens):
            if old_idx == 0 or token is None:
                continue
            mapping.append((old_idx, live.intern(token)))
        if all(old == new for old, new in mapping):
            return
        with engine._state_lock:
            state = engine._state
            for name in ("tenant_event_count", "tenant_alert_count"):
                ref = getattr(state, name)
                rows = np.asarray(ref)
                out = np.zeros_like(rows)
                out[..., 0] = rows[..., 0]  # unknown-tenant bucket stays
                for old_idx, new_idx in mapping:
                    # sharded layout is [S, T]; flat is [T] — index the
                    # trailing axis either way
                    if old_idx < rows.shape[-1] and new_idx < out.shape[-1]:
                        out[..., new_idx] += rows[..., old_idx]
                state = state.replace(
                    **{name: jax.device_put(out, ref.sharding)})
            engine._state = state

    @staticmethod
    def _rules_manifest(engine) -> List[Dict]:
        from sitewhere_tpu.pipeline.engine import rule_to_dict

        return [rule_to_dict(kind, rule)
                for kind, rule_list in engine.list_rules().items()
                for rule in rule_list]

    @staticmethod
    def _restore_rules(engine, rules: List[Dict]) -> None:
        from sitewhere_tpu.pipeline.engine import rule_from_dict

        for data in rules:
            kind, rule = rule_from_dict(dict(data))
            engine.upsert_rule(kind, rule)

    @staticmethod
    def _restore_rule_programs(engine, rows: Optional[List[Dict]]) -> None:
        """Re-install checkpointed rule programs, pinning each to its
        saved (slot, epoch) so the restored RuleStateTensors generations
        line up and temporal operators resume mid-window. A program the
        engine's static buckets cannot hold logs and skips (its slot's
        state resets) rather than failing the whole restore."""
        if not rows or not hasattr(engine, "upsert_rule_program"):
            return
        for row in rows:
            try:
                engine.upsert_rule_program(dict(row.get("spec") or {}),
                                           slot=row.get("slot"),
                                           epoch=row.get("epoch"))
            except Exception:
                import logging

                logging.getLogger("sitewhere.checkpoint").exception(
                    "checkpointed rule program %r did not restore",
                    (row.get("spec") or {}).get("token"))

    @staticmethod
    def _restore_anomaly_models(engine, rows: Optional[List[Dict]]) -> None:
        """Re-install checkpointed anomaly models, pinning each to its
        saved (slot, epoch) so the restored ModelStateTensors generations
        line up and feature accumulators / rising-edge latches resume
        mid-flight. A model the engine's static buckets cannot hold logs
        and skips (its slot's state resets) rather than failing the whole
        restore."""
        if not rows or not hasattr(engine, "upsert_anomaly_model"):
            return
        for row in rows:
            try:
                engine.upsert_anomaly_model(dict(row.get("spec") or {}),
                                            slot=row.get("slot"),
                                            epoch=row.get("epoch"))
            except Exception:
                import logging

                logging.getLogger("sitewhere.checkpoint").exception(
                    "checkpointed anomaly model %r did not restore",
                    (row.get("spec") or {}).get("token"))

    @staticmethod
    def _restore_actuation_policies(engine,
                                    rows: Optional[List[Dict]]) -> None:
        """Re-install checkpointed actuation policies, pinning each to
        its saved (slot, epoch) so the restored debounce rows line up and
        mid-window suppression resumes. A policy the engine's static
        buckets cannot hold logs and skips (its slot's state resets)
        rather than failing the whole restore."""
        if not rows or not hasattr(engine, "upsert_actuation_policy"):
            return
        for row in rows:
            try:
                engine.upsert_actuation_policy(dict(row.get("spec") or {}),
                                               slot=row.get("slot"),
                                               epoch=row.get("epoch"))
            except Exception:
                import logging

                logging.getLogger("sitewhere.checkpoint").exception(
                    "checkpointed actuation policy %r did not restore",
                    (row.get("spec") or {}).get("token"))

    # -- recovery ----------------------------------------------------------
    def recover(self, engine, bus, topic: str, group_id: str,
                replay_handler, max_records: int = 4096) -> int:
        """Restore the latest checkpoint, then replay every bus record past
        the checkpointed offsets through `replay_handler(records)` until
        caught up. Returns the number of replayed records.

        This is the crash-recovery contract of SURVEY.md §5: HBM state is a
        cache; checkpoint + at-least-once replay rebuilds it."""
        offsets = self.restore(engine)
        consumer = bus.consumer(topic, group_id)
        saved = offsets.get(f"{topic}@{group_id}")
        if saved is None:
            # Checkpoint carries no cursor for this group: the only safe
            # at-least-once choice is a full replay of the retained log —
            # the bus's own committed offsets may be AHEAD of the
            # checkpointed state (committed after save), which would lose
            # those events.
            consumer.seek_to_beginning()
        else:
            n = len(consumer.topic.partitions)
            consumer.committed = (list(saved) + [0] * n)[:n]
            consumer.seek_to_committed()
        replayed = 0
        while True:
            batch = consumer.poll(max_records)
            if not batch:
                break
            replay_handler(batch)
            bus.commit(consumer)
            replayed += len(batch)
        return replayed


class InstanceCheckpointManager:
    """Wires PipelineCheckpointer into a running SiteWhereInstance: restore
    the latest checkpoint at boot (rewinding the inbound consumer groups to
    the checkpointed cursors so replay closes the gap), then save
    periodically and on demand (REST POST /api/instance/checkpoint).

    Lifecycle-shaped (start/stop) so SiteWhereInstance can nest it between
    the pipeline engine (whose state it restores — must already be started)
    and the tenant engine manager (whose consumers must not start polling
    until the cursors are rewound)."""

    def __init__(self, instance, directory: str,
                 interval_s: Optional[float] = None):
        from sitewhere_tpu.runtime.lifecycle import LifecycleComponent

        self.instance = instance
        self.checkpointer = PipelineCheckpointer(directory)
        self.interval_s = interval_s
        self.last_restore_offsets: Dict[str, List[int]] = {}
        self._stop = None
        self._thread = None

        outer = self

        class _Component(LifecycleComponent):
            def __init__(self):
                super().__init__("checkpoint-manager")

            def on_start(self, monitor) -> None:
                outer._on_start()

            def on_stop(self, monitor) -> None:
                outer._on_stop()

        self.component = _Component()

    # -- save --------------------------------------------------------------
    def _inbound_groups(self):
        """Consumer groups feeding the pipeline: one per KNOWN tenant, not
        per running engine — a tenant whose engine is admin-stopped (or a
        save racing shutdown after engines cleared) still has a persisted
        cursor that must be captured, or the next boot restore would zero
        it and double-replay the retained log into already-complete
        state. bus.consumer() loads the persisted committed offsets even
        when no engine is consuming."""
        groups = []
        for tenant in self.instance.tenant_management.tenants.all():
            topic = self.instance.naming.event_source_decoded_events(
                tenant.token)
            groups.append(self.instance.bus.consumer(
                topic, f"inbound-processing-{tenant.token}"))
        if getattr(self.instance, "cluster_hooks", None) is not None:
            # the forwarded foreign-rows consumer also advances device
            # state; capture its cursor so restore replays only the gap
            from sitewhere_tpu.parallel.cluster import (
                FOREIGN_ROWS_GROUP, foreign_rows_topic)

            groups.append(self.instance.bus.consumer(
                foreign_rows_topic(self.instance.naming),
                FOREIGN_ROWS_GROUP))
        return groups

    def save(self) -> str:
        """Checkpoint now (offsets captured before state; see
        PipelineCheckpointer.save). Returns the checkpoint path."""
        engine = self.instance.pipeline_engine
        if engine is None:
            raise SiteWhereCheckpointError("instance has no pipeline engine")
        # instance-level payloads (VERDICT r4 item 3): user scripts +
        # scripted-rule installs travel with the checkpoint so an
        # assembled/cross-topology restore carries the scripting state,
        # not just the tensors. Provisioning (tenants/users/authorities +
        # tombstones) travels too: a gang restart rebuilds the same
        # tenant set from durable state, not boot templates
        # (multitenant/replication.py).
        from sitewhere_tpu.multitenant.replication import (
            export_provisioning)

        extra = {
            "scripts": self.instance.script_manager.export_state(),
            "scripted_rules": self.instance.scripted_rules.export_state(),
            # the durable LWW store state (tenant scoping + stamps) rides
            # alongside the engine's slot/epoch manifest ("rule_programs")
            "rule_program_installs":
                self.instance.rule_programs.export_state(),
            "anomaly_model_installs":
                self.instance.anomaly_models.export_state(),
            "actuation_policy_installs":
                self.instance.actuation_policies.export_state(),
            "provisioning": export_provisioning(self.instance),
            # exactly-once-effects replay (runtime/recovery.py): the
            # per-tenant eventlog high-watermarks are the replay cursor's
            # twin — on restore, rows durable ABOVE these marks are the
            # budget of inbound records whose effects must not re-fire
            "eventlog_watermarks": self._eventlog_watermarks(),
            # recent-duplicate LRU windows ride along so a restart does
            # not forget what the store lookup is too slow to re-learn
            "dedup_windows": self._dedup_windows(),
        }
        return self.checkpointer.save(
            engine, consumer_groups=self._inbound_groups(),
            extra_manifest=extra)

    # bounded per-source checkpoint payload: newest ids win (LRU order)
    DEDUP_WINDOW_LIMIT = 4096

    def _dedup_windows(self) -> Dict[str, Dict[str, List[str]]]:
        """{tenant: {source_id: [alternate ids, oldest first]}} across
        every running tenant engine's event sources."""
        windows: Dict[str, Dict[str, List[str]]] = {}
        manager = getattr(self.instance, "engine_manager", None)
        if manager is None:
            return windows
        with manager._lock:
            engines = dict(manager.engines)
        for token, engine in engines.items():
            sources = getattr(getattr(engine, "event_sources", None),
                              "sources", [])
            per_source = {}
            for source in sources:
                export = getattr(getattr(source, "deduplicator", None),
                                 "export_window", None)
                if export is None:
                    continue
                ids = export(limit=self.DEDUP_WINDOW_LIMIT)
                if ids:
                    per_source[source.source_id] = ids
            if per_source:
                windows[token] = per_source
        return windows

    def _eventlog_watermarks(self) -> Dict[str, Dict[str, int]]:
        """Per-tenant `(id_prefix -> max id_seq)` maxima, merged across
        the shared default log and any dedicated tenant stores that
        support watermarks (widerow stores don't; their tenants simply
        skip the replay barrier and fall back to at-least-once)."""
        marks: Dict[str, Dict[str, int]] = {}

        def _merge(per_tenant):
            for tenant, m in (per_tenant or {}).items():
                merged = marks.setdefault(tenant, {})
                for prefix, seq in m.items():
                    if int(seq) > merged.get(prefix, -1):
                        merged[prefix] = int(seq)

        # Seal the buffered tail first: rows at-or-below the watermark are
        # never re-offered by the bus once the offsets commit, so their
        # durability cannot ride at-least-once replay the way the un-sealed
        # tail normally does — the checkpoint boundary must be on disk.
        log = getattr(self.instance, "event_log", None)
        if hasattr(log, "flush"):
            log.flush()
        if hasattr(log, "sequence_watermarks"):
            _merge(log.sequence_watermarks())
        datastores = getattr(self.instance, "datastores", None)
        for store in getattr(datastores, "_dedicated", {}).values():
            if hasattr(store, "flush"):
                store.flush()
            if hasattr(store, "sequence_watermarks"):
                _merge(store.sequence_watermarks())
        return marks

    def list_checkpoints(self) -> List[str]:
        return sorted(
            name for name in os.listdir(self.checkpointer.directory)
            if name.startswith("ckpt-") and not name.endswith(".tmp")
            and not name.endswith(".quarantine"))

    # -- boot restore ------------------------------------------------------
    def restore_on_boot(self) -> bool:
        """Load the latest checkpoint into the engine and rewind every
        checkpointed consumer group to its saved cursor. Runs before the
        tenant engines' consumers start polling; the bus's own committed
        offsets may be AHEAD of the checkpoint (commits raced the save or
        happened after it), and replaying from the older checkpoint cursor
        is what makes the restored state catch up (at-least-once)."""
        engine = self.instance.pipeline_engine
        path = self.checkpointer.latest()
        if engine is None or path is None:
            return False
        self._restore_scripting(path)
        offsets = self.checkpointer.restore(engine)
        self.last_restore_offsets = offsets
        for key, saved in offsets.items():
            topic, _, group = key.rpartition("@")
            consumer = self.instance.bus.consumer(topic, group)
            n = len(consumer.topic.partitions)
            consumer.committed = (list(saved) + [0] * n)[:n]
            consumer.seek_to_committed()
        # Inbound groups ABSENT from the manifest (tenant created after the
        # checkpoint, or a save that raced engine boot): the restored state
        # has none of their events, but the bus's own persisted committed
        # offsets may be past them — the only at-least-once choice is a
        # full replay of the retained log for those groups (mirrors
        # PipelineCheckpointer.recover's no-cursor rule).
        for tenant in self.instance.tenant_management.tenants.all():
            topic = self.instance.naming.event_source_decoded_events(
                tenant.token)
            group = f"inbound-processing-{tenant.token}"
            if f"{topic}@{group}" in offsets:
                continue
            consumer = self.instance.bus.consumer(topic, group)
            consumer.committed = [0] * len(consumer.topic.partitions)
            consumer.seek_to_committed()
        self._arm_replay_guards(path)
        return True

    def _arm_replay_guards(self, path: str) -> None:
        """Exactly-once effects for the replay that follows this restore:
        arm the global replay barrier with per-tenant budgets (durable
        rows ABOVE the checkpointed watermarks == the replay overlap)
        and stage the checkpointed dedup windows for the event sources
        that boot later (runtime/recovery.py)."""
        from sitewhere_tpu.runtime.recovery import (
            GLOBAL_REPLAY_BARRIER, stash_dedup_seeds)

        try:
            with open(os.path.join(path, "manifest.json"),
                      encoding="utf-8") as fh:
                manifest = json.load(fh)
        except (OSError, ValueError):
            return
        stash_dedup_seeds(manifest.get("dedup_windows") or {})
        marks = manifest.get("eventlog_watermarks") or {}
        # tenants with durable rows but NO checkpointed watermark (created
        # after the save) replay their whole retained log: every durable
        # row of theirs is overlap too, so enumerate the live log as well
        default_log = self.instance.event_log
        tenants = set(marks)
        if hasattr(default_log, "sequence_watermarks"):
            tenants |= set(default_log.sequence_watermarks())
        budgets: Dict[str, int] = {}
        datastores = getattr(self.instance, "datastores", None)
        for tenant in tenants:
            log = (datastores.event_log_for(tenant)
                   if datastores is not None else default_log)
            if hasattr(log, "rows_above"):
                budgets[tenant] = int(
                    log.rows_above(tenant, marks.get(tenant, {})))
        GLOBAL_REPLAY_BARRIER.arm(budgets, watermarks=marks)

    def _restore_scripting(self, path: str) -> None:
        """Merge checkpointed instance-level payloads — provisioning
        (tenants/users/authorities), scripts, scripted-rule installs —
        into the local stores (last-writer-wins: whatever the local
        durable stores already hold stays if newer). Runs before tenant
        engines exist — the restored tenant set decides which engines
        boot, and installs take effect when each engine boots and reads
        the store."""
        try:
            with open(os.path.join(path, "manifest.json"),
                      encoding="utf-8") as fh:
                manifest = json.load(fh)
        except (OSError, ValueError):
            return
        from sitewhere_tpu.multitenant.replication import apply_provisioning

        try:
            # BEFORE the engine manager boots: the restored tenant set —
            # not the boot templates — decides which engines come up
            apply_provisioning(self.instance, manifest.get("provisioning"))
        except Exception:
            import logging

            logging.getLogger("sitewhere.checkpoint").exception(
                "checkpointed provisioning state did not restore")
        scripts = self.instance.script_manager
        for state in manifest.get("scripts", []):
            try:
                scripts.apply_replicated(state)
            except Exception:
                import logging

                logging.getLogger("sitewhere.checkpoint").exception(
                    "checkpointed script %s/%s did not restore",
                    state.get("scope"), state.get("scriptId"))
        for row in (manifest.get("scripted_rules") or {}).get(
                "installs", []):
            self.instance.scripted_rules.apply_add(
                row["tenant"], row["token"], row["script"],
                int(row.get("stamp", 0)))
        for row in (manifest.get("rule_program_installs") or {}).get(
                "installs", []):
            try:
                self.instance.apply_replicated_rule_program(
                    "add", row["tenant"], row["token"],
                    {"spec": row["spec"],
                     "stamp": int(row.get("stamp", 0))})
            except Exception:
                import logging

                logging.getLogger("sitewhere.checkpoint").exception(
                    "checkpointed rule program %s/%s did not restore",
                    row.get("tenant"), row.get("token"))
        for row in (manifest.get("anomaly_model_installs") or {}).get(
                "installs", []):
            try:
                self.instance.apply_replicated_anomaly_model(
                    "add", row["tenant"], row["token"],
                    {"spec": row["spec"],
                     "stamp": int(row.get("stamp", 0))})
            except Exception:
                import logging

                logging.getLogger("sitewhere.checkpoint").exception(
                    "checkpointed anomaly model %s/%s did not restore",
                    row.get("tenant"), row.get("token"))
        for row in (manifest.get("actuation_policy_installs") or {}).get(
                "installs", []):
            try:
                self.instance.apply_replicated_actuation_policy(
                    "add", row["tenant"], row["token"],
                    {"spec": row["spec"],
                     "stamp": int(row.get("stamp", 0))})
            except Exception:
                import logging

                logging.getLogger("sitewhere.checkpoint").exception(
                    "checkpointed actuation policy %s/%s did not restore",
                    row.get("tenant"), row.get("token"))

    # -- lifecycle ---------------------------------------------------------
    def _on_start(self) -> None:
        import threading

        self.restore_on_boot()
        if self.interval_s:
            self._stop = threading.Event()

            def _loop():
                while not self._stop.wait(self.interval_s):
                    try:
                        self.save()
                    except Exception:  # noqa: BLE001 - keep checkpointing
                        import logging

                        logging.getLogger("sitewhere.checkpoint").exception(
                            "periodic checkpoint failed")

            self._thread = threading.Thread(target=_loop, daemon=True,
                                            name="checkpoint-loop")
            self._thread.start()

    def _on_stop(self) -> None:
        if self._stop is not None:
            self._stop.set()
        if self._thread is not None:
            self._thread.join(timeout=10)
            self._thread = None


class SiteWhereCheckpointError(RuntimeError):
    pass
