"""Checkpoint/restore of HBM device-state + replay recovery.

Reference: SiteWhere has *no* snapshotting — durable truth lives in the
datastores and Kafka offsets, and a restarted service replays from committed
offsets (SURVEY.md §5; offset commit at DecodedEventsConsumer.java:194-199).
Here the HBM DeviceStateTensors are exactly such a rebuildable cache: the
checkpointer snapshots them (plus the interner tables and packer epoch that
give the indices meaning, plus the bus committed offsets) so recovery is
  restore latest checkpoint -> replay bus records past the saved offsets
instead of a full-history replay.

Format: a directory per checkpoint (`ckpt-<n>/`) holding one .npz of all
state arrays + a JSON manifest; written to a temp dir and atomically renamed,
so a crash mid-write never corrupts the latest checkpoint. (orbax serves the
same role for model training; this state is a handful of dense arrays, so a
direct npz keeps restore dependency-free and fast.)
"""

from __future__ import annotations

import dataclasses
import json
import os
import shutil
from typing import Any, Dict, List, Optional

import jax
import numpy as np

from sitewhere_tpu.model.common import _asdict
from sitewhere_tpu.model.event import DeviceAlert
from sitewhere_tpu.pipeline.state_tensors import DeviceStateTensors


def _alert_from_dict(d: Dict[str, Any]) -> DeviceAlert:
    """Manifest dict -> DeviceAlert (enum fields coerced by annotation)."""
    import enum
    import typing

    hints = typing.get_type_hints(DeviceAlert)
    kwargs: Dict[str, Any] = {}
    for f in dataclasses.fields(DeviceAlert):
        if f.name not in d:
            continue
        val = d[f.name]
        t = hints.get(f.name)
        if (isinstance(t, type) and issubclass(t, enum.Enum)
                and val is not None and not isinstance(val, t)):
            val = t(val)
        kwargs[f.name] = val
    return DeviceAlert(**kwargs)


class PipelineCheckpointer:
    """Snapshot/restore a PipelineEngine's recoverable state."""

    def __init__(self, directory: str, keep: int = 3):
        self.directory = directory
        self.keep = keep
        # save() has multiple callers (periodic thread + REST POST):
        # racing saves would compute the same sequence and interleave
        # writes into one tmp dir, promoting a mixed-snapshot checkpoint
        import threading

        self._save_lock = threading.Lock()
        os.makedirs(directory, exist_ok=True)

    # -- save --------------------------------------------------------------
    def save(self, engine, bus=None,
             consumer_groups: Optional[List] = None) -> str:
        """Write a new checkpoint; returns its path.

        `consumer_groups` are bus ConsumerGroup objects whose committed
        offsets should be captured (the replay cursor).

        Offsets are captured BEFORE the state arrays: a commit racing the
        snapshot then yields offsets <= state, i.e. at worst a duplicate
        replay (at-least-once, like the reference's Kafka semantics);
        offsets ahead of state would silently LOSE events."""
        with self._save_lock:
            return self._save_locked(engine, consumer_groups)

    def _save_locked(self, engine,
                     consumer_groups: Optional[List]) -> str:
        captured_offsets = {
            f"{g.topic.name}@{g.group_id}": list(g.committed)
            for g in consumer_groups or []
        }
        multihost = bool(getattr(engine, "is_multiprocess", False))
        layout: Dict[str, Any] = {}
        if multihost:
            # Per-HOST shard layout (gang-restart recovery): draining the
            # overflow would run a host-local number of collective steps
            # (lockstep violation), so the parked overflow batch is saved
            # VERBATIM instead — its bus offsets may already be committed,
            # and restoring it preserves the offsets<=state invariant.
            # Restores onto the SAME cluster topology only.
            shard_ids, blocks = engine.local_state_shards()
            arrays = {f"state.{name}": np.asarray(block)
                      for name, block in blocks.items()}
            overflow = engine.pending_overflow_batch()
            if overflow is not None:
                for f in dataclasses.fields(overflow):
                    arrays[f"overflow.{f.name}"] = np.asarray(
                        getattr(overflow, f.name))
            layout = {"layout": "host-shards", "shard_ids": list(shard_ids),
                      "n_shards": engine.n_shards,
                      "process_id": jax.process_index()}
        else:
            # parked shard-overflow rows must fold into state before the
            # snapshot: their bus offsets may already be committed, and a
            # snapshot without them would break the offsets<=state
            # invariant
            drain = getattr(engine, "drain_pending", None)
            if drain is not None:
                drain()
            # canonical flat layout: topology-independent, so a checkpoint
            # taken on an N-shard mesh restores onto any other mesh size
            state = engine.canonical_state()
            arrays = {
                f"state.{f.name}": np.asarray(getattr(state, f.name))
                for f in dataclasses.fields(state)
            }
        packer = engine.packer
        manifest: Dict[str, Any] = {
            "epoch_base_ms": packer.epoch_base_ms,
            "interners": {
                "devices": packer.devices.snapshot(),
                "measurements": packer.measurements.snapshot(),
                "alert_types": packer.alert_types.snapshot(),
            },
            "offsets": captured_offsets,
            # alerts stashed by the pre-snapshot drain (and any earlier
            # internal drain steps) travel WITH the checkpoint: the drained
            # events' offsets are committed, so replay will not re-fire
            # them — without this, a crash before the next
            # materialize_alerts would silently lose them. Not cleared
            # here (a live process still delivers them; a restore may
            # duplicate — at-least-once, like everything else).
            "pending_alerts": [_asdict(a) for a in
                               getattr(engine, "_pending_alerts", [])],
            # rules are config, but REST-added ones exist only in the
            # engine — a restart must not silently drop the operator's
            # alerting (pipeline/engine.py rule management surface)
            "rules": self._rules_manifest(engine),
            **layout,
        }
        seq = self._next_seq()
        final = os.path.join(self.directory, f"ckpt-{seq:08d}")
        tmp = final + ".tmp"
        os.makedirs(tmp, exist_ok=True)
        np.savez_compressed(os.path.join(tmp, "state.npz"), **arrays)
        with open(os.path.join(tmp, "manifest.json"), "w",
                  encoding="utf-8") as fh:
            json.dump(manifest, fh)
        os.replace(tmp, final)
        self._gc()
        return final

    def _next_seq(self) -> int:
        existing = [int(n.split("-")[1]) for n in os.listdir(self.directory)
                    if n.startswith("ckpt-") and not n.endswith(".tmp")]
        return (max(existing) + 1) if existing else 0

    def _gc(self) -> None:
        ckpts = sorted(n for n in os.listdir(self.directory)
                       if n.startswith("ckpt-") and not n.endswith(".tmp"))
        for stale in ckpts[:-self.keep]:
            shutil.rmtree(os.path.join(self.directory, stale),
                          ignore_errors=True)

    # -- restore -----------------------------------------------------------
    def latest(self) -> Optional[str]:
        ckpts = sorted(n for n in os.listdir(self.directory)
                       if n.startswith("ckpt-") and not n.endswith(".tmp"))
        return os.path.join(self.directory, ckpts[-1]) if ckpts else None

    def restore(self, engine, path: Optional[str] = None) -> Dict[str, List[int]]:
        """Load a checkpoint into the engine; returns the saved bus offsets
        keyed `topic@group` so the caller can seed replay consumers."""
        path = path or self.latest()
        if path is None:
            return {}
        with open(os.path.join(path, "manifest.json"), encoding="utf-8") as fh:
            manifest = json.load(fh)
        with np.load(os.path.join(path, "state.npz")) as data:
            kwargs = {
                f.name: np.asarray(data[f"state.{f.name}"])
                for f in dataclasses.fields(DeviceStateTensors)
            }
            overflow_cols = {
                key[len("overflow."):]: np.asarray(data[key])
                for key in data.files if key.startswith("overflow.")
            }
        if manifest.get("layout") == "host-shards":
            # per-host gang-restart checkpoint: same-topology restore of
            # this host's shard blocks + the verbatim overflow batch
            engine.load_local_state_shards(manifest["shard_ids"], kwargs)
            if overflow_cols:
                from sitewhere_tpu.ops.pack import EventBatch

                engine.set_pending_overflow_batch(EventBatch(**overflow_cols))
        else:
            engine.load_canonical_state(DeviceStateTensors(**kwargs))
        packer = engine.packer
        packer.epoch_base_ms = manifest["epoch_base_ms"]
        packer.devices.restore(manifest["interners"]["devices"])
        packer.measurements.restore(manifest["interners"]["measurements"])
        packer.alert_types.restore(manifest["interners"]["alert_types"])
        pending = manifest.get("pending_alerts", [])
        if pending and hasattr(engine, "_pending_alerts"):
            engine._pending_alerts.extend(
                _alert_from_dict(d) for d in pending)
        self._restore_rules(engine, manifest.get("rules", []))
        return manifest.get("offsets", {})

    @staticmethod
    def _rules_manifest(engine) -> List[Dict]:
        from sitewhere_tpu.pipeline.engine import rule_to_dict

        return [rule_to_dict(kind, rule)
                for kind, rule_list in engine.list_rules().items()
                for rule in rule_list]

    @staticmethod
    def _restore_rules(engine, rules: List[Dict]) -> None:
        from sitewhere_tpu.pipeline.engine import rule_from_dict

        for data in rules:
            kind, rule = rule_from_dict(dict(data))
            engine.upsert_rule(kind, rule)

    # -- recovery ----------------------------------------------------------
    def recover(self, engine, bus, topic: str, group_id: str,
                replay_handler, max_records: int = 4096) -> int:
        """Restore the latest checkpoint, then replay every bus record past
        the checkpointed offsets through `replay_handler(records)` until
        caught up. Returns the number of replayed records.

        This is the crash-recovery contract of SURVEY.md §5: HBM state is a
        cache; checkpoint + at-least-once replay rebuilds it."""
        offsets = self.restore(engine)
        consumer = bus.consumer(topic, group_id)
        saved = offsets.get(f"{topic}@{group_id}")
        if saved is None:
            # Checkpoint carries no cursor for this group: the only safe
            # at-least-once choice is a full replay of the retained log —
            # the bus's own committed offsets may be AHEAD of the
            # checkpointed state (committed after save), which would lose
            # those events.
            consumer.seek_to_beginning()
        else:
            n = len(consumer.topic.partitions)
            consumer.committed = (list(saved) + [0] * n)[:n]
            consumer.seek_to_committed()
        replayed = 0
        while True:
            batch = consumer.poll(max_records)
            if not batch:
                break
            replay_handler(batch)
            bus.commit(consumer)
            replayed += len(batch)
        return replayed


class InstanceCheckpointManager:
    """Wires PipelineCheckpointer into a running SiteWhereInstance: restore
    the latest checkpoint at boot (rewinding the inbound consumer groups to
    the checkpointed cursors so replay closes the gap), then save
    periodically and on demand (REST POST /api/instance/checkpoint).

    Lifecycle-shaped (start/stop) so SiteWhereInstance can nest it between
    the pipeline engine (whose state it restores — must already be started)
    and the tenant engine manager (whose consumers must not start polling
    until the cursors are rewound)."""

    def __init__(self, instance, directory: str,
                 interval_s: Optional[float] = None):
        from sitewhere_tpu.runtime.lifecycle import LifecycleComponent

        self.instance = instance
        self.checkpointer = PipelineCheckpointer(directory)
        self.interval_s = interval_s
        self.last_restore_offsets: Dict[str, List[int]] = {}
        self._stop = None
        self._thread = None

        outer = self

        class _Component(LifecycleComponent):
            def __init__(self):
                super().__init__("checkpoint-manager")

            def on_start(self, monitor) -> None:
                outer._on_start()

            def on_stop(self, monitor) -> None:
                outer._on_stop()

        self.component = _Component()

    # -- save --------------------------------------------------------------
    def _inbound_groups(self):
        """Consumer groups feeding the pipeline: one per KNOWN tenant, not
        per running engine — a tenant whose engine is admin-stopped (or a
        save racing shutdown after engines cleared) still has a persisted
        cursor that must be captured, or the next boot restore would zero
        it and double-replay the retained log into already-complete
        state. bus.consumer() loads the persisted committed offsets even
        when no engine is consuming."""
        groups = []
        for tenant in self.instance.tenant_management.tenants.all():
            topic = self.instance.naming.event_source_decoded_events(
                tenant.token)
            groups.append(self.instance.bus.consumer(
                topic, f"inbound-processing-{tenant.token}"))
        if getattr(self.instance, "cluster_hooks", None) is not None:
            # the forwarded foreign-rows consumer also advances device
            # state; capture its cursor so restore replays only the gap
            from sitewhere_tpu.parallel.cluster import (
                FOREIGN_ROWS_GROUP, foreign_rows_topic)

            groups.append(self.instance.bus.consumer(
                foreign_rows_topic(self.instance.naming),
                FOREIGN_ROWS_GROUP))
        return groups

    def save(self) -> str:
        """Checkpoint now (offsets captured before state; see
        PipelineCheckpointer.save). Returns the checkpoint path."""
        engine = self.instance.pipeline_engine
        if engine is None:
            raise SiteWhereCheckpointError("instance has no pipeline engine")
        return self.checkpointer.save(
            engine, consumer_groups=self._inbound_groups())

    def list_checkpoints(self) -> List[str]:
        return sorted(
            name for name in os.listdir(self.checkpointer.directory)
            if name.startswith("ckpt-") and not name.endswith(".tmp"))

    # -- boot restore ------------------------------------------------------
    def restore_on_boot(self) -> bool:
        """Load the latest checkpoint into the engine and rewind every
        checkpointed consumer group to its saved cursor. Runs before the
        tenant engines' consumers start polling; the bus's own committed
        offsets may be AHEAD of the checkpoint (commits raced the save or
        happened after it), and replaying from the older checkpoint cursor
        is what makes the restored state catch up (at-least-once)."""
        engine = self.instance.pipeline_engine
        if engine is None or self.checkpointer.latest() is None:
            return False
        offsets = self.checkpointer.restore(engine)
        self.last_restore_offsets = offsets
        for key, saved in offsets.items():
            topic, _, group = key.rpartition("@")
            consumer = self.instance.bus.consumer(topic, group)
            n = len(consumer.topic.partitions)
            consumer.committed = (list(saved) + [0] * n)[:n]
            consumer.seek_to_committed()
        # Inbound groups ABSENT from the manifest (tenant created after the
        # checkpoint, or a save that raced engine boot): the restored state
        # has none of their events, but the bus's own persisted committed
        # offsets may be past them — the only at-least-once choice is a
        # full replay of the retained log for those groups (mirrors
        # PipelineCheckpointer.recover's no-cursor rule).
        for tenant in self.instance.tenant_management.tenants.all():
            topic = self.instance.naming.event_source_decoded_events(
                tenant.token)
            group = f"inbound-processing-{tenant.token}"
            if f"{topic}@{group}" in offsets:
                continue
            consumer = self.instance.bus.consumer(topic, group)
            consumer.committed = [0] * len(consumer.topic.partitions)
            consumer.seek_to_committed()
        return True

    # -- lifecycle ---------------------------------------------------------
    def _on_start(self) -> None:
        import threading

        self.restore_on_boot()
        if self.interval_s:
            self._stop = threading.Event()

            def _loop():
                while not self._stop.wait(self.interval_s):
                    try:
                        self.save()
                    except Exception:  # noqa: BLE001 - keep checkpointing
                        import logging

                        logging.getLogger("sitewhere.checkpoint").exception(
                            "periodic checkpoint failed")

            self._thread = threading.Thread(target=_loop, daemon=True,
                                            name="checkpoint-loop")
            self._thread.start()

    def _on_stop(self) -> None:
        if self._stop is not None:
            self._stop.set()
        if self._thread is not None:
            self._thread.join(timeout=10)
            self._thread = None


class SiteWhereCheckpointError(RuntimeError):
    pass
