"""Persistence tier: columnar event log, event-management API, checkpoints.

Reference layer L3 (SURVEY.md §2.3): the reference persists events through
pluggable stores (MongoDB bulk-insert buffer, HBase, Cassandra bucket tables,
InfluxDB series) behind `IDeviceEventManagement`. Here the single TPU-native
store is an append-only *columnar* event log (Arrow/Parquet segments): events
arrive already packed as SoA tensors on the hot path, so persistence is a
column append — no per-event serialization — and analytics read the same
columns back as tensors (sitewhere_tpu.analytics).
"""

from sitewhere_tpu.persist.eventlog import ColumnarEventLog, EventFilter
from sitewhere_tpu.persist.event_management import (
    DeviceEventManagement, EventIndex, EventPersistenceTriggers)
from sitewhere_tpu.persist.checkpoint import PipelineCheckpointer
from sitewhere_tpu.persist.worker import AsyncEventPersister

__all__ = [
    "ColumnarEventLog", "EventFilter", "DeviceEventManagement", "EventIndex",
    "EventPersistenceTriggers", "PipelineCheckpointer", "AsyncEventPersister",
]
