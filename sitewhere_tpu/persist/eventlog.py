"""Append-only columnar event log: the TPU-native event store.

Reference: the MongoDB event store with its bulk-insert buffer
(service-event-management/…/mongodb/MongoDeviceEventManagement.java:65,
DeviceEventBuffer.java:34 — 10k queue, batched writer thread, 200/chunk,
250 ms linger) and the time-bucketed Cassandra/HBase event tables.

Design (TPU-first): events on the hot path already live as SoA columns
(ops/pack.py EventBatch), so the store keeps them columnar end to end:

  append (columns or API objects) -> in-memory column buffer
    -> background flusher (chunk size + linger, like DeviceEventBuffer)
    -> immutable Arrow record-batch segment, optionally spilled to Parquet

Queries run as vectorized predicate scans over segments (numpy masks over
column arrays — the same shape of work the TPU rule kernels do), newest
first with offset/limit paging, and materialize model dataclasses only for
the requested page. Analytics (sitewhere_tpu/analytics) reads the raw
columns without materialization.

One unified nullable schema covers every DeviceEventType — the same trade
the reference's GDeviceEventPayload union makes, resolved as nullable
columns instead of a protobuf oneof.
"""

from __future__ import annotations

import dataclasses
import json
import logging
import os
import re
import threading
import time
import uuid
import weakref
from dataclasses import dataclass, field
from typing import Any, Dict, Iterator, List, Optional, Sequence, Tuple

import numpy as np

import pyarrow as pa
import pyarrow.parquet as pq

from sitewhere_tpu.model.common import (
    DateRangeCriteria, SearchCriteria, SearchResults, new_id)
from sitewhere_tpu.model.event import (
    AlertLevel, AlertSource, CommandInitiator, CommandTarget, DeviceAlert,
    DeviceCommandInvocation, DeviceCommandResponse, DeviceEvent,
    DeviceEventType, DeviceLocation, DeviceMeasurement, DeviceStateChange,
    DeviceStreamData)

# Unified event schema. String/object fields are nullable; numeric hot-path
# columns are dense. `device_idx`/`mm_idx`/`alert_type_idx` mirror the interned
# tensor indices so analytics can go straight back to tensors.
_SCHEMA = pa.schema([
    ("id", pa.string()),
    # Hot-path rows carry (id_prefix, id_seq) instead of a per-row id string:
    # building 131k formatted strings per batch was 70%+ of append_batch's
    # cost. The string id is derived on read (`_derive_id`); `id` stays for
    # control-plane events with caller-chosen ids.
    ("id_prefix", pa.string()),
    ("id_seq", pa.int64()),
    ("alternate_id", pa.string()),
    ("event_type", pa.int32()),
    ("device_idx", pa.int32()),
    ("device_token", pa.string()),
    ("assignment_token", pa.string()),
    ("customer_id", pa.string()),
    ("area_id", pa.string()),
    ("asset_id", pa.string()),
    ("event_date", pa.int64()),      # absolute ms
    ("received_date", pa.int64()),   # absolute ms
    ("mm_idx", pa.int32()),
    ("mm_name", pa.string()),
    ("value", pa.float32()),
    ("latitude", pa.float32()),
    ("longitude", pa.float32()),
    ("elevation", pa.float32()),
    ("alert_source", pa.int32()),
    ("alert_level", pa.int32()),
    ("alert_type_idx", pa.int32()),
    ("alert_type", pa.string()),
    ("alert_message", pa.string()),
    ("initiator", pa.int32()),
    ("initiator_id", pa.string()),
    ("target", pa.int32()),
    ("target_id", pa.string()),
    ("command_token", pa.string()),
    ("parameters", pa.string()),     # json map
    ("originating_event_id", pa.string()),
    ("response_event_id", pa.string()),
    ("response", pa.string()),
    ("attribute", pa.string()),
    ("state_type", pa.string()),
    ("previous_state", pa.string()),
    ("new_state", pa.string()),
    ("stream_id", pa.string()),
    ("sequence_number", pa.int64()),
    ("stream_data", pa.binary()),
    ("metadata", pa.string()),       # json map
])

_COLUMNS = [f.name for f in _SCHEMA]
_ID_PREFIX = uuid.uuid4().hex[:10]  # process-unique; see append_batch ids
_INT_COLS = {f.name for f in _SCHEMA if pa.types.is_integer(f.type)}
_FLOAT_COLS = {f.name for f in _SCHEMA if pa.types.is_floating(f.type)}
_I64_COLS = ("event_date", "received_date", "sequence_number", "id_seq")

_ID_RE = re.compile(r"ev-([0-9a-f]{10})-([0-9a-f]{12})")

# interner -> (length-at-snapshot, object-array snapshot); see resolve()
_SNAPSHOT_CACHE = weakref.WeakKeyDictionary()


def _snapshot_array(interner) -> np.ndarray:
    # Keyed on the interner's mutation version (not its length: a
    # checkpoint restore can swap same-length contents).
    version = getattr(interner, "version", None)
    if version is None:  # foreign interner-like object: don't cache
        return np.array(interner.snapshot(), dtype=object)
    cached = _SNAPSHOT_CACHE.get(interner)
    if cached is not None and cached[0] == version:
        return cached[1]
    snap = np.array(interner.snapshot(), dtype=object)
    _SNAPSHOT_CACHE[interner] = (version, snap)
    return snap


def _derive_id(prefix: str, seq: int) -> str:
    return f"ev-{prefix}-{seq:012x}"


@dataclass
class EventFilter:
    """Predicate for event queries (the reference's per-index list rpcs +
    ISearchCriteria date range, device-event-management.proto:37-93)."""

    event_type: Optional[DeviceEventType] = None
    device_idx: Optional[int] = None
    device_token: Optional[str] = None
    assignment_token: Optional[str] = None
    area_id: Optional[str] = None
    customer_id: Optional[str] = None
    asset_id: Optional[str] = None
    start_date: Optional[int] = None   # ms, inclusive
    end_date: Optional[int] = None     # ms, inclusive
    id: Optional[str] = None
    alternate_id: Optional[str] = None
    mm_name: Optional[str] = None
    originating_event_id: Optional[str] = None
    stream_id: Optional[str] = None
    sequence_number: Optional[int] = None

    def _mask(self, cols: Dict[str, np.ndarray]) -> np.ndarray:
        n = len(cols["event_date"])
        mask = np.ones(n, bool)
        if self.event_type is not None:
            mask &= cols["event_type"] == int(self.event_type)
        if self.sequence_number is not None:
            mask &= cols["sequence_number"] == self.sequence_number
        if self.device_idx is not None:
            mask &= cols["device_idx"] == self.device_idx
        if self.start_date is not None:
            mask &= cols["event_date"] >= self.start_date
        if self.end_date is not None:
            mask &= cols["event_date"] <= self.end_date
        if self.id is not None:
            id_mask = cols["id"] == self.id
            m = _ID_RE.fullmatch(self.id)
            if m is not None:  # derived hot-path id: match (prefix, seq)
                id_mask |= ((cols["id_prefix"] == m.group(1))
                            & (cols["id_seq"] == int(m.group(2), 16)))
            mask &= id_mask
        for attr, col in (("device_token", "device_token"),
                          ("assignment_token", "assignment_token"),
                          ("area_id", "area_id"),
                          ("customer_id", "customer_id"),
                          ("asset_id", "asset_id"),
                          ("alternate_id", "alternate_id"),
                          ("mm_name", "mm_name"),
                          ("originating_event_id", "originating_event_id"),
                          ("stream_id", "stream_id")):
            want = getattr(self, attr)
            if want is not None:
                val = cols[col]
                mask &= (val.eq_mask(want)
                         if isinstance(val, _LazyTokenCol) else val == want)
        return mask


class _Segment:
    """Immutable flushed chunk: numpy column dict + min/max skip-index over
    event_date and device_idx for segment pruning (the reference's Cassandra
    time buckets serve the same skip-scan purpose for time;
    device-partitioned logs additionally skip on the device range)."""

    __slots__ = ("cols", "n", "min_date", "max_date", "min_dev", "max_dev")

    def __init__(self, cols: Dict[str, np.ndarray]):
        self.cols = cols
        self.n = len(cols["event_date"])
        dates = cols["event_date"]
        self.min_date = int(dates.min()) if self.n else 0
        self.max_date = int(dates.max()) if self.n else 0
        devs = cols["device_idx"]
        self.min_dev = int(devs.min()) if self.n else 0
        self.max_dev = int(devs.max()) if self.n else 0

    def to_arrow(self) -> pa.Table:
        arrays = []
        for fld in _SCHEMA:
            col = self.cols[fld.name]
            if isinstance(col, _LazyTokenCol):
                # spill runs on the linger thread, off the append hot path
                col = col.materialize()
            if _is_const(col) and _const_value(col) is None:
                arrays.append(pa.nulls(len(col), type=fld.type))
            elif _is_const(col):
                arrays.append(pa.array(list(col), type=fld.type))
            elif fld.name == "stream_data":
                arrays.append(pa.array(list(col), type=pa.binary()))
            else:
                arrays.append(pa.array(col, type=fld.type))
        return pa.Table.from_arrays(arrays, schema=_SCHEMA)

    @classmethod
    def from_arrow(cls, table: pa.Table) -> "_Segment":
        # schema evolution: parquet written by an older build lacks newer
        # columns (e.g. id_prefix/id_seq) — start from defaults, overwrite
        # with whatever the file has
        cols = _full_cols(table.num_rows, const_strings=True)
        names = set(table.column_names)
        for fld in _SCHEMA:
            if fld.name not in names:
                continue
            arr = table.column(fld.name)
            if fld.name in _INT_COLS or fld.name in _FLOAT_COLS:
                np_dtype = arr.type.to_pandas_dtype()
                cols[fld.name] = np.asarray(
                    arr.fill_null(0).to_numpy(zero_copy_only=False),
                    dtype=np_dtype)
            elif arr.null_count == len(arr):
                cols[fld.name] = _const_col(table.num_rows)
            else:
                cols[fld.name] = np.asarray(arr.to_pylist(), dtype=object)
        return cls(cols)


def _merge_col(parts: List[np.ndarray]) -> np.ndarray:
    """Concatenate column chunks, keeping const views const (merging
    all-None const columns must not materialize the 8n bytes a const view
    exists to avoid) and lazy token chunks lazy when they share one
    dictionary snapshot (the steady-state ingest case: the interner is not
    growing, so `_snapshot_array` hands every chunk the same cached
    array). Mixed or differing-snapshot chunks materialize — a restore can
    swap same-length interner contents, so identity is the only safe
    fast-path key."""
    if len(parts) == 1:
        return parts[0]
    if any(isinstance(p, _LazyTokenCol) for p in parts):
        first = next(p for p in parts if isinstance(p, _LazyTokenCol))
        if all(isinstance(p, _LazyTokenCol) and p.snap is first.snap
               for p in parts):
            return _LazyTokenCol(np.concatenate([p.idx for p in parts]),
                                 first.snap)
        parts = [p.materialize() if isinstance(p, _LazyTokenCol) else p
                 for p in parts]
    if all(_is_const(p) for p in parts):
        shared = next((_const_value(p) for p in parts if len(p)), None)
        if all(len(p) == 0 or _const_value(p) is shared for p in parts):
            return _const_col(sum(len(p) for p in parts), shared)
    return np.concatenate(parts)


class _ColumnBuffer:
    """Mutable append buffer; column-major lists of row-chunks."""

    def __init__(self) -> None:
        self.chunks: List[Dict[str, np.ndarray]] = []
        self.n = 0
        self._peek_cache: Optional[Tuple[int, _Segment]] = None

    def append(self, cols: Dict[str, np.ndarray], n: int) -> None:
        self.chunks.append(cols)
        self.n += n

    def _merge(self) -> Dict[str, np.ndarray]:
        return {name: _merge_col([c[name] for c in self.chunks])
                for name in _COLUMNS}

    def drain(self) -> Optional[_Segment]:
        if not self.chunks:
            return None
        cached = self._peek_cache
        seg = (cached[1] if cached is not None and cached[0] == len(self.chunks)
               else _Segment(self._merge()))
        self.chunks = []
        self.n = 0
        self._peek_cache = None
        return seg

    def peek(self) -> Optional[_Segment]:
        """Transient view of buffered rows for scans — does NOT seal a
        segment, so trickle-rate tenants don't fragment the log. The merged
        view is cached until the next append (chunk count is the version:
        chunks are append-only), so repeated analytics replays don't pay
        the column merge each query."""
        if not self.chunks:
            return None
        cached = self._peek_cache
        if cached is not None and cached[0] == len(self.chunks):
            return cached[1]
        seg = _Segment(self._merge())
        self._peek_cache = (len(self.chunks), seg)
        return seg


class _LazyTokenCol:
    """Dictionary-encoded token column: row i reads `snap[idx[i]]` (None
    when the index is out of the snapshot's range or the reserved slot 0 —
    exactly `TokenInterner.token_of` semantics).

    The append hot path stores only the (already-materialized) int32 index
    column plus a reference to the interner's cached snapshot; the object
    column of Python strings materializes lazily — at Parquet spill (linger
    thread), or per-row/per-page at query time. Building those strings
    eagerly was >40% of `append_batch` cost at the 131k production batch,
    paid for rows whose tokens nobody ever reads (VERDICT r5 item 2: the
    sustained-system rate was persist-bound). Supports exactly the access
    patterns the log uses: len, scalar/fancy indexing, equality masking
    (on the int dictionary — cheaper than string compares), merge, and
    full materialization."""

    __slots__ = ("idx", "snap", "_mat")
    dtype = np.dtype(object)

    def __init__(self, idx: np.ndarray, snap: np.ndarray):
        self.idx = idx
        self.snap = snap
        self._mat: Optional[np.ndarray] = None

    def __len__(self) -> int:
        return len(self.idx)

    def materialize(self) -> np.ndarray:
        if self._mat is None:
            clipped = np.clip(self.idx, 0, len(self.snap) - 1)
            out = self.snap[clipped]
            out[(self.idx <= 0) | (self.idx >= len(self.snap))] = None
            self._mat = out
        return self._mat

    def __getitem__(self, key):
        if self._mat is not None:
            return self._mat[key]
        if isinstance(key, (int, np.integer)):
            i = int(self.idx[key])
            return self.snap[i] if 0 < i < len(self.snap) else None
        sub = self.idx[key]
        clipped = np.clip(sub, 0, len(self.snap) - 1)
        out = self.snap[clipped]
        out[(sub <= 0) | (sub >= len(self.snap))] = None
        return out

    def eq_mask(self, want) -> np.ndarray:
        """Boolean column == `want`, computed as integer compares against
        the dictionary instead of n string compares."""
        hits = np.nonzero(self.snap == want)[0]
        hits = hits[hits > 0]
        if len(hits) == 0:
            return np.zeros(len(self.idx), bool)
        if len(hits) == 1:
            return self.idx == hits[0]
        return np.isin(self.idx, hits)


def _obj_col(n: int, value: Any = None) -> np.ndarray:
    out = np.empty(n, object)
    out[:] = value
    return out


def _const_col(n: int, value: Any = None) -> np.ndarray:
    """All-`value` object column as a stride-0 broadcast view: 8 bytes of
    storage instead of 8n. Appending 131k-row batches was dominated by
    page-faulting ~20 fresh 1MB all-None object arrays per batch (cost grows
    with process RSS); a read-only view sidesteps the allocation entirely.
    Reads (fancy indexing, ==, scalar access) behave like a real column."""
    base = np.empty((), object)
    base[()] = value
    return np.broadcast_to(base, (n,))


def _const_value(col: np.ndarray) -> Any:
    """The shared value of a stride-0 const column (None for empty)."""
    return col[0] if len(col) else None


def _is_const(col: np.ndarray) -> bool:
    return (isinstance(col, np.ndarray) and col.dtype == object
            and col.ndim == 1 and col.strides == (0,))


def _full_cols(n: int, const_strings: bool = False,
               **given: np.ndarray) -> Dict[str, np.ndarray]:
    """Build a complete column dict; unspecified columns default to 0/None.
    `const_strings=True` makes defaulted object columns read-only const
    views (hot path); leave False when rows are filled in afterwards."""
    cols: Dict[str, np.ndarray] = {}
    for name in _COLUMNS:
        if name in given:
            cols[name] = given[name]
        elif name in _INT_COLS:
            cols[name] = np.zeros(n, np.int64 if name in _I64_COLS
                                  else np.int32)
        elif name in _FLOAT_COLS:
            cols[name] = np.zeros(n, np.float32)
        elif const_strings:
            cols[name] = _const_col(n)
        else:
            cols[name] = _obj_col(n)
    return cols


class TenantEventLog:
    """One tenant's log: buffer + segments (+ optional Parquet spill dir)."""

    def __init__(self, tenant: str, data_dir: Optional[str],
                 segment_rows: int, spill: bool):
        self.tenant = tenant
        self.segment_rows = segment_rows
        self._buffer = _ColumnBuffer()
        self._segments: List[_Segment] = []
        self._seg_paths: List[Optional[str]] = []
        self._lock = threading.Lock()
        # Bumped whenever sealed segments are REMOVED (retention). Sealing
        # only appends, so `(retention_epoch, len(_segments))` is a
        # monotonic watermark within an epoch: anything cached over sealed
        # segments [0, n) stays exact until the epoch changes
        # (serving/wincache.py keys its grids on this pair).
        self.retention_epoch = 0
        self._dir = None
        self._spill = spill and data_dir is not None
        self._next_seg = 0
        if data_dir is not None:
            self._dir = os.path.join(data_dir, tenant.replace("/", "_"))
            os.makedirs(self._dir, exist_ok=True)
            # record the TRUE tenant name: reload keys tenants by it, not by
            # the sanitized directory name (they differ for e.g. "acme/eu")
            name_path = os.path.join(self._dir, "_tenant.name")
            if not os.path.exists(name_path):
                with open(name_path, "w", encoding="utf-8") as fh:
                    fh.write(tenant)
            self._load()

    def _load(self) -> None:
        # sweep orphaned .tmp spills first: a crash mid-seal leaves a
        # partial `events-N.parquet.tmp` that must never be read — and
        # must not survive to confuse a later crash's triage either
        for name in os.listdir(self._dir):
            if name.endswith(".tmp"):
                try:
                    os.remove(os.path.join(self._dir, name))
                except OSError:
                    pass
        names = sorted(f for f in os.listdir(self._dir)
                       if f.endswith(".parquet"))
        for name in names:
            path = os.path.join(self._dir, name)
            try:
                seg = _Segment.from_arrow(pq.read_table(path))
            except Exception:
                # a sealed segment that no longer parses (torn pre-fsync
                # write, bit rot): quarantine instead of poisoning boot;
                # its rows are rebuildable from the bus log (at-least-once)
                logging.getLogger("sitewhere.eventlog").exception(
                    "quarantining unreadable segment %s", path)
                try:
                    os.replace(path, path + ".quarantine")
                except OSError:
                    pass
                continue
            self._segments.append(seg)
            self._seg_paths.append(path)
            seq = int(name.split("-")[1].split(".")[0])
            self._next_seg = max(self._next_seg, seq + 1)

    def append(self, cols: Dict[str, np.ndarray], n: int) -> None:
        """Buffer only — never touches disk, so the ingest hot path pays a
        list append. Sealing happens on the linger thread (flush_if_full) or
        an explicit flush(); scans see buffered rows via peek()."""
        with self._lock:
            self._buffer.append(cols, n)

    def flush_if_full(self) -> None:
        """Seal only when a full segment's worth is buffered — the linger
        loop calls this, so trickle-rate appends never fragment into tiny
        parquet files. Durability for the un-sealed tail rides the event bus
        log (at-least-once replay rebuilds it), the same trade the reference
        makes with DeviceEventBuffer's in-memory 10k queue."""
        self._seal(only_if_full=True)

    def flush(self) -> None:
        self._seal(only_if_full=False)

    def _seal(self, only_if_full: bool) -> None:
        """Drain buffer -> immutable segment under the lock; write Parquet
        OUTSIDE the lock so concurrent appends/scans never stall on disk."""
        with self._lock:
            if only_if_full and self._buffer.n < self.segment_rows:
                return
            seg = self._buffer.drain()
            if seg is None:
                return
            self._segments.append(seg)
            path = None
            if self._spill:
                path = os.path.join(self._dir,
                                    f"events-{self._next_seg:06d}.parquet")
                self._next_seg += 1
            self._seg_paths.append(path)
        if path is not None:
            from sitewhere_tpu.persist.atomic import fsync_dir, fsync_file

            tmp = path + ".tmp"
            pq.write_table(seg.to_arrow(), tmp)
            # fsync BEFORE the rename: without it a crash can leave a
            # renamed-but-empty parquet that poisons the next boot
            fsync_file(tmp)
            os.replace(tmp, path)
            fsync_dir(self._dir)

    def scan(self, flt: EventFilter) -> Iterator[Tuple[Dict[str, np.ndarray], np.ndarray]]:
        """Yield (cols, selected_row_indices) per segment, newest segment
        first (global ordering is the caller's job — see query())."""
        with self._lock:
            segments = list(self._segments)
            pending = self._buffer.peek()
        if pending is not None:
            segments.append(pending)
        for seg in reversed(segments):
            if flt.start_date is not None and seg.max_date < flt.start_date:
                continue
            if flt.end_date is not None and seg.min_date > flt.end_date:
                continue
            if flt.device_idx is not None and not (
                    seg.min_dev <= flt.device_idx <= seg.max_dev):
                continue
            idx = np.nonzero(flt._mask(seg.cols))[0]
            if len(idx):
                yield seg.cols, idx

    def count(self) -> int:
        with self._lock:
            return self._buffer.n + sum(s.n for s in self._segments)

    def sealed_snapshot(self) -> Tuple[int, List[_Segment],
                                       Optional[_Segment]]:
        """`(retention_epoch, sealed_segments, pending)` under one lock
        acquisition. Segments are immutable and the list is append-only
        within an epoch, so a reader can fold the snapshot lock-free while
        appends/seals proceed — the snapshot-isolation contract the
        serving tier's cache and delta scans are built on. `pending` is
        the buffered (unsealed, still-growing) tail; it must be re-read
        per query, never cached."""
        with self._lock:
            return (self.retention_epoch, list(self._segments),
                    self._buffer.peek())

    def estimate_rows(self, flt: EventFilter) -> int:
        """Upper-bound row count a scan of `flt` would touch, from the
        per-segment skip index alone — O(segments), no column reads. The
        query planner routes host-vs-mesh on this estimate."""
        with self._lock:
            segments = list(self._segments)
            pending_n = self._buffer.n
        n = pending_n
        for seg in segments:
            if flt.start_date is not None and seg.max_date < flt.start_date:
                continue
            if flt.end_date is not None and seg.min_date > flt.end_date:
                continue
            if flt.device_idx is not None and not (
                    seg.min_dev <= flt.device_idx <= seg.max_dev):
                continue
            n += seg.n
        return n

    def retain_max_segments(self, keep: int) -> int:
        """Drop the OLDEST sealed segments past `keep` (retention). Bumps
        `retention_epoch` so every cached grid over this log invalidates;
        parquet spills are unlinked outside the lock. Returns segments
        dropped."""
        keep = max(0, int(keep))
        with self._lock:
            drop = len(self._segments) - keep
            if drop <= 0:
                return 0
            dropped_paths = self._seg_paths[:drop]
            self._segments = self._segments[drop:]
            self._seg_paths = self._seg_paths[drop:]
            self.retention_epoch += 1
        for path in dropped_paths:
            if path is not None:
                try:
                    os.remove(path)
                except OSError:
                    pass
        return drop

    def _id_segments(self) -> List[Dict[str, np.ndarray]]:
        with self._lock:
            segments = list(self._segments)
            pending = self._buffer.peek()
        if pending is not None:
            segments.append(pending)
        return [seg.cols for seg in segments]

    def sequence_watermarks(self) -> Dict[str, int]:
        """Per `id_prefix` max `id_seq` over this tenant's rows (buffered
        + sealed). Each prefix is one process incarnation, each seq is
        monotonic within it, so the map is a compact high-watermark of
        everything this log has materialized — the instance checkpoint
        captures it next to the bus offsets (persist/checkpoint.py) and
        the replay barrier suppresses re-emission below it."""
        marks: Dict[str, int] = {}
        for cols in self._id_segments():
            prefixes = np.asarray(cols["id_prefix"], dtype=object)
            seqs = cols["id_seq"]
            for prefix in set(prefixes.tolist()):
                if prefix is None:
                    continue  # legacy rows without sequence identity
                top = int(seqs[prefixes == prefix].max())
                if top > marks.get(prefix, -1):
                    marks[prefix] = top
        return marks

    def rows_above(self, marks: Dict[str, int]) -> int:
        """Count rows whose (id_prefix, id_seq) lies ABOVE `marks` — at
        restore, with `marks` from the checkpoint manifest, this is the
        already-durable replay overlap (rows the retained log will
        re-offer past the saved offsets), i.e. the tenant's replay
        barrier budget."""
        n = 0
        for cols in self._id_segments():
            prefixes = np.asarray(cols["id_prefix"], dtype=object)
            seqs = cols["id_seq"]
            for prefix in set(prefixes.tolist()):
                if prefix is None:
                    continue
                sel = seqs[prefixes == prefix]
                n += int((sel > marks.get(prefix, -1)).sum())
        return n


class ColumnarEventLog:
    """Multi-tenant event store facade.

    Appends accept either packed `EventBatch` columns (hot path — vectorized,
    no per-event Python) or model dataclasses (control plane). Both land in
    the same unified schema.
    """

    def __init__(self, data_dir: Optional[str] = None,
                 segment_rows: int = 65536, linger_ms: int = 250,
                 spill_parquet: bool = True):
        self._data_dir = data_dir
        self._segment_rows = segment_rows
        self._linger_ms = linger_ms
        self._spill = spill_parquet
        self._tenants: Dict[str, TenantEventLog] = {}
        self._lock = threading.Lock()
        self._stop = threading.Event()
        self._flusher: Optional[threading.Thread] = None
        if data_dir:
            os.makedirs(data_dir, exist_ok=True)
            for name in sorted(os.listdir(data_dir)):
                tdir = os.path.join(data_dir, name)
                if not os.path.isdir(tdir):
                    continue
                name_path = os.path.join(tdir, "_tenant.name")
                if os.path.exists(name_path):
                    with open(name_path, encoding="utf-8") as fh:
                        name = fh.read().strip() or name
                self._tenants[name] = TenantEventLog(
                    name, data_dir, segment_rows, spill_parquet)

    # -- lifecycle ---------------------------------------------------------
    def start(self) -> None:
        """Start the linger flusher (DeviceEventBuffer.java:99 writer thread)."""
        if self._flusher is None:
            self._stop.clear()
            self._flusher = threading.Thread(
                target=self._linger_loop, name="eventlog-flusher", daemon=True)
            self._flusher.start()

    def _linger_loop(self) -> None:
        while not self._stop.wait(self._linger_ms / 1000.0):
            for log in self._tenant_list():
                log.flush_if_full()

    def stop(self) -> None:
        self._stop.set()
        if self._flusher is not None:
            self._flusher.join(timeout=5.0)
            self._flusher = None
        self.flush()

    def flush(self) -> None:
        for log in self._tenant_list():
            log.flush()

    def _tenant_list(self) -> List[TenantEventLog]:
        with self._lock:
            return list(self._tenants.values())

    def tenant(self, tenant: str) -> TenantEventLog:
        """Write-path accessor: creates the tenant log (and its directory)."""
        with self._lock:
            if tenant not in self._tenants:
                self._tenants[tenant] = TenantEventLog(
                    tenant, self._data_dir, self._segment_rows, self._spill)
            return self._tenants[tenant]

    def tenant_if_exists(self, tenant: str) -> Optional[TenantEventLog]:
        """Read-path accessor: never creates phantom tenants on disk."""
        with self._lock:
            return self._tenants.get(tenant)

    def flush_tenant(self, tenant: str) -> None:
        log = self.tenant_if_exists(tenant)
        if log is not None:
            log.flush()

    def sequence_watermarks(self) -> Dict[str, Dict[str, int]]:
        """Per-tenant `(id_prefix -> max id_seq)` high-watermarks — the
        checkpoint's exactly-once-effects anchor."""
        return {log.tenant: log.sequence_watermarks()
                for log in self._tenant_list()}

    def rows_above(self, tenant: str, marks: Dict[str, int]) -> int:
        log = self.tenant_if_exists(tenant)
        return 0 if log is None else log.rows_above(marks)

    def estimate_rows(self, tenant: str, flt: EventFilter) -> int:
        """Skip-index scan-size estimate for the query planner (see
        TenantEventLog.estimate_rows)."""
        log = self.tenant_if_exists(tenant)
        return 0 if log is None else log.estimate_rows(flt)

    def retain_max_segments(self, tenant: str, keep: int) -> int:
        """Retention facade: drop a tenant's oldest sealed segments past
        `keep` (bumps that log's retention_epoch — cached grids over it
        invalidate)."""
        log = self.tenant_if_exists(tenant)
        return 0 if log is None else log.retain_max_segments(keep)

    # -- hot-path append ---------------------------------------------------
    def append_batch(self, tenant: str, batch, packer,
                     received_ms: Optional[int] = None, registry=None) -> int:
        """Append the valid rows of a packed EventBatch. Vectorized: device
        tokens (and, when `registry` is given, assignment/area/customer/asset
        context — the GDeviceEventContext fields) are resolved once per
        unique device index, not per row, so index-based list queries work
        identically for hot-path and control-plane events."""
        valid = np.asarray(batch.valid)
        n = int(valid.sum())
        if n == 0:
            return 0
        sel = np.nonzero(valid)[0]
        # fancy-indexing already copies; astype(copy=False) avoids a second
        # copy per column when the dtype already matches (it always does on
        # the hot path — EventBatch columns are i32/f32 by construction)
        device_idx = np.asarray(batch.device_idx)[sel].astype(
            np.int32, copy=False)
        event_type = np.asarray(batch.event_type)[sel].astype(
            np.int32, copy=False)
        ts = np.add(np.asarray(batch.ts)[sel], packer.epoch_base_ms,
                    dtype=np.int64)
        mm_idx = np.asarray(batch.mm_idx)[sel].astype(np.int32, copy=False)
        alert_type_idx = np.asarray(batch.alert_type_idx)[sel].astype(
            np.int32, copy=False)
        now = received_ms if received_ms is not None else int(time.time() * 1000)

        # bulk ids: <process-unique prefix> + <monotonic counter>, stored as
        # (id_prefix, id_seq) columns. The prefix cell is ONE shared Python
        # string (no per-row allocation); the string form "ev-<prefix>-<seq>"
        # is derived on read — formatting 131k id strings per batch was 70%+
        # of append cost. The random prefix keeps ids unique across restarts
        # over the same parquet log.
        base = self._next_ids(n)
        id_seq = np.arange(base, base + n, dtype=np.int64)
        id_prefix = _const_col(n, _ID_PREFIX)

        context_cols: Dict[str, np.ndarray] = {}
        if registry is not None:
            # one lookup per unique device, then a vectorized gather through
            # an inverse index (np.unique is O(n log n), not O(U * n))
            uniq, inverse = np.unique(device_idx, return_inverse=True)
            u_assign = np.array([None] * len(uniq), dtype=object)
            u_customer = np.array([None] * len(uniq), dtype=object)
            u_area = np.array([None] * len(uniq), dtype=object)
            u_asset = np.array([None] * len(uniq), dtype=object)
            for j, u in enumerate(uniq):
                token = packer.devices.token_of(int(u))
                device = registry.get_device_by_token(token) if token else None
                assignment = (registry.get_active_assignment(device.id)
                              if device is not None else None)
                if assignment is None:
                    continue
                u_assign[j] = assignment.token
                u_customer[j] = assignment.customer_id or None
                u_area[j] = assignment.area_id or None
                u_asset[j] = assignment.asset_id or None
            context_cols = dict(assignment_token=u_assign[inverse],
                                customer_id=u_customer[inverse],
                                area_id=u_area[inverse],
                                asset_id=u_asset[inverse])

        cols = _full_cols(
            n,
            const_strings=True,
            id_prefix=id_prefix,
            id_seq=id_seq,
            event_type=event_type,
            device_idx=device_idx,
            # token strings are dictionary-encoded: the idx columns are
            # already selected above, so the string columns cost two
            # pointer stores here and materialize off the hot path
            device_token=_LazyTokenCol(device_idx,
                                       _snapshot_array(packer.devices)),
            event_date=ts,
            received_date=np.full(n, now, np.int64),
            mm_idx=mm_idx,
            mm_name=_LazyTokenCol(mm_idx,
                                  _snapshot_array(packer.measurements)),
            value=np.asarray(batch.value)[sel].astype(np.float32, copy=False),
            latitude=np.asarray(batch.lat)[sel].astype(np.float32, copy=False),
            longitude=np.asarray(batch.lon)[sel].astype(
                np.float32, copy=False),
            elevation=np.asarray(batch.elevation)[sel].astype(
                np.float32, copy=False),
            alert_level=np.asarray(batch.alert_level)[sel].astype(
                np.int32, copy=False),
            alert_type_idx=alert_type_idx,
            alert_type=_LazyTokenCol(alert_type_idx,
                                     _snapshot_array(packer.alert_types)),
            **context_cols,
        )
        self.tenant(tenant).append(cols, n)
        return n

    _id_counter = 0
    _id_lock = threading.Lock()

    @classmethod
    def _next_ids(cls, n: int) -> int:
        with cls._id_lock:
            base = cls._id_counter
            cls._id_counter += n
            return base

    # -- control-plane append ---------------------------------------------
    def append_events(self, tenant: str, events: Sequence[DeviceEvent],
                      device_interner=None) -> None:
        n = len(events)
        if n == 0:
            return
        # control-plane rows carry (id_prefix, id_seq) too — the explicit
        # event id stays authoritative on read, but sequence identity is
        # what the checkpoint watermarks and replay-barrier budgets count,
        # and inbound persist lands here rather than on the packed path
        base = self._next_ids(n)
        cols = _full_cols(n,
                          id_prefix=_const_col(n, _ID_PREFIX),
                          id_seq=np.arange(base, base + n, dtype=np.int64))
        for i, ev in enumerate(events):
            self._fill_row(cols, i, ev, device_interner)
        self.tenant(tenant).append(cols, n)

    @staticmethod
    def _fill_row(cols: Dict[str, np.ndarray], i: int, ev: DeviceEvent,
                  device_interner) -> None:
        cols["id"][i] = ev.id or new_id()
        cols["alternate_id"][i] = ev.alternate_id or None
        cols["event_type"][i] = int(ev.event_type)
        cols["device_token"][i] = ev.device_id or None
        if device_interner is not None and ev.device_id:
            cols["device_idx"][i] = device_interner.lookup(ev.device_id)
        cols["assignment_token"][i] = ev.device_assignment_id or None
        cols["customer_id"][i] = ev.customer_id or None
        cols["area_id"][i] = ev.area_id or None
        cols["asset_id"][i] = ev.asset_id or None
        cols["event_date"][i] = ev.event_date
        cols["received_date"][i] = ev.received_date
        if ev.metadata:
            cols["metadata"][i] = json.dumps(ev.metadata)
        if isinstance(ev, DeviceMeasurement):
            cols["mm_name"][i] = ev.name
            cols["value"][i] = ev.value
        elif isinstance(ev, DeviceLocation):
            cols["latitude"][i] = ev.latitude
            cols["longitude"][i] = ev.longitude
            cols["elevation"][i] = ev.elevation
        elif isinstance(ev, DeviceAlert):
            cols["alert_source"][i] = int(ev.source)
            cols["alert_level"][i] = int(ev.level)
            cols["alert_type"][i] = ev.type or None
            cols["alert_message"][i] = ev.message or None
        elif isinstance(ev, DeviceCommandInvocation):
            cols["initiator"][i] = int(ev.initiator)
            cols["initiator_id"][i] = ev.initiator_id or None
            cols["target"][i] = int(ev.target)
            cols["target_id"][i] = ev.target_id or None
            cols["command_token"][i] = ev.command_token or None
            if ev.parameter_values:
                cols["parameters"][i] = json.dumps(ev.parameter_values)
        elif isinstance(ev, DeviceCommandResponse):
            cols["originating_event_id"][i] = ev.originating_event_id or None
            cols["response_event_id"][i] = ev.response_event_id or None
            cols["response"][i] = ev.response or None
        elif isinstance(ev, DeviceStateChange):
            cols["attribute"][i] = ev.attribute or None
            cols["state_type"][i] = ev.type or None
            cols["previous_state"][i] = ev.previous_state or None
            cols["new_state"][i] = ev.new_state or None
        elif isinstance(ev, DeviceStreamData):
            cols["stream_id"][i] = ev.stream_id or None
            cols["sequence_number"][i] = ev.sequence_number
            cols["stream_data"][i] = ev.data

    # -- query -------------------------------------------------------------
    def query(self, tenant: str, flt: EventFilter,
              criteria: Optional[SearchCriteria] = None,
              order_by: str = "event_date_desc"
              ) -> SearchResults[DeviceEvent]:
        """Globally ordered paged query (default newest-first by event_date
        across ALL segments — late/replayed events interleave correctly),
        materializing dataclasses only for the requested page.

        `order_by`: "event_date_desc" | "sequence_asc" (stream reassembly).
        The caller's filter is never mutated."""
        criteria = criteria or SearchCriteria()
        flt = dataclasses.replace(flt)
        if isinstance(criteria, DateRangeCriteria):
            if criteria.start_date is not None and flt.start_date is None:
                flt.start_date = criteria.start_date
            if criteria.end_date is not None and flt.end_date is None:
                flt.end_date = criteria.end_date
        tlog = self.tenant_if_exists(tenant)
        matches: List[Tuple[Dict[str, np.ndarray], np.ndarray]] = \
            list(tlog.scan(flt)) if tlog is not None else []
        if not matches:
            return SearchResults(results=[], num_results=0)
        key_col = ("sequence_number" if order_by == "sequence_asc"
                   else "event_date")
        keys = np.concatenate([cols[key_col][idx] for cols, idx in matches])
        order = np.argsort(keys, kind="stable")
        if order_by != "sequence_asc":
            # descending; reversing the stable ascending order also puts the
            # latest-appended event first among same-millisecond ties
            order = order[::-1]
        total = len(order)
        skip = criteria.offset
        page = order[skip:skip + criteria.page_size]
        # map flat positions back to (segment, row)
        bounds = np.cumsum([0] + [len(idx) for _, idx in matches])
        events: List[DeviceEvent] = []
        for pos in page:
            seg_i = int(np.searchsorted(bounds, pos, side="right") - 1)
            cols, idx = matches[seg_i]
            events.append(self._materialize(cols, int(idx[pos - bounds[seg_i]])))
        return SearchResults(results=events, num_results=total)

    def query_columns(self, tenant: str, flt: EventFilter,
                      names: Sequence[str]) -> Dict[str, np.ndarray]:
        """Analytics path: concatenated raw columns for all matching rows —
        no dataclass materialization (feeds windowed tensor reductions)."""
        parts: Dict[str, List[np.ndarray]] = {n: [] for n in names}
        tlog = self.tenant_if_exists(tenant)
        for cols, idx in (tlog.scan(flt) if tlog is not None else ()):
            for n in names:
                parts[n].append(cols[n][idx])

        def empty(name: str) -> np.ndarray:
            fld = _SCHEMA.field(name)
            if name in _INT_COLS or name in _FLOAT_COLS:
                return np.array([], dtype=fld.type.to_pandas_dtype())
            return np.array([], dtype=object)

        return {
            n: (np.concatenate(v) if v else empty(n))
            for n, v in parts.items()
        }

    def count(self, tenant: str) -> int:
        tlog = self.tenant_if_exists(tenant)
        return tlog.count() if tlog is not None else 0

    @staticmethod
    def _materialize(cols: Dict[str, np.ndarray], i: int) -> DeviceEvent:
        etype = DeviceEventType(int(cols["event_type"][i]))

        def s(name: str) -> str:
            v = cols[name][i]
            return "" if v is None else str(v)

        meta = json.loads(s("metadata")) if cols["metadata"][i] else {}
        event_id = cols["id"][i]
        if event_id is None and cols["id_prefix"][i] is not None:
            event_id = _derive_id(cols["id_prefix"][i], int(cols["id_seq"][i]))
        common = dict(
            id=event_id or "", alternate_id=s("alternate_id"), event_type=etype,
            device_id=s("device_token"),
            device_assignment_id=s("assignment_token"),
            customer_id=s("customer_id"), area_id=s("area_id"),
            asset_id=s("asset_id"), event_date=int(cols["event_date"][i]),
            received_date=int(cols["received_date"][i]), metadata=meta)
        if etype == DeviceEventType.MEASUREMENT:
            return DeviceMeasurement(**common, name=s("mm_name"),
                                     value=float(cols["value"][i]))
        if etype == DeviceEventType.LOCATION:
            return DeviceLocation(
                **common, latitude=float(cols["latitude"][i]),
                longitude=float(cols["longitude"][i]),
                elevation=float(cols["elevation"][i]))
        if etype == DeviceEventType.ALERT:
            return DeviceAlert(
                **common, source=AlertSource(int(cols["alert_source"][i])),
                level=AlertLevel(int(cols["alert_level"][i])),
                type=s("alert_type"), message=s("alert_message"))
        if etype == DeviceEventType.COMMAND_INVOCATION:
            params = json.loads(s("parameters")) if cols["parameters"][i] else {}
            return DeviceCommandInvocation(
                **common, initiator=CommandInitiator(int(cols["initiator"][i])),
                initiator_id=s("initiator_id"),
                target=CommandTarget(int(cols["target"][i])),
                target_id=s("target_id"), command_token=s("command_token"),
                parameter_values=params)
        if etype == DeviceEventType.COMMAND_RESPONSE:
            return DeviceCommandResponse(
                **common, originating_event_id=s("originating_event_id"),
                response_event_id=s("response_event_id"),
                response=s("response"))
        if etype == DeviceEventType.STATE_CHANGE:
            return DeviceStateChange(
                **common, attribute=s("attribute"), type=s("state_type"),
                previous_state=s("previous_state"), new_state=s("new_state"))
        data = cols["stream_data"][i]
        return DeviceStreamData(
            **common, stream_id=s("stream_id"),
            sequence_number=int(cols["sequence_number"][i]),
            data=data if isinstance(data, bytes) else b"")
