"""Wide-row historical event store — the second interchangeable backend.

Reference: the legacy wide-column historical stores — sitewhere-hbase
(`hbase/device/HBaseDeviceEvent.java`: events in time-bucketed wide rows
keyed by assignment + inverted timestamp) and sitewhere-cassandra
(`cassandra/CassandraClient.java`: `events_by_id` / `events_by_*` tables
partitioned by a configurable time bucket) — selectable PER TENANT
against the primary store through `DatastoreConfigurationParser`.

This backend fills that slot with the same interchangeability contract:
`DatastoreConfig(kind="widerow")` gives a tenant an ACID, row-oriented
store instead of the columnar scan log. One sqlite row per event, keyed
by a time bucket (the Cassandra partition analog), secondary indexes on
the reference's query axes (device, assignment, type — the
`events_by_*` tables' role), WAL journaling, and whole-bucket retention
pruning. The trade-off vs the columnar log is honest and deliberate:
transactional durability and indexed point lookups in exchange for scan
bandwidth — the hot analytics path stays on the columnar default unless
a tenant opts out (data-residency, audit tenants, small fleets).

Duck-compatible with ColumnarEventLog's consumer surface
(`EventManagement`, `AnalyticsEngine`, `StreamManager`,
`PersistWorker`): start/stop/flush/flush_tenant, append_events,
append_batch, query, query_columns, count.

Hot-batch rows (MEASUREMENT / LOCATION / ALERT from packed EventBatches)
store typed SQL columns only; control-plane appends additionally keep
the full event document so every event kind round-trips losslessly.
"""

from __future__ import annotations

import json
import os
import sqlite3
import threading
import time
from typing import Dict, List, Optional, Sequence, Tuple

import numpy as np

from sitewhere_tpu.model.common import SearchCriteria, SearchResults
from sitewhere_tpu.model.event import (
    AlertLevel, AlertSource, DeviceAlert, DeviceEvent, DeviceEventType,
    DeviceLocation, DeviceMeasurement, event_from_dict)
from sitewhere_tpu.persist.eventlog import (
    _ID_PREFIX, _derive_id, DateRangeCriteria, EventFilter)

_SCHEMA_SQL = """
CREATE TABLE IF NOT EXISTS events (
    seq INTEGER PRIMARY KEY AUTOINCREMENT,
    tenant TEXT NOT NULL,
    bucket INTEGER NOT NULL,
    id TEXT,
    alternate_id TEXT,
    event_type INTEGER NOT NULL,
    device_idx INTEGER NOT NULL DEFAULT 0,
    device_token TEXT,
    assignment_token TEXT,
    customer_id TEXT,
    area_id TEXT,
    asset_id TEXT,
    event_date INTEGER NOT NULL,
    received_date INTEGER NOT NULL,
    mm_idx INTEGER NOT NULL DEFAULT 0,
    mm_name TEXT,
    value REAL NOT NULL DEFAULT 0,
    latitude REAL NOT NULL DEFAULT 0,
    longitude REAL NOT NULL DEFAULT 0,
    elevation REAL NOT NULL DEFAULT 0,
    alert_source INTEGER NOT NULL DEFAULT 0,
    alert_level INTEGER NOT NULL DEFAULT 0,
    alert_type TEXT,
    alert_message TEXT,
    stream_id TEXT,
    sequence_number INTEGER NOT NULL DEFAULT 0,
    originating_event_id TEXT,
    doc TEXT
);
CREATE INDEX IF NOT EXISTS ix_ev_bucket ON events(tenant, bucket);
CREATE INDEX IF NOT EXISTS ix_ev_device
    ON events(tenant, device_token, event_date);
CREATE INDEX IF NOT EXISTS ix_ev_assn
    ON events(tenant, assignment_token, event_date);
CREATE INDEX IF NOT EXISTS ix_ev_type
    ON events(tenant, event_type, event_date);
CREATE INDEX IF NOT EXISTS ix_ev_id ON events(tenant, id);
"""

# filter field -> SQL column for the exact-match predicates
_EQ_COLUMNS = {
    "device_idx": "device_idx",
    "device_token": "device_token",
    "assignment_token": "assignment_token",
    "area_id": "area_id",
    "customer_id": "customer_id",
    "asset_id": "asset_id",
    "id": "id",
    "alternate_id": "alternate_id",
    "mm_name": "mm_name",
    "originating_event_id": "originating_event_id",
    "stream_id": "stream_id",
    "sequence_number": "sequence_number",
}

_I64_NAMES = frozenset({"event_date", "received_date", "sequence_number",
                        "seq", "bucket"})
_I32_NAMES = frozenset({"event_type", "device_idx", "mm_idx",
                        "alert_source", "alert_level"})
_F32_NAMES = frozenset({"value", "latitude", "longitude", "elevation"})

_INSERT_COLS = (
    "tenant", "bucket", "id", "alternate_id", "event_type", "device_idx",
    "device_token", "assignment_token", "customer_id", "area_id",
    "asset_id", "event_date", "received_date", "mm_idx", "mm_name",
    "value", "latitude", "longitude", "elevation", "alert_source",
    "alert_level", "alert_type", "alert_message", "stream_id",
    "sequence_number", "originating_event_id", "doc")
_INSERT_SQL = (f"INSERT INTO events ({', '.join(_INSERT_COLS)}) "
               f"VALUES ({', '.join('?' * len(_INSERT_COLS))})")


class WideRowEventStore:
    """sqlite-backed wide-row event store (HBase/Cassandra historical
    store role), duck-compatible with ColumnarEventLog."""

    kind = "widerow"

    def __init__(self, db_path: Optional[str] = None,
                 bucket_ms: int = 3_600_000):
        self.db_path = db_path
        self.bucket_ms = int(bucket_ms)
        self._lock = threading.RLock()
        if db_path:
            os.makedirs(os.path.dirname(os.path.abspath(db_path)),
                        exist_ok=True)
        self._conn: Optional[sqlite3.Connection] = None
        self._connect()

    def _connect(self) -> None:
        self._conn = sqlite3.connect(self.db_path or ":memory:",
                                     check_same_thread=False)
        self._conn.executescript(_SCHEMA_SQL)
        if self.db_path:
            self._conn.execute("PRAGMA journal_mode=WAL")
        self._conn.commit()

    # -- lifecycle (ColumnarEventLog surface) ------------------------------
    def start(self) -> None:
        """Appends commit synchronously — start only reopens a connection
        a prior stop() closed (instance.restart() cycles stop->start)."""
        with self._lock:
            if self._conn is None:
                self._connect()

    def stop(self) -> None:
        with self._lock:
            if self._conn is None:
                return
            self._conn.commit()
            if self.db_path:
                self._conn.close()
                self._conn = None
            # :memory: connections stay open: closing would drop the data
            # across an engine restart (the in-memory columnar log keeps
            # its segments across stop/start the same way)

    def flush(self) -> None:
        # shutdown ordering: lifecycle teardown may flush components in
        # any order — a flush after stop() is a no-op, not an
        # AttributeError (same for the other post-stop guards below)
        with self._lock:
            if self._conn is None:
                return
            self._conn.commit()

    def flush_tenant(self, tenant: str) -> None:
        self.flush()

    # -- ids ---------------------------------------------------------------
    @staticmethod
    def _next_ids(n: int) -> int:
        # one process-wide locked counter SHARED with the columnar log:
        # both stores derive ids as ev-<_ID_PREFIX>-<seq>, so independent
        # counters would mint colliding ids (and the columnar log's
        # structural id matching would then resolve a widerow id to an
        # unrelated event)
        from sitewhere_tpu.persist.eventlog import ColumnarEventLog
        return ColumnarEventLog._next_ids(n)

    # -- appends -----------------------------------------------------------
    def append_events(self, tenant: str, events: Sequence[DeviceEvent],
                      device_interner=None) -> None:
        """Control-plane append: full document kept per row (lossless for
        every event kind), typed columns mirrored for indexed queries."""
        if not events:
            return
        from sitewhere_tpu.model.common import new_id

        rows = []
        for ev in events:
            doc = ev.to_dict()
            if not doc.get("id"):
                doc["id"] = new_id()
            if isinstance(doc.get("data"), bytes):
                # stream chunks: JSON documents carry the payload hex
                # (decoded back in _materialize)
                doc["data"] = doc["data"].hex()
            idx = 0
            if device_interner is not None and ev.device_id:
                idx = max(0, int(device_interner.lookup(ev.device_id)))
            rows.append((
                tenant, int(ev.event_date) // self.bucket_ms,
                doc["id"], ev.alternate_id or None,
                int(ev.event_type.value), idx,
                ev.device_id or None, ev.device_assignment_id or None,
                ev.customer_id or None, ev.area_id or None,
                ev.asset_id or None, int(ev.event_date),
                int(ev.received_date or ev.event_date),
                0, getattr(ev, "name", None),
                float(getattr(ev, "value", 0.0) or 0.0),
                float(getattr(ev, "latitude", 0.0) or 0.0),
                float(getattr(ev, "longitude", 0.0) or 0.0),
                float(getattr(ev, "elevation", 0.0) or 0.0),
                int(getattr(getattr(ev, "source", None), "value", 0) or 0),
                int(getattr(getattr(ev, "level", None), "value", 0) or 0),
                getattr(ev, "type", None),
                getattr(ev, "message", None),
                getattr(ev, "stream_id", None),
                int(getattr(ev, "sequence_number", 0) or 0),
                getattr(ev, "originating_event_id", None),
                json.dumps(doc),
            ))
        with self._lock:
            if self._conn is None:
                return  # stopped: late append no-ops (shutdown ordering)
            self._conn.executemany(_INSERT_SQL, rows)
            self._conn.commit()

    def append_batch(self, tenant: str, batch, packer,
                     received_ms: Optional[int] = None,
                     registry=None) -> int:
        """Hot-path append from a packed EventBatch: one transaction per
        batch, typed columns only (no per-row document). Same unique-
        device context resolution as the columnar log so index-based list
        queries behave identically."""
        valid = np.asarray(batch.valid)
        n = int(valid.sum())
        if n == 0:
            return 0
        sel = np.nonzero(valid)[0]
        device_idx = np.asarray(batch.device_idx)[sel]
        event_type = np.asarray(batch.event_type)[sel]
        ts = np.add(np.asarray(batch.ts)[sel], packer.epoch_base_ms,
                    dtype=np.int64)
        mm_idx = np.asarray(batch.mm_idx)[sel]
        value = np.asarray(batch.value)[sel]
        lat = np.asarray(batch.lat)[sel]
        lon = np.asarray(batch.lon)[sel]
        elevation = np.asarray(batch.elevation)[sel]
        alert_level = np.asarray(batch.alert_level)[sel]
        alert_type_idx = np.asarray(batch.alert_type_idx)[sel]
        now = received_ms if received_ms is not None \
            else int(time.time() * 1000)

        uniq, inverse = np.unique(device_idx, return_inverse=True)
        u_token = [packer.devices.token_of(int(u)) for u in uniq]
        u_assign = [None] * len(uniq)
        u_customer = [None] * len(uniq)
        u_area = [None] * len(uniq)
        u_asset = [None] * len(uniq)
        if registry is not None:
            for j, token in enumerate(u_token):
                device = (registry.get_device_by_token(token)
                          if token else None)
                assignment = (registry.get_active_assignment(device.id)
                              if device is not None else None)
                if assignment is None:
                    continue
                u_assign[j] = assignment.token
                u_customer[j] = assignment.customer_id or None
                u_area[j] = assignment.area_id or None
                u_asset[j] = assignment.asset_id or None

        mm_map = {int(m): (packer.measurements.token_of(int(m)) or None)
                  for m in np.unique(mm_idx)}
        at_names = {int(a): (packer.alert_types.token_of(int(a)) or None)
                    for a in np.unique(alert_type_idx)}

        base = self._next_ids(n)
        bucket_ms = self.bucket_ms
        rows = []
        for i in range(n):
            j = int(inverse[i])
            et = int(event_type[i])
            rows.append((
                tenant, int(ts[i]) // bucket_ms,
                _derive_id(_ID_PREFIX, base + i), None, et,
                int(device_idx[i]), u_token[j], u_assign[j],
                u_customer[j], u_area[j], u_asset[j],
                int(ts[i]), now, int(mm_idx[i]),
                mm_map[int(mm_idx[i])]
                if et == DeviceEventType.MEASUREMENT.value else None,
                float(value[i]), float(lat[i]), float(lon[i]),
                float(elevation[i]), 0, int(alert_level[i]),
                at_names[int(alert_type_idx[i])]
                if et == DeviceEventType.ALERT.value else None,
                None, None, 0, None, None,
            ))
        with self._lock:
            if self._conn is None:
                return 0  # stopped: late append no-ops (shutdown ordering)
            self._conn.executemany(_INSERT_SQL, rows)
            self._conn.commit()
        return n

    # -- queries -----------------------------------------------------------
    @staticmethod
    def _where(tenant: str, flt: EventFilter) -> Tuple[str, list]:
        clauses, params = ["tenant = ?"], [tenant]
        if flt.event_type is not None:
            clauses.append("event_type = ?")
            params.append(int(flt.event_type.value))
        for field, column in _EQ_COLUMNS.items():
            val = getattr(flt, field)
            if val is not None:
                clauses.append(f"{column} = ?")
                params.append(val)
        if flt.start_date is not None:
            clauses.append("event_date >= ?")
            params.append(int(flt.start_date))
        if flt.end_date is not None:
            clauses.append("event_date <= ?")
            params.append(int(flt.end_date))
        return " AND ".join(clauses), params

    def query(self, tenant: str, flt: EventFilter,
              criteria: Optional[SearchCriteria] = None,
              order_by: str = "event_date_desc"
              ) -> SearchResults[DeviceEvent]:
        criteria = criteria or SearchCriteria()
        import dataclasses as _dc
        flt = _dc.replace(flt)
        if isinstance(criteria, DateRangeCriteria):
            if criteria.start_date is not None and flt.start_date is None:
                flt.start_date = criteria.start_date
            if criteria.end_date is not None and flt.end_date is None:
                flt.end_date = criteria.end_date
        where, params = self._where(tenant, flt)
        order = ("sequence_number ASC, seq ASC"
                 if order_by == "sequence_asc"
                 else "event_date DESC, seq DESC")
        with self._lock:
            if self._conn is None:
                return SearchResults(results=[], num_results=0)
            total = self._conn.execute(
                f"SELECT COUNT(*) FROM events WHERE {where}",
                params).fetchone()[0]
            cur = self._conn.execute(
                f"SELECT * FROM events WHERE {where} ORDER BY {order} "
                f"LIMIT ? OFFSET ?",
                params + [criteria.page_size, criteria.offset])
            names = [d[0] for d in cur.description]
            rows = cur.fetchall()
        events = [self._materialize(dict(zip(names, row))) for row in rows]
        return SearchResults(results=events, num_results=int(total))

    def query_columns(self, tenant: str, flt: EventFilter,
                      names: Sequence[str]) -> Dict[str, np.ndarray]:
        where, params = self._where(tenant, flt)
        cols = ", ".join(names)
        with self._lock:
            rows = ([] if self._conn is None else self._conn.execute(
                f"SELECT {cols} FROM events WHERE {where}",
                params).fetchall())

        def column(i: int, name: str) -> np.ndarray:
            vals = [r[i] for r in rows]
            if name in _I64_NAMES:
                return np.array(vals, dtype=np.int64)
            if name in _I32_NAMES:
                return np.array(vals, dtype=np.int32)
            if name in _F32_NAMES:
                return np.array(vals, dtype=np.float32)
            return np.array(vals, dtype=object)

        return {name: column(i, name) for i, name in enumerate(names)}

    def count(self, tenant: str) -> int:
        with self._lock:
            if self._conn is None:
                return 0
            return self._conn.execute(
                "SELECT COUNT(*) FROM events WHERE tenant = ?",
                (tenant,)).fetchone()[0]

    # -- retention (the time-bucketed layout's point) ----------------------
    def buckets(self, tenant: str) -> List[Tuple[int, int]]:
        """(bucket, rows) pairs, oldest first."""
        with self._lock:
            if self._conn is None:
                return []
            return list(self._conn.execute(
                "SELECT bucket, COUNT(*) FROM events WHERE tenant = ? "
                "GROUP BY bucket ORDER BY bucket", (tenant,)))

    def prune(self, tenant: str, before_ms: int) -> int:
        """Drop every WHOLE bucket strictly older than `before_ms` — the
        wide-row layout's cheap retention path (delete by partition key,
        never row-by-row scans)."""
        cutoff_bucket = int(before_ms) // self.bucket_ms
        with self._lock:
            if self._conn is None:
                return 0
            cur = self._conn.execute(
                "DELETE FROM events WHERE tenant = ? AND bucket < ?",
                (tenant, cutoff_bucket))
            self._conn.commit()
            return cur.rowcount

    # -- materialization ---------------------------------------------------
    @staticmethod
    def _materialize(row: Dict) -> DeviceEvent:
        if row.get("doc"):
            doc = json.loads(row["doc"])
            if isinstance(doc.get("data"), str):
                doc["data"] = bytes.fromhex(doc["data"])
            return event_from_dict(doc)
        etype = DeviceEventType(int(row["event_type"]))
        common = dict(
            id=row["id"] or "", alternate_id=row["alternate_id"] or "",
            event_type=etype, device_id=row["device_token"] or "",
            device_assignment_id=row["assignment_token"] or "",
            customer_id=row["customer_id"] or "",
            area_id=row["area_id"] or "", asset_id=row["asset_id"] or "",
            event_date=int(row["event_date"]),
            received_date=int(row["received_date"]), metadata={})
        if etype == DeviceEventType.LOCATION:
            return DeviceLocation(
                **common, latitude=float(row["latitude"]),
                longitude=float(row["longitude"]),
                elevation=float(row["elevation"]))
        if etype == DeviceEventType.ALERT:
            return DeviceAlert(
                **common, source=AlertSource(int(row["alert_source"])),
                level=AlertLevel(int(row["alert_level"])),
                type=row["alert_type"] or "",
                message=row["alert_message"] or "")
        return DeviceMeasurement(**common, name=row["mm_name"] or "",
                                 value=float(row["value"]))
