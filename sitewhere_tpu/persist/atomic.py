"""Crash-safe file writes: fsync + rename + content digests.

Shared by the checkpoint writer (persist/checkpoint.py) and the eventlog
segment sealer (persist/eventlog.py). The contract:

  write tmp -> fsync(tmp) -> rename -> fsync(parent dir)

so a crash at any instant leaves either the old state or the complete
new state — never a torn file that the next boot trusts. Checkpoint
directories additionally carry a ``digest.json`` (sha256 per payload
file) so a restore can *verify* completeness instead of assuming it, and
quarantine what fails.
"""

from __future__ import annotations

import hashlib
import json
import os
from typing import Dict, Optional

DIGEST_NAME = "digest.json"


def fsync_file(path: str) -> None:
    fd = os.open(path, os.O_RDONLY)
    try:
        os.fsync(fd)
    finally:
        os.close(fd)


def fsync_dir(path: str) -> None:
    """Durably record a rename/create in its parent directory. Some
    platforms refuse O_RDONLY on directories — best-effort there."""
    try:
        fd = os.open(path, os.O_RDONLY)
    except OSError:
        return
    try:
        os.fsync(fd)
    except OSError:
        pass
    finally:
        os.close(fd)


def file_digest(path: str) -> str:
    h = hashlib.sha256()
    with open(path, "rb") as fh:
        for chunk in iter(lambda: fh.read(1 << 20), b""):
            h.update(chunk)
    return h.hexdigest()


def write_digest_manifest(directory: str) -> None:
    """Write `digest.json` covering every regular file in `directory`
    (itself excluded), fsyncing payloads first so the digest never
    describes bytes that did not reach the platter."""
    digests: Dict[str, str] = {}
    for name in sorted(os.listdir(directory)):
        if name == DIGEST_NAME:
            continue
        path = os.path.join(directory, name)
        if not os.path.isfile(path):
            continue
        fsync_file(path)
        digests[name] = file_digest(path)
    digest_path = os.path.join(directory, DIGEST_NAME)
    with open(digest_path, "w", encoding="utf-8") as fh:
        json.dump(digests, fh)
        fh.flush()
        os.fsync(fh.fileno())


def verify_digest_manifest(directory: str) -> Optional[bool]:
    """True = every digest matches; False = torn/corrupt; None = no
    digest.json (a pre-digest legacy write — caller decides trust)."""
    digest_path = os.path.join(directory, DIGEST_NAME)
    if not os.path.exists(digest_path):
        return None
    try:
        with open(digest_path, encoding="utf-8") as fh:
            digests = json.load(fh)
        for name, expect in digests.items():
            path = os.path.join(directory, name)
            if not os.path.isfile(path) or file_digest(path) != expect:
                return False
    except (OSError, ValueError):
        return False
    return True
