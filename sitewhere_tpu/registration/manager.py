"""Device auto-registration manager.

Reference: service-device-registration DefaultRegistrationManager.java:39 —
consumes inbound-device-registration-events (decoded registration requests
routed by the event sources, InboundEventSource -> registration topic) and
inbound-unregistered-device-events (events from devices the validation step
didn't recognize), creates device + assignment when allowed
(handleDeviceRegistration :81), and answers with a RegistrationAck system
command through command delivery (:226).
"""

from __future__ import annotations

import enum
import logging
from typing import List, Optional

import msgpack

from sitewhere_tpu.errors import SiteWhereError
from sitewhere_tpu.model.device import Device, DeviceAssignment
from sitewhere_tpu.model.event import DeviceRegistrationRequest
from sitewhere_tpu.runtime.bus import ConsumerHost, EventBus, Record, TopicNaming
from sitewhere_tpu.runtime.lifecycle import LifecycleComponent
from sitewhere_tpu.runtime.metrics import MetricsRegistry
from sitewhere_tpu.transport.wire import MessageType, WireCodec

LOGGER = logging.getLogger("sitewhere.registration")


class RegistrationAckState(enum.Enum):
    """RegistrationAckState in sitewhere.proto:36-47."""

    NEW_REGISTRATION = "NEW_REGISTRATION"
    ALREADY_REGISTERED = "ALREADY_REGISTERED"
    REGISTRATION_ERROR = "REGISTRATION_ERROR"


class RegistrationManager(LifecycleComponent):
    """Per-tenant registration engine.

    Options mirror DefaultRegistrationManager: `allow_new_devices`, and
    fallback tokens used when a request omits its device type / area.
    `command_delivery` (a CommandDeliveryService) is optional — without it
    acks are only counted, not sent.
    """

    def __init__(self, bus: EventBus, registry, tenant: str = "default",
                 naming: Optional[TopicNaming] = None,
                 allow_new_devices: bool = True,
                 default_device_type_token: Optional[str] = None,
                 default_area_token: Optional[str] = None,
                 auto_assign: bool = True,
                 command_delivery=None,
                 metrics: Optional[MetricsRegistry] = None):
        super().__init__(f"registration:{tenant}")
        self.bus = bus
        self.registry = registry
        self.tenant = tenant
        self.naming = naming or TopicNaming()
        self.allow_new_devices = allow_new_devices
        self.default_device_type_token = default_device_type_token
        self.default_area_token = default_area_token
        self.auto_assign = auto_assign
        self.command_delivery = command_delivery
        m = (metrics or MetricsRegistry()).scoped("registration")
        self.registered_counter = m.counter("registered")
        self.rejected_counter = m.counter("rejected")
        self._registration_host = ConsumerHost(
            bus, self.naming.inbound_device_registration_events(tenant),
            group_id=f"registration-{tenant}", handler=self._process)
        self._unregistered_host = ConsumerHost(
            bus, self.naming.inbound_unregistered_device_events(tenant),
            group_id=f"registration-unreg-{tenant}",
            handler=self._process_unregistered)

    def on_start(self, monitor) -> None:
        self._registration_host.start()
        self._unregistered_host.start()

    def on_stop(self, monitor) -> None:
        self._registration_host.stop()
        self._unregistered_host.stop()

    # -- registration topic ------------------------------------------------
    def _process(self, records: List[Record]) -> None:
        for record in records:
            try:
                data = msgpack.unpackb(record.value, raw=False)
                request = DeviceRegistrationRequest(**{
                    k: v for k, v in data["request"].items()
                    if k in DeviceRegistrationRequest.__dataclass_fields__})
                if not request.device_token:
                    request.device_token = data.get("deviceToken", "")
            except Exception:
                self.rejected_counter.inc()
                continue
            try:
                self.handle_registration(request)
            except Exception as exc:
                LOGGER.warning("registration failed for '%s': %s",
                               request.device_token, exc)
                self.rejected_counter.inc()
                self._ack(request.device_token,
                          RegistrationAckState.REGISTRATION_ERROR, str(exc))

    def handle_registration(self, request: DeviceRegistrationRequest
                            ) -> Device:
        """handleDeviceRegistration :81 — create-or-acknowledge."""
        existing = self.registry.get_device_by_token(request.device_token)
        if existing is not None:
            self._ack(request.device_token,
                      RegistrationAckState.ALREADY_REGISTERED)
            return existing
        if not self.allow_new_devices:
            # counting + error ack happen in _process's catch; direct callers
            # (REST, tests) see the raise
            raise SiteWhereError("new device registration is not allowed")
        type_token = (request.device_type_token
                      or self.default_device_type_token)
        if not type_token:
            raise SiteWhereError("no device type for registration")
        # Resolve everything BEFORE creating the device: a half-registered
        # device (no assignment) would ack ALREADY_REGISTERED on retry and
        # never become able to send events.
        device_type = self.registry.get_device_type_by_token(type_token)
        area_id = ""
        customer_id = ""
        if self.auto_assign:
            area_token = request.area_token or self.default_area_token
            if area_token:
                area_id = self.registry.get_area_by_token(area_token).id
            if request.customer_token:
                customer = self.registry.customers.get_by_token(
                    request.customer_token)
                customer_id = customer.id if customer else ""
        device = self.registry.create_device(Device(
            token=request.device_token, device_type_id=device_type.id,
            metadata=dict(request.metadata)))
        if self.auto_assign:
            self.registry.create_device_assignment(DeviceAssignment(
                device_id=device.id, area_id=area_id,
                customer_id=customer_id))
        self.registered_counter.inc()
        self._ack(request.device_token, RegistrationAckState.NEW_REGISTRATION)
        return device

    # -- unregistered-device events ---------------------------------------
    def _process_unregistered(self, records: List[Record]) -> None:
        """Devices that sent data without being registered: auto-register
        when a default device type is configured, else just count — the
        reference sends a RegistrationRequired prompt here."""
        for record in records:
            token = record.key.decode("utf-8", "replace")
            if not token or self.registry.get_device_by_token(token):
                continue
            if self.allow_new_devices and self.default_device_type_token:
                try:
                    self.handle_registration(
                        DeviceRegistrationRequest(device_token=token))
                except Exception:
                    self.rejected_counter.inc()
            else:
                self.rejected_counter.inc()

    # -- acks --------------------------------------------------------------
    def _ack(self, device_token: str, state: RegistrationAckState,
             reason: str = "") -> None:
        if self.command_delivery is None or not device_token:
            return
        from sitewhere_tpu.commands.encoding import SystemCommand
        payload = WireCodec.encode_register_ack(device_token, state.value,
                                                reason)
        try:
            self.command_delivery.send_system_command(
                device_token, SystemCommand(MessageType.REGISTER_ACK, payload))
        except SiteWhereError:
            pass  # device may not exist on error acks; nothing to deliver to
