"""Device auto-registration (reference: service-device-registration)."""

from sitewhere_tpu.registration.manager import (
    RegistrationAckState, RegistrationManager)

__all__ = ["RegistrationAckState", "RegistrationManager"]
