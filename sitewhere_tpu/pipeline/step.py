"""The fused pipeline step: validate + rules + device-state in ONE jit.

This function is the TPU rebuild of the reference's entire hot path
(SURVEY.md §3.2-3.3). What the reference does with five microservices, three
Kafka round-trips and two gRPC hops per event —
  InboundPayloadProcessingLogic (validate, gRPC device lookup)
  -> UnaryEventStorageStrategy (gRPC persist per event)
  -> OutboundPayloadEnrichmentLogic (re-fetch + enrich)
  -> ZoneTestRuleProcessor (JTS containment per event)
  -> DeviceStateProcessingLogic (Mongo upsert per event)
— happens here as one XLA program over an 8k-event batch: gathers against the
registry mirror replace the gRPC lookups, broadcast compares replace the rule
hosts, keyed reductions replace the Mongo upserts. Stage boundaries are
registers/HBM, not broker round-trips.

Persistence (the reference's event-management store) is intentionally NOT in
the jit: the host appends the raw batch to the columnar event log
(persist/eventlog.py) in parallel with device compute.
"""

from __future__ import annotations

from typing import Tuple

import jax
import jax.numpy as jnp
from flax import struct

from sitewhere_tpu.model.event import DeviceEventType
from sitewhere_tpu.ops.compact import (
    DEFAULT_ALERT_LANE_CAPACITY, compact_alert_lanes,
)
from sitewhere_tpu.ops.geofence import (
    GeofenceRuleTable, ZoneTable, eval_geofence_rules,
)
from sitewhere_tpu.ops.pack import EventBatch
from sitewhere_tpu.ops.segments import (
    batch_device_order, count_by_key, last_by_key, scatter_max_by_key,
)
from sitewhere_tpu.ops.actuate import (
    COMMAND_LANE_ROWS, DEFAULT_COMMAND_LANE_CAPACITY,
    ActuationStateTensors, eval_actuation_policies,
)
from sitewhere_tpu.ops.anomaly import ModelStateTensors, eval_anomaly_models
from sitewhere_tpu.ops.stateful import (
    RuleStateTensors, eval_rule_programs, observations_of_batch,
)
from sitewhere_tpu.ops.threshold import ThresholdRuleTable, eval_threshold_rules
from sitewhere_tpu.pipeline.state_tensors import DeviceStateTensors
from sitewhere_tpu.actuation.compiler import ActuationPolicyTable
from sitewhere_tpu.ml.compiler import AnomalyModelTable
from sitewhere_tpu.rules.compiler import RuleProgramTable

_NEG = -(2 ** 31)


@struct.dataclass
class PipelineParams:
    """Everything the step reads but does not write: registry mirror + rule
    tables. A pytree of device arrays; contents change without recompiling."""

    # registry mirror (registry/tensors.py), [D]
    assignment_status: jnp.ndarray
    tenant_idx: jnp.ndarray
    area_idx: jnp.ndarray
    device_type_idx: jnp.ndarray
    # rule tables
    threshold: ThresholdRuleTable
    zones: ZoneTable
    geofence: GeofenceRuleTable
    # compiled rule programs (rules/compiler.py); replicated like the
    # other rule tables on sharded meshes
    programs: RuleProgramTable
    # compiled anomaly-model weight tables (ml/compiler.py); also
    # replicated — features ride the shard axis, weights don't
    models: AnomalyModelTable
    # compiled actuation policies (actuation/compiler.py); replicated —
    # debounce state rides the shard axis, the policy table doesn't
    policies: ActuationPolicyTable


@struct.dataclass
class ProcessOutputs:
    """Per-batch outputs consumed host-side (alert materialization, failed
    events -> registration topic, stats)."""

    valid: jnp.ndarray              # bool [B] passed validation
    unregistered: jnp.ndarray       # bool [B] had no active assignment
    threshold_fired: jnp.ndarray    # bool [B]
    threshold_first_rule: jnp.ndarray  # int32 [B]
    threshold_alert_level: jnp.ndarray  # int32 [B]
    geofence_fired: jnp.ndarray     # bool [B]
    geofence_first_rule: jnp.ndarray   # int32 [B]
    geofence_alert_level: jnp.ndarray  # int32 [B]
    # composite rule-program fires mapped to their attach rows (the
    # device's last tracked-measurement row this step — ops/stateful.py)
    program_fired: jnp.ndarray      # bool [B]
    program_first_rule: jnp.ndarray    # int32 [B] program slot, -1 = none
    program_alert_level: jnp.ndarray   # int32 [B]
    # anomaly-model scoring fires, also attach-row mapped (ops/anomaly.py)
    model_fired: jnp.ndarray        # bool [B]
    model_first: jnp.ndarray        # int32 [B] model slot, -1 = none
    model_level: jnp.ndarray        # int32 [B] max fired level, -1 = none
    model_score: jnp.ndarray        # f32 [B] lowest scored slot's score
    tenant_counts: jnp.ndarray      # int32 [T] events this batch per tenant
    processed: jnp.ndarray          # int32 scalar, valid events
    alerts: jnp.ndarray             # int32 scalar, alerts fired
    # device-compacted alert lanes (ops/compact.py): fired rows packed by
    # prefix sum into a fixed [ALERT_LANE_ROWS, K] int32 array so alert
    # materialization is ONE tiny fixed-shape D2H fetch per step — the
    # per-row masks above stay for device-side consumers and tests; the
    # host fast path never fetches them
    alert_lanes: jnp.ndarray        # int32 [ALERT_LANE_ROWS, K]
    # device-compacted command lane (ops/actuate.py): actuation-policy
    # fires packed the same way into a SECOND fixed [4, K_cmd] int32
    # array, fetched in the SAME materialize pass as the alert lanes —
    # the fetch budget is exactly TWO fixed-shape arrays per step
    command_lanes: jnp.ndarray      # int32 [COMMAND_LANE_ROWS, K_cmd]


def process_batch(params: PipelineParams, state: DeviceStateTensors,
                  rule_state: RuleStateTensors,
                  model_state: ModelStateTensors,
                  actuation_state: ActuationStateTensors,
                  batch: EventBatch, *,
                  geofence_impl: str = "xla",
                  alert_lane_capacity: int = DEFAULT_ALERT_LANE_CAPACITY,
                  programs_enabled: bool = True,
                  program_node_limit: int = 0,
                  models_enabled: bool = True,
                  actuation_enabled: bool = True,
                  command_lane_capacity: int = DEFAULT_COMMAND_LANE_CAPACITY
                  ) -> Tuple[DeviceStateTensors, RuleStateTensors,
                             ModelStateTensors, ActuationStateTensors,
                             ProcessOutputs]:
    """One fused step. Shapes static; jit/shard_map safe; donate `state`,
    `rule_state`, `model_state` and `actuation_state`.

    `geofence_impl` selects the containment kernel ("xla" scan,
    "pallas" TPU kernel, "pallas_interpret" for CPU tests) — resolved by the
    engines via ops.geofence.resolve_geofence_impl.
    `alert_lane_capacity` is the K of the compacted alert lanes (static;
    one cached program per capacity like any other shape).
    `programs_enabled` (trace-time static) drops the whole rule-program
    stage when no programs are installed, so the empty-table common case
    costs nothing on the hot path (the engines rebuild the jit on the
    rare empty<->non-empty transition, like any other shape change).
    `program_node_limit` (also static) trims the unrolled node pass to
    the slots the compiled table populates.
    `models_enabled` (trace-time static) likewise drops the anomaly-model
    scoring stage when the model table is empty.
    `actuation_enabled` (trace-time static) drops the actuation stage
    when no policies are installed — the command lane is then a zero
    placeholder so the materialize fetch shape never changes.
    `command_lane_capacity` is the K of the compacted command lane.
    """
    D = state.num_devices
    M = state.num_measurement_slots
    T = state.tenant_event_count.shape[0]

    # ---- stage 1: validation (replaces gRPC hop #1 + assignment check) -----
    # Unknown tokens intern to index 0 whose registry row always holds
    # status 0, so a single status gather covers both "unknown device" and
    # "no active assignment" (local index 0 is a real device on shards > 0).
    # named_scope labels carry the flight recorder's stage vocabulary into
    # device profiler traces (trace-time only, no runtime cost).
    with jax.named_scope("step_validate"):
        status = params.assignment_status[batch.device_idx]      # gather [B]
        registered = status == 1  # DeviceAssignmentStatus.ACTIVE
        unregistered = batch.valid & ~registered
        valid = batch.valid & registered
        tenant = params.tenant_idx[batch.device_idx]
        device_type = params.device_type_idx[batch.device_idx]
        batch = batch.replace(tenant_idx=tenant, valid=valid)

    # ---- stage 2: rule evaluation (replaces rule-processing service) -------
    with jax.named_scope("step_rules"):
        thr = eval_threshold_rules(batch, params.threshold, device_type)
        geo = eval_geofence_rules(batch, params.zones, params.geofence,
                                  impl=geofence_impl)

    # ---- stage 3: device-state fold (replaces device-state service) --------
    with jax.named_scope("step_state_fold"):
        dev = batch.device_idx
        ts = batch.ts
        last_interaction = scatter_max_by_key(dev, ts, valid, D,
                                              state.last_interaction)
        event_count = state.event_count + count_by_key(dev, valid, D)

        # presence restore: any device with a valid event is present again
        touched = count_by_key(dev, valid, D) > 0
        present = state.present | touched
        presence_missing_since = jnp.where(touched, _NEG,
                                           state.presence_missing_since)

        # last location (location events only)
        is_loc = valid & (batch.event_type == DeviceEventType.LOCATION)
        loc_vals = jnp.stack([batch.lat, batch.lon, batch.elevation], axis=1)
        loc_ts, (last_location,) = last_by_key(
            dev, ts, is_loc, D, state.last_location_ts,
            (state.last_location,), (loc_vals,))

        # last measurement per (device, slot<M)
        is_mm = (valid & (batch.event_type == DeviceEventType.MEASUREMENT)
                 & (batch.mm_idx < M))
        mm_key = dev * M + batch.mm_idx
        mm_ts_flat, (mm_val_flat,) = last_by_key(
            mm_key, ts, is_mm, D * M, state.last_measurement_ts.reshape(-1),
            (state.last_measurement.reshape(-1),), (batch.value,))
        last_measurement_ts = mm_ts_flat.reshape(D, M)
        last_measurement = mm_val_flat.reshape(D, M)

        # last alert per device (device-sent alerts; rule-fired alerts
        # merge on the next batch once materialized as events)
        is_alert = valid & (batch.event_type == DeviceEventType.ALERT)
        alert_ts, (last_alert_type, last_alert_level) = last_by_key(
            dev, ts, is_alert, D, state.last_alert_ts,
            (state.last_alert_type, state.last_alert_level),
            (batch.alert_type_idx, batch.alert_level))

    # ---- stage 3b: stateful rule programs (CEP-lite; ops/stateful.py) ------
    # Runs BETWEEN the built-in rules and the stats so composite fires
    # feed the same alert-lane compaction; reads the POST-fold
    # measurement state so conditions across measurements that arrived in
    # different events compose. Dropped at trace time when no programs
    # are installed.
    B = batch.device_idx.shape[0]
    if programs_enabled or models_enabled:
        # the observation masks and attach rows feed BOTH stateful stages.
        # ONE shared stable argsort groups batch rows by device so both
        # kernels' HBM slab gathers and attach scatters run over
        # contiguous device segments; per-row outputs un-sort with the
        # inverse permutation. Per-row math depends only on own-row
        # inputs and the attach scatter targets are unique, so results
        # are bit-identical to the unsorted evaluation.
        obs_mm, _touched, now_d, attach_row = observations_of_batch(
            batch, M, D)
        order, inv = batch_device_order(dev)
        sdev = dev[order]
        sattach = attach_row[order]
        s_obs = obs_mm[sdev]
        s_lm = last_measurement[sdev]
        s_lmts = last_measurement_ts[sdev]
        s_tenant = params.tenant_idx[sdev]
        s_dtype = params.device_type_idx[sdev]
    if programs_enabled:
        with jax.named_scope("step_rule_programs"):
            # per-ROW evaluation over attach-sorted rows: state gathers/
            # scatters ride contiguous device segments (attach rows are
            # the unique writers), so program evaluation costs O(batch),
            # not O(device capacity)
            rule_state, prog = eval_rule_programs(
                params.programs, rule_state,
                dev=sdev, attach=sattach,
                obs_row=s_obs, now_row=now_d[sdev],
                lm_row=s_lm, lmts_row=s_lmts,
                tenant_row=s_tenant, dtype_row=s_dtype,
                node_limit=program_node_limit)
            prog = {k: v[inv] for k, v in prog.items()}
    else:
        prog = {"fired": jnp.zeros((B,), bool),
                "first_rule": jnp.full((B,), -1, jnp.int32),
                "alert_level": jnp.full((B,), -1, jnp.int32)}

    # ---- stage 3c: anomaly-model scoring (ops/anomaly.py) ------------------
    # After the rule programs so both stateful stages read the same
    # post-fold measurement state; fires ride the spare alert-lane meta
    # bits, so the one-fetch-per-step budget is untouched. Dropped at
    # trace time when no models are installed, like the programs stage.
    if models_enabled:
        with jax.named_scope("step_model_eval"):
            model_state, model = eval_anomaly_models(
                params.models, model_state,
                dev=sdev, attach=sattach,
                obs_row=s_obs,
                lm_row=s_lm, lmts_row=s_lmts,
                tenant_row=s_tenant, dtype_row=s_dtype)
            model = {k: v[inv] for k, v in model.items()}
    else:
        model = {"fired": jnp.zeros((B,), bool),
                 "first_model": jnp.full((B,), -1, jnp.int32),
                 "alert_level": jnp.full((B,), -1, jnp.int32),
                 "score": jnp.zeros((B,), jnp.float32)}

    # ---- stage 3d: actuation policies (ops/actuate.py) ---------------------
    # After every alert family has fired so policies see the step's full
    # fire bits; per-(device, policy) debounce state advances in HBM and
    # fired commands compact into the second fixed-shape lane. Dropped
    # at trace time when no policies are installed.
    if actuation_enabled:
        with jax.named_scope("step_actuate"):
            actuation_state, command_lanes = eval_actuation_policies(
                params.policies, actuation_state,
                dev=dev, ts=ts, tenant_row=tenant,
                thr=thr, geo=geo, prog=prog, model=model,
                capacity=command_lane_capacity)
    else:
        # fixed-shape placeholder: the materialize pass always fetches
        # two lanes, so enabling actuation never changes the fetch count
        command_lanes = jnp.zeros(
            (COMMAND_LANE_ROWS, command_lane_capacity), jnp.int32)

    # ---- stage 4: stats (replaces Dropwizard meters / Kafka state topics) --
    with jax.named_scope("step_stats_compact"):
        tenant_counts = count_by_key(tenant, valid, T)
        alerts = (jnp.sum(thr["fired"], dtype=jnp.int32)
                  + jnp.sum(geo["fired"], dtype=jnp.int32)
                  + jnp.sum(prog["fired"], dtype=jnp.int32)
                  + jnp.sum(model["fired"], dtype=jnp.int32))
        alert_lanes = compact_alert_lanes(thr, geo, alert_lane_capacity,
                                          prog, model)

    new_state = DeviceStateTensors(
        last_interaction=last_interaction,
        present=present,
        presence_missing_since=presence_missing_since,
        event_count=event_count,
        last_location=last_location,
        last_location_ts=loc_ts,
        last_measurement=last_measurement,
        last_measurement_ts=last_measurement_ts,
        last_alert_type=last_alert_type,
        last_alert_level=last_alert_level,
        last_alert_ts=alert_ts,
        tenant_event_count=state.tenant_event_count + tenant_counts,
        tenant_alert_count=state.tenant_alert_count + count_by_key(
            tenant,
            valid & (thr["fired"] | geo["fired"] | prog["fired"]
                     | model["fired"]),
            T),
    )
    outputs = ProcessOutputs(
        valid=valid,
        unregistered=unregistered,
        threshold_fired=thr["fired"],
        threshold_first_rule=thr["first_rule"],
        threshold_alert_level=thr["alert_level"],
        geofence_fired=geo["fired"],
        geofence_first_rule=geo["first_rule"],
        geofence_alert_level=geo["alert_level"],
        program_fired=prog["fired"],
        program_first_rule=prog["first_rule"],
        program_alert_level=prog["alert_level"],
        model_fired=model["fired"],
        model_first=model["first_model"],
        model_level=model["alert_level"],
        model_score=model["score"],
        tenant_counts=tenant_counts,
        processed=jnp.sum(valid, dtype=jnp.int32),
        alerts=alerts,
        alert_lanes=alert_lanes,
        command_lanes=command_lanes,
    )
    return new_state, rule_state, model_state, actuation_state, outputs


def check_presence(state: DeviceStateTensors, registered: jnp.ndarray,
                   now_rel: jnp.ndarray, missing_interval_ms: jnp.ndarray
                   ) -> Tuple[DeviceStateTensors, jnp.ndarray]:
    """Periodic presence sweep (replaces DevicePresenceManager's
    PresenceChecker thread, DevicePresenceManager.java:110-135).

    A registered device that has interacted before and whose last interaction
    is older than `missing_interval_ms` transitions to NOT_PRESENT exactly
    once (send-once notification strategy): returns the newly-missing mask so
    the host can emit PresenceState change events.
    """
    has_interacted = state.last_interaction > _NEG
    overdue = (now_rel - state.last_interaction) > missing_interval_ms
    newly_missing = registered & has_interacted & state.present & overdue
    new_state = state.replace(
        present=state.present & ~newly_missing,
        presence_missing_since=jnp.where(newly_missing, now_rel,
                                         state.presence_missing_since),
    )
    return new_state, newly_missing
