"""The hot event path + domain services.

The reference runs the hot path as five microservices exchanging Kafka batches
(SURVEY.md §3.2-3.3: event-sources -> inbound-processing -> event-management ->
enrichment -> {rule-processing, device-state, outbound-connectors}); here those
stages fuse into ONE jit-compiled step over an EventBatch
(pipeline/step.py::process_batch), and the surrounding services (sources,
registration, command delivery, connectors, batch ops, schedules) run host-side
around it.
"""

from sitewhere_tpu.pipeline.state_tensors import DeviceStateTensors, init_device_state
from sitewhere_tpu.pipeline.step import PipelineParams, ProcessOutputs, process_batch
from sitewhere_tpu.pipeline.engine import PipelineEngine

__all__ = ["DeviceStateTensors", "init_device_state", "PipelineParams",
           "ProcessOutputs", "process_batch", "PipelineEngine"]
