"""Pipelined host->device feeding: overlap staging with device compute.

VERDICT r2 finding: the single-chip headline path left the TPU ~3.5% busy —
the device program finishes in ~0.11 ms while ~2.9 ms of host pack + H2D
staging serialized ahead of every step. The fix is the classic
double-buffered accelerator input pipeline (the reference's nearest analog
is the DeviceEventBuffer linger thread that stages bulk writes ahead of
Mongo, DeviceEventBuffer.java:99-123 — applied here to the accelerator
boundary instead of the datastore):

  stager thread(s):  pack batch N+1 into a rotating wire-blob buffer and
                     start its H2D transfer (jax.device_put is async)
  step thread:       dispatch the fused step for batch N in submission
                     order (state donation serializes execution anyway)

Throughput becomes max(host_stage_time, device_step_time) instead of their
sum. With 2+ stagers, pack of batch N+2 also overlaps the (possibly
synchronous, on tunneled runtimes) transfer of batch N+1.

Ordering: steps are dispatched strictly in submission order (sequence
numbers; the step thread waits for the next sequence), so per-device event
order — the bus's per-key ordering contract — is preserved even though
stagers pack concurrently.
"""

from __future__ import annotations

import heapq
import queue
import threading
import time
from typing import List, Optional

import jax
import numpy as np

from sitewhere_tpu.ops.pack import EventBatch, batch_to_blob
from sitewhere_tpu.runtime.faults import FaultError, fault_point


def _stage_window(depth: int, engine) -> int:
    """How far ahead of the dispatch cursor a stager may run (allowed:
    seq - next_step <= window). Bounded by the engine's H2D staging-ring
    depth: with window <= ring_depth - 1, the slots held by sequences
    LATER than the earliest unstaged one can never fill the ring, so the
    ordered ring grant (pipeline/staging.py) always reaches it — the
    pigeonhole half of the deadlock-freedom argument. Ring depth 1
    degenerates to window 0: stage strictly in dispatch order (today's
    serial transfer behavior, the differential-test baseline)."""
    ring_depth = int(getattr(engine, "h2d_buffer_depth", depth))
    return min(max(1, depth), max(0, ring_depth - 1))


class StepFuture:
    """Result handle for one pipelined submit."""

    __slots__ = ("_event", "_outputs", "_error")

    def __init__(self):
        self._event = threading.Event()
        self._outputs = None
        self._error: Optional[BaseException] = None

    def done(self) -> bool:
        return self._event.is_set()

    def result(self, timeout: Optional[float] = None):
        """The step's ProcessOutputs (dispatch-complete, not necessarily
        device-complete — block_until_ready a field for that)."""
        if not self._event.wait(timeout):
            raise TimeoutError("step not dispatched within timeout")
        if self._error is not None:
            raise self._error
        return self._outputs

    def _resolve(self, outputs=None, error: Optional[BaseException] = None):
        self._outputs = outputs
        self._error = error
        self._event.set()


class _PrePackedBlob:
    """A host wire blob that arrived already packed (a feeder's remote
    pack, feeders/service.py): the stager skips the pack stage and goes
    straight to the staging-ring grant + H2D."""

    __slots__ = ("blob", "n_events")

    def __init__(self, blob: np.ndarray, n_events: int):
        self.blob = blob
        self.n_events = int(n_events)


class PipelinedSubmitter:
    """Stage-ahead feeder for a PipelineEngine.

    `submit(batch)` enqueues and returns a StepFuture immediately (blocking
    only when `depth` batches are already in flight — natural backpressure).
    `stagers` host threads pack + device_put ahead; one step thread
    dispatches in order. Call `flush()` to drain and get the last outputs,
    `close()` to stop the threads.

    Works with the single-chip PipelineEngine (the sharded engine's
    submit() already overlaps routing with the previous step's execution
    because dispatch is async; its host routing is a single fused native
    pass — see parallel/router.py route_batch).
    """

    def __init__(self, engine, depth: int = 3, stagers: int = 2):
        self.engine = engine
        self.depth = max(1, depth)
        self._in: "queue.Queue" = queue.Queue(maxsize=self.depth)
        self._ready_lock = threading.Condition()
        self._ready: List = []          # heap of (seq, blob, n, future)
        self._next_seq = 0              # next sequence to assign
        self._next_step = 0             # next sequence to dispatch
        self._dispatched = 0            # steps whose dispatch has RETURNED
        self._stage_window = _stage_window(self.depth, engine)
        self._stop = threading.Event()
        self._close_lock = threading.Lock()  # atomic submit-vs-close gate
        self._stagers = [
            threading.Thread(target=self._stage_loop, name=f"feed-stage-{i}",
                             daemon=True)
            for i in range(max(1, stagers))]
        self._step_thread = threading.Thread(target=self._step_loop,
                                             name="feed-step", daemon=True)
        for t in self._stagers:
            t.start()
        self._step_thread.start()

    # -- producer ----------------------------------------------------------
    def submit(self, batch: EventBatch, age=None) -> StepFuture:
        fut = StepFuture()
        item = (self._alloc_seq(), batch, fut, age)
        # closure check and enqueue are atomic under _close_lock: close()
        # sets _stop under the same lock, so once close() proceeds to
        # drain, no producer can slip an item into the unattended queue
        # (a sleep-based window would lose the future forever on a
        # descheduled producer). The lock is never held across a blocking
        # put — full queues back off outside it.
        while True:
            with self._close_lock:
                if self._stop.is_set():
                    raise RuntimeError("submitter closed")
                try:
                    self._in.put_nowait(item)
                    return fut
                except queue.Full:
                    pass
            time.sleep(0.005)

    def _alloc_seq(self) -> int:
        with self._ready_lock:
            seq = self._next_seq
            self._next_seq += 1
            return seq

    def submit_blob(self, blob: np.ndarray, n_events: int,
                    age=None) -> StepFuture:
        """Enqueue a PRE-PACKED host wire blob (a feeder's remote pack,
        feeders/service.py): same ordered stage->dispatch path as
        submit(), minus the pack stage — interleaves correctly with
        concurrent submit() calls because both draw from the one
        sequence counter."""
        return self.submit(_PrePackedBlob(blob, n_events), age=age)

    # -- stager ------------------------------------------------------------
    def _stage_loop(self) -> None:
        while not self._stop.is_set():
            try:
                seq, batch, fut, age = self._in.get(timeout=0.1)
            except queue.Empty:
                continue
            # Bound the staged-ahead window: without this the ready heap
            # (and its device-resident blobs) would grow without limit
            # whenever staging outpaces dispatch, and a staging-ring slot
            # could be repacked while its H2D copy was still in flight.
            # The window is additionally capped at h2d_buffer_depth - 1
            # (_stage_window) so the ordered ring grant always reaches
            # the earliest unstaged sequence — the deadlock-freedom
            # invariant of the on-device staging ring.
            with self._ready_lock:
                while (not self._stop.is_set()
                       and seq - self._next_step > self._stage_window):
                    self._ready_lock.wait(timeout=0.1)
            if self._stop.is_set():
                fut._resolve(error=RuntimeError("submitter closed"))
                continue
            try:
                fault_point("feeder_thread_death")
                # flight record opened HERE on the stager thread and
                # handed to the step thread inside the heap item — the
                # explicit trace-context handoff that thread-local span
                # stacks cannot express. pack/guard/h2d land on this
                # thread; dispatch lands on the step thread; both sides
                # share one monotonic clock so overlap is computable.
                rec = self.engine.flight.begin_step(engine=self.engine.name)
                if age is not None:
                    # the ingest-age sidecar crosses threads on the record
                    # itself, exactly like the stage timeline
                    rec.age = age
                if isinstance(batch, _PrePackedBlob):
                    # a feeder's remote pack: no pack stage on this host —
                    # the blob goes straight to the ring grant + H2D, the
                    # whole point of the disaggregated fleet
                    blob = np.ascontiguousarray(batch.blob)
                    n = batch.n_events
                else:
                    buf = self.engine._staging_blob_buffer(batch,
                                                           flight_rec=rec)
                    rec.begin_stage("pack")
                    blob = batch_to_blob(batch, out=buf)
                    rec.end_stage("pack")
                    n = int(np.asarray(batch.valid).sum())
                # acquire an on-device staging-ring slot (granted in seq
                # order; backpressure when all h2d_buffer_depth transfers
                # are in flight) and start the H2D transfer — on async
                # runtimes it overlaps both other stagers' packs and
                # device compute. stage_blob arms the h2d_error fault
                # point with bounded retry/backoff and notes the host
                # blob-ring guard; submit_blob releases the slot with the
                # step's output as the reuse guard.
                dev_blob = self.engine.stage_blob(blob, flight_rec=rec,
                                                  order=seq)
                item = (seq, dev_blob, n, fut, rec, None)
            except BaseException as exc:  # surface through the future
                item = (seq, None, 0, fut, None, exc)
            with self._ready_lock:
                heapq.heappush(self._ready, item)
                self._ready_lock.notify_all()
            exc = item[5]
            if (isinstance(exc, FaultError)
                    and exc.point == "feeder_thread_death"):
                # drill: the batch's error is already in the heap (the
                # future resolves, the batch parks downstream) and THEN
                # this stager dies for real — remaining stagers carry on
                raise exc

    # -- step dispatcher ---------------------------------------------------
    def _step_loop(self) -> None:
        from collections import deque

        executing: deque = deque()
        while not self._stop.is_set():
            with self._ready_lock:
                while not (self._ready
                           and self._ready[0][0] == self._next_step):
                    if self._stop.is_set():
                        return
                    self._ready_lock.wait(timeout=0.1)
                seq, dev_blob, n, fut, rec, exc = heapq.heappop(self._ready)
                self._next_step += 1
            outputs = None
            try:
                if exc is None:
                    outputs = self.engine.submit_blob(
                        dev_blob, n_events=n, flight_rec=rec)
            except BaseException as step_exc:
                exc = step_exc
            finally:
                with self._ready_lock:
                    self._dispatched += 1
                    self._ready_lock.notify_all()
            if outputs is None:
                fut._resolve(error=exc)
                continue
            fut._resolve(outputs)
            # bound the device-side queue to `depth` in-flight steps:
            # keeps memory bounded AND guarantees a staging-ring slot's
            # H2D transfer finished before a stager can recycle it
            # (step N executed => its input was consumed)
            executing.append(outputs.processed)
            if len(executing) > self.depth:
                try:
                    executing.popleft().block_until_ready()
                except Exception:
                    pass  # a failed earlier step already surfaced there

    # -- draining ----------------------------------------------------------
    def flush(self, timeout: Optional[float] = 60.0) -> None:
        """Wait until every submitted batch's dispatch has RETURNED (so a
        direct engine.submit() afterwards cannot overtake a pipelined
        batch). Keep the StepFuture of your last submit if you need its
        outputs."""
        import time as _time

        deadline = None if timeout is None else _time.monotonic() + timeout
        with self._ready_lock:
            target = self._next_seq
            while self._dispatched < target:
                remaining = (None if deadline is None
                             else deadline - _time.monotonic())
                if remaining is not None and remaining <= 0:
                    raise TimeoutError("pipelined flush timed out")
                self._ready_lock.wait(timeout=0.05 if remaining is None
                                      else min(0.05, remaining))

    def close(self) -> None:
        with self._close_lock:
            self._stop.set()
        # past this point submit() can only raise: nothing new enqueues
        with self._ready_lock:
            self._ready_lock.notify_all()
        for t in self._stagers:
            t.join(timeout=5.0)
        self._step_thread.join(timeout=5.0)
        # resolve anything still queued or staged so no caller blocks
        # forever on a future the stopped threads will never touch
        leftovers = []
        while True:
            try:
                leftovers.append(self._in.get_nowait())
            except queue.Empty:
                break
        with self._ready_lock:
            while self._ready:
                leftovers.append(heapq.heappop(self._ready))
        for item in leftovers:
            fut = item[2] if len(item) == 4 else item[3]
            if not fut.done():
                fut._resolve(error=RuntimeError("submitter closed"))
            # staged-but-never-dispatched blobs still hold ring slots;
            # hand them back (guard-free) so a later submitter over the
            # same engine isn't starved
            staged = item[1] if len(item) == 6 else None
            slot = getattr(staged, "slot", None)
            if slot is not None:
                self.engine.staging_ring.release(slot)


class ShardedPipelinedSubmitter:
    """Stage-ahead feeder for the ShardedPipelineEngine.

    The sharded submit() serializes route -> device_put -> dispatch on
    the caller thread; under a tunneled runtime the H2D staging alone can
    dwarf the device step, leaving the mesh idle between submits. This
    feeder applies the same double-buffered discipline PipelinedSubmitter
    gives the single-chip engine, adapted to the sharded path's extra
    invariant — ROUTING IS STATEFUL (it consumes and produces the
    engine's overflow backlog, and per-device order requires requeued
    rows to ride the next routed batch):

      stagers:   take batch N; PREPARE it in strict submission order (a
                 routing turnstile). With device routing on (the default
                 on real multi-shard meshes) preparing is pack + a cheap
                 lane-fit guard — the mesh itself routes the rows inside
                 the step (ops/route.py); otherwise the host arena route
                 runs here. Then start the mesh transfer
                 (engine.stage_prepared, async device_put) concurrently
                 with other stagers' prep/transfers
      step thread: dispatch staged steps in submission order (state
                 donation serializes device execution anyway)

    Backpressure parity with submit(): when the backlog exceeds
    `engine.max_overflow_events` at routing time, drain blobs (backlog
    only, no new rows) are staged as extra steps under the same routing
    turn; their alerts stash on the engine's pending-alert buffer exactly
    like submit()'s internal drain.

    Single-controller only: a multi-host cluster feeds through
    parallel/cluster.py's lockstep loop (drain steps here would desync
    the collective count across hosts).

    `submit(batch)` returns a StepFuture resolving to (routed view,
    outputs) — the same pair engine.submit returns.
    """

    def __init__(self, engine, depth: int = 3, stagers: int = 2):
        if engine.is_multiprocess:
            raise RuntimeError(
                "ShardedPipelinedSubmitter is single-controller only; "
                "multi-host clusters feed through the lockstep step loop "
                "(parallel/cluster.py)")
        self.engine = engine
        self.depth = max(1, depth)
        self._in: "queue.Queue" = queue.Queue(maxsize=self.depth)
        self._ready_lock = threading.Condition()
        self._ready: List = []      # heap of (seq, staged_list, fut, exc)
        self._next_seq = 0
        self._next_route = 0        # routing turnstile position
        self._next_step = 0
        self._dispatched = 0
        self._stage_window = _stage_window(self.depth, engine)
        self._stop = threading.Event()
        self._close_lock = threading.Lock()
        self._stagers = [
            threading.Thread(target=self._stage_loop,
                             name=f"shard-feed-stage-{i}", daemon=True)
            for i in range(max(1, stagers))]
        self._step_thread = threading.Thread(target=self._step_loop,
                                             name="shard-feed-step",
                                             daemon=True)
        for t in self._stagers:
            t.start()
        self._step_thread.start()

    # -- producer ----------------------------------------------------------
    def submit(self, batch: EventBatch, age=None) -> StepFuture:
        fut = StepFuture()
        item = (self._alloc_seq(), batch, fut, age)
        while True:
            with self._close_lock:
                if self._stop.is_set():
                    raise RuntimeError("submitter closed")
                try:
                    self._in.put_nowait(item)
                    return fut
                except queue.Full:
                    pass
            time.sleep(0.005)

    def _alloc_seq(self) -> int:
        with self._ready_lock:
            seq = self._next_seq
            self._next_seq += 1
            return seq

    # -- stager ------------------------------------------------------------
    def _stage_loop(self) -> None:
        while not self._stop.is_set():
            try:
                seq, batch, fut, age = self._in.get(timeout=0.1)
            except queue.Empty:
                continue
            # bound the staged-ahead window (see PipelinedSubmitter; the
            # h2d_buffer_depth - 1 cap keeps the staging ring's ordered
            # grant deadlock-free here too)
            with self._ready_lock:
                while (not self._stop.is_set()
                       and seq - self._next_step > self._stage_window):
                    self._ready_lock.wait(timeout=0.1)
            # routing turnstile: strict submission order — routing folds
            # in (and re-parks) the engine overflow backlog, so two
            # batches must never route concurrently or out of order
            with self._ready_lock:
                while (not self._stop.is_set()
                       and self._next_route != seq):
                    self._ready_lock.wait(timeout=0.1)
            if self._stop.is_set():
                fut._resolve(error=RuntimeError("submitter closed"))
                continue
            eng = self.engine
            staged = None
            exc: Optional[BaseException] = None
            try:
                try:
                    fault_point("feeder_thread_death")
                    # _prepare_step: with device routing on (the default
                    # on real multi-shard meshes) this is pack + the
                    # cheap lane-fit guard ONLY — the mesh does the
                    # bucketing in the step's prologue (ops/route.py),
                    # freeing stager CPU for persist/consumer work; the
                    # host arena route runs just for skewed spills
                    merged = eng.merge_pending_overflow(batch)
                    prepared, over = eng._prepare_step(merged, age=age)
                    eng.park_overflow(merged, over)
                    prepped = [prepared]
                    # backpressure: route drain blobs (backlog only) as
                    # extra steps under the same turn, like submit()
                    while eng.pending_overflow > eng.max_overflow_events:
                        backlog = eng.pending_overflow_batch()
                        eng.set_pending_overflow_batch(None)
                        dprep, dover = eng._prepare_step(backlog)
                        eng.park_overflow(backlog, dover)
                        prepped.append(dprep)
                finally:
                    with self._ready_lock:
                        self._next_route += 1
                        self._ready_lock.notify_all()
                # mesh transfers start here, OUTSIDE the turnstile: they
                # overlap other stagers' routing and the device compute.
                # The step's first blob takes a staging-ring slot in seq
                # order (backpressure edge); drain blobs bypass the ring
                # (use_ring=False) — they dispatch before this step's
                # heap push, so blocking on slots held by their own
                # siblings would self-deadlock (see stage_prepared)
                staged = [eng.stage_prepared(p, order=seq if i == 0
                                             else None,
                                             use_ring=(i == 0))
                          for i, p in enumerate(prepped)]
            except BaseException as stage_exc:
                exc = stage_exc
            with self._ready_lock:
                heapq.heappush(self._ready, (seq, staged, fut, exc))
                self._ready_lock.notify_all()
            if (isinstance(exc, FaultError)
                    and exc.point == "feeder_thread_death"):
                # drill: error item is in the heap (future resolves, the
                # routing turnstile already advanced in the finally) and
                # then this stager dies for real
                raise exc

    # -- step dispatcher ---------------------------------------------------
    def _step_loop(self) -> None:
        from collections import deque

        executing: deque = deque()
        while not self._stop.is_set():
            with self._ready_lock:
                while not (self._ready
                           and self._ready[0][0] == self._next_step):
                    if self._stop.is_set():
                        return
                    self._ready_lock.wait(timeout=0.1)
                seq, staged, fut, exc = heapq.heappop(self._ready)
                self._next_step += 1
            result = None
            try:
                if exc is None:
                    eng = self.engine
                    params = eng._ensure_params()
                    for s in staged[:-1]:
                        # drained steps' alerts stash exactly like
                        # submit()'s internal drain (the caller only
                        # sees the LAST step's outputs)
                        view, outputs = eng.dispatch_staged(params, s)
                        eng._stash_pending_alerts(
                            eng._materialize_routed(view, outputs))
                        eng.drain_steps += 1
                    result = eng.dispatch_staged(params, staged[-1])
            except BaseException as step_exc:
                exc = step_exc
            finally:
                with self._ready_lock:
                    self._dispatched += 1
                    self._ready_lock.notify_all()
            if result is None:
                fut._resolve(error=exc)
                continue
            fut._resolve(result)
            # bound the device-side queue to `depth` in-flight steps
            executing.append(result[1].processed)
            if len(executing) > self.depth:
                try:
                    executing.popleft().block_until_ready()
                except Exception:
                    pass  # a failed earlier step already surfaced there

    # -- draining ----------------------------------------------------------
    def flush(self, timeout: Optional[float] = 60.0) -> None:
        """Wait until every submitted batch's dispatch has RETURNED (a
        direct engine.submit() afterwards cannot overtake)."""
        deadline = None if timeout is None else time.monotonic() + timeout
        with self._ready_lock:
            target = self._next_seq
            while self._dispatched < target:
                remaining = (None if deadline is None
                             else deadline - time.monotonic())
                if remaining is not None and remaining <= 0:
                    raise TimeoutError("pipelined flush timed out")
                self._ready_lock.wait(timeout=0.05 if remaining is None
                                      else min(0.05, remaining))

    def close(self) -> None:
        with self._close_lock:
            self._stop.set()
        with self._ready_lock:
            self._ready_lock.notify_all()
        for t in self._stagers:
            t.join(timeout=5.0)
        self._step_thread.join(timeout=5.0)
        leftovers = []
        while True:
            try:
                leftovers.append(self._in.get_nowait())
            except queue.Empty:
                break
        with self._ready_lock:
            while self._ready:
                leftovers.append(heapq.heappop(self._ready))
        for item in leftovers:
            fut = item[2]
            if not fut.done():
                fut._resolve(error=RuntimeError("submitter closed"))
            # release ring slots of staged-but-never-dispatched steps
            # (ready-heap items carry a staged LIST; _in queue items
            # carry the raw EventBatch — skip those)
            if isinstance(item[1], list):
                for s in item[1]:
                    if getattr(s, "slot", None) is not None:
                        self.engine.staging_ring.release(s.slot)


class AdaptiveBatcher:
    """Latency-tier submitter: flush on fill OR linger deadline.

    The throughput tier (PipelinedSubmitter) maximizes events/sec by
    keeping full production batches in flight; a latency-sensitive source
    instead wants each event through ingest -> rules -> alert within a
    wall-clock budget (BASELINE's p99 < 10 ms). `offer(events, tokens)`
    buffers; a flusher thread submits the pending rows as soon as either
    (a) a full engine batch is pending — no point waiting — or (b) the
    OLDEST pending offer has waited `linger_ms`. Small batches keep the
    pack + H2D + step wall time in single-digit milliseconds (the blob is
    bytes-per-event * batch_size, so at 4096 rows the transfer is ~100x
    smaller than the 131k throughput batch), and the linger bound caps
    the queueing delay added on top.

    The engine is expected to be sized for the tier
    (``pipeline.mode = "latency"`` boots it at
    ``pipeline.latency_batch_size``); an engine-per-mode is the TPU
    reality — batch size is a compiled shape, not a runtime knob.

    ``adaptive=True`` turns on ADAPTIVE LINGER: an ``offer()`` delivers a
    complete burst, so the flusher dispatches as soon as anything is
    pending instead of always sleeping out the full linger window — the
    window only coalesces offers that arrive while a flush is already in
    flight (and ``linger_ms`` stays the fill-wait upper bound). On the
    latency tier the linger sleep was the second-largest constant in the
    end-to-end number after D2H fetches (docs/ALERT_LANES.md). Default
    off: the classic fixed linger maximizes coalescing for bursty
    multi-producer ingest.

    Kafka analog: linger.ms + batch.size on the reference's producers
    (the reference never surfaces an end-to-end latency tier; this
    exceeds it).
    """

    def __init__(self, engine, linger_ms: float = 2.0,
                 max_rows: Optional[int] = None, adaptive: bool = False):
        self.engine = engine
        self.linger_s = max(0.0, linger_ms) / 1000.0
        self.adaptive = adaptive
        self.max_rows = max_rows or engine.batch_size
        self._lock = threading.Condition()
        self._events: List = []
        self._tokens: List[str] = []
        self._futures: List[StepFuture] = []
        # (ingest stamp, event count) per offer — folded into one
        # AgeSidecar at flush so the age waterfall sees linger time
        self._ages: List = []
        self._oldest: Optional[float] = None
        self._stop = threading.Event()
        # steady-state accounting: flushes counts every engine flush this
        # batcher ran; warm() moves the cold-path work (jit compiles,
        # interner fills, thread ramp-up) BEFORE measurement and records
        # how many flushes were warmup, so a latency harness can report
        # percentiles over the steady-state window only
        self.flushes = 0
        self.warm_flushes = 0
        self._thread = threading.Thread(target=self._flush_loop,
                                        name="feed-latency", daemon=True)
        self._thread.start()

    @property
    def steady_flushes(self) -> int:
        """Flushes run after the last warm() — the steady-state window."""
        return max(0, self.flushes - self.warm_flushes)

    def warm(self, events, tokens, repeats: int = 2,
             timeout: float = 600.0) -> int:
        """Bring the latency tier to steady state for this traffic shape:
        run `repeats` full offer -> linger -> pack -> step -> materialized
        alerts cycles and mark them as warmup. The first cycle pays the
        jit compile of the engine's program for this batch shape and wire
        variant plus the interner fills; p99 percentiles measured AFTER
        warm() describe the steady-state path BASELINE's latency budget
        is about (a compile must never count against a 10 ms budget — it
        happens once per shape per process, not per event)."""
        import jax

        for _ in range(max(1, repeats)):
            fut = self.offer(events, tokens)
            for batch, outputs in fut.result(timeout=timeout):
                jax.block_until_ready(outputs.processed)
                self.engine.materialize_alerts(batch, outputs)
        with self._lock:
            self.warm_flushes = self.flushes
        return self.warm_flushes

    def offer(self, events, tokens, received_at=None) -> StepFuture:
        """Buffer events (parallel `tokens` list, one per event); the
        returned future resolves with the flush's list of
        (batch, outputs) pairs — one pair per engine batch the flush
        needed (usually one; a flush bigger than the engine batch packs
        into several) — once every fused step covering these rows has
        been dispatched. `received_at` is the offer's ingest stamp
        (time.perf_counter at the receive edge); None stamps now."""
        fut = StepFuture()
        if not events:
            fut._resolve([])  # nothing to wait for; don't arm the linger
            return fut
        with self._lock:
            if self._stop.is_set():
                raise RuntimeError("batcher closed")
            self._events.extend(events)
            self._tokens.extend(tokens)
            self._ages.append((received_at if received_at is not None
                               else time.perf_counter(), len(events)))
            self._futures.append(fut)
            if self._oldest is None:
                self._oldest = time.monotonic()
            self._lock.notify_all()
        return fut

    def _flush_loop(self) -> None:
        while True:
            with self._lock:
                while not self._stop.is_set():
                    if self._oldest is not None:
                        # adaptive linger: pending offers are complete
                        # bursts — dispatch now; coalescing happens
                        # naturally while a flush is in flight
                        if self.adaptive:
                            break
                        wait = self._oldest + self.linger_s - time.monotonic()
                        if wait <= 0 or len(self._events) >= self.max_rows:
                            break
                        self._lock.wait(timeout=wait)
                    else:
                        # both state transitions (offer, close) notify —
                        # no poll timeout needed while idle
                        self._lock.wait()
                if self._stop.is_set() and not self._events:
                    return
                events, self._events = self._events, []
                tokens, self._tokens = self._tokens, []
                futures, self._futures = self._futures, []
                ages, self._ages = self._ages, []
                self._oldest = None
            self._flush(events, tokens, futures, ages)

    def _flush(self, events, tokens, futures, ages=()) -> None:
        from sitewhere_tpu.runtime.eventage import AgeSidecar

        age = AgeSidecar()
        for stamp, n in ages:
            age.add(stamp, n)
        try:
            # the whole flush's sidecar rides the FIRST batch (a flush
            # rarely spans batches; splitting per-offer stamps across
            # them would be guesswork, double-attaching would double-count)
            results = [self.engine.submit_routed(
                           batch, age=(age if i == 0 else None))
                       for i, batch in enumerate(
                           self.engine.packer.pack_events(events, tokens))]
            with self._lock:
                self.flushes += 1
            for fut in futures:
                fut._resolve(results)
        except BaseException as exc:
            for fut in futures:
                fut._resolve(error=exc)

    def close(self) -> None:
        with self._lock:
            self._stop.set()
            self._lock.notify_all()
        self._thread.join(timeout=10.0)
