"""PipelineEngine: host orchestrator for the fused TPU step.

Owns the jitted `process_batch`, the HBM device-state, the registry tensor
mirror, and the compiled rule tables; refreshes device-side params when the
registry or rules change (version counter — the reference reacts to ZK config
watches and Kafka model-update topics the same way); materializes rule-fired
alerts back into API-level DeviceAlert events; runs the presence sweep.

This is the rebuild of the *composition* of service-inbound-processing +
service-rule-processing + service-device-state (their per-service manager
classes collapse into one engine because the stages fused into one step).
"""

from __future__ import annotations

import threading
import time
from dataclasses import dataclass, field
from typing import Callable, Dict, List, Optional, Tuple

import jax
import jax.numpy as jnp
import numpy as np

from sitewhere_tpu.model import DeviceAlert, AlertLevel, AlertSource, DeviceState, PresenceState
from sitewhere_tpu.model.event import DeviceEventType
from sitewhere_tpu.ops.geofence import GeofenceCondition, GeofenceRuleTable, ZoneTable, empty_geofence_table
from sitewhere_tpu.ops.pack import (
    EventBatch, EventPacker, batch_to_blob, blob_to_batch)
from sitewhere_tpu.ops.threshold import ThresholdOp, ThresholdRuleTable, empty_threshold_table
from sitewhere_tpu.pipeline.staging import StagedBlob, StagingRing
from sitewhere_tpu.pipeline.state_tensors import DeviceStateTensors, init_device_state
from sitewhere_tpu.pipeline.step import PipelineParams, ProcessOutputs, check_presence, process_batch
from sitewhere_tpu.registry.tensors import RegistryTensors
from sitewhere_tpu.runtime.bus import jittered
from sitewhere_tpu.runtime.eventage import age_histogram, observe_summary
from sitewhere_tpu.runtime.faults import fault_point
from sitewhere_tpu.runtime.lifecycle import LifecycleComponent
from sitewhere_tpu.runtime.flight import GLOBAL_FLIGHT
from sitewhere_tpu.runtime.metrics import GLOBAL_METRICS

_NEG = -(2 ** 31)

# int -> AlertLevel member (enum __call__ costs ~1 us/row in a storm;
# the materialize hot loop indexes this instead)
_ALERT_LEVELS = {int(level): level for level in AlertLevel}


def materialize_alerts_maskscan(engine, batch, outputs,
                                ) -> List[DeviceAlert]:
    """The pre-lane mask-scan materializer, kept verbatim as the
    differential-test oracle and micro-bench reference for the
    device-compacted alert lanes (docs/ALERT_LANES.md): fetch the
    per-row mask/level/rule arrays (two phases on big batches), nonzero
    the fired mask on the host, and walk fired rows with per-row
    `token_of` lookups. Flat batches/outputs only (the sharded engine
    flattens before delegating — tests do the same); returns ALL fired
    rows' alerts and never touches engine counters or pending stashes.
    Rule-program fires (outputs.program_*) emit after the per-row
    threshold/geofence alerts, and anomaly-model fires (outputs.model_*)
    after those — the same within-row order the lane materializer
    uses."""
    small_batch = outputs.threshold_fired.size <= 16384
    if small_batch:
        (thr_fired, geo_fired, prog_fired, model_fired,
         thr_level, geo_level, prog_level,
         thr_rule, geo_rule, prog_rule, model_first) = jax.device_get(
            (outputs.threshold_fired, outputs.geofence_fired,
             outputs.program_fired, outputs.model_fired,
             outputs.threshold_alert_level, outputs.geofence_alert_level,
             outputs.program_alert_level,
             outputs.threshold_first_rule, outputs.geofence_first_rule,
             outputs.program_first_rule, outputs.model_first))
    else:
        thr_fired, geo_fired, prog_fired, model_fired = jax.device_get(
            (outputs.threshold_fired, outputs.geofence_fired,
             outputs.program_fired, outputs.model_fired))
    fired_rows = np.nonzero(thr_fired | geo_fired | prog_fired
                            | model_fired)[0]
    if fired_rows.size == 0:
        return []
    if not small_batch:
        (thr_level, geo_level, prog_level, thr_rule, geo_rule,
         prog_rule, model_first) = jax.device_get(
            (outputs.threshold_alert_level, outputs.geofence_alert_level,
             outputs.program_alert_level,
             outputs.threshold_first_rule, outputs.geofence_first_rule,
             outputs.program_first_rule, outputs.model_first))
    device_idx = np.asarray(batch.device_idx)
    ts = np.asarray(batch.ts)
    rules = engine.list_rules()
    thr_rules, geo_rules = rules["threshold"], rules["geofence"]
    programs = engine.rule_programs_by_slot()
    models = engine.anomaly_models_by_slot()
    alerts: List[DeviceAlert] = []
    for row in fired_rows:
        token = engine.registry.devices.token_of(int(device_idx[row])) or ""
        if thr_fired[row] and 0 <= thr_rule[row] < len(thr_rules):
            rule = thr_rules[int(thr_rule[row])]
            alerts.append(DeviceAlert(
                device_id=token, source=AlertSource.SYSTEM,
                level=AlertLevel(int(thr_level[row])), type=rule.alert_type,
                message=rule.alert_message
                or f"threshold rule {rule.token} fired",
                event_date=engine.packer.abs_ts(int(ts[row]))))
        if geo_fired[row] and 0 <= geo_rule[row] < len(geo_rules):
            rule = geo_rules[int(geo_rule[row])]
            alerts.append(DeviceAlert(
                device_id=token, source=AlertSource.SYSTEM,
                level=AlertLevel(int(geo_level[row])), type=rule.alert_type,
                message=rule.alert_message
                or f"geofence rule {rule.token} fired",
                event_date=engine.packer.abs_ts(int(ts[row]))))
        if prog_fired[row] and int(prog_rule[row]) in programs:
            spec = programs[int(prog_rule[row])]
            alerts.append(DeviceAlert(
                device_id=token, source=AlertSource.SYSTEM,
                level=AlertLevel(int(prog_level[row])),
                type=spec["alert_type"],
                message=spec["alert_message"]
                or f"rule program {spec['token']} fired",
                event_date=engine.packer.abs_ts(int(ts[row]))))
        if model_fired[row] and int(model_first[row]) in models:
            # the lane path carries only the model SLOT; level/type come
            # from the installed spec on both paths so they match exactly
            spec = models[int(model_first[row])]
            alerts.append(DeviceAlert(
                device_id=token, source=AlertSource.SYSTEM,
                level=AlertLevel(int(spec["alert_level"])),
                type=spec["alert_type"],
                message=spec["alert_message"]
                or f"anomaly model {spec['token']} fired",
                event_date=engine.packer.abs_ts(int(ts[row]))))
    return alerts


@dataclass
class ThresholdRule:
    """Host-side rule definition; compiled into ThresholdRuleTable rows."""

    token: str
    measurement_name: str = ""       # "" = any
    operator: str = ">"
    threshold: float = 0.0
    alert_type: str = "threshold.violation"
    alert_level: AlertLevel = AlertLevel.WARNING
    alert_message: str = ""
    tenant_token: str = ""           # "" = any
    device_type_token: str = ""      # "" = any
    active: bool = True


@dataclass
class GeofenceRule:
    """Host-side geofence rule (the reference's ZoneTestRuleProcessor config:
    zone token + containment condition + alert to fire)."""

    token: str
    zone_token: str = ""
    condition: str = "outside"       # fire when point is inside|outside
    alert_type: str = "zone.violation"
    alert_level: AlertLevel = AlertLevel.ERROR
    alert_message: str = ""
    active: bool = True


def rule_to_dict(kind: str, rule) -> Dict:
    """Wire/REST form of a rule: plain JSON types plus a `type` tag."""
    import dataclasses

    data = dataclasses.asdict(rule)
    data["alert_level"] = int(rule.alert_level)
    data["type"] = kind
    return data


def rule_from_dict(data: Dict):
    """(kind, rule) from the wire/REST form; validates against the same
    choices the config metamodel declares (runtime/config_model.py
    rule_processing_model) AND coerces field types — a rule that passes
    here must compile into the rule tables without crashing the hot path.
    Raises SiteWhereError on bad input."""
    from sitewhere_tpu.errors import ErrorCode, SiteWhereError

    kind = data.get("type")
    token = data.get("token") or ""
    if not token or not isinstance(token, str):
        raise SiteWhereError("rule requires a string token",
                             ErrorCode.GENERIC)

    def fields_for(cls):
        import dataclasses

        names = {f.name for f in dataclasses.fields(cls)}
        out = {k: v for k, v in data.items() if k in names and v is not None}
        try:
            if "threshold" in out:
                out["threshold"] = float(out["threshold"])
            if "active" in out:
                out["active"] = bool(out["active"])
            if "alert_level" in out:
                level = out["alert_level"]
                out["alert_level"] = (AlertLevel[level]
                                      if isinstance(level, str)
                                      and not level.lstrip("-").isdigit()
                                      else AlertLevel(int(level)))
        except (KeyError, ValueError, TypeError) as exc:
            raise SiteWhereError(f"invalid rule field value: {exc}",
                                 ErrorCode.GENERIC)
        for name, value in out.items():
            if name not in ("threshold", "active", "alert_level") \
                    and not isinstance(value, str):
                raise SiteWhereError(
                    f"rule field '{name}' must be a string",
                    ErrorCode.GENERIC)
        return out

    if kind == "threshold":
        rule = ThresholdRule(**fields_for(ThresholdRule))
        if rule.operator not in ThresholdOp.BY_NAME:
            raise SiteWhereError(
                f"unknown operator {rule.operator!r} (one of "
                f"{sorted(ThresholdOp.BY_NAME)})", ErrorCode.GENERIC)
        return kind, rule
    if kind == "geofence":
        rule = GeofenceRule(**fields_for(GeofenceRule))
        if rule.condition not in ("inside", "outside"):
            raise SiteWhereError(
                f"geofence condition must be inside|outside, got "
                f"{rule.condition!r}", ErrorCode.GENERIC)
        if not rule.zone_token:
            raise SiteWhereError("geofence rule requires zone_token",
                                 ErrorCode.GENERIC)
        return kind, rule
    raise SiteWhereError(
        f"unknown rule type {kind!r} (threshold|geofence)",
        ErrorCode.GENERIC)


class PipelineEngine(LifecycleComponent):
    """One engine per process; multi-tenant by construction (tenant axis is a
    tensor column, not a separate engine — SURVEY.md §2.5 tenant parallelism).
    """

    def __init__(self, registry_tensors: RegistryTensors, batch_size: int = 8192,
                 measurement_slots: int = 32, max_tenants: int = 16,
                 max_threshold_rules: int = 256, max_geofence_rules: int = 256,
                 presence_missing_interval_ms: int = 8 * 60 * 60 * 1000,
                 name: str = "pipeline-engine", geofence_impl: str = "auto",
                 alert_lane_capacity: Optional[int] = None,
                 max_rule_programs: int = 32,
                 rule_program_nodes: int = 16,
                 rule_program_state_slots: int = 8,
                 max_anomaly_models: int = 8,
                 anomaly_model_features: int = 4,
                 anomaly_model_layers: int = 2,
                 anomaly_model_width: int = 8,
                 h2d_buffer_depth: int = 3,
                 max_actuation_policies: int = 8,
                 command_lane_capacity: Optional[int] = None,
                 max_command_tokens: int = 1024):
        from sitewhere_tpu.actuation.compiler import MAX_POLICY_BUCKET
        from sitewhere_tpu.ml.compiler import MAX_MODEL_BUCKET
        from sitewhere_tpu.ops.actuate import (
            DEFAULT_COMMAND_LANE_CAPACITY, MIN_COMMAND_LANE_CAPACITY)
        from sitewhere_tpu.ops.compact import (
            DEFAULT_ALERT_LANE_CAPACITY, MIN_ALERT_LANE_CAPACITY)
        from sitewhere_tpu.registry.interning import TokenInterner
        from sitewhere_tpu.rules.compiler import MAX_PROGRAM_BUCKET

        super().__init__(name)
        self.registry = registry_tensors
        self.batch_size = batch_size
        self.max_tenants = max_tenants
        self.measurement_slots = measurement_slots
        self.max_threshold_rules = max_threshold_rules
        self.max_geofence_rules = max_geofence_rules
        # rule ids travel in int16 halves of the alert-lane rules row
        if max(max_threshold_rules, max_geofence_rules) >= (1 << 15):
            raise ValueError("rule table capacity must be < 32768 "
                             "(alert-lane rule-id field width)")
        # rule-program slot ids travel in 8 alert-lane meta bits
        if not (0 < max_rule_programs <= MAX_PROGRAM_BUCKET):
            raise ValueError(
                f"max_rule_programs must be in 1..{MAX_PROGRAM_BUCKET} "
                f"(alert-lane program-id field width)")
        self.max_rule_programs = max_rule_programs
        self.rule_program_nodes = rule_program_nodes
        self.rule_program_state_slots = rule_program_state_slots
        # anomaly-model slot ids travel in 8 alert-lane meta bits
        # (ops/compact.py: the two spare level nibbles)
        if not (0 < max_anomaly_models <= MAX_MODEL_BUCKET):
            raise ValueError(
                f"max_anomaly_models must be in 1..{MAX_MODEL_BUCKET} "
                f"(alert-lane model-id field width)")
        if anomaly_model_features > anomaly_model_width:
            raise ValueError(
                "anomaly_model_features must be <= anomaly_model_width "
                "(features embed in the activation vector)")
        self.max_anomaly_models = max_anomaly_models
        self.anomaly_model_features = anomaly_model_features
        self.anomaly_model_layers = anomaly_model_layers
        self.anomaly_model_width = anomaly_model_width
        # actuation-policy slot ids travel in 8 command-lane meta bits
        # (ops/actuate.py lane meta packing)
        if not (0 < max_actuation_policies <= MAX_POLICY_BUCKET):
            raise ValueError(
                f"max_actuation_policies must be in 1..{MAX_POLICY_BUCKET} "
                f"(command-lane policy-id field width)")
        self.max_actuation_policies = max_actuation_policies
        self.command_lane_capacity = (
            command_lane_capacity if command_lane_capacity is not None
            else DEFAULT_COMMAND_LANE_CAPACITY)
        if self.command_lane_capacity < MIN_COMMAND_LANE_CAPACITY:
            raise ValueError(
                f"command_lane_capacity must be >= "
                f"{MIN_COMMAND_LANE_CAPACITY}")
        # command tokens the dispatcher resolves lane rows back through
        # (the same dense-index discipline the device interner uses)
        self.commands = TokenInterner(max_command_tokens, "commands")
        self.alert_lane_capacity = (alert_lane_capacity
                                    if alert_lane_capacity is not None
                                    else DEFAULT_ALERT_LANE_CAPACITY)
        if self.alert_lane_capacity < MIN_ALERT_LANE_CAPACITY:
            raise ValueError(
                f"alert_lane_capacity must be >= {MIN_ALERT_LANE_CAPACITY}")
        self.presence_missing_interval_ms = presence_missing_interval_ms
        self.packer = EventPacker(batch_size, registry_tensors.devices)

        self._threshold_rules: List[ThresholdRule] = []
        self._geofence_rules: List[GeofenceRule] = []
        # rule programs: token -> {"slot", "epoch", "spec"} with STABLE
        # slot assignment (lowest free slot on install) — per-(device,
        # program) temporal state is keyed by slot, and the epoch
        # generation makes a recycled slot reset its state inside the
        # fused step (rules/compiler.py RuleProgramTable.epoch)
        self._rule_programs: Dict[str, Dict] = {}
        self._program_epoch = 0
        self._programs_enabled = False
        self._rule_state = None
        # anomaly models: same token -> {"slot", "epoch", "spec"} shape
        # and stable-slot/epoch discipline as the rule programs
        # (ml/compiler.py AnomalyModelTable.epoch)
        self._anomaly_models: Dict[str, Dict] = {}
        self._model_epoch = 0
        self._models_enabled = False
        self._model_state = None
        # actuation policies: token -> {"slot", "epoch", "spec"}, the same
        # stable-slot/epoch discipline (actuation/compiler.py
        # ActuationPolicyTable.epoch drives lazy debounce-state reset)
        self._actuation_policies: Dict[str, Dict] = {}
        self._actuation_epoch = 0
        self._actuation_enabled = False
        self._actuation_state = None
        # command fan-out: decoded lane rows hand off here. With no
        # dispatcher attached (tests, bare engines) fires park on the
        # pending list and drain via take_command_fires().
        self.command_dispatcher = None
        self._pending_commands: List[Dict] = []
        self.commands_fired = 0
        self.commands_debounced = 0
        self.commands_dropped = 0
        self._rules_version = 0
        # (op, kind, rule-or-token) feed over rule mutations — the rule
        # management surface rides it (REST audit, cluster replication)
        self._rules_listeners: List[Callable[[str, str, object], None]] = []
        # serializes rule mutation + listener fire (see _mutate_rule)
        self._rules_io_lock = threading.RLock()
        self._params_built_for: Tuple[int, int] = (-1, -1)
        self._params: Optional[PipelineParams] = None
        self._state: Optional[DeviceStateTensors] = None
        self._lock = threading.RLock()
        # Serializes state ADVANCE (submit/presence donate the old buffers,
        # deleting them at dispatch) against state READS/SWAPS from other
        # threads (REST get_device_state, presence sweep thread, checkpoint
        # save, restore) — without it a reader holding the pre-donation
        # reference crashes on "Array has been deleted". Held only around
        # dispatch + the reference swap / the row copy, never around
        # block_until_ready, so hot-path cost is nanoseconds.
        self._state_lock = threading.RLock()
        self._metrics = GLOBAL_METRICS.scoped(f"pipeline.{name}")
        # step flight recorder: one fixed-shape record per step with the
        # stage timeline (runtime/flight.py); feeders pass records they
        # opened on stager threads via submit_blob(flight_rec=...)
        self.flight = GLOBAL_FLIGHT
        self._flight_last = None
        self._flight_step_n = 0
        # Prometheus bucketed histogram for step-path stage durations
        # (labels: engine, stage) — replaces the reservoir summaries the
        # step path used to feed via timer("pack")/timer("step")
        self._stage_hist = GLOBAL_METRICS.histogram(
            "pipeline.step_stage_seconds")
        # per-tenant event volume, sampled every Nth step (a full-batch
        # tenant bincount per step would not hold the <1% overhead pin)
        self._tenant_hist = GLOBAL_METRICS.histogram(
            "pipeline.step_tenant_events",
            buckets=(1.0, 8.0, 64.0, 512.0, 4096.0, 32768.0))
        # ingest->effect event-age histogram (runtime/eventage.py);
        # ingest attaches an AgeSidecar per submit, materialize closes it
        self._age_hist = age_histogram(GLOBAL_METRICS)
        self._flight_sample_every = 16
        from sitewhere_tpu.ops.geofence import resolve_geofence_impl
        self.geofence_impl = resolve_geofence_impl(
            geofence_impl, self._target_platform())
        self._build_step_blob()
        self._presence = jax.jit(check_presence, donate_argnums=(0,))
        self.batches_processed = 0
        # bounded materialization (max_alerts) AND alert-lane overflow
        # (> capacity fired rows in one step) both count here
        self.alerts_dropped = 0
        # D2H materialization accounting: how many fetches / bytes the
        # alert path ships per step — the latency tier's fetch budget
        # (perf_gate latency_fetch_budget) reads the per-offer deltas
        self.d2h_fetches = 0
        self.d2h_bytes = 0
        # alerts stashed outside the submit->materialize cycle (overflow
        # restored from a checkpoint, restored manifests): drained by the
        # next materialize_alerts, persisted by checkpoint save
        self._pending_alerts: List[DeviceAlert] = []
        # rotating staging buffers for the wire blob (see
        # _staging_blob_buffer) — fresh 2.6 MB mmap-backed allocations per
        # step cost page faults on the hot path. _blob_ring_guards[i] is a
        # device array whose readiness proves slot i's H2D transfer
        # completed; slot reuse blocks on it (async PJRT DMA reads the
        # host buffer after dispatch returns).
        self._blob_ring: Optional[list] = None
        self._blob_ring_guards: Optional[list] = None
        self._blob_ring_pos = 0
        self._blob_ring_lock = threading.Lock()
        # on-device H2D staging ring (pipeline/staging.py): every hot-path
        # device_put first takes a slot, so at most `h2d_buffer_depth`
        # transfers are in flight and slot reuse recycles the same
        # fixed-shape HBM destinations. Depth 1 degenerates to today's
        # serial transfer behavior; built lazily so config can tune it
        # before first submit.
        if not (1 <= int(h2d_buffer_depth) <= 8):
            raise ValueError("h2d_buffer_depth must be in 1..8")
        self.h2d_buffer_depth = int(h2d_buffer_depth)
        self._staging_ring = None
        self._staging_ring_lock = threading.Lock()
        # Degradation machinery (runtime/health.py, runtime/faults.py):
        # transient H2D/dispatch failures retry with backoff + jitter
        # (step_retries attempts past the first) instead of poisoning the
        # submitter; the health state machine tracks the ladder
        # healthy -> degraded -> draining -> failed and is surfaced on
        # /api/instance/topology and the pipeline.health_state gauge.
        from sitewhere_tpu.runtime.health import EngineHealth
        self.step_retries = 2
        self.health = EngineHealth(name, metrics=self._metrics)
        self._retry_counter = self._metrics.counter("step_retries")

    def _target_platform(self) -> str:
        """Platform the step will compile for (sharded engines override from
        their mesh devices)."""
        return jax.default_backend()

    def _step_static_config(self):
        """Trace-time statics of the stateful stages: (programs enabled,
        node trim, models enabled). A change — programs or models going
        empty<->non-empty, or a program using more node slots than any
        before — rebuilds the jit (rare; a normal table edit reuses the
        compiled program like any other params refresh)."""
        return (self._programs_enabled,
                getattr(self, "_program_nodes_in_use", 0),
                self._models_enabled,
                self._actuation_enabled)

    def _build_step_blob(self) -> None:
        """(Re)build the jitted fused step. Called at construction and on
        the rare program-stage static transitions: the stage is dropped
        at TRACE time when no programs are installed, so the common case
        pays nothing — one recompile per transition, like any other
        static-shape change."""
        (programs_enabled, node_limit, models_enabled,
         actuation_enabled) = self._step_static_config()

        def step_blob(params, state, rule_state, model_state,
                      actuation_state, blob):
            return process_batch(params, state, rule_state, model_state,
                                 actuation_state, blob_to_batch(blob),
                                 geofence_impl=self.geofence_impl,
                                 alert_lane_capacity=self.alert_lane_capacity,
                                 programs_enabled=programs_enabled,
                                 program_node_limit=node_limit,
                                 models_enabled=models_enabled,
                                 actuation_enabled=actuation_enabled,
                                 command_lane_capacity=(
                                     self.command_lane_capacity))

        self._step_blob = jax.jit(step_blob, donate_argnums=(1, 2, 3, 4))
        self._step_built_config = (programs_enabled, node_limit,
                                   models_enabled, actuation_enabled)

    def _ensure_step_current(self) -> None:
        if self._step_built_config != self._step_static_config():
            self._ensure_rule_state_sized()
            self._ensure_model_state_sized()
            self._ensure_actuation_state_sized()
            self._build_step_blob()

    def _rule_state_dims(self):
        """(P, S) the resident RuleStateTensors are sized for. With NO
        programs installed the stage is dropped at trace time and the
        state is a pass-through, so a [D, 1, 1] placeholder keeps the
        empty case free — the full [D, P, S] group allocates on the
        empty->non-empty transition, alongside the step rebuild."""
        if self._programs_enabled:
            return (self.max_rule_programs, self.rule_program_state_slots)
        return (1, 1)

    def _init_rule_state(self):
        from sitewhere_tpu.ops.stateful import init_rule_state

        dims = self._rule_state_dims()
        self._rule_state_built_dims = dims
        return init_rule_state(self.registry.devices.capacity, *dims)

    def _ensure_rule_state_sized(self) -> None:
        if (self._rule_state is not None
                and getattr(self, "_rule_state_built_dims", None)
                != self._rule_state_dims()):
            with self._state_lock:
                self._rule_state = self._init_rule_state()

    def _model_state_dims(self):
        """(P, F) the resident ModelStateTensors are sized for — the same
        placeholder-when-empty discipline as _rule_state_dims."""
        if self._models_enabled:
            return (self.max_anomaly_models, self.anomaly_model_features)
        return (1, 1)

    def _init_model_state(self):
        from sitewhere_tpu.ops.anomaly import init_model_state

        dims = self._model_state_dims()
        self._model_state_built_dims = dims
        return init_model_state(self.registry.devices.capacity, *dims)

    def _ensure_model_state_sized(self) -> None:
        if (self._model_state is not None
                and getattr(self, "_model_state_built_dims", None)
                != self._model_state_dims()):
            with self._state_lock:
                self._model_state = self._init_model_state()

    def _actuation_state_dims(self):
        """(P,) the resident ActuationStateTensors are sized for — the
        same placeholder-when-empty discipline as _rule_state_dims."""
        if self._actuation_enabled:
            return (self.max_actuation_policies,)
        return (1,)

    def _init_actuation_state(self):
        from sitewhere_tpu.ops.actuate import init_actuation_state

        dims = self._actuation_state_dims()
        self._actuation_state_built_dims = dims
        return init_actuation_state(self.registry.devices.capacity, *dims)

    def _ensure_actuation_state_sized(self) -> None:
        if (self._actuation_state is not None
                and getattr(self, "_actuation_state_built_dims", None)
                != self._actuation_state_dims()):
            with self._state_lock:
                self._actuation_state = self._init_actuation_state()

    # -- lifecycle ------------------------------------------------------------

    def on_initialize(self, monitor) -> None:
        self._state = init_device_state(self.registry.devices.capacity,
                                        self.measurement_slots, self.max_tenants)
        if self._rule_state is None:
            self._rule_state = self._init_rule_state()
        if self._model_state is None:
            self._model_state = self._init_model_state()
        if self._actuation_state is None:
            self._actuation_state = self._init_actuation_state()
        self._refresh_params()

    def on_start(self, monitor) -> None:
        if self._state is None:
            self.on_initialize(monitor)

    # -- rules ----------------------------------------------------------------

    def add_rules_listener(
            self, callback: Callable[[str, str, object], None]) -> None:
        """Subscribe to rule mutations: callback(op, kind, payload) with
        op 'add' (payload = the rule) or 'remove' (payload = token)."""
        self._rules_listeners.append(callback)

    def _fire_rules(self, op: str, kind: str, payload) -> None:
        for callback in list(self._rules_listeners):
            callback(op, kind, payload)

    def _mutate_rule(self, kind: str, rule, replace: bool) -> None:
        """Single mutation path for rule installs. `_rules_io_lock` is
        held across mutate + listener fire so listeners (cluster gossip)
        observe mutations in the order they happened; `_lock` (shared
        with the hot path's params compile) is held only around the list
        mutation — a stalled gossip publish must never block a step."""
        from sitewhere_tpu.errors import (
            DuplicateTokenError, ErrorCode, SiteWhereError)

        if kind == "threshold" and not isinstance(rule, ThresholdRule):
            raise SiteWhereError("threshold rule expected", ErrorCode.GENERIC)
        if kind == "geofence" and not isinstance(rule, GeofenceRule):
            raise SiteWhereError("geofence rule expected", ErrorCode.GENERIC)
        with self._rules_io_lock:
            with self._lock:
                exists = any(
                    r.token == rule.token
                    for r in self._threshold_rules + self._geofence_rules)
                if exists and not replace:
                    raise DuplicateTokenError(
                        f"rule '{rule.token}' already exists")
                target, cap = (
                    (self._threshold_rules, self.max_threshold_rules)
                    if kind == "threshold"
                    else (self._geofence_rules, self.max_geofence_rules))
                # capacity BEFORE any removal: a failed upsert must leave
                # the rule set untouched (the replaced rule frees a slot
                # only when it lives in the same kind's table)
                freed = exists and any(r.token == rule.token
                                       for r in target)
                if len(target) - (1 if freed else 0) >= cap:
                    raise SiteWhereError(f"{kind} rule capacity exceeded",
                                         ErrorCode.CAPACITY_EXCEEDED)
                if exists:
                    self._threshold_rules = [
                        r for r in self._threshold_rules
                        if r.token != rule.token]
                    self._geofence_rules = [
                        r for r in self._geofence_rules
                        if r.token != rule.token]
                    target = (self._threshold_rules if kind == "threshold"
                              else self._geofence_rules)
                target.append(rule)
                self._rules_version += 1
            self._fire_rules("add", kind, rule)

    def create_rule(self, kind: str, rule) -> None:
        """Install a NEW rule; raises DuplicateTokenError on a token
        collision (atomically — the REST create contract)."""
        self._mutate_rule(kind, rule, replace=False)

    def upsert_rule(self, kind: str, rule) -> None:
        """Install or replace the rule with this token — the idempotent
        entry used by boot config, checkpoint restore, and cluster
        replication."""
        self._mutate_rule(kind, rule, replace=True)

    # upsert semantics: in a cluster, replication may install the same
    # rule concurrently with local provisioning (every host boots the
    # same config) — programmatic installs must be idempotent. The strict
    # duplicate check lives in create_rule (the REST create contract).
    def add_threshold_rule(self, rule: ThresholdRule) -> None:
        self.upsert_rule("threshold", rule)

    def add_geofence_rule(self, rule: GeofenceRule) -> None:
        self.upsert_rule("geofence", rule)

    def remove_rule(self, token: str) -> bool:
        with self._rules_io_lock:
            with self._lock:
                n = len(self._threshold_rules) + len(self._geofence_rules)
                self._threshold_rules = [r for r in self._threshold_rules
                                         if r.token != token]
                self._geofence_rules = [r for r in self._geofence_rules
                                        if r.token != token]
                changed = n != (len(self._threshold_rules)
                                + len(self._geofence_rules))
                if changed:
                    self._rules_version += 1
            if changed:
                self._fire_rules("remove", "", token)
        return changed

    def get_rule(self, token: str):
        """(kind, rule) for a token, or (None, None)."""
        with self._lock:
            for rule in self._threshold_rules:
                if rule.token == token:
                    return "threshold", rule
            for rule in self._geofence_rules:
                if rule.token == token:
                    return "geofence", rule
        return None, None

    def list_rules(self) -> Dict[str, list]:
        with self._lock:
            return {"threshold": list(self._threshold_rules),
                    "geofence": list(self._geofence_rules)}

    def _compile_threshold_table(self) -> ThresholdRuleTable:
        table = empty_threshold_table(self.max_threshold_rules)
        for i, rule in enumerate(self._threshold_rules):
            active = rule.active
            tenant_idx = mm_idx = dtype_idx = 0
            # A scoping token that doesn't resolve must deactivate the rule,
            # not silently widen to "any" (index 0 means wildcard on device).
            if rule.tenant_token:
                tenant_idx = self.registry.tenants.lookup(rule.tenant_token)
                active = active and tenant_idx > 0
            if rule.device_type_token:
                dtype_idx = self.registry.device_types.lookup(rule.device_type_token)
                active = active and dtype_idx > 0
            if rule.measurement_name:
                mm_idx = self.packer.measurements.intern(rule.measurement_name)
            table.active[i] = active
            table.tenant_idx[i] = tenant_idx
            table.mm_idx[i] = mm_idx
            table.device_type_idx[i] = dtype_idx
            table.op[i] = ThresholdOp.BY_NAME[rule.operator]
            table.threshold[i] = rule.threshold
            table.alert_level[i] = int(rule.alert_level)
            table.alert_type_idx[i] = self.packer.alert_types.intern(rule.alert_type)
        return table

    def _compile_geofence_table(self) -> GeofenceRuleTable:
        table = empty_geofence_table(self.max_geofence_rules)
        for i, rule in enumerate(self._geofence_rules):
            zidx = self.registry.zones_interner.lookup(rule.zone_token)
            table.active[i] = rule.active and zidx > 0
            table.zone_row[i] = max(0, zidx - 1)
            table.condition[i] = (GeofenceCondition.INSIDE
                                  if rule.condition == "inside"
                                  else GeofenceCondition.OUTSIDE)
            table.alert_level[i] = int(rule.alert_level)
            table.alert_type_idx[i] = self.packer.alert_types.intern(rule.alert_type)
        return table

    # -- rule programs (CEP-lite compiler; rules/compiler.py) ---------------

    def _compile_program_table(self):
        from sitewhere_tpu.rules.compiler import (
            compile_program_into, empty_program_table)

        table = empty_program_table(self.max_rule_programs,
                                    self.rule_program_nodes)
        for entry in self._rule_programs.values():
            compile_program_into(
                table, entry["slot"], entry["spec"], entry["epoch"],
                intern_measurement=self.packer.measurements.intern,
                intern_alert_type=self.packer.alert_types.intern,
                lookup_tenant=self.registry.tenants.lookup,
                lookup_device_type=self.registry.device_types.lookup,
                measurement_slots=self.measurement_slots,
                max_state_slots=self.rule_program_state_slots)
        # node slots actually populated, for the static unroll trim (the
        # NOP opcode is 0, and node 0 of a used program is never NOP)
        used = np.nonzero((table.opcode != 0).any(axis=0))[0]
        self._program_nodes_in_use = int(used.max()) + 1 if used.size else 0
        return table

    def _validate_program_spec(self, spec: Dict) -> Dict:
        """Full dry-run compile against THIS engine's static buckets and
        interners: a spec that passes here turns into table rows without
        crashing the hot path. Raises RuleProgramError (409, names the
        offending node) otherwise — the structured-validation contract
        shared by the REST and replicated-apply paths."""
        from sitewhere_tpu.rules.compiler import dry_run_compile

        return dry_run_compile(
            spec, measurement_slots=self.measurement_slots,
            max_nodes=self.rule_program_nodes,
            max_state_slots=self.rule_program_state_slots,
            intern_measurement=self.packer.measurements.intern)

    def upsert_rule_program(self, spec: Dict, *, slot: Optional[int] = None,
                            epoch: Optional[int] = None) -> Dict:
        """Install or replace a rule program (idempotent — boot config,
        checkpoint restore, cluster replication). A replace bumps the
        slot's epoch so its temporal state resets inside the fused step.
        `slot`/`epoch` pin the assignment on checkpoint restore so
        mid-window temporal state lines back up with its program."""
        from sitewhere_tpu.errors import ErrorCode, SiteWhereError

        spec = self._validate_program_spec(spec)
        token = spec["token"]
        with self._rules_io_lock:
            with self._lock:
                existing = self._rule_programs.get(token)
                if slot is None:
                    if existing is not None:
                        slot = existing["slot"]
                    else:
                        used = {e["slot"]
                                for e in self._rule_programs.values()}
                        free = [s for s in range(self.max_rule_programs)
                                if s not in used]
                        if not free:
                            raise SiteWhereError(
                                "rule program capacity exceeded "
                                f"({self.max_rule_programs} slots)",
                                ErrorCode.CAPACITY_EXCEEDED,
                                http_status=409)
                        slot = free[0]
                if epoch is None:
                    self._program_epoch += 1
                    epoch = self._program_epoch
                else:
                    self._program_epoch = max(self._program_epoch, epoch)
                entry = {"slot": int(slot), "epoch": int(epoch),
                         "spec": spec}
                self._rule_programs[token] = entry
                self._programs_enabled = True
                self._rules_version += 1
            self._fire_rules("add", "program", dict(spec))
        return entry

    def create_rule_program(self, spec: Dict) -> Dict:
        """REST create semantics: duplicate token 409s atomically."""
        from sitewhere_tpu.errors import DuplicateTokenError

        with self._lock:
            token = (spec or {}).get("token")
            if token in self._rule_programs:
                raise DuplicateTokenError(
                    f"rule program '{token}' already exists")
        return self.upsert_rule_program(spec)

    def remove_rule_program(self, token: str) -> bool:
        with self._rules_io_lock:
            with self._lock:
                entry = self._rule_programs.pop(token, None)
                if entry is None:
                    return False
                self._programs_enabled = bool(self._rule_programs)
                self._rules_version += 1
            self._fire_rules("remove", "program", token)
        return True

    def get_rule_program(self, token: str) -> Optional[Dict]:
        with self._lock:
            entry = self._rule_programs.get(token)
            return dict(entry["spec"]) if entry else None

    def list_rule_programs(self) -> List[Dict]:
        """Program specs in slot order (the order fires resolve in)."""
        with self._lock:
            entries = sorted(self._rule_programs.values(),
                             key=lambda e: e["slot"])
            return [dict(e["spec"]) for e in entries]

    def rule_programs_by_slot(self) -> Dict[int, Dict]:
        with self._lock:
            return {e["slot"]: dict(e["spec"])
                    for e in self._rule_programs.values()}

    def rule_program_manifest(self) -> List[Dict]:
        """Checkpoint form: spec + the runtime (slot, epoch) assignment,
        so a restore re-pins temporal state to its program mid-window."""
        with self._lock:
            return [{"slot": e["slot"], "epoch": e["epoch"],
                     "spec": dict(e["spec"])}
                    for e in sorted(self._rule_programs.values(),
                                    key=lambda e: e["slot"])]

    def rule_program_counters(self) -> Dict[str, Dict[str, int]]:
        """Per-program cumulative fire/suppress counters (one on-demand
        D2H fetch of two [P] vectors — never on the hot path). Counters
        live in the rule state so they survive checkpoints; sharded
        engines hold per-shard partials summed here."""
        if self._rule_state is None:
            return {}
        with self._state_lock:
            fires = np.asarray(self._rule_state.fire_count)
            supp = np.asarray(self._rule_state.suppress_count)
        if fires.ndim == 2:  # sharded [S, P] partials
            fires, supp = fires.sum(0), supp.sum(0)
        with self._lock:
            # a slot past the resident counter row means the full-size
            # state hasn't stepped yet (program installed, no submit) —
            # its counters are zero by definition
            return {token: {"fires": int(fires[e["slot"]])
                            if e["slot"] < fires.shape[0] else 0,
                            "suppressed": int(supp[e["slot"]])
                            if e["slot"] < supp.shape[0] else 0}
                    for token, e in self._rule_programs.items()}

    # -- rule-program state (checkpointing) ---------------------------------

    def canonical_rule_state(self):
        """Host snapshot of the rule-program temporal state, flat
        device-major like canonical_state (sharded engine overrides)."""
        import jax.numpy as jnp

        if self._rule_state is None:
            return None
        with self._state_lock:
            snap = jax.tree_util.tree_map(jnp.copy, self._rule_state)
        return jax.tree_util.tree_map(lambda a: np.asarray(a), snap)

    def _expected_rule_state_shapes(self):
        """Canonical (flat device-major) shape per rule-state field for
        THIS engine's current program dims — what checkpoints must match
        (computed, not allocated: the resident state may still be the
        no-programs placeholder when a restore re-installs programs)."""
        from sitewhere_tpu.ops.stateful import state_slab_lanes

        D = self.registry.devices.capacity
        P, S = self._rule_state_dims()
        return {"slab": (D, P, state_slab_lanes(S)), "gen": (P,),
                "fire_count": (P,), "suppress_count": (P,)}

    def _validate_canonical_rule_state(self, rule_state) -> None:
        for name, want in self._expected_rule_state_shapes().items():
            got = tuple(np.asarray(getattr(rule_state, name)).shape)
            if got != want:
                raise ValueError(
                    f"rule-state checkpoint shape mismatch for {name}: "
                    f"got {got}, engine expects {want} (program bucket/"
                    f"state slots/device capacity must match)")

    def load_canonical_rule_state(self, rule_state) -> None:
        self._validate_canonical_rule_state(rule_state)
        with self._state_lock:
            self._rule_state = jax.device_put(rule_state)
            self._rule_state_built_dims = self._rule_state_dims()

    # -- anomaly models (on-TPU inference; ml/compiler.py) ------------------

    def _compile_model_table(self):
        from sitewhere_tpu.ml.compiler import (
            compile_model_into, empty_model_table)

        table = empty_model_table(
            self.max_anomaly_models, self.anomaly_model_features,
            self.anomaly_model_layers, self.anomaly_model_width)
        for entry in self._anomaly_models.values():
            compile_model_into(
                table, entry["slot"], entry["spec"], entry["epoch"],
                intern_measurement=self.packer.measurements.intern,
                intern_alert_type=self.packer.alert_types.intern,
                lookup_tenant=self.registry.tenants.lookup,
                lookup_device_type=self.registry.device_types.lookup,
                measurement_slots=self.measurement_slots)
        return table

    def _validate_model_spec(self, spec: Dict) -> Dict:
        """Dry-run compile against THIS engine's static buckets: a spec
        that passes turns into table rows without crashing the hot path.
        Raises AnomalyModelError (409, names the field) otherwise — the
        contract shared by the REST and replicated-apply paths."""
        from sitewhere_tpu.ml.compiler import dry_run_compile

        return dry_run_compile(
            spec, measurement_slots=self.measurement_slots,
            max_features=self.anomaly_model_features,
            max_layers=self.anomaly_model_layers,
            width=self.anomaly_model_width,
            intern_measurement=self.packer.measurements.intern)

    def upsert_anomaly_model(self, spec: Dict, *,
                             slot: Optional[int] = None,
                             epoch: Optional[int] = None) -> Dict:
        """Install or replace an anomaly model (idempotent — boot config,
        checkpoint restore, cluster replication). A replace bumps the
        slot's epoch so its feature state resets inside the fused step;
        `slot`/`epoch` pin the assignment on checkpoint restore so
        mid-flight EWMA/rate state lines back up with its model."""
        from sitewhere_tpu.errors import ErrorCode, SiteWhereError

        spec = self._validate_model_spec(spec)
        token = spec["token"]
        with self._rules_io_lock:
            with self._lock:
                existing = self._anomaly_models.get(token)
                if slot is None:
                    if existing is not None:
                        slot = existing["slot"]
                    else:
                        used = {e["slot"]
                                for e in self._anomaly_models.values()}
                        free = [s for s in range(self.max_anomaly_models)
                                if s not in used]
                        if not free:
                            raise SiteWhereError(
                                "anomaly model capacity exceeded "
                                f"({self.max_anomaly_models} slots)",
                                ErrorCode.CAPACITY_EXCEEDED,
                                http_status=409)
                        slot = free[0]
                if epoch is None:
                    self._model_epoch += 1
                    epoch = self._model_epoch
                else:
                    self._model_epoch = max(self._model_epoch, epoch)
                entry = {"slot": int(slot), "epoch": int(epoch),
                         "spec": spec}
                self._anomaly_models[token] = entry
                self._models_enabled = True
                self._rules_version += 1
        return entry

    def create_anomaly_model(self, spec: Dict) -> Dict:
        """REST create semantics: duplicate token 409s atomically."""
        from sitewhere_tpu.errors import DuplicateTokenError

        with self._lock:
            token = (spec or {}).get("token")
            if token in self._anomaly_models:
                raise DuplicateTokenError(
                    f"anomaly model '{token}' already exists")
        return self.upsert_anomaly_model(spec)

    def remove_anomaly_model(self, token: str) -> bool:
        with self._rules_io_lock:
            with self._lock:
                entry = self._anomaly_models.pop(token, None)
                if entry is None:
                    return False
                self._models_enabled = bool(self._anomaly_models)
                self._rules_version += 1
        return True

    def get_anomaly_model(self, token: str) -> Optional[Dict]:
        with self._lock:
            entry = self._anomaly_models.get(token)
            return dict(entry["spec"]) if entry else None

    def list_anomaly_models(self) -> List[Dict]:
        """Model specs in slot order (the order fires resolve in)."""
        with self._lock:
            entries = sorted(self._anomaly_models.values(),
                             key=lambda e: e["slot"])
            return [dict(e["spec"]) for e in entries]

    def anomaly_models_by_slot(self) -> Dict[int, Dict]:
        with self._lock:
            return {e["slot"]: dict(e["spec"])
                    for e in self._anomaly_models.values()}

    def anomaly_model_manifest(self) -> List[Dict]:
        """Checkpoint form: spec + the runtime (slot, epoch) assignment,
        so a restore re-pins feature state to its model mid-flight."""
        with self._lock:
            return [{"slot": e["slot"], "epoch": e["epoch"],
                     "spec": dict(e["spec"])}
                    for e in sorted(self._anomaly_models.values(),
                                    key=lambda e: e["slot"])]

    def anomaly_model_counters(self) -> Dict[str, Dict[str, int]]:
        """Per-model cumulative fire/eval counters (one on-demand D2H
        fetch of two [P] vectors — never on the hot path). Counters live
        in the model state so they survive checkpoints; sharded engines
        hold per-shard partials summed here."""
        if self._model_state is None:
            return {}
        with self._state_lock:
            fires = np.asarray(self._model_state.fire_count)
            evals = np.asarray(self._model_state.eval_count)
        if fires.ndim == 2:  # sharded [S, P] partials
            fires, evals = fires.sum(0), evals.sum(0)
        with self._lock:
            return {token: {"fires": int(fires[e["slot"]])
                            if e["slot"] < fires.shape[0] else 0,
                            "evals": int(evals[e["slot"]])
                            if e["slot"] < evals.shape[0] else 0}
                    for token, e in self._anomaly_models.items()}

    # -- anomaly-model state (checkpointing) --------------------------------

    def canonical_model_state(self):
        """Host snapshot of the model feature state, flat device-major
        like canonical_state (sharded engine overrides)."""
        import jax.numpy as jnp

        if self._model_state is None:
            return None
        with self._state_lock:
            snap = jax.tree_util.tree_map(jnp.copy, self._model_state)
        return jax.tree_util.tree_map(lambda a: np.asarray(a), snap)

    def _expected_model_state_shapes(self):
        from sitewhere_tpu.ops.stateful import state_slab_lanes

        D = self.registry.devices.capacity
        P, F = self._model_state_dims()
        return {"slab": (D, P, state_slab_lanes(F)), "gen": (P,),
                "fire_count": (P,), "eval_count": (P,)}

    def _validate_canonical_model_state(self, model_state) -> None:
        for name, want in self._expected_model_state_shapes().items():
            got = tuple(np.asarray(getattr(model_state, name)).shape)
            if got != want:
                raise ValueError(
                    f"model-state checkpoint shape mismatch for {name}: "
                    f"got {got}, engine expects {want} (model bucket/"
                    f"feature slots/device capacity must match)")

    def load_canonical_model_state(self, model_state) -> None:
        self._validate_canonical_model_state(model_state)
        with self._state_lock:
            self._model_state = jax.device_put(model_state)
            self._model_state_built_dims = self._model_state_dims()

    # -- actuation policies (alert->command; actuation/compiler.py) ---------

    def _compile_policy_table(self):
        from sitewhere_tpu.actuation.compiler import (
            compile_policy_into, empty_policy_table)

        table = empty_policy_table(self.max_actuation_policies)
        for entry in self._actuation_policies.values():
            compile_policy_into(
                table, entry["slot"], entry["spec"], entry["epoch"],
                intern_command=self.commands.intern,
                lookup_tenant=self.registry.tenants.lookup)
        return table

    def _validate_policy_spec(self, spec: Dict) -> Dict:
        """Dry-run compile against THIS engine's command interner: a spec
        that passes turns into table rows without crashing the hot path.
        Raises ActuationPolicyError (409, names the field) otherwise —
        the contract shared by the REST and replicated-apply paths."""
        from sitewhere_tpu.actuation.compiler import dry_run_compile

        return dry_run_compile(spec, intern_command=self.commands.intern)

    def upsert_actuation_policy(self, spec: Dict, *,
                                slot: Optional[int] = None,
                                epoch: Optional[int] = None) -> Dict:
        """Install or replace an actuation policy (idempotent — boot
        config, checkpoint restore, cluster replication). A replace bumps
        the slot's epoch so its per-(device, policy) debounce state resets
        inside the fused step; `slot`/`epoch` pin the assignment on
        checkpoint restore so mid-window debounce state lines back up
        with its policy."""
        from sitewhere_tpu.errors import ErrorCode, SiteWhereError

        spec = self._validate_policy_spec(spec)
        token = spec["token"]
        with self._rules_io_lock:
            with self._lock:
                existing = self._actuation_policies.get(token)
                if slot is None:
                    if existing is not None:
                        slot = existing["slot"]
                    else:
                        used = {e["slot"]
                                for e in self._actuation_policies.values()}
                        free = [s for s
                                in range(self.max_actuation_policies)
                                if s not in used]
                        if not free:
                            raise SiteWhereError(
                                "actuation policy capacity exceeded "
                                f"({self.max_actuation_policies} slots)",
                                ErrorCode.CAPACITY_EXCEEDED,
                                http_status=409)
                        slot = free[0]
                if epoch is None:
                    self._actuation_epoch += 1
                    epoch = self._actuation_epoch
                else:
                    self._actuation_epoch = max(self._actuation_epoch,
                                                epoch)
                entry = {"slot": int(slot), "epoch": int(epoch),
                         "spec": spec}
                self._actuation_policies[token] = entry
                self._actuation_enabled = True
                self._rules_version += 1
        return entry

    def create_actuation_policy(self, spec: Dict) -> Dict:
        """REST create semantics: duplicate token 409s atomically."""
        from sitewhere_tpu.errors import DuplicateTokenError

        with self._lock:
            token = (spec or {}).get("token")
            if token in self._actuation_policies:
                raise DuplicateTokenError(
                    f"actuation policy '{token}' already exists")
        return self.upsert_actuation_policy(spec)

    def remove_actuation_policy(self, token: str) -> bool:
        with self._rules_io_lock:
            with self._lock:
                entry = self._actuation_policies.pop(token, None)
                if entry is None:
                    return False
                self._actuation_enabled = bool(self._actuation_policies)
                self._rules_version += 1
        return True

    def get_actuation_policy(self, token: str) -> Optional[Dict]:
        with self._lock:
            entry = self._actuation_policies.get(token)
            return dict(entry["spec"]) if entry else None

    def list_actuation_policies(self) -> List[Dict]:
        """Policy specs in slot order (the order lane rows resolve in)."""
        with self._lock:
            entries = sorted(self._actuation_policies.values(),
                             key=lambda e: e["slot"])
            return [dict(e["spec"]) for e in entries]

    def actuation_policies_by_slot(self) -> Dict[int, Dict]:
        with self._lock:
            return {e["slot"]: dict(e["spec"])
                    for e in self._actuation_policies.values()}

    def actuation_policy_manifest(self) -> List[Dict]:
        """Checkpoint form: spec + the runtime (slot, epoch) assignment,
        so a restore re-pins debounce state to its policy mid-window."""
        with self._lock:
            return [{"slot": e["slot"], "epoch": e["epoch"],
                     "spec": dict(e["spec"])}
                    for e in sorted(self._actuation_policies.values(),
                                    key=lambda e: e["slot"])]

    def actuation_policy_counters(self) -> Dict[str, Dict[str, int]]:
        """Per-policy cumulative fire/debounce counters (one on-demand
        D2H fetch of two [P] vectors — never on the hot path). Counters
        live in the actuation state so they survive checkpoints; sharded
        engines hold per-shard partials summed here."""
        if self._actuation_state is None:
            return {}
        with self._state_lock:
            fires = np.asarray(self._actuation_state.fire_count)
            deb = np.asarray(self._actuation_state.debounce_count)
        if fires.ndim == 2:  # sharded [S, P] partials
            fires, deb = fires.sum(0), deb.sum(0)
        with self._lock:
            return {token: {"fires": int(fires[e["slot"]])
                            if e["slot"] < fires.shape[0] else 0,
                            "debounced": int(deb[e["slot"]])
                            if e["slot"] < deb.shape[0] else 0}
                    for token, e in self._actuation_policies.items()}

    # -- actuation state (checkpointing) ------------------------------------

    def canonical_actuation_state(self):
        """Host snapshot of the per-(device, policy) debounce state, flat
        device-major like canonical_state (sharded engine overrides)."""
        import jax.numpy as jnp

        if self._actuation_state is None:
            return None
        with self._state_lock:
            snap = jax.tree_util.tree_map(jnp.copy, self._actuation_state)
        return jax.tree_util.tree_map(lambda a: np.asarray(a), snap)

    def _expected_actuation_state_shapes(self):
        from sitewhere_tpu.ops.stateful import state_slab_lanes

        D = self.registry.devices.capacity
        (P,) = self._actuation_state_dims()
        return {"slab": (D, P, state_slab_lanes(1)), "gen": (P,),
                "fire_count": (P,), "debounce_count": (P,)}

    def _validate_canonical_actuation_state(self, actuation_state) -> None:
        for name, want in self._expected_actuation_state_shapes().items():
            got = tuple(np.asarray(getattr(actuation_state, name)).shape)
            if got != want:
                raise ValueError(
                    f"actuation-state checkpoint shape mismatch for "
                    f"{name}: got {got}, engine expects {want} (policy "
                    f"bucket/device capacity must match)")

    def load_canonical_actuation_state(self, actuation_state) -> None:
        self._validate_canonical_actuation_state(actuation_state)
        with self._state_lock:
            self._actuation_state = jax.device_put(actuation_state)
            self._actuation_state_built_dims = self._actuation_state_dims()

    def take_command_fires(self) -> List[Dict]:
        """Drain command fires parked while no dispatcher was attached
        (tests, bare engines). With a dispatcher set this is empty."""
        out, self._pending_commands = self._pending_commands, []
        return out

    # -- params refresh -------------------------------------------------------

    def _refresh_params(self) -> None:
        with self._lock:
            snap = self.registry.snapshot()
            threshold = self._compile_threshold_table()
            geofence = self._compile_geofence_table()
            programs = self._compile_program_table()
            models = self._compile_model_table()
            policies = self._compile_policy_table()
            zones = ZoneTable(vertices=snap.zone_vertices, nvert=snap.zone_nvert,
                              tenant_idx=snap.zone_tenant, active=snap.zone_active)
            self._params = jax.device_put(PipelineParams(
                assignment_status=snap.assignment_status,
                tenant_idx=snap.tenant_idx,
                area_idx=snap.area_idx,
                device_type_idx=snap.device_type_idx,
                threshold=threshold, zones=zones, geofence=geofence,
                programs=programs, models=models, policies=policies))
            self._params_built_for = (snap.version, self._rules_version)

    def _ensure_params(self) -> PipelineParams:
        if self._params_built_for != (self.registry.version, self._rules_version):
            self._refresh_params()
        self._ensure_step_current()
        assert self._params is not None
        return self._params

    # -- processing -----------------------------------------------------------

    def _staging_blob_buffer(self, batch: EventBatch,
                             flight_rec=None) -> Optional[np.ndarray]:
        """Rotating reusable [WIRE_ROWS, B] staging buffer for full-size flat
        batches (ring of 6: blob contents stay stable through dispatch +
        async H2D even with pipelined staging depth 3 and two stager
        threads). Odd-size batches allocate fresh (returns None).

        ACCELERATOR BACKENDS ONLY: on the cpu backend jax zero-copies
        suitably-aligned numpy arrays into device buffers — a later pack
        into the recycled slot would corrupt an in-flight step's input
        (observed as a flaky one-row diff under pytest). On cpu the
        "transfer" IS a host copy anyway, so reuse saves nothing; on
        TPU/GPU device memory is separate and device_put always copies."""
        from sitewhere_tpu.ops.pack import WIRE_ROWS

        if (self._target_platform() == "cpu"
                or batch.device_idx.ndim != 1
                or batch.device_idx.shape[0] != self.batch_size):
            return None
        with self._blob_ring_lock:
            if self._blob_ring is None:
                self._blob_ring = [
                    np.empty((WIRE_ROWS, self.batch_size), np.int32)
                    for _ in range(6)]
                self._blob_ring_guards = [None] * len(self._blob_ring)
            pos = self._blob_ring_pos
            self._blob_ring_pos = (pos + 1) % len(self._blob_ring)
            buf = self._blob_ring[pos]
            guard, self._blob_ring_guards[pos] = (
                self._blob_ring_guards[pos], None)
        if guard is not None:
            # slot reuse must wait for the slot's previous H2D transfer:
            # the guard (consuming step's output, or the transferred
            # array itself) is ready no earlier than the transfer. By the
            # time a 6-slot ring cycles back this is almost always ready.
            if flight_rec is not None:
                flight_rec.begin_stage("guard")
            try:
                guard.block_until_ready()
            except Exception:
                pass  # a failed step still implies the transfer finished
            if flight_rec is not None:
                flight_rec.end_stage("guard")
        return buf

    def _note_blob_guard(self, buf, guard) -> None:
        """Record the transfer-completion guard for a ring slot after its
        blob was handed to jax (no-op for non-ring buffers). Compact
        4-row blobs are VIEWS into the 5-row ring slots — match through
        .base as well as identity."""
        base = getattr(buf, "base", None)
        with self._blob_ring_lock:
            if self._blob_ring is None:
                return
            for i, ring_buf in enumerate(self._blob_ring):
                if ring_buf is buf or ring_buf is base:
                    self._blob_ring_guards[i] = guard
                    return

    @property
    def staging_ring(self) -> StagingRing:
        """Lazily-built on-device H2D staging ring (pipeline/staging.py).
        Lazy so config can set `h2d_buffer_depth` before first use and so
        engines that never stage explicitly (pure serial submit of numpy
        blobs) pay nothing."""
        ring = self._staging_ring
        if ring is None:
            with self._staging_ring_lock:
                if self._staging_ring is None:
                    self._staging_ring = StagingRing(
                        self.h2d_buffer_depth, metrics=self._metrics)
                ring = self._staging_ring
        return ring

    def _h2d_with_retry(self, put):
        """Bounded retry/backoff around a host->device transfer. The host
        blob is intact regardless of how far a failed transfer got (no
        donation on this edge), so re-issuing the put is always safe."""
        attempt = 0
        while True:
            try:
                fault_point("h2d_error")
                return put()
            except Exception:
                attempt += 1
                if attempt > self.step_retries:
                    raise
                self._retry_counter.inc()
                self.health.note_retry()
                time.sleep(jittered(0.01 * (2 ** (attempt - 1))))

    def _acquire_staging_slot(self, flight_rec, order: Optional[int],
                              use_ring: bool):
        """Ring-slot acquisition for a staging edge: ordered + blocking
        on the normal path (backpressure when the ring is full), skipped
        entirely when the caller bypasses (overflow drain blobs — see
        stage_prepared). Stamps the at-acquire ring snapshot on the
        flight record for the occupancy rollup."""
        if not use_ring:
            return None
        ring = self.staging_ring
        slot = ring.acquire(order=order, flight_rec=flight_rec)
        if flight_rec is not None:
            flight_rec.ring = (ring.occupancy(), ring.depth)
        return slot

    def stage_blob(self, blob, flight_rec=None,
                   order: Optional[int] = None) -> StagedBlob:
        """Stage a packed wire blob through the H2D staging ring: acquire
        a slot (backpressure when all `h2d_buffer_depth` transfers are in
        flight), start the async device_put — arming the `h2d_error`
        fault point with the same bounded retry/backoff as every transfer
        edge — and return a handle submit_blob dispatches and releases.
        The pipelined feeder passes its sequence as `order` so slots are
        granted in dispatch order (see staging.py on why that matters).
        A failed transfer releases the slot guard-free and propagates, so
        neighboring in-flight slots are never disturbed."""
        slot = self._acquire_staging_slot(flight_rec, order, True)
        if flight_rec is not None:
            flight_rec.begin_stage("h2d")
        try:
            dev = self._h2d_with_retry(lambda: jax.device_put(blob))
        except BaseException:
            self.staging_ring.release(slot)
            raise
        finally:
            if flight_rec is not None:
                flight_rec.end_stage("h2d")
        slot.device_blob = dev
        if isinstance(blob, np.ndarray):
            # host-side blob-ring guard unchanged: the device array's
            # readiness proves the host staging buffer was fully read
            self._note_blob_guard(blob, dev)
        return StagedBlob(dev, slot, self.staging_ring)

    def submit(self, batch: EventBatch, age=None) -> ProcessOutputs:
        """Run one fused step; state advances in place (donated). `age`
        is the optional ingest-age sidecar (runtime/eventage.py) the
        caller opened at the receive edge — it rides the flight record
        and is closed by materialize_alerts."""
        # single-transfer host->device staging (see ops.pack.batch_to_blob).
        # The flight record's "pack" segment keeps host staging visible
        # now that "dispatch" covers only the jit call (pack used to be
        # inside it); the staging-ring guard wait is marked separately.
        rec = self.flight.begin_step(engine=self.name)
        if age is not None:
            rec.age = age
        # buffer acquisition first: its ring-guard wait is the "guard"
        # segment and must not nest inside (double-count with) "pack"
        out_buf = self._staging_blob_buffer(batch, flight_rec=rec)
        rec.begin_stage("pack")
        fault_point("pack_fail")
        blob = batch_to_blob(batch, out=out_buf)
        rec.end_stage("pack")
        self._stage_hist.observe(rec.stage_s("pack"),
                                 engine=self.name, stage="pack")
        self._sample_tenant_mix(rec, batch)
        return self.submit_blob(
            blob, n_events=int(np.asarray(batch.valid).sum()),
            flight_rec=rec)

    def _sample_tenant_mix(self, rec, batch: EventBatch) -> None:
        """Every Nth step, attach the batch's tenant mix (host bincount
        over the registry's tenant mirror — never a device fetch) to the
        flight record and the per-tenant event histogram."""
        self._flight_step_n += 1
        if self._flight_step_n % self._flight_sample_every:
            return
        try:
            dev = np.asarray(batch.device_idx).ravel()
            valid = np.asarray(batch.valid).ravel().astype(bool)
            tenants = self.registry._tenant_idx[dev[valid]]
            mix = np.bincount(tenants, minlength=1)
        except Exception:
            return
        rec.tenant_mix = tuple(int(x) for x in mix[:self.max_tenants])
        for tenant, count in enumerate(rec.tenant_mix):
            if count:
                self._tenant_hist.observe(
                    float(count), engine=self.name, tenant=str(tenant))

    def submit_blob(self, blob, n_events: Optional[int] = None,
                    flight_rec=None) -> ProcessOutputs:
        """Run one fused step on an already-packed wire blob (numpy or
        device-resident). The pipelined feeder (pipeline/feed.py) stages
        blobs — pack + async device_put — on worker threads so host staging
        of batch N+1 overlaps device compute of step N. `n_events` feeds
        the events meter (counting valid bits of a device-resident blob
        here would force a D2H sync on the hot path). `flight_rec` is a
        flight record opened by the caller (submit(), or a feeder's
        stager thread — the explicit cross-thread handoff); when None
        this opens a dispatch-only record."""
        slot = None
        if isinstance(blob, StagedBlob):
            # stage_blob already ran the transfer through a ring slot;
            # dispatch here, then hand the slot back with the step output
            # as the reuse guard
            slot, blob = blob.slot, blob.blob
        if self._state is None:  # lazy init for direct (un-started) use
            self.initialize()  # full lifecycle init so a later start() won't re-init
        if self._rule_state is None:  # set_state() without lifecycle init
            self._rule_state = self._init_rule_state()
        if self._model_state is None:
            self._model_state = self._init_model_state()
        if self._actuation_state is None:
            self._actuation_state = self._init_actuation_state()
        params = self._ensure_params()
        rec = flight_rec if flight_rec is not None else (
            self.flight.begin_step(engine=self.name))
        rec.begin_stage("dispatch")
        try:
            outputs = self._dispatch_with_retry(
                lambda: self._step_blob(params, self._state, self._rule_state,
                                        self._model_state,
                                        self._actuation_state, blob))
        except BaseException:
            if slot is not None:
                # guard-free release: the failed step's input array is
                # dropped at next reuse without waiting on anything
                self.staging_ring.release(slot)
            raise
        rec.end_stage("dispatch")
        if slot is not None:
            self.staging_ring.release(slot, outputs.processed)
        if n_events is not None:
            rec.events = int(n_events)
        self._flight_last = rec
        self._stage_hist.observe(rec.stage_s("dispatch"),
                                 engine=self.name, stage="dispatch")
        if isinstance(blob, np.ndarray):
            # ring-slot transfer guard: the implicit jit transfer of a
            # numpy blob completes no later than the step's outputs
            self._note_blob_guard(blob, outputs.processed)
        self.batches_processed += 1
        if n_events is not None:
            self._metrics.meter("events").mark(n_events)
        return outputs

    def _dispatch_with_retry(self, step_call,
                             points=("h2d_error", "dispatch_error")):
        """Run one state-advancing step call with bounded retry around
        transient H2D/dispatch failures: `step_retries` extra attempts
        with exponential backoff + jitter, then the error propagates so
        the consumer layer can park the batch on its dead-letter topic —
        the submitter is never wedged. Injected faults (runtime/faults.py
        `h2d_error`/`dispatch_error`) raise BEFORE the jitted call, so
        drill retries are always state-safe; an organic failure inside
        the call may have consumed the donated state buffers, in which
        case the retries fail too and the error escalates through the
        same path. `step_call` returns (state, rule_state, model_state,
        actuation_state, outputs). `points` lists the fault points armed
        on this path — the sharded engine stages H2D separately, so its
        dispatch drops h2d_error."""
        attempt = 0
        while True:
            try:
                for point in points:
                    fault_point(point)
                with self._state_lock:
                    (self._state, self._rule_state, self._model_state,
                     self._actuation_state, outputs) = step_call()
                self.health.note_success()
                return outputs
            except Exception:
                attempt += 1
                if attempt > self.step_retries:
                    raise
                self._retry_counter.inc()
                self.health.note_retry()
                time.sleep(jittered(0.01 * (2 ** (attempt - 1))))

    def submit_routed(self, batch: EventBatch, age=None):
        """Engine-agnostic submit: returns (batch_for_materialization,
        outputs) on both engine kinds. The sharded engine's submit already
        returns its routed [S, B] batch; here the input batch doubles as the
        materialization batch. Callers that support either engine
        (pipeline/inbound.py, sources/fastlane.py) use this instead of
        type-sniffing submit()'s return."""
        return batch, self.submit(batch, age=age)

    def _fetch_lanes_with_retry(self, outputs: ProcessOutputs):
        """D2H fetch of BOTH fixed-shape lanes (alert + command) in one
        device_get, with the same bounded retry/backoff contract as
        `_dispatch_with_retry`. Unlike dispatch, the fetch never donates
        buffers, so retrying a genuinely failed device_get is always safe."""
        attempt = 0
        while True:
            try:
                fault_point("lane_fetch_error")
                lanes = jax.device_get((outputs.alert_lanes,
                                        outputs.command_lanes))
                self.health.note_success()
                return lanes
            except Exception:
                attempt += 1
                if attempt > self.step_retries:
                    raise
                self._retry_counter.inc()
                self.health.note_retry()
                time.sleep(jittered(0.01 * (2 ** (attempt - 1))))

    def materialize_alerts(self, batch: EventBatch, outputs: ProcessOutputs,
                           max_alerts: Optional[int] = None
                           ) -> List[DeviceAlert]:
        """Turn the step's device-compacted alert lanes back into
        API-level DeviceAlert events.

        On a tunneled runtime fetch count and fetch bytes — not compute —
        set the latency floor (~100 ms per round trip when the link's
        burst bucket is drained; docs/PERF.md), so the step packs fired
        rows into fixed-capacity lanes ON DEVICE (ops/compact.py +
        ops/actuate.py) and this ships exactly TWO fixed-shape,
        lane-sized fetches per step — the alert lane and the command lane,
        in one device_get — regardless of batch size, replacing the
        six-array / two-phase fetch. Device tokens resolve through the
        interner's cached token array (one fancy-index, no per-row Python
        lookups).

        A `max_alerts` bound and lane overflow (> capacity fired rows)
        both count on `alerts_dropped`, surface as a metric, and log —
        never a silent drop. Differential contract: the returned list is
        exactly what the mask-scan reference (materialize_alerts_maskscan)
        produces for the first `alert_lane_capacity` fired rows, order
        included (tests/test_alert_lanes.py)."""
        from sitewhere_tpu.ops.compact import decode_alert_lanes

        pending, self._pending_alerts = self._pending_alerts, []
        # amend the last-dispatched flight record: the fetch/materialize
        # segments belong to the step whose outputs these are
        rec = self._flight_last
        if rec is not None:
            rec.begin_stage("lane_fetch")
        # THE one device_get: both fixed-shape lanes in a single round trip
        lanes, cmd_lanes = self._fetch_lanes_with_retry(outputs)
        if rec is not None:
            rec.end_stage("lane_fetch")
            rec.begin_stage("materialize")
            self._stage_hist.observe(rec.stage_s("lane_fetch"),
                                     engine=self.name, stage="lane_fetch")
        try:
            self.d2h_fetches += 2
            self.d2h_bytes += lanes.nbytes + cmd_lanes.nbytes
            dec = decode_alert_lanes(lanes)
            self._account_lane_overflow(dec.dropped_alerts)
            dec = self._bound_alert_rows(dec, max_alerts)
            if dec.n == 0:
                return pending
            rows = dec.rows
            dev_rows = np.asarray(batch.device_idx)[rows]
            ts_rows = np.asarray(batch.ts)[rows]
            return pending + self._emit_alerts(dec, dev_rows, ts_rows)
        finally:
            if rec is not None:
                rec.end_stage("materialize")
                self._stage_hist.observe(
                    rec.stage_s("materialize"),
                    engine=self.name, stage="materialize")
            self._materialize_commands(cmd_lanes, rec)
            if rec is not None:
                self._close_age(rec)

    def _close_age(self, rec) -> None:
        """Close the step's ingest-age sidecar at the materialize edge:
        the open AgeSidecar resolves (pure close — the ingest service
        re-closes the same sidecar at its persist/alert edges) into the
        AgeSummary that replaces it on the record, feeding the rollup
        ride-along and the (engine, edge) histogram."""
        age = rec.age
        if age is None or not hasattr(age, "close"):
            return
        summary = age.close()
        rec.age = summary
        observe_summary(self._age_hist, summary,
                        engine=self.name, edge="materialize")
        if getattr(rec, "commands", 0):
            # the closing waterfall edge: ingest -> command fan-out done.
            # Fan-out ran synchronously inside this materialize pass, so
            # the same summary closed after it IS the detection->actuation
            # age for every event in the step.
            observe_summary(self._age_hist, summary, engine=self.name,
                            edge="detection_to_actuation")

    def _materialize_commands(self, cmd_lanes, rec) -> None:
        """Decode the step's command lane, account fire/debounce/overflow
        activity, and hand resolved fires to the dispatcher (or the
        pending list when none is attached). Differential contract: the
        resolved fires are bit-derived from the lane the NumPy oracle
        reproduces (tests/test_actuation.py)."""
        from sitewhere_tpu.ops.actuate import decode_command_lanes

        if rec is not None:
            rec.begin_stage("actuate")
        dec = decode_command_lanes(np.asarray(cmd_lanes))
        self._account_command_activity(dec)
        fires = self._emit_command_fires(dec) if dec.n else []
        if rec is not None:
            rec.commands = len(fires)
            rec.end_stage("actuate")
            self._stage_hist.observe(rec.stage_s("actuate"),
                                     engine=self.name, stage="actuate")
        self._fanout_commands(fires, rec)

    def _fanout_commands(self, fires: List[Dict], rec) -> None:
        """Hand resolved fires to the attached dispatcher (or park them);
        shared by both engines' materialize passes."""
        if not fires:
            return
        if rec is not None:
            rec.begin_stage("command_fanout")
        try:
            if self.command_dispatcher is not None:
                self.command_dispatcher.dispatch(self, fires)
            else:
                self._pending_commands.extend(fires)
        finally:
            if rec is not None:
                rec.end_stage("command_fanout")
                self._stage_hist.observe(
                    rec.stage_s("command_fanout"),
                    engine=self.name, stage="command_fanout")

    def _account_command_activity(self, dec) -> None:
        fired = int(dec.fired) - int(dec.dropped)
        if fired:
            self.commands_fired += fired
            self._metrics.counter("actuation.fires").inc(fired)
        if dec.debounced:
            self.commands_debounced += int(dec.debounced)
            self._metrics.counter("actuation.debounced").inc(
                int(dec.debounced))
        if dec.dropped:
            self.commands_dropped += int(dec.dropped)
            self._metrics.counter("commands.dropped").inc(int(dec.dropped))
            import logging
            logging.getLogger("sitewhere.pipeline").warning(
                "command-lane overflow: %d policy fires beyond the %d-row "
                "lane capacity dropped on device (commands_dropped=%d "
                "total)", int(dec.dropped), self.command_lane_capacity,
                self.commands_dropped)

    def _emit_command_fires(self, dec) -> List[Dict]:
        """Resolve decoded command-lane slots into dispatchable fire
        records: device token via the cached interner array (one fancy
        index), command token + params from the installed policy spec."""
        policies = self.actuation_policies_by_slot()
        tokens = self.registry.devices.token_array()[dec.dev].tolist()
        slots = dec.policy_slot.tolist()
        levels = dec.level.tolist()
        sources = dec.source.tolist()
        fires: List[Dict] = []
        for i in range(dec.n):
            spec = policies.get(slots[i])
            if spec is None:  # policy removed between dispatch and fetch
                continue
            fires.append({
                "policy": spec["token"], "slot": slots[i],
                "device": tokens[i], "command": spec["command"],
                "params": list(spec.get("params", ())),
                "level": levels[i], "source": sources[i],
                "tenant": spec.get("tenant_token", "")})
        return fires

    def _account_lane_overflow(self, dropped: int) -> None:
        if not dropped:
            return
        self.alerts_dropped += dropped
        self._metrics.counter("alerts.dropped").inc(dropped)
        import logging
        logging.getLogger("sitewhere.pipeline").warning(
            "alert-lane overflow: %d alerts beyond the %d-row lane "
            "capacity dropped on device (alerts_dropped=%d total)",
            dropped, self.alert_lane_capacity, self.alerts_dropped)

    def _bound_alert_rows(self, dec, max_alerts: Optional[int]):
        """Apply a caller's max_alerts bound to decoded lanes (row count,
        matching the pre-lane contract) with the same loud accounting."""
        if max_alerts is None or dec.n <= max_alerts:
            return dec
        dropped = dec.n - max_alerts
        self.alerts_dropped += dropped
        self._metrics.counter("alerts.dropped").inc(dropped)
        import logging
        logging.getLogger("sitewhere.pipeline").warning(
            "alert storm: %d fired rows exceed max_alerts=%d; "
            "dropping %d (alerts_dropped=%d total)",
            dec.n, max_alerts, dropped, self.alerts_dropped)
        return dec.head(max_alerts)

    def _emit_alerts(self, dec, dev_rows: np.ndarray,
                     ts_rows: np.ndarray) -> List[DeviceAlert]:
        """DeviceAlert list for decoded lane slots. `dev_rows`/`ts_rows`
        are the fired rows' device indices and relative timestamps;
        everything vectorizable (tokens, dates, level enums) is resolved
        by array ops before the per-alert object loop."""
        with self._lock:
            thr_rules = list(self._threshold_rules)
            geo_rules = list(self._geofence_rules)
        programs = self.rule_programs_by_slot()
        # model-fire resolution gets its own flight segment (nested inside
        # materialize): the lane carries only slot ids, so the spec lookup
        # + bit decode here is the host-side cost of on-device scoring
        flight = self._flight_last
        if flight is not None:
            flight.begin_stage("model_eval")
        models = self.anomaly_models_by_slot()
        model_f = dec.model_fired.tolist()
        model_s = dec.model_slot.tolist()
        if flight is not None:
            flight.end_stage("model_eval")
        tokens = self.registry.devices.token_array()[dev_rows].tolist()
        dates = (ts_rows.astype(np.int64)
                 + self.packer.epoch_base_ms).tolist()
        thr_f = dec.thr_fired.tolist()
        geo_f = dec.geo_fired.tolist()
        prog_f = dec.prog_fired.tolist()
        thr_r = dec.thr_rule.tolist()
        geo_r = dec.geo_rule.tolist()
        prog_r = dec.prog_rule.tolist()
        thr_l = dec.thr_level.tolist()
        geo_l = dec.geo_level.tolist()
        prog_l = dec.prog_level.tolist()
        n_thr, n_geo = len(thr_rules), len(geo_rules)
        levels = _ALERT_LEVELS
        alerts: List[DeviceAlert] = []
        for i in range(dec.n):
            token = tokens[i]
            if thr_f[i] and 0 <= thr_r[i] < n_thr:
                rule = thr_rules[thr_r[i]]
                alerts.append(DeviceAlert(
                    device_id=token, source=AlertSource.SYSTEM,
                    level=levels.get(thr_l[i]) or AlertLevel(thr_l[i]),
                    type=rule.alert_type,
                    message=rule.alert_message
                    or f"threshold rule {rule.token} fired",
                    event_date=dates[i]))
            if geo_f[i] and 0 <= geo_r[i] < n_geo:
                rule = geo_rules[geo_r[i]]
                alerts.append(DeviceAlert(
                    device_id=token, source=AlertSource.SYSTEM,
                    level=levels.get(geo_l[i]) or AlertLevel(geo_l[i]),
                    type=rule.alert_type,
                    message=rule.alert_message
                    or f"geofence rule {rule.token} fired",
                    event_date=dates[i]))
            if prog_f[i] and prog_r[i] in programs:
                spec = programs[prog_r[i]]
                alerts.append(DeviceAlert(
                    device_id=token, source=AlertSource.SYSTEM,
                    level=levels.get(prog_l[i]) or AlertLevel(prog_l[i]),
                    type=spec["alert_type"],
                    message=spec["alert_message"]
                    or f"rule program {spec['token']} fired",
                    event_date=dates[i]))
            if model_f[i] and model_s[i] in models:
                # the lane carries only the 8-bit model slot; level and
                # type resolve from the installed spec host-side
                spec = models[model_s[i]]
                alerts.append(DeviceAlert(
                    device_id=token, source=AlertSource.SYSTEM,
                    level=levels.get(int(spec["alert_level"]))
                    or AlertLevel(int(spec["alert_level"])),
                    type=spec["alert_type"],
                    message=spec["alert_message"]
                    or f"anomaly model {spec['token']} fired",
                    event_date=dates[i]))
        return alerts

    # -- presence -------------------------------------------------------------

    def presence_sweep(self) -> List[str]:
        """Run the presence check; returns tokens of newly-missing devices."""
        params = self._ensure_params()
        now_rel = np.int32(self.packer.rel_ts(int(time.time() * 1000)))
        registered = params.assignment_status == 1
        with self._state_lock:
            self._state, newly_missing = self._presence(
                self._state, registered, now_rel,
                np.int32(min(self.presence_missing_interval_ms, 2 ** 31 - 1)))
        rows = np.nonzero(np.asarray(newly_missing))[0]
        if rows.size == 0:
            return []
        # vectorized token resolution (cached dense array, one fancy
        # index) — "" marks unknown/gap slots
        tokens = self.registry.devices.token_array()[rows].tolist()
        return [t for t in tokens if t]

    # -- state reads ----------------------------------------------------------

    @property
    def state(self) -> DeviceStateTensors:
        assert self._state is not None, "engine not initialized"
        return self._state

    def set_state(self, state: DeviceStateTensors) -> None:
        """Checkpoint restore."""
        with self._state_lock:
            self._state = jax.device_put(state)

    def canonical_state(self) -> DeviceStateTensors:
        """Topology-independent host snapshot: flat device-major layout,
        identical no matter how many shards produced it — what checkpoints
        store, so a checkpoint taken on one mesh restores onto any other
        (elastic recovery; the reference's equivalent is Kafka replay into
        a rebuilt store)."""
        import jax.numpy as jnp

        # device-side copy under the lock (fast HBM copy that detaches
        # from the donate-able buffers); the slow D2H conversion runs
        # OUTSIDE the lock so checkpoint saves don't stall the hot path
        with self._state_lock:
            snap = jax.tree_util.tree_map(jnp.copy, self.state)
        return jax.tree_util.tree_map(lambda a: np.asarray(a), snap)

    def _canonical_shape_of(self, field_name: str):
        """Expected canonical (flat) shape for one state field — .shape on
        the resident array costs nothing (no device transfer)."""
        return getattr(self.state, field_name).shape

    def _validate_canonical(self, state: DeviceStateTensors) -> None:
        """Every dimension must match this engine — a silent
        measurement-slot or tenant-width mismatch would corrupt state via
        clamped scatters. Shared by both engines (expected shapes differ
        via _canonical_shape_of)."""
        import dataclasses as _dc

        for f in _dc.fields(state):
            got = tuple(getattr(state, f.name).shape)
            expect = self._canonical_shape_of(f.name)
            if got != tuple(expect):
                raise ValueError(
                    f"checkpoint shape mismatch for {f.name}: got {got}, "
                    f"engine expects {tuple(expect)} (device capacity/"
                    f"measurement slots/tenant width must match)")

    def load_canonical_state(self, state: DeviceStateTensors) -> None:
        """Inverse of canonical_state (single-chip: plain placement)."""
        self._validate_canonical(state)
        self.set_state(state)

    def _state_row(self, idx: int):
        """Fetch one device's row from every state tensor (overridden by the
        sharded engine, which remaps global -> (shard, local))."""
        class Row:
            pass

        row = Row()
        with self._state_lock:  # vs concurrent donation (see __init__)
            s = self._state
            for field_name in ("last_interaction", "present",
                               "presence_missing_since",
                               "event_count", "last_location",
                               "last_location_ts",
                               "last_measurement", "last_measurement_ts",
                               "last_alert_type", "last_alert_level",
                               "last_alert_ts"):
                setattr(row, field_name,
                        np.asarray(getattr(s, field_name)[idx]))
        return row

    def get_device_state(self, device_token: str) -> Optional[DeviceState]:
        """Materialize one device's state row as the API-level DeviceState."""
        idx = self.registry.devices.lookup(device_token)
        if idx == 0 or self._state is None:
            return None
        row = self._state_row(idx)
        if row is None:  # multi-host: owned by another process
            return None
        state = DeviceState(device_id=device_token)
        if int(row.last_interaction) > _NEG:
            state.last_interaction_date = self.packer.abs_ts(int(row.last_interaction))
        state.presence = (PresenceState.PRESENT if bool(row.present)
                          else PresenceState.NOT_PRESENT)
        if int(row.presence_missing_since) > _NEG:
            state.presence_missing_date = self.packer.abs_ts(
                int(row.presence_missing_since))
        if int(row.last_location_ts) > _NEG:
            lat, lon, elev = (float(x) for x in row.last_location)
            state.last_location = (self.packer.abs_ts(int(row.last_location_ts)),
                                   lat, lon, elev)
        # cached dense slot -> name array instead of a token_of call per
        # measurement slot (this runs per REST device-state read)
        names = self.packer.measurements.token_array()
        for slot in range(self.measurement_slots):
            ts_slot = int(row.last_measurement_ts[slot])
            if ts_slot > _NEG:
                name = names[slot] or f"slot{slot}"
                state.last_measurements[name] = (self.packer.abs_ts(ts_slot),
                                                 float(row.last_measurement[slot]))
        if int(row.last_alert_ts) > _NEG:
            atype = self.packer.alert_types.token_of(int(row.last_alert_type)) or ""
            state.last_alerts[atype] = (self.packer.abs_ts(int(row.last_alert_ts)),
                                        int(row.last_alert_level), "")
        return state

    def stats(self) -> Dict[str, int]:
        with self._state_lock:  # tenant-count reads vs donation
            s = self._state
            tenant_events = np.asarray(s.tenant_event_count).tolist()
            tenant_alerts = np.asarray(s.tenant_alert_count).tolist()
        return {
            "batches": self.batches_processed,
            "tenant_event_count": tenant_events,
            "tenant_alert_count": tenant_alerts,
            "scope": "global",  # single-controller: totals are global
        }

    # -- device profiling (the reference's Jaeger span surface; on-device
    # the equivalent is an XLA profiler trace — runtime/tracing.py) ---------

    def start_device_trace(self, log_dir: str) -> None:
        """Begin capturing an XLA/jax profiler trace (HLO timelines, memory)
        to `log_dir` (view with TensorBoard or xprof). Idempotent: a second
        call while tracing is a no-op."""
        if getattr(self, "_tracing", False):
            return
        jax.profiler.start_trace(log_dir)
        self._tracing = True

    def stop_device_trace(self) -> None:
        if getattr(self, "_tracing", False):
            jax.profiler.stop_trace()
            self._tracing = False

    def on_stop(self, monitor) -> None:
        # never leave an XLA profiler trace open past the engine
        self.stop_device_trace()
