"""Inbound processing: decoded events -> validate -> persist -> TPU step.

Reference: service-inbound-processing — DecodedEventsConsumer.java:38 reads
event-source-decoded-events, InboundPayloadProcessingLogic.java:91-197
validates device + active assignment (gRPC lookups in the reference; registry
dict lookups here), unregistered devices route to
inbound-unregistered-device-events, and UnaryEventStorageStrategy.java:54
persists each event through event management.

TPU-first difference: persistence and rule/state processing are NOT two more
microservice hops. One consumer batch is (a) persisted through
DeviceEventManagement (whose triggers feed the persisted->enriched topics for
control-plane consumers) and (b) packed into a fixed-width EventBatch and
submitted to the fused pjit step, which does rule-eval + device-state in one
XLA program. Rule alerts are materialized host-side and persisted as system
events, closing the loop the reference runs through three services.
"""

from __future__ import annotations

import logging
from typing import Any, Dict, List, Optional, Tuple

import msgpack

from sitewhere_tpu.errors import SiteWhereError
from sitewhere_tpu.model.event import (
    DeviceAlert, DeviceCommandResponse, DeviceEvent, DeviceEventBatch,
    DeviceLocation, DeviceMeasurement, DeviceStreamData, event_from_dict)
from sitewhere_tpu.runtime.bus import ConsumerHost, EventBus, Record, TopicNaming
from sitewhere_tpu.runtime.lifecycle import LifecycleComponent
from sitewhere_tpu.runtime.metrics import MetricsRegistry
from sitewhere_tpu.runtime.recovery import GLOBAL_REPLAY_BARRIER

LOGGER = logging.getLogger("sitewhere.inbound")


def _events_from_request(kind: str, request: Dict[str, Any]) -> List[DeviceEvent]:
    """Rebuild API events from a decoded-request payload (sources/manager
    _pack_request's `request` dict)."""
    if kind == "DeviceEventBatch":
        events: List[DeviceEvent] = []
        for group in ("measurements", "locations", "alerts"):
            for data in request.get(group, []):
                events.append(event_from_dict(data))
        return events
    if kind in ("DeviceCommandResponse", "DeviceStreamData"):
        return [event_from_dict(request)]
    raise SiteWhereError(f"unsupported decoded request kind '{kind}'")


class InboundProcessingService(LifecycleComponent):
    """Tenant-scoped inbound processor (InboundProcessingTenantEngine).

    `engine` is a PipelineEngine (or ShardedPipelineEngine); `events` is the
    tenant's DeviceEventManagement. Either may be None for partial wiring
    (e.g. persist-only during replay).
    """

    def __init__(self, bus: EventBus, registry, events=None, engine=None,
                 tenant: str = "default",
                 naming: Optional[TopicNaming] = None,
                 persist_rule_alerts: bool = True,
                 cluster=None,
                 batcher=None,
                 metrics: Optional[MetricsRegistry] = None):
        super().__init__(f"inbound-processing:{tenant}")
        self.bus = bus
        self.registry = registry
        self.events = events
        self.engine = engine
        self.tenant = tenant
        self.naming = naming or TopicNaming()
        self.persist_rule_alerts = persist_rule_alerts
        # latency tier (pipeline.mode="latency"): hot events route through
        # the shared AdaptiveBatcher (pipeline/feed.py) instead of packing
        # a per-consumer-poll batch — offers coalesce across tenants and
        # flush on fill or linger, bounding ingest->alert wall time
        self.batcher = batcher
        # multi-host hooks (parallel/cluster.py ClusterService): ownership
        # routing of decoded records + lockstep step-loop feeding. None =
        # single-process (direct engine submit).
        self.cluster = cluster
        m = (metrics or MetricsRegistry()).scoped("inbound")
        self.processed_meter = m.meter("processed")
        self.unregistered_counter = m.counter("unregistered")
        self.failed_counter = m.counter("failed")
        self.dead_letter_counter = m.counter("step_dead_lettered")
        self._host = ConsumerHost(
            bus, self.naming.event_source_decoded_events(tenant),
            group_id=f"inbound-processing-{tenant}", handler=self.process)
        # the reprocess loop is a first-class pipeline input (reference:
        # KafkaTopicNaming.java:48-69): records an operator replays from a
        # dead-letter topic (runtime/deadletter.py) re-enter here with the
        # same validate -> persist -> fused-step handling
        self._reprocess_host = ConsumerHost(
            bus, self.naming.inbound_reprocess_events(tenant),
            group_id=f"inbound-reprocess-{tenant}", handler=self.process)

    def on_start(self, monitor) -> None:
        self._host.start()
        self._reprocess_host.start()

    def on_stop(self, monitor) -> None:
        self._reprocess_host.stop()
        self._host.stop()

    # -- processing --------------------------------------------------------
    def process(self, records: List[Record]) -> None:
        """One consumer batch end-to-end. Public so replay/tests can drive
        it synchronously without the poll thread."""
        hot: List[Tuple[DeviceEvent, str]] = []
        hot_records: List[Record] = []
        forward: Dict[int, List[Record]] = {}
        replay_all: Optional[bool] = None  # every hot record suppressed?
        for record in records:
            try:
                data = msgpack.unpackb(record.value, raw=False)
                token = data.get("deviceToken", "")
                events = _events_from_request(data.get("kind", ""),
                                              data.get("request", {}))
            except Exception:
                self.failed_counter.inc()
                continue
            if self.cluster is not None:
                # ownership routing (multi-host): records for devices whose
                # shard lives on another host forward BEFORE persist — the
                # owner persists + steps its own devices, so event log and
                # device state agree on ownership (the Kafka analog: the
                # record key routes to the owning consumer)
                owner = self.cluster.owner_process(token)
                if owner != self.cluster.process_id:
                    if data.get("fwdFrom") is not None:
                        # already forwarded once and this host STILL does
                        # not own it: the hosts' registries disagree
                        # (provisioning drift) — park it on the misroute
                        # surface (visible to `deadletters list`, like
                        # ForeignRowsConsumer's disowned rows), never
                        # ping-pong
                        self.failed_counter.inc()
                        self.bus.publish(
                            self.naming.event_source_decoded_events(
                                self.tenant) + ".misrouted",
                            token.encode(), record.value)
                        continue
                    forward.setdefault(owner, []).append(record)
                    continue
            if not self._validate(token, record):
                continue
            # exactly-once effects under checkpoint replay
            # (runtime/recovery.py): while this tenant's replay budget
            # lasts, a record's events still rebuild device/rule/model
            # state (they join `hot`) but skip re-persisting — the rows
            # are already durable, and skipping the persist also skips
            # the trigger fan-out (enriched topics, command delivery,
            # analytics increments). A PARTIAL take at the budget
            # boundary persists anyway: at-least-once for that record,
            # with sequence-watermark dedup catching stamped stragglers.
            suppressed = False
            if events and GLOBAL_REPLAY_BARRIER.active(self.tenant):
                took = GLOBAL_REPLAY_BARRIER.take(self.tenant, len(events))
                suppressed = took >= len(events)
            if suppressed:
                persisted = list(events)
            else:
                persisted = self._persist(token, events)
            if persisted:
                hot_records.append(record)
                replay_all = suppressed if replay_all is None \
                    else (replay_all and suppressed)
            for event in persisted:
                hot.append((event, token))
            self.processed_meter.mark(len(persisted))
        if forward:
            # raises on delivery failure -> the whole batch redelivers
            # (at-least-once; locally-persisted records may duplicate,
            # which the model's idempotent event ids tolerate)
            self.cluster.forward_decoded(forward, self.tenant)
        if self.cluster is not None and hot:
            # lockstep feeding: queue for the cluster step loop and wait
            # for the fold ticket so the consumer commit happens only
            # after the rows reached device state (or were forwarded)
            for ticket in self.cluster.feed_hot([e for e, _ in hot],
                                                [t for _, t in hot]):
                if not ticket.wait(timeout=60.0):
                    raise TimeoutError(
                        "cluster step loop did not fold batch in 60s")
        elif self.engine is not None and hot:
            # Never let the hot path poison the consumer: a raising handler
            # would redeliver the batch and re-persist duplicates forever.
            # A batch that exhausts the engine's dispatch retries parks on
            # the dead-letter topic instead (replayable via `deadletters
            # replay` -> the reprocess loop; re-persist on replay is
            # tolerated by the model's idempotent event ids) — every
            # offered event either materializes, parks, or is counted
            # shed, never silently lost.
            try:
                self._submit_hot(hot, suppress_effects=bool(replay_all))
            except Exception:
                self.failed_counter.inc()
                LOGGER.exception("fused step failed for batch of %d events",
                                 len(hot))
                self._park_hot(hot_records)

    def _park_hot(self, hot_records: List[Record]) -> None:
        """Park the source records of a step-poisoned batch on the decoded
        topic's dead-letter surface and mark the engine draining — the
        no-silent-loss half of the swallow above."""
        dlq = (self.naming.event_source_decoded_events(self.tenant)
               + ".dead-letter")
        for record in hot_records:
            self.bus.publish(dlq, record.key, record.value)
        self.dead_letter_counter.inc(len(hot_records))
        health = getattr(self.engine, "health", None)
        if health is not None:
            health.note_poison()

    def _validate(self, token: str, record: Record) -> bool:
        """Device + active-assignment check
        (InboundPayloadProcessingLogic.validateAssignment :156-193)."""
        device = self.registry.get_device_by_token(token)
        if device is None or self.registry.get_active_assignment(device.id) is None:
            self.unregistered_counter.inc()
            self.bus.publish(
                self.naming.inbound_unregistered_device_events(self.tenant),
                token.encode(), record.value)
            return False
        return True

    def _persist(self, token: str,
                 events: List[DeviceEvent]) -> List[DeviceEvent]:
        if self.events is None:
            return events
        try:
            batch = DeviceEventBatch(device_token=token)
            extra: List[DeviceEvent] = []
            for event in events:
                if isinstance(event, DeviceAlert):
                    batch.alerts.append(event)
                elif isinstance(event, DeviceMeasurement):
                    batch.measurements.append(event)
                elif isinstance(event, DeviceLocation):
                    batch.locations.append(event)
                else:
                    extra.append(event)
            persisted = self.events.add_device_event_batch(token, batch)
            if extra:
                device = self.registry.get_device_by_token(token)
                assignment = self.registry.get_active_assignment(device.id)
                for event in extra:
                    if isinstance(event, DeviceCommandResponse):
                        persisted.extend(self.events.add_command_responses(
                            assignment.token, event))
                    else:
                        persisted.extend(self.events.add_stream_data(
                            assignment.token, event))
            return persisted
        except Exception:
            self.failed_counter.inc()
            LOGGER.exception("persist failed for device '%s'", token)
            return []

    def _submit_hot(self, hot: List[Tuple[DeviceEvent, str]],
                    suppress_effects: bool = False) -> None:
        """Pack + run the fused step; rule alerts feed back into persistence
        (the reference's ZoneTestRuleProcessor -> addDeviceAlerts loop).

        `suppress_effects` (replay barrier): the step still runs — the
        replayed events must rebuild rule/device state — but the derived
        alerts fired the first time around, so their persist + fan-out
        is skipped for an all-replay batch."""
        events = [e for e, _ in hot]
        tokens = [t for _, t in hot]
        if self.batcher is not None:
            # latency tier: coalesce into the shared adaptive batcher and
            # wait for the flush (so consumer commit still means "reached
            # device state", the same contract as the direct path)
            pairs = self.batcher.offer(events, tokens).result(timeout=60.0)
        else:
            pairs = (self.engine.submit_routed(batch)
                     for batch in self.engine.packer.pack_events(events,
                                                                 tokens))
        for batch, outputs in pairs:
            if not self.persist_rule_alerts or self.events is None \
                    or suppress_effects:
                continue
            for alert in self.engine.materialize_alerts(batch, outputs):
                device = self.registry.get_device_by_token(alert.device_id)
                if device is None:
                    continue
                assignment = self.registry.get_active_assignment(device.id)
                if assignment is None:
                    continue
                self.events.add_alerts(assignment.token, alert)
