"""Device presence management: background sweep marking missing devices.

Reference: service-device-state presence/DevicePresenceManager.java:47 — a
PresenceChecker thread (:110-135) periodically scans device state for devices
whose last interaction exceeds the missing interval and fires a
PresenceState.NOT_PRESENT state change through PresenceNotificationStrategies
(send-once semantics).

TPU-first: the scan is not a datastore query — it is the `check_presence`
kernel over the HBM-resident device-state tensors (pipeline/state_tensors.py),
which flips `present` in place and returns only newly-missing rows, giving
send-once for free. This component is just the cadence + the state-change
event fan-out.
"""

from __future__ import annotations

import logging
import threading
from typing import Callable, List, Optional

from sitewhere_tpu.model.event import DeviceStateChange
from sitewhere_tpu.model.state import PresenceState
from sitewhere_tpu.runtime.lifecycle import LifecycleComponent
from sitewhere_tpu.runtime.metrics import MetricsRegistry

LOGGER = logging.getLogger("sitewhere.presence")


class DevicePresenceManager(LifecycleComponent):
    """Periodic presence sweep over a PipelineEngine's state tensors.

    `events` (DeviceEventManagement, optional) persists NOT_PRESENT state
    changes; `registry` resolves assignments for them. Additional callbacks
    registered with `add_listener` receive the newly-missing token list —
    the PresenceNotificationStrategy extension point.
    """

    def __init__(self, engine, registry=None, events=None,
                 check_interval_s: float = 60.0,
                 metrics: Optional[MetricsRegistry] = None):
        super().__init__("presence-manager")
        self.engine = engine
        self.registry = registry
        self.events = events
        self.check_interval_s = check_interval_s
        self._listeners: List[Callable[[List[str]], None]] = []
        self._stop = threading.Event()
        self._thread: Optional[threading.Thread] = None
        m = (metrics or MetricsRegistry()).scoped("presence")
        self.missing_counter = m.counter("marked_missing")

    def add_listener(self, callback: Callable[[List[str]], None]) -> None:
        self._listeners.append(callback)

    def on_start(self, monitor) -> None:
        self._stop.clear()
        self._thread = threading.Thread(target=self._run,
                                        name="presence-checker", daemon=True)
        self._thread.start()

    def on_stop(self, monitor) -> None:
        self._stop.set()
        if self._thread is not None:
            self._thread.join(timeout=5.0)
            self._thread = None

    def _run(self) -> None:
        while not self._stop.wait(self.check_interval_s):
            try:
                self.sweep()
            except Exception:
                LOGGER.exception("presence sweep failed")

    def sweep(self) -> List[str]:
        """One pass; returns tokens newly marked missing. Public so tests and
        schedulers can drive it synchronously."""
        missing = self.engine.presence_sweep()
        if not missing:
            return missing
        self.missing_counter.inc(len(missing))
        if self.events is not None and self.registry is not None:
            for token in missing:
                device = self.registry.get_device_by_token(token)
                if device is None:
                    continue
                assignment = self.registry.get_active_assignment(device.id)
                if assignment is None:
                    continue
                self.events.add_state_changes(assignment.token, DeviceStateChange(
                    device_id=token, attribute="presence", type="presence",
                    previous_state=PresenceState.PRESENT.name,
                    new_state=PresenceState.NOT_PRESENT.name))
        for callback in self._listeners:
            callback(missing)
        return missing
