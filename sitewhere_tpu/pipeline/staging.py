"""On-device H2D staging-buffer ring: bounded, ordered, guarded slots.

ROADMAP item 2 / "Extending TensorFlow's Semantics with Pipelined
Execution": a depth-configurable ring of on-device staging destinations
so batch N+1's host->device transfer overlaps batch N's compute. Every
transfer on the hot path — the single-chip feeder's ``device_put``, the
sharded engine's ``stage_prepared``/``stage_routed_blob`` — first
acquires a ring slot; the ring bounds how many transfers can be in
flight (backpressure when full), recycles each slot's fixed-shape HBM
destination (the previous step's array is dropped only after its
consuming step proved the transfer complete, so the allocator hands the
same block back to the next ``device_put`` instead of growing the
working set), and preserves dispatch order via ordered acquisition.

Why ordered acquisition matters: stagers pack concurrently, so the
stager holding sequence N can reach the ring AFTER the stagers holding
N+1 and N+2. Granting free slots in arrival order could then fill the
ring with later sequences while the step thread waits for N — every
slot held by a step that cannot dispatch until N does. ``acquire``
therefore grants a free slot to the LOWEST pending order key; callers
without an order (serial submit paths) draw keys from a high counter so
they never starve an ordered feeder. The feeders additionally bound
their stage-ahead window to the ring depth (pipeline/feed.py), so the
earliest unstaged sequence always finds a slot — the pigeonhole
argument that makes the ring deadlock-free.

Slot lifecycle::

    acquire(order)        wait for a free slot (counting full_waits and
                          marking the flight "stage_wait" segment when
                          the ring is full), then wait on the slot's
                          guard (the previous consumer's output — ready
                          no earlier than the previous transfer) and
                          drop the previous device array
    slot.device_blob = .. the caller's device_put result parks here;
                          resident ring bytes show in the HBM ledger
    release(guard)        slot returns to the free pool; `guard` is the
                          consuming step's output (or None on an error
                          path — reuse then skips the guard wait)

The disarmed cost is one lock acquisition and a couple of list ops per
step — no allocation, no device sync (the guard wait is almost always
already-ready by the time a slot cycles back).
"""

from __future__ import annotations

import heapq
import itertools
import threading
from typing import List, Optional

# order keys for callers that do not pass one (serial submit paths):
# drawn from a counter starting far above any plausible feeder sequence,
# so an ordered feeder's keys always win the grant when both wait
_UNORDERED_BASE = 1 << 60


class RingSlot:
    """One staging destination: the device array most recently
    transferred into this slot and the guard proving its consumer is
    done with it."""

    __slots__ = ("index", "device_blob", "guard", "in_flight")

    def __init__(self, index: int) -> None:
        self.index = index
        self.device_blob = None   # last device_put result staged here
        self.guard = None         # consuming step's output (readiness
        self.in_flight = False    # proves the transfer completed)


class StagedBlob:
    """Handle for a wire blob whose H2D transfer went through a ring
    slot (PipelineEngine.stage_blob): `blob` is the device array,
    `slot`/`ring` let submit_blob release the slot with the consuming
    step's output as the reuse guard."""

    __slots__ = ("blob", "slot", "ring")

    def __init__(self, blob, slot: RingSlot, ring: "StagingRing") -> None:
        self.blob = blob
        self.slot = slot
        self.ring = ring


class StagingRing:
    """Fixed-depth ring of on-device staging slots with ordered,
    backpressured acquisition (module docstring has the full contract).

    `metrics` is the owning engine's scoped registry; the ring counts
    `staging_ring.full_waits` there (every acquire that found no free
    slot) so a stalled ring is visible per engine.
    """

    def __init__(self, depth: int, metrics=None) -> None:
        self.depth = max(1, int(depth))
        self._slots = [RingSlot(i) for i in range(self.depth)]
        self._free: List[RingSlot] = list(self._slots)
        self._cv = threading.Condition()
        self._waiters: List = []              # heap of (key, tiebreak)
        self._tiebreak = itertools.count()
        self._unordered = itertools.count(_UNORDERED_BASE)
        self.full_waits = 0
        self.acquires = 0
        self._full_counter = (metrics.counter("staging_ring.full_waits")
                              if metrics is not None else None)

    # -- hot path -----------------------------------------------------
    def acquire(self, order: Optional[int] = None, flight_rec=None,
                blocking: bool = True) -> Optional[RingSlot]:
        """Take a free slot, granting in `order` (lowest pending key
        first). Blocks while the ring is full — the backpressure edge —
        counting `full_waits` and marking the flight record's
        "stage_wait" segment. `blocking=False` returns None instead of
        waiting (drain-step bypass). After the grant, waits on the
        slot's guard so the previous occupant's transfer is provably
        complete before its device array is dropped for reuse."""
        key = (order if order is not None else next(self._unordered),
               next(self._tiebreak))
        waited = False
        with self._cv:
            if not blocking:
                if not self._free:
                    return None
                slot = self._free.pop(0)
                slot.in_flight = True
            else:
                heapq.heappush(self._waiters, key)
                while not (self._free and self._waiters[0] == key):
                    if not waited and not self._free:
                        # the ring-full wait is the backpressure signal;
                        # an ordering wait (slot free, earlier sequence
                        # pending) is not "full" and stays uncounted
                        waited = True
                        self.full_waits += 1
                        if self._full_counter is not None:
                            self._full_counter.inc()
                        if flight_rec is not None:
                            flight_rec.begin_stage("stage_wait")
                    self._cv.wait(timeout=0.1)
                heapq.heappop(self._waiters)
                slot = self._free.pop(0)
                slot.in_flight = True
                self._cv.notify_all()   # next-lowest waiter re-checks
            self.acquires += 1
        if waited and flight_rec is not None:
            flight_rec.end_stage("stage_wait")
        guard, slot.guard = slot.guard, None
        if guard is not None:
            # reuse must wait for the slot's previous consumer: its
            # output is ready no earlier than the transfer it consumed.
            # By the time a ring cycles back this is almost always done.
            if flight_rec is not None:
                flight_rec.begin_stage("guard")
            try:
                guard.block_until_ready()
            except Exception:
                pass  # a failed step still implies the transfer finished
            if flight_rec is not None:
                flight_rec.end_stage("guard")
        # drop the previous occupant only now: the allocator hands the
        # same fixed-shape block to the caller's next device_put instead
        # of growing the steady-state working set
        slot.device_blob = None
        return slot

    def release(self, slot: RingSlot, guard=None) -> None:
        """Return `slot` to the free pool. `guard` is the consuming
        step's output; None (error paths) makes the next reuse skip the
        guard wait — safe, because the error path never recycles the
        host buffer the failed transfer may still be reading."""
        with self._cv:
            if not slot.in_flight:
                return  # double-release guard (error-path idempotence)
            slot.guard = guard
            slot.in_flight = False
            self._free.append(slot)
            self._cv.notify_all()

    # -- telemetry ----------------------------------------------------
    def occupancy(self) -> int:
        """Slots currently acquired (in flight)."""
        with self._cv:
            return self.depth - len(self._free)

    def resident_bytes(self) -> int:
        """Device bytes currently parked in ring slots (the HBM
        ledger's `staging_ring` table row)."""
        total = 0
        for slot in self._slots:
            blob = slot.device_blob
            total += int(getattr(blob, "nbytes", 0) or 0)
        return total

    def state(self) -> dict:
        """Snapshot for flight export / REST diagnosis: per-slot
        in-flight bits plus the backpressure counters — a stalled ring
        shows every slot in flight and `full_waits` climbing."""
        with self._cv:
            return {
                "depth": self.depth,
                "occupancy": self.depth - len(self._free),
                "in_flight": [s.in_flight for s in self._slots],
                "full_waits": self.full_waits,
                "acquires": self.acquires,
            }
