"""HBM-resident device state: the tensorized service-device-state.

Reference: service-device-state keeps one Mongo document per assignment with
last-interaction date, last location, last measurement per name, last alert
per type, and presence (DeviceStateProcessingLogic.java:116+,
DevicePresenceManager.java:47). Here the same state is fixed-capacity tensors
indexed by interned device index, updated wholesale per batch by
deterministic keyed reductions (ops/segments.py) and periodically
checkpointed to host storage (persist/checkpoint.py) — the HBM copy is a
cache rebuildable by bus replay (SURVEY.md §5 checkpoint/resume).

Capacity knobs: D devices, M tracked measurement slots (measurement names with
interned index < M get per-name last values; all names still update
last-interaction), T tenants for the stat rows.
"""

from __future__ import annotations

from typing import Tuple

import jax.numpy as jnp
import numpy as np
from flax import struct

_NEG = -(2 ** 31)


@struct.dataclass
class DeviceStateTensors:
    """All tensors device-indexed unless noted. ts columns are rebased int32 ms
    (EventPacker.epoch_base_ms); -2^31 = never."""

    last_interaction: jnp.ndarray    # int32 [D]
    present: jnp.ndarray             # bool [D]
    presence_missing_since: jnp.ndarray  # int32 [D]
    event_count: jnp.ndarray         # int32 [D]

    last_location: jnp.ndarray       # f32 [D,3] lat/lon/elev
    last_location_ts: jnp.ndarray    # int32 [D]

    last_measurement: jnp.ndarray    # f32 [D,M]
    last_measurement_ts: jnp.ndarray  # int32 [D,M]

    last_alert_type: jnp.ndarray     # int32 [D]
    last_alert_level: jnp.ndarray    # int32 [D]
    last_alert_ts: jnp.ndarray       # int32 [D]

    tenant_event_count: jnp.ndarray  # int32 [T]
    tenant_alert_count: jnp.ndarray  # int32 [T]

    @property
    def num_devices(self) -> int:
        return self.last_interaction.shape[0]

    @property
    def num_measurement_slots(self) -> int:
        return self.last_measurement.shape[1]


def init_device_state_np(max_devices: int, measurement_slots: int = 32,
                         max_tenants: int = 16) -> DeviceStateTensors:
    """Numpy-leaved initial state: allocates no device buffers, so callers
    with a non-default device mesh (sharded engines, the driver's virtual CPU
    mesh) can place the whole tree with ONE explicit device_put instead of
    dispatching per-leaf ops on whatever backend happens to be default."""
    D, M, T = max_devices, measurement_slots, max_tenants
    return DeviceStateTensors(
        last_interaction=np.full((D,), _NEG, np.int32),
        present=np.zeros((D,), bool),
        presence_missing_since=np.full((D,), _NEG, np.int32),
        event_count=np.zeros((D,), np.int32),
        last_location=np.zeros((D, 3), np.float32),
        last_location_ts=np.full((D,), _NEG, np.int32),
        last_measurement=np.zeros((D, M), np.float32),
        last_measurement_ts=np.full((D, M), _NEG, np.int32),
        last_alert_type=np.zeros((D,), np.int32),
        last_alert_level=np.full((D,), -1, np.int32),
        last_alert_ts=np.full((D,), _NEG, np.int32),
        tenant_event_count=np.zeros((T,), np.int32),
        tenant_alert_count=np.zeros((T,), np.int32),
    )


def init_device_state(max_devices: int, measurement_slots: int = 32,
                      max_tenants: int = 16) -> DeviceStateTensors:
    import jax

    return jax.tree_util.tree_map(
        jnp.asarray,
        init_device_state_np(max_devices, measurement_slots, max_tenants))
