"""Outbound payload enrichment: persisted events -> enriched topics.

Reference: service-inbound-processing PersistedEventsConsumer.java:41 ->
OutboundPayloadEnrichmentLogic.java:54-93 — for every event read back from
inbound-persisted-events, re-resolve the assignment + device, attach a
GDeviceEventContext, and publish to inbound-enriched-events (all events) and
inbound-enriched-command-invocations (command invocations only, :89-92), keyed
by device token for per-device ordering.

TPU-first note: the *hot* consumers of enrichment (rule eval + device state)
do NOT read these topics — they run inside the fused pjit step
(pipeline/step.py) against the registry mirror, so enrichment is a gather, not
an RPC. These topics exist for the control-plane consumers the reference
fans out to: outbound connectors, command delivery, and external readers.
"""

from __future__ import annotations

from dataclasses import asdict
from typing import List, Optional

import msgpack

from sitewhere_tpu.model.event import (
    DeviceEvent, DeviceEventContext, DeviceEventType, event_from_dict)
from sitewhere_tpu.runtime.bus import ConsumerHost, EventBus, Record, TopicNaming
from sitewhere_tpu.runtime.lifecycle import LifecycleComponent
from sitewhere_tpu.runtime.metrics import MetricsRegistry


def pack_enriched(context: DeviceEventContext, event: DeviceEvent) -> bytes:
    """GEnrichedEventPayload: context envelope + event."""
    return msgpack.packb({"context": asdict(context),
                          "event": event.to_dict()}, use_bin_type=True)


def unpack_enriched(payload: bytes):
    """-> (DeviceEventContext, DeviceEvent)"""
    data = msgpack.unpackb(payload, raw=False)
    ctx = DeviceEventContext(**data["context"])
    return ctx, event_from_dict(data["event"])


class PayloadEnrichment(LifecycleComponent):
    """Consumes inbound-persisted-events and republishes enriched payloads.

    The reference re-fetches assignment + device over gRPC per event
    (OutboundPayloadEnrichmentLogic.java:60-76); here it is two dict lookups
    against the in-proc registry.
    """

    def __init__(self, bus: EventBus, registry, tenant: str = "default",
                 naming: Optional[TopicNaming] = None,
                 metrics: Optional[MetricsRegistry] = None):
        super().__init__(f"enrichment:{tenant}")
        self.bus = bus
        self.registry = registry
        self.tenant = tenant
        self.naming = naming or TopicNaming()
        m = (metrics or MetricsRegistry()).scoped("enrichment")
        self.enriched_meter = m.meter("enriched")
        self.failed_counter = m.counter("failed")
        self._host = ConsumerHost(
            bus, self.naming.inbound_persisted_events(tenant),
            group_id=f"enrichment-{tenant}", handler=self._process)

    def on_start(self, monitor) -> None:
        self._host.start()

    def on_stop(self, monitor) -> None:
        self._host.stop()

    # -- processing --------------------------------------------------------
    def _context_for(self, event: DeviceEvent) -> DeviceEventContext:
        from sitewhere_tpu.persist.event_management import context_for_assignment
        return context_for_assignment(self.registry,
                                      event.device_assignment_id, self.tenant)

    def _process(self, records: List[Record]) -> None:
        enriched_topic = self.naming.inbound_enriched_events(self.tenant)
        command_topic = self.naming.inbound_enriched_command_invocations(
            self.tenant)
        for record in records:
            try:
                event = event_from_dict(msgpack.unpackb(record.value, raw=False))
                context = self._context_for(event)
            except Exception:
                self.failed_counter.inc()
                continue
            payload = pack_enriched(context, event)
            key = context.device_token.encode()
            self.bus.publish(enriched_topic, key, payload)
            if event.event_type == DeviceEventType.COMMAND_INVOCATION:
                self.bus.publish(command_topic, key, payload)
            self.enriched_meter.mark(1)
