"""Top-level instance: the whole platform composed as one component tree.

Reference: in SiteWhere an "instance" is ~20 separate Spring Boot processes
(service-* dirs, SURVEY.md §2.4) bootstrapped by service-instance-management
(InstanceTemplateManager.java:32) and coordinated through ZooKeeper + Kafka.
Here the instance is ONE process (scaling happens on the TPU mesh, not by
process fan-out): shared event bus + columnar log + TPU pipeline engine,
per-tenant engines managed by TenantEngineManager, user/tenant managements,
JWT token service, and instance bootstrap — all under a single lifecycle
root so `start()`/`stop()` brings the platform up/down deterministically.
"""

from __future__ import annotations

import logging
import os
import threading
from typing import Callable, Dict, Optional

from sitewhere_tpu.model.tenant import Tenant
from sitewhere_tpu.multitenant.engine import TenantEngine, TenantEngineManager
from sitewhere_tpu.multitenant.instance import InstanceBootstrap
from sitewhere_tpu.multitenant.tenants import TenantManagement
from sitewhere_tpu.persist.eventlog import ColumnarEventLog
from sitewhere_tpu.registry.store import SqliteStore
from sitewhere_tpu.runtime.bus import EventBus, TopicNaming
from sitewhere_tpu.runtime.lifecycle import LifecycleComponent
from sitewhere_tpu.runtime.metrics import GLOBAL_METRICS
from sitewhere_tpu.security.tokens import TokenManagement
from sitewhere_tpu.security.users import UserManagement

LOGGER = logging.getLogger("sitewhere.instance")


class SiteWhereInstance(LifecycleComponent):
    """Single-process platform instance.

    Parameters mirror the reference's instance settings
    (instance/InstanceSettings.java): instance id, data directory (replaces
    the ZK/Mongo split), and pipeline sizing knobs. With ``enable_pipeline``
    the fused TPU hot path is attached; without it the control plane still
    runs fully (useful for API-only deployments and tests).
    """

    def __init__(self, instance_id: str = "default",
                 data_dir: Optional[str] = None,
                 enable_pipeline: bool = False,
                 max_devices: int = 8192, max_zones: int = 64,
                 max_zone_vertices: int = 16, batch_size: int = 2048,
                 measurement_slots: int = 8, max_tenants: int = 16,
                 bus_partitions: int = 8,
                 default_tenant: Optional[str] = "default",
                 admin_username: str = "admin",
                 admin_password: str = "password",
                 shards: int = 1,
                 mesh=None,
                 device_routing: Optional[bool] = None,
                 tenant_datastores: Optional[Dict] = None,
                 checkpoint_interval_s: Optional[float] = None,
                 latency_linger_ms: Optional[float] = None,
                 latency_adaptive: bool = True,
                 allow_fault_drills: bool = False,
                 fault_plan: Optional[Dict] = None,
                 admission_step_budget_ms: Optional[float] = None,
                 admission_queue_depth_budget: Optional[int] = None,
                 trace_sample_n: int = 0,
                 h2d_buffer_depth: int = 3,
                 serving_workers: int = 4,
                 serving_queue_depth_budget: int = 64,
                 serving_latency_budget_ms: float = 0.0,
                 serving_cache_mb: float = 64.0,
                 serving_mesh_row_threshold: Optional[int] = None,
                 refit_interval_s: Optional[float] = None):
        super().__init__(f"instance:{instance_id}")
        self.instance_id = instance_id
        self.data_dir = data_dir
        # observability.trace_sample_n: sample 1 in N ingest deliveries
        # into a journey span stitched across busnet hops (0 disables);
        # ingest services read this at construction (sources/fastlane.py)
        self.trace_sample_n = int(trace_sample_n or 0)
        # multi-host deployment hooks (parallel/cluster.py ClusterService
        # installs itself here BEFORE start(); tenant engines pass it into
        # their inbound processors for ownership routing + lockstep feeds)
        self.cluster_hooks = None
        self.naming = TopicNaming(instance=instance_id)
        self.metrics = GLOBAL_METRICS
        # recovery epoch (runtime/recovery.py): minted once per boot,
        # durable under data_dir — stamps checkpoint manifests, gossip/
        # provisioning envelopes, and busnet RPCs so anything this
        # incarnation wrote can be fenced after a takeover, and a
        # restarted host always comes back above its fenced floor
        from sitewhere_tpu.runtime.recovery import mint_epoch
        self.recovery_epoch = mint_epoch(data_dir)

        bus_dir = os.path.join(data_dir, "bus") if data_dir else None
        log_dir = os.path.join(data_dir, "events") if data_dir else None
        self.bus = EventBus(partitions=bus_partitions, data_dir=bus_dir)
        self.event_log = ColumnarEventLog(data_dir=log_dir)
        # per-tenant datastore choices (reference: tenants select their
        # store via DatastoreConfigurationParser) — overrides come from the
        # operator (config model) or `datastore.*` tenant metadata; tenants
        # without one share self.event_log
        from sitewhere_tpu.persist.datastore import TenantDatastoreManager
        self.datastores = TenantDatastoreManager(
            self.event_log, base_dir=data_dir,
            overrides=tenant_datastores)

        self.registry_tensors = None
        self.pipeline_engine = None
        if enable_pipeline:
            from sitewhere_tpu.parallel.mesh import shard_axis_size
            from sitewhere_tpu.registry.tensors import RegistryTensors
            n_shards = (shard_axis_size(mesh) if mesh is not None
                        else max(1, shards))
            if max_devices % max(1, n_shards):
                raise ValueError(
                    f"max_devices {max_devices} must be divisible by "
                    f"{n_shards} shards")
            # shard-congruent device interning: ownership (idx % S) is a
            # pure function of the token, so cluster hosts need not
            # provision in identical order (registry/tensors.py)
            self.registry_tensors = RegistryTensors(
                max_devices=max_devices, max_zones=max_zones,
                max_zone_vertices=max_zone_vertices,
                shard_classes=n_shards)
            if shards > 1 or mesh is not None:
                # SPMD hot path over a device mesh (config model's
                # pipeline.shards; parallel/engine.py). An explicit `mesh`
                # (e.g. parallel.distributed.make_global_mesh() under
                # jax.distributed) overrides the local shard count — the
                # multi-host serve mode passes the global mesh here.
                from sitewhere_tpu.parallel import (
                    ShardedPipelineEngine, make_mesh)
                self.pipeline_engine = ShardedPipelineEngine(
                    self.registry_tensors,
                    mesh=mesh if mesh is not None else make_mesh(shards),
                    per_shard_batch=batch_size,
                    measurement_slots=measurement_slots,
                    max_tenants=max_tenants,
                    device_routing=device_routing,
                    h2d_buffer_depth=h2d_buffer_depth)
            else:
                from sitewhere_tpu.pipeline.engine import PipelineEngine
                self.pipeline_engine = PipelineEngine(
                    self.registry_tensors, batch_size=batch_size,
                    measurement_slots=measurement_slots,
                    max_tenants=max_tenants,
                    h2d_buffer_depth=h2d_buffer_depth)
        # latency tier (pipeline.mode="latency"): one shared adaptive
        # batcher coalesces every tenant's hot events and flushes on fill
        # or linger (pipeline/feed.py) — inbound consumers offer to it
        # instead of packing per-poll batches. Adaptive linger (default)
        # dispatches a complete offered burst immediately; linger_ms then
        # only bounds coalescing behind an in-flight flush
        # (pipeline.adaptive_linger turns the classic fixed linger back on)
        self.latency_batcher = None
        if latency_linger_ms is not None and self.pipeline_engine is not None:
            from sitewhere_tpu.pipeline.feed import AdaptiveBatcher
            self.latency_batcher = AdaptiveBatcher(
                self.pipeline_engine, linger_ms=latency_linger_ms,
                adaptive=latency_adaptive)

        # concurrent query serving tier (serving/, docs/SERVING.md):
        # planner-routed measurement-window reads — host kernel for small
        # scans, sharded replay over the live mesh for large ones — behind
        # an incremental [K, W] grid cache and bounded read admission, so
        # dashboard pollers never stall ingest. The planner's mesh provider
        # prefers the pipeline's own mesh (already forming the step loop's
        # shard axis); a pipeline-less instance falls back to live_mesh().
        from sitewhere_tpu.analytics.engine import WindowedAnalyticsEngine
        from sitewhere_tpu.serving import (
            QueryExecutor, QueryPlanner, WindowGridCache)
        from sitewhere_tpu.serving.planner import DEFAULT_MESH_ROW_THRESHOLD
        self.analytics_planner = QueryPlanner(
            self.event_log, mesh_provider=self._serving_mesh,
            mesh_row_threshold=(serving_mesh_row_threshold
                                if serving_mesh_row_threshold is not None
                                else DEFAULT_MESH_ROW_THRESHOLD))
        self.analytics_engine = WindowedAnalyticsEngine(
            self.event_log, planner=self.analytics_planner)
        self.window_cache = WindowGridCache(
            max_bytes=int(float(serving_cache_mb) * (1 << 20)))
        self.serving = QueryExecutor(
            self.analytics_engine, self.analytics_planner, self.window_cache,
            workers=serving_workers,
            queue_depth_budget=serving_queue_depth_budget,
            latency_budget_ms=serving_latency_budget_ms or 0.0)
        # unattended drift-refit sweeps (actuation/refit.py): when set, a
        # SIMPLE-trigger schedule + DRIFT_REFIT job is installed on every
        # tenant engine at boot (_make_engine). Off by default: refits
        # rewrite live model constants, so autonomy is an operator opt-in.
        self.refit_interval_s = (float(refit_interval_s)
                                 if refit_interval_s else None)

        # robustness plane (runtime/faults.py, sources/manager.py):
        # `allow_fault_drills` gates the POST /api/instance/faults drill
        # endpoint (403 otherwise — drills are an operator action, never
        # ambient); `fault_plan` arms a seeded schedule at boot (config
        # model faults.*); admission budgets turn on front-door overload
        # shedding fed by the flight recorder + decoded-events backlog
        self.allow_fault_drills = bool(allow_fault_drills)
        if fault_plan:
            from sitewhere_tpu.runtime.faults import FaultPlan, arm
            arm(FaultPlan.from_json(fault_plan))
        if (admission_step_budget_ms is not None
                or admission_queue_depth_budget is not None):
            from sitewhere_tpu.sources.manager import GLOBAL_ADMISSION
            GLOBAL_ADMISSION.configure(
                step_budget_ms=admission_step_budget_ms,
                queue_depth_budget=admission_queue_depth_budget,
                queue_depth=self._ingest_backlog)

        # global (non-multitenant) managements — reference:
        # service-user-management / service-tenant-management
        self.user_management = UserManagement(self._make_store("users"))
        self.tenant_management = TenantManagement(
            self._make_store("tenants"), bus=self.bus, naming=self.naming)
        self.token_management = TokenManagement()
        # user mutations (local REST or cluster-replicated applies —
        # multitenant/replication.py) invalidate cached JWT auth state:
        # an update drops the claims cache, a delete revokes every token
        # the user already holds
        self.user_management.add_mutation_listener(self._on_user_mutation)
        self.bootstrap = InstanceBootstrap(
            self.user_management, self.tenant_management,
            admin_username=admin_username, admin_password=admin_password)

        self.engine_manager = TenantEngineManager(
            self.tenant_management, self._make_engine, bus=self.bus,
            naming=self.naming)
        self._default_tenant = default_tenant

        # label generation (reference: service-label-generation) — generators
        # are stateless, so one manager serves every tenant
        from sitewhere_tpu.labels import LabelGeneratorManager
        self.label_generators = LabelGeneratorManager()

        # versioned user scripts (reference: Groovy scripting + ZK script
        # management), synced under data_dir when persistent
        from sitewhere_tpu.runtime.scripts import ScriptManager
        self.script_manager = ScriptManager(data_dir=self.data_dir)
        # durable scripted-rule installs (reference: ZK-synced script
        # config, ScriptSynchronizer.java:32): survives restarts, rides
        # the instance checkpoint, and replicates via cluster gossip —
        # tenant engines re-install from it at boot (_make_engine)
        from sitewhere_tpu.rules.store import (
            RuleProgramStore, ScriptedRuleStore)
        self.scripted_rules = ScriptedRuleStore(data_dir=self.data_dir)
        # durable rule-program installs (the CEP-lite compiler's control
        # plane — rules/compiler.py): tenant-scoped CRUD persisted with
        # the ScriptedRuleStore pattern, replicated cluster-wide with the
        # LWW/tombstone algebra, re-installed into the pipeline engine at
        # boot below
        self.rule_programs = RuleProgramStore(data_dir=self.data_dir)
        self._rule_program_lock = threading.Lock()
        if self.pipeline_engine is not None:
            for row in self.rule_programs.all_installs():
                try:
                    self.pipeline_engine.upsert_rule_program(row["spec"])
                except Exception:
                    logging.getLogger("sitewhere.instance").exception(
                        "could not restore rule program %r for tenant %s",
                        row["token"], row["tenant"])
        # durable anomaly-model installs (on-TPU inference — ml/): same
        # store pattern as the rule programs, re-installed into the
        # engine's weight tables at boot
        from sitewhere_tpu.ml import ModelStore
        self.anomaly_models = ModelStore(data_dir=self.data_dir)
        self._anomaly_model_lock = threading.Lock()
        if self.pipeline_engine is not None:
            for row in self.anomaly_models.all_installs():
                try:
                    self.pipeline_engine.upsert_anomaly_model(row["spec"])
                except Exception:
                    logging.getLogger("sitewhere.instance").exception(
                        "could not restore anomaly model %r for tenant %s",
                        row["token"], row["tenant"])
        # durable actuation-policy installs (alert -> command policies —
        # actuation/): same store pattern as the anomaly models,
        # re-installed into the engine's policy table at boot so
        # in-flight debounce windows resume against the same slots
        from sitewhere_tpu.actuation import ActuationPolicyStore, CommandFanout
        self.actuation_policies = ActuationPolicyStore(data_dir=self.data_dir)
        self._actuation_policy_lock = threading.Lock()
        self.command_fanout = None
        if self.pipeline_engine is not None:
            for row in self.actuation_policies.all_installs():
                try:
                    self.pipeline_engine.upsert_actuation_policy(row["spec"])
                except Exception:
                    logging.getLogger("sitewhere.instance").exception(
                        "could not restore actuation policy %r for tenant %s",
                        row["token"], row["tenant"])
            # delivery fan-out: lane fires route through the firing
            # tenant's command-delivery stack (resolve + route + encode);
            # bounded retry then dead-letter, replay-barrier suppression
            self.command_fanout = CommandFanout(self._deliver_command_fire)
            self.pipeline_engine.command_dispatcher = self.command_fanout
        # serializes scripted-rule check+attach+commit sequences: a gossip
        # apply that passed its LWW pre-check must not interleave with a
        # local install, or the loser's attach could replace the winner's
        # live processor while the store keeps the winner (silent
        # live/durable divergence on this host)
        self._scripted_rule_lock = threading.Lock()

        # centralized logging over the bus (reference:
        # MicroserviceLogProducer -> instance-logging topic). The handler
        # attaches to the process-global "sitewhere" logger: with several
        # instances in one process (tests), each captures the shared stream
        # under its own source label — matching the reference, where one
        # process is one microservice instance.
        from sitewhere_tpu.runtime.logs import BusLogHandler, LogAggregator
        self.log_handler = BusLogHandler(self.bus, self.naming,
                                         source=instance_id)
        self.log_aggregator = LogAggregator(self.bus, self.naming)

        # checkpoint manager: restore-at-boot + periodic saves. Nested
        # AFTER the pipeline engine (whose state it restores) and BEFORE
        # the tenant engine manager (whose inbound consumers must not
        # start polling until the cursors are rewound to the checkpoint).
        self.checkpoint_manager = None
        if self.pipeline_engine is not None and data_dir is not None:
            from sitewhere_tpu.persist.checkpoint import (
                InstanceCheckpointManager)
            self.checkpoint_manager = InstanceCheckpointManager(
                self, os.path.join(data_dir, "checkpoints"),
                interval_s=checkpoint_interval_s)
            # manifests carry this boot's epoch; a zombie writer (taken
            # over elsewhere) is refused by the stale-save fence
            self.checkpoint_manager.checkpointer.recovery_epoch = \
                self.recovery_epoch

        # scripts load from disk FIRST so the checkpoint restore's
        # last-writer-wins apply sees the local copies (and tenant
        # engines, built later, can resolve script-backed rules)
        self.add_nested(self.script_manager)
        if self.pipeline_engine is not None:
            self.add_nested(self.pipeline_engine)
        if self.checkpoint_manager is not None:
            self.add_nested(self.checkpoint_manager.component)
        self.add_nested(self.engine_manager)
        self.add_nested(self.label_generators)

    # -- wiring ------------------------------------------------------------
    def _on_user_mutation(self, kind: str, op: str, entity) -> None:
        if kind != "user" or op == "create":
            return
        username = getattr(entity, "username", "") or getattr(
            entity, "token", "")
        self.token_management.invalidate_user(username,
                                              revoke=(op == "delete"))

    def _make_store(self, kind: str):
        if self.data_dir is None:
            return None
        return SqliteStore(os.path.join(self.data_dir, f"{kind}.db"))

    def _serving_mesh(self):
        """Planner mesh provider: the pipeline's own mesh when the hot
        path is sharded (its shard axis IS the replay axis), else the
        process-wide live mesh (parallel/distributed.live_mesh — None on
        single-chip hosts, which keeps every query on the host kernel)."""
        engine = self.pipeline_engine
        mesh = getattr(engine, "mesh", None) if engine is not None else None
        if mesh is not None:
            return mesh
        from sitewhere_tpu.parallel.distributed import live_mesh
        return live_mesh()

    def _make_engine(self, tenant: Tenant) -> TenantEngine:
        store_factory: Optional[Callable] = None
        if self.data_dir is not None:
            tenant_dir = os.path.join(self.data_dir, "tenants", tenant.token)
            os.makedirs(tenant_dir, exist_ok=True)
            store_factory = lambda kind: SqliteStore(
                os.path.join(tenant_dir, f"{kind}.db"))
        engine = TenantEngine(
            tenant, self.bus, self.datastores.event_log_for(tenant),
            pipeline_engine=self.pipeline_engine,
            registry_tensors=self.registry_tensors,
            store_factory=store_factory, naming=self.naming,
            cluster=self.cluster_hooks, batcher=self.latency_batcher)
        self.bootstrap.apply_template(engine)
        # re-install this tenant's durable scripted rules (they start with
        # the engine's rule_processors manager)
        for row in self.scripted_rules.installs_for(tenant.token):
            try:
                self._install_scripted_processor(
                    engine, tenant.token, row["token"], row["script"])
            except Exception:
                logging.getLogger("sitewhere.instance").exception(
                    "could not restore scripted rule %r (script %r) for "
                    "tenant %s", row["token"], row["script"], tenant.token)
        if self.refit_interval_s and engine.drift_refitter is not None:
            try:
                self._install_refit_schedule(engine)
            except Exception:
                logging.getLogger("sitewhere.instance").exception(
                    "could not install drift-refit schedule for tenant %s",
                    tenant.token)
        return engine

    # fixed tokens: the install is idempotent across restarts (durable
    # per-tenant schedule stores would otherwise accrete one job per boot)
    REFIT_SCHEDULE_TOKEN = "drift-refit-interval"
    REFIT_JOB_TOKEN = "drift-refit-sweep"

    def _install_refit_schedule(self, engine: TenantEngine) -> None:
        """Arm the unattended refit loop on one tenant engine: a
        SIMPLE-trigger schedule at `actuation.refit_interval_s` plus an
        ACTIVE DRIFT_REFIT job. Created before engine.start(), so the
        schedule manager's on_start resubmit picks the job up exactly
        like any job that survived a restart."""
        from sitewhere_tpu.model.schedule import (
            Schedule, ScheduledJob, ScheduledJobState, ScheduledJobType,
            TriggerConstants, TriggerType)
        management = engine.schedule_management
        interval_ms = max(1, int(self.refit_interval_s * 1000.0))
        existing = management.schedules.get_by_token(self.REFIT_SCHEDULE_TOKEN)
        if existing is None:
            management.create_schedule(Schedule(
                token=self.REFIT_SCHEDULE_TOKEN, name="drift refit interval",
                trigger_type=TriggerType.SIMPLE,
                trigger_configuration={
                    TriggerConstants.REPEAT_INTERVAL: str(interval_ms)}))
        elif existing.trigger_configuration.get(
                TriggerConstants.REPEAT_INTERVAL) != str(interval_ms):
            # config changed between boots: durable schedule follows it
            management.schedules.update(existing.id, {
                "trigger_configuration": {
                    TriggerConstants.REPEAT_INTERVAL: str(interval_ms)}})
        if management.jobs.get_by_token(self.REFIT_JOB_TOKEN) is None:
            management.create_scheduled_job(ScheduledJob(
                token=self.REFIT_JOB_TOKEN,
                schedule_token=self.REFIT_SCHEDULE_TOKEN,
                job_type=ScheduledJobType.DRIFT_REFIT,
                job_state=ScheduledJobState.ACTIVE))

    # -- scripted rules (durable + replicated) -----------------------------
    def _install_scripted_processor(self, engine, tenant: str, token: str,
                                    script_id: str,
                                    replace: bool = True) -> None:
        """Resolve + attach the processor on an engine. With `replace`
        (boot restore, gossip apply — LWW semantics) an existing processor
        for the token is swapped when its backing script differs; without
        it (REST create) `add_processor`'s atomic duplicate check raises,
        so two concurrent installs of one token cannot both succeed."""
        from sitewhere_tpu.errors import ErrorCode, NotFoundError
        from sitewhere_tpu.rules import ScriptedRuleProcessor
        from sitewhere_tpu.runtime.scripts import GLOBAL_SCOPE

        if replace:
            existing = engine.rule_processors.get_processor(token)
            if existing is not None:
                if getattr(existing, "script_id", None) == script_id:
                    return
                engine.rule_processors.remove_processor(token)
        else:
            # duplicate BEFORE resolve: a conflicting token must 409 even
            # when its script id is unresolvable (and skip the wasted
            # resolve). Race-free: every mutation path holds
            # _scripted_rule_lock; add_processor's atomic check remains
            # the backstop.
            if engine.rule_processors.get_processor(token) is not None:
                from sitewhere_tpu.errors import DuplicateTokenError
                raise DuplicateTokenError(f"rule '{token}' already exists")
        try:
            try:
                handler = self.script_manager.resolve(
                    tenant, script_id, "process", require_entry=True)
            except Exception:
                handler = self.script_manager.resolve(
                    GLOBAL_SCOPE, script_id, "process", require_entry=True)
        except Exception as exc:
            # normalized for the gossip applier: a not-yet-replicated
            # script is a retryable dependency miss, not a hard failure
            raise NotFoundError(
                f"script '{script_id}' not resolvable for rule '{token}': "
                f"{exc}", ErrorCode.GENERIC) from exc
        engine.rule_processors.add_processor(
            ScriptedRuleProcessor(token, handler, script_id=script_id))

    def install_scripted_rule(self, tenant: str, token: str,
                              script_id: str,
                              replace: bool = False) -> None:
        """Install a script-backed rule processor on `tenant`: live attach
        + durable record (+ gossip via the store's listeners). The default
        is create semantics (duplicate token raises, atomically); config
        boot passes `replace=True` because config declares desired state."""
        engine = self.get_tenant_engine(tenant)
        if engine is None:
            from sitewhere_tpu.errors import ErrorCode, NotFoundError
            raise NotFoundError(f"unknown tenant '{tenant}'",
                                ErrorCode.INVALID_TENANT_TOKEN)
        with self._scripted_rule_lock:
            self._install_scripted_processor(engine, tenant, token,
                                             script_id, replace=replace)
            # notify deferred: the listener publishes to peer bus edges,
            # which must not run inside the critical section (one slow
            # peer socket would stall every install AND the gossip
            # applier blocked on this lock)
            payload = self.scripted_rules.record(tenant, token, script_id,
                                                 notify=False)
        self.scripted_rules.emit("add", tenant, token, payload)

    def remove_scripted_rule(self, tenant: str, token: str) -> bool:
        """Live detach + durable tombstone (+ gossip). True if removed."""
        engine = self.get_tenant_engine(tenant)
        with self._scripted_rule_lock:
            removed = bool(
                engine is not None
                and engine.rule_processors.remove_processor(token))
            stamp = self.scripted_rules.erase(tenant, token, notify=False)
        if stamp is not None:
            self.scripted_rules.emit("remove", tenant, token, stamp)
        return stamp is not None or removed

    def apply_replicated_scripted_rule(self, op: str, tenant: str,
                                       token: str, payload) -> bool:
        """Gossip receive side (parallel/cluster.py): converge the durable
        store, then mirror the live processor state. Raises NotFoundError
        while the backing script has not replicated yet — the caller's
        at-least-once redelivery retries until it has. Returns True when
        local state actually changed (the caller's applied counter)."""
        if op == "add":
            script_id, stamp = payload["script"], payload["stamp"]
            with self._scripted_rule_lock:
                if not self.scripted_rules.would_apply_add(
                        tenant, token, script_id, stamp):
                    return False  # older than local state: no-op
                # live attach FIRST: if the backing script has not
                # replicated yet this raises NotFoundError and the store
                # stays unchanged, so the redelivered record retries the
                # whole apply. The lock keeps check+attach+commit atomic
                # vs local installs (see _scripted_rule_lock).
                engine = self.get_tenant_engine(tenant)
                if engine is not None:
                    self._install_scripted_processor(engine, tenant, token,
                                                     script_id)
                return self.scripted_rules.apply_add(tenant, token,
                                                     script_id, stamp)
        if op == "remove":
            with self._scripted_rule_lock:
                if self.scripted_rules.apply_remove(tenant, token,
                                                    int(payload)):
                    engine = self.engine_manager.get_engine(tenant)
                    if engine is not None:
                        engine.rule_processors.remove_processor(token)
                    return True
        return False

    # -- rule programs (durable + replicated; the CEP-lite fused rules) ----
    def install_rule_program(self, tenant: str, spec: Dict,
                             replace: bool = False) -> Dict:
        """Validate + install a rule program on the fused pipeline: live
        engine install (the dry-run compile 409s with the offending node
        BEFORE any mutation), durable record, gossip via the store's
        listeners. Program tokens are instance-global (the engine is);
        the store scopes listing and removal by tenant."""
        from sitewhere_tpu.errors import ErrorCode, SiteWhereError

        engine = self.pipeline_engine
        if engine is None:
            raise SiteWhereError(
                "rule programs require a pipeline engine (pipeline.enabled)",
                ErrorCode.GENERIC, http_status=409)
        spec = dict(spec or {})
        spec["tenant_token"] = tenant  # force the request tenant's scope
        with self._rule_program_lock:
            if replace:
                entry = engine.upsert_rule_program(spec)
            else:
                entry = engine.create_rule_program(spec)
            payload = self.rule_programs.record(
                tenant, entry["spec"]["token"], entry["spec"], notify=False)
        self.rule_programs.emit("add", tenant, entry["spec"]["token"],
                                payload)
        return dict(entry["spec"])

    def remove_rule_program(self, tenant: str, token: str) -> bool:
        engine = self.pipeline_engine
        with self._rule_program_lock:
            removed = bool(engine is not None
                           and self.rule_programs.get(tenant, token)
                           is not None
                           and engine.remove_rule_program(token))
            stamp = self.rule_programs.erase(tenant, token, notify=False)
        if stamp is not None:
            self.rule_programs.emit("remove", tenant, token, stamp)
        return stamp is not None or removed

    def apply_replicated_rule_program(self, op: str, tenant: str,
                                      token: str, payload) -> bool:
        """Gossip receive side: converge the durable store, then mirror
        the live engine. An invalid spec raises RuleProgramError — the
        structured 409 naming the offending node — BEFORE any store
        mutation, so the gossip handler surfaces it as a conflict, not a
        stack trace, and the loser's state stays untouched."""
        engine = self.pipeline_engine
        if op == "add":
            spec, stamp = dict(payload["spec"]), int(payload["stamp"])
            with self._rule_program_lock:
                if not self.rule_programs.would_apply_add(
                        tenant, token, spec, stamp):
                    return False
                if engine is not None:
                    # validate + live install FIRST: a spec this engine's
                    # static buckets cannot hold must leave the store
                    # unchanged (RuleProgramError propagates, structured)
                    engine.upsert_rule_program(spec)
                return self.rule_programs.apply_add(tenant, token, spec,
                                                    stamp)
        if op == "remove":
            with self._rule_program_lock:
                if self.rule_programs.apply_remove(tenant, token,
                                                   int(payload)):
                    if engine is not None:
                        engine.remove_rule_program(token)
                    return True
        return False

    # -- anomaly models (durable + replicated; on-TPU inference) -----------
    def install_anomaly_model(self, tenant: str, spec: Dict,
                              replace: bool = False) -> Dict:
        """Validate + install an anomaly model on the fused pipeline:
        live engine install (the dry-run compile 409s naming the
        offending field BEFORE any mutation), durable record, gossip via
        the store's listeners. Model tokens are instance-global (the
        engine is); the store scopes listing and removal by tenant."""
        from sitewhere_tpu.errors import ErrorCode, SiteWhereError

        engine = self.pipeline_engine
        if engine is None:
            raise SiteWhereError(
                "anomaly models require a pipeline engine "
                "(pipeline.enabled)", ErrorCode.GENERIC, http_status=409)
        spec = dict(spec or {})
        spec["tenant_token"] = tenant  # force the request tenant's scope
        with self._anomaly_model_lock:
            if replace:
                entry = engine.upsert_anomaly_model(spec)
            else:
                entry = engine.create_anomaly_model(spec)
            payload = self.anomaly_models.record(
                tenant, entry["spec"]["token"], entry["spec"], notify=False)
        self.anomaly_models.emit("add", tenant, entry["spec"]["token"],
                                 payload)
        return dict(entry["spec"])

    def remove_anomaly_model(self, tenant: str, token: str) -> bool:
        engine = self.pipeline_engine
        with self._anomaly_model_lock:
            removed = bool(engine is not None
                           and self.anomaly_models.get(tenant, token)
                           is not None
                           and engine.remove_anomaly_model(token))
            stamp = self.anomaly_models.erase(tenant, token, notify=False)
        if stamp is not None:
            self.anomaly_models.emit("remove", tenant, token, stamp)
        return stamp is not None or removed

    def apply_replicated_anomaly_model(self, op: str, tenant: str,
                                       token: str, payload) -> bool:
        """Gossip receive side: converge the durable store, then mirror
        the live engine. An invalid spec raises AnomalyModelError — the
        structured 409 naming the offending field — BEFORE any store
        mutation (same contract as the rule programs)."""
        engine = self.pipeline_engine
        if op == "add":
            spec, stamp = dict(payload["spec"]), int(payload["stamp"])
            with self._anomaly_model_lock:
                if not self.anomaly_models.would_apply_add(
                        tenant, token, spec, stamp):
                    return False
                if engine is not None:
                    engine.upsert_anomaly_model(spec)
                return self.anomaly_models.apply_add(tenant, token, spec,
                                                     stamp)
        if op == "remove":
            with self._anomaly_model_lock:
                if self.anomaly_models.apply_remove(tenant, token,
                                                    int(payload)):
                    if engine is not None:
                        engine.remove_anomaly_model(token)
                    return True
        return False

    # -- actuation policies (durable + replicated; alert -> command) -------
    def install_actuation_policy(self, tenant: str, spec: Dict,
                                 replace: bool = False) -> Dict:
        """Validate + install an alert->command policy on the fused
        pipeline: live engine install first (the compile 409s naming the
        offending field BEFORE any mutation), then durable record, then
        gossip via the store's listeners. Policy tokens are
        instance-global (the engine's slot table is); the store scopes
        listing and removal by tenant."""
        from sitewhere_tpu.errors import ErrorCode, SiteWhereError

        engine = self.pipeline_engine
        if engine is None:
            raise SiteWhereError(
                "actuation policies require a pipeline engine "
                "(pipeline.enabled)", ErrorCode.GENERIC, http_status=409)
        spec = dict(spec or {})
        spec["tenant_token"] = tenant  # force the request tenant's scope
        with self._actuation_policy_lock:
            if replace:
                entry = engine.upsert_actuation_policy(spec)
            else:
                entry = engine.create_actuation_policy(spec)
            payload = self.actuation_policies.record(
                tenant, entry["spec"]["token"], entry["spec"], notify=False)
        self.actuation_policies.emit("add", tenant, entry["spec"]["token"],
                                     payload)
        return dict(entry["spec"])

    def remove_actuation_policy(self, tenant: str, token: str) -> bool:
        engine = self.pipeline_engine
        with self._actuation_policy_lock:
            removed = bool(engine is not None
                           and self.actuation_policies.get(tenant, token)
                           is not None
                           and engine.remove_actuation_policy(token))
            stamp = self.actuation_policies.erase(tenant, token,
                                                  notify=False)
        if stamp is not None:
            self.actuation_policies.emit("remove", tenant, token, stamp)
        return stamp is not None or removed

    def apply_replicated_actuation_policy(self, op: str, tenant: str,
                                          token: str, payload) -> bool:
        """Gossip receive side: converge the durable store, then mirror
        the live engine. An invalid spec raises ActuationPolicyError —
        the structured 409 naming the offending field — BEFORE any store
        mutation (same contract as the anomaly models)."""
        engine = self.pipeline_engine
        if op == "add":
            spec, stamp = dict(payload["spec"]), int(payload["stamp"])
            with self._actuation_policy_lock:
                if not self.actuation_policies.would_apply_add(
                        tenant, token, spec, stamp):
                    return False
                if engine is not None:
                    engine.upsert_actuation_policy(spec)
                return self.actuation_policies.apply_add(
                    tenant, token, spec, stamp)
        if op == "remove":
            with self._actuation_policy_lock:
                if self.actuation_policies.apply_remove(tenant, token,
                                                        int(payload)):
                    if engine is not None:
                        engine.remove_actuation_policy(token)
                    return True
        return False

    def _deliver_command_fire(self, fire: Dict) -> None:
        """CommandFanout transport: route one lane fire through the
        firing tenant's command-delivery stack. Raises (-> bounded retry,
        then dead-letter) when the tenant engine is down or the device
        has no active assignment."""
        from sitewhere_tpu.actuation import deliver_via_service
        from sitewhere_tpu.errors import SiteWhereError

        tenant = fire.get("tenant") or ""
        engine = self.engine_manager.get_engine(tenant)
        if engine is None:
            raise SiteWhereError(
                f"no running tenant engine for '{tenant}'")
        deliver_via_service(engine.command_delivery)(fire)

    # -- lifecycle ---------------------------------------------------------
    def on_initialize(self, monitor) -> None:
        self.event_log.start()  # background linger-flush thread
        self.datastores.start()
        self.bootstrap.bootstrap_users()
        if self._default_tenant:
            self.bootstrap.bootstrap_default_tenant(self._default_tenant)

    def on_start(self, monitor) -> None:
        # centralized logging wiring lives in on_start (not on_initialize,
        # which lifecycle runs only once) so instance.restart() re-attaches
        self.log_handler.start()
        self.log_aggregator.start()
        framework_logger = logging.getLogger("sitewhere")
        if framework_logger.level == logging.NOTSET:
            # the root default (WARNING) would filter INFO before the bus
            # handler ever sees it; only set when the operator hasn't
            framework_logger.setLevel(logging.INFO)
        if self.log_handler not in framework_logger.handlers:
            framework_logger.addHandler(self.log_handler)

    def on_stop(self, monitor) -> None:
        logging.getLogger("sitewhere").removeHandler(self.log_handler)
        self.log_handler.stop()
        self.log_aggregator.stop()
        self.serving.stop()  # drain in-flight reads before the log closes
        if self.latency_batcher is not None:
            self.latency_batcher.close()  # flushes pending offers
        self.datastores.stop()
        self.event_log.stop()
        self.bus.flush()  # durable bus logs visible to a successor instance

    def _ingest_backlog(self) -> int:
        """Worst decoded-events consumer lag across tenants — the
        admission controller's queue-depth signal (Kafka analog: max
        consumer group lag on the decoded topics)."""
        with self.bus._lock:
            groups = list(self.bus._groups.items())
        worst = 0
        for (topic_name, _group_id), group in groups:
            if topic_name.endswith("event-source-decoded-events"):
                worst = max(worst, group.lag())
        return worst

    # -- convenience accessors --------------------------------------------
    def get_tenant_engine(self, tenant_token: str) -> Optional[TenantEngine]:
        engine = self.engine_manager.get_engine(tenant_token)
        if engine is None and not self.engine_manager.is_stopped(tenant_token):
            # lazy boot on first use — but never resurrect an engine an
            # admin explicitly stopped
            engine = self.engine_manager.start_engine(tenant_token)
        return engine

    def topology(self) -> Dict:
        """Instance topology snapshot (replaces Kafka state heartbeats +
        TopologyStateAggregator.java for the single-process design)."""
        with self.engine_manager._lock:
            engines = {tok: eng.status.name
                       for tok, eng in self.engine_manager.engines.items()}
            failed = dict(self.engine_manager.failed)
        out = {
            "instance_id": self.instance_id,
            "status": self.status.name,
            "pipeline_enabled": self.pipeline_engine is not None,
            "tenant_engines": engines,
            "failed_tenant_engines": failed,
        }
        if self.pipeline_engine is not None:
            health = getattr(self.pipeline_engine, "health", None)
            if health is not None:
                # degradation ladder (runtime/health.py):
                # healthy -> degraded -> draining -> failed
                out["pipeline_health"] = health.to_json()
            # HBM residency ledger (runtime/hbmledger.py): per-table
            # resident bytes + backend headroom for capacity planning
            from sitewhere_tpu.runtime import hbmledger
            out["hbm"] = hbmledger.ledger(self.pipeline_engine)
        from sitewhere_tpu.sources.manager import GLOBAL_ADMISSION
        if GLOBAL_ADMISSION.enabled:
            out["admission"] = GLOBAL_ADMISSION.report()
        # failover plane (runtime/recovery.py): this boot's epoch, the
        # replay barrier's remaining suppression budget, and — with a
        # cluster — lease/takeover state from the monitor
        from sitewhere_tpu.runtime.recovery import GLOBAL_REPLAY_BARRIER
        recovery: Dict = {
            "epoch": getattr(self, "recovery_epoch", 0),
            "replay_barrier_active": GLOBAL_REPLAY_BARRIER.active(),
            "replay_suppressed_effects": GLOBAL_REPLAY_BARRIER.suppressed,
        }
        if self.checkpoint_manager is not None:
            recovery["last_restore_epoch"] = \
                self.checkpoint_manager.checkpointer.last_restore_epoch
        out["recovery"] = recovery
        if self.cluster_hooks is not None:
            # multi-host deployment: per-process heartbeat states with
            # liveness (reference: TopologyStateAggregator.java)
            out["processes"] = self.cluster_hooks.processes()
            out["process_id"] = self.cluster_hooks.process_id
            out["degraded_peers"] = list(self.cluster_hooks.degraded)
            monitor = getattr(self.cluster_hooks, "takeover_monitor", None)
            if monitor is not None:
                recovery.update(monitor.snapshot())
        return out

    def extra_gauges(self) -> Dict[str, float]:
        """Derived gauges folded into the Prometheus exposition alongside
        the registry's own metrics: engine counters (one on-demand D2H
        fetch for the per-program/per-model vectors), cluster replication
        stats, the failover epoch, and the HBM residency ledger. Shared by
        GET /metrics and the cluster telemetry fan-in, so every peer's
        snapshot carries the same gauge families."""
        extra: Dict[str, float] = {}
        engine = self.pipeline_engine
        if engine is not None:
            extra["pipeline.batches_processed"] = engine.batches_processed
            extra["pipeline.alerts_dropped"] = engine.alerts_dropped
            health = getattr(engine, "health", None)
            if health is not None:
                # 0=healthy 1=degraded 2=draining 3=failed
                extra["pipeline.health_state"] = health.code
            # H2D staging ring (pipeline/staging.py): instantaneous slot
            # occupancy + configured depth. Only exported once the ring
            # has been built (first staged transfer) — a never-staging
            # engine keeps its exposition unchanged.
            ring = getattr(engine, "_staging_ring", None)
            if ring is not None:
                extra["pipeline.staging_ring.occupancy"] = ring.occupancy()
                extra["pipeline.staging_ring.depth"] = ring.depth
            for ptoken, c in engine.rule_program_counters().items():
                extra[f"pipeline.rule_program.fires.{ptoken}"] = c["fires"]
                extra[f"pipeline.rule_program.suppressed.{ptoken}"] = \
                    c["suppressed"]
            for mtoken, c in engine.anomaly_model_counters().items():
                extra[f"pipeline.anomaly_model.fires.{mtoken}"] = c["fires"]
                extra[f"pipeline.anomaly_model.evals.{mtoken}"] = c["evals"]
            for atoken, c in engine.actuation_policy_counters().items():
                extra[f"pipeline.actuation.fires.{atoken}"] = c["fires"]
                extra[f"pipeline.actuation.debounced.{atoken}"] = \
                    c["debounced"]
            if self.command_fanout is not None:
                for key, val in self.command_fanout.stats().items():
                    extra[f"pipeline.command_fanout.{key}"] = val
            # HBM residency: hbm.table_bytes{table="..."} per resident
            # table + hbm.total_bytes (host-side nbytes walk, no device
            # sync — runtime/hbmledger.py)
            from sitewhere_tpu.runtime import hbmledger
            extra.update(hbmledger.export_gauges(engine))
        hooks = self.cluster_hooks
        if hooks is not None:
            gossip = hooks.gossip
            if gossip is not None:
                extra.update({
                    "cluster.gossip.published": gossip.published,
                    "cluster.gossip.applied": gossip.applied,
                    "cluster.gossip.conflicts": gossip.conflicts,
                    "cluster.gossip.publish_errors": gossip.publish_errors,
                })
            provisioning = getattr(hooks, "provisioning", None)
            if provisioning is not None:
                extra.update({
                    "cluster.provisioning.published":
                        provisioning.published,
                    "cluster.provisioning.applied": provisioning.applied,
                    "cluster.provisioning.publish_errors":
                        provisioning.publish_errors,
                    "cluster.provisioning.parked_rows":
                        provisioning.parked_rows,
                })
            if getattr(hooks, "data_plane", True):
                extra["cluster.forwarded_rows"] = hooks.forwarder.forwarded
                extra["cluster.forward_dead_lettered"] = \
                    hooks.forwarder.dead_lettered
                extra["cluster.step_ticks"] = hooks.loop.tick_count
            extra["cluster.degraded_peers"] = len(hooks.degraded)
        # serving tier: window-grid cache residency rides the hbm.* gauge
        # family (host RAM here, but the same capacity-planning ledger)
        extra["hbm.wincache_bytes"] = float(self.window_cache.resident_bytes)
        # failover epoch (runtime/recovery.py): lets dashboards graph
        # restarts/takeovers as step changes and alert on epoch skew
        extra["recovery.epoch"] = float(getattr(self, "recovery_epoch", 0))
        return extra

    def prometheus_text(self) -> str:
        """Full Prometheus exposition for this process: registry metrics
        plus every derived gauge from extra_gauges()."""
        return self.metrics.prometheus_text(self.extra_gauges())
