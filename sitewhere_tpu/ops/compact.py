"""On-device alert-lane compaction: prefix-sum pack of fired rows.

The latency tier's floor is set by D2H round trips, not compute: on a
tunneled runtime every separate fetch is its own ~100 ms round trip when
the link's burst bucket is drained (docs/PERF.md), and the pre-lane
materializer shipped six per-row arrays (two phases on big batches) to
find the handful of rows that actually fired. The tf.data / pipelined-
execution principle (arXiv:2101.12127, arXiv:1908.09291) — move the data
reduction to where the data lives — applied to the *output* side of the
fused step: a prefix-sum over the fired mask packs fired rows into
fixed-capacity lanes INSIDE the jit, so alert materialization ships one
fixed-shape, lane-capacity-sized int32 array per step regardless of
batch size.

Lane layout ([ALERT_LANE_ROWS, K] int32; slot i = i-th fired row in
batch-row order, so materialization order matches a mask scan exactly):

  row 0 (idx):   batch-row index of the fired row; -1 in unused slots
  row 1 (rules): threshold first_rule in bits 0-15, geofence first_rule
                 in bits 16-31 (int16 two's complement; -1 = none)
  row 2 (meta):  threshold alert_level bits 0-3 | anomaly-model slot
                 low nibble bits 4-7 | geofence alert_level bits 8-11 |
                 anomaly-model slot high nibble bits 12-15 |
                 threshold_fired bit 16 | geofence_fired bit 17 |
                 program_fired bit 18 | program slot id bits 19-26 |
                 program alert_level bits 27-30 | model_fired bit 31
                 (the sign bit: a negative meta word IS a model fire).
                 Levels/ids are only meaningful under their fired bit.
                 AlertLevel tops out at 3 (model/event.py), so the
                 built-in level fields always fit a nibble — the upper
                 nibbles of the old 8-bit level fields are the spare
                 bits the anomaly-model slot id rides. Rule-program and
                 anomaly-model fires both ride spare meta bits so the
                 lane layout and the perf gate's bytes budget are
                 unchanged; the model's alert LEVEL is resolved host
                 side from its slot's spec (no bits needed)
  row 3 (counts): [0] = fired rows this step (INCLUDING rows beyond
                 capacity), [1] = alerts dropped by lane overflow (each
                 fired rule family on a row beyond capacity counts one),
                 [2] = total alerts fired (mirrors ProcessOutputs.alerts),
                 [3] = rows the on-device shard route had to drop
                 (ops/route.py ROUTE_DROPPED_SLOT; zero on host-routed
                 steps and whenever the host lane-fit guard ran)

Overflow contract: rows beyond the K capacity are counted on device
(counts[1]) and surface on the engine's `alerts_dropped` — an alert
storm degrades to bounded delivery with loud accounting, never silent
loss of the count. Capacity is a compile-time constant (one cached jit
program per capacity, like every other static shape here).
"""

from __future__ import annotations

from dataclasses import dataclass
from typing import Dict

import numpy as np

ALERT_LANE_ROWS = 4
# bytes each lane slot costs on the wire (ALERT_LANE_ROWS int32 rows) —
# the perf gate's fetch-size budget is capacity * this
ALERT_LANE_BYTES_PER_SLOT = ALERT_LANE_ROWS * 4
DEFAULT_ALERT_LANE_CAPACITY = 128
# counts ride slots 0..2 of the counts row
MIN_ALERT_LANE_CAPACITY = 4

_THR_FIRED_BIT = 16
_GEO_FIRED_BIT = 17
# rule-program fire fields ride the SPARE meta bits (18-30) so the lane
# layout — and the perf gate's bytes-per-slot budget — stays unchanged:
# bit 18 = program fired, bits 19-26 = program slot id (table bucket is
# capped at 256 programs), bits 27-30 = program alert level (<= 15)
_PROG_FIRED_BIT = 18
_PROG_RULE_SHIFT = 19
_PROG_LEVEL_SHIFT = 27
# anomaly-model fires (ops/anomaly.py): fired rides the sign bit, the
# 8-bit model slot id (table bucket capped at 64, so 8 bits is roomy)
# splits across the two nibbles the 4-bit level fields never used
_MODEL_FIRED_BIT = 31
_MODEL_SLOT_LO_SHIFT = 4
_MODEL_SLOT_HI_SHIFT = 12


def compact_alert_lanes(thr: Dict, geo: Dict, capacity: int,
                        prog: Dict = None, model: Dict = None):
    """Pack the step's fired rows into alert lanes (jax, call under jit).

    `thr`/`geo` are the eval_threshold_rules / eval_geofence_rules output
    dicts (fired/first_rule/alert_level, all [B]); `prog` is the optional
    rule-program row dict of the same shape (ops/stateful.py fires mapped
    to attach rows); `model` is the optional anomaly-model row dict
    (ops/anomaly.py: fired/first_model, also attach-row mapped). Returns
    the [ALERT_LANE_ROWS, capacity] int32 lane array described above.
    Works per shard under shard_map (row indices are shard-local).
    """
    import jax.numpy as jnp

    if capacity < MIN_ALERT_LANE_CAPACITY:
        raise ValueError(
            f"alert lane capacity {capacity} < {MIN_ALERT_LANE_CAPACITY}")
    B = thr["fired"].shape[0]
    if prog is None:
        zero = jnp.zeros((B,), jnp.int32)
        prog = {"fired": jnp.zeros((B,), bool), "first_rule": zero,
                "alert_level": zero}
    if model is None:
        model = {"fired": jnp.zeros((B,), bool),
                 "first_model": jnp.full((B,), -1, jnp.int32)}
    fired = (thr["fired"] | geo["fired"] | prog["fired"]
             | model["fired"])                                # bool [B]
    fired_i = fired.astype(jnp.int32)
    rank = jnp.cumsum(fired_i) - 1                            # 0-based
    keep = fired & (rank < capacity)
    # out-of-capacity rows scatter to index `capacity` -> dropped by the
    # OOB mode; kept ranks are unique by construction
    slot = jnp.where(keep, rank, capacity)
    idx_lane = jnp.full((capacity,), -1, jnp.int32).at[slot].set(
        jnp.arange(B, dtype=jnp.int32), mode="drop")
    rules = ((thr["first_rule"] & 0xFFFF)
             | ((geo["first_rule"] & 0xFFFF) << 16))
    rules_lane = jnp.zeros((capacity,), jnp.int32).at[slot].set(
        rules, mode="drop")
    prog_fired_i = prog["fired"].astype(jnp.int32)
    model_slot = jnp.where(model["fired"], model["first_model"] & 0xFF, 0)
    meta = ((thr["alert_level"] & 0xF)
            | ((model_slot & 0xF) << _MODEL_SLOT_LO_SHIFT)
            | ((geo["alert_level"] & 0xF) << 8)
            | (((model_slot >> 4) & 0xF) << _MODEL_SLOT_HI_SHIFT)
            | (thr["fired"].astype(jnp.int32) << _THR_FIRED_BIT)
            | (geo["fired"].astype(jnp.int32) << _GEO_FIRED_BIT)
            | (prog_fired_i << _PROG_FIRED_BIT)
            | (jnp.where(prog["fired"], prog["first_rule"] & 0xFF, 0)
               << _PROG_RULE_SHIFT)
            | (jnp.where(prog["fired"], prog["alert_level"] & 0xF, 0)
               << _PROG_LEVEL_SHIFT))
    # bit 31 via the sign: `x << 31` on a positive int is undefined
    # territory in some numpy paths, so set the sign bit with where
    meta = jnp.where(model["fired"], meta | jnp.int32(-(2 ** 31)), meta)
    meta_lane = jnp.zeros((capacity,), jnp.int32).at[slot].set(
        meta, mode="drop")
    alerts_of = (thr["fired"].astype(jnp.int32)
                 + geo["fired"].astype(jnp.int32)
                 + prog_fired_i
                 + model["fired"].astype(jnp.int32))          # 0..4 per row
    total_alerts = jnp.sum(alerts_of)
    kept_alerts = jnp.sum(jnp.where(keep, alerts_of, 0))
    counts_lane = (jnp.zeros((capacity,), jnp.int32)
                   .at[0].set(jnp.sum(fired_i))
                   .at[1].set(total_alerts - kept_alerts)
                   .at[2].set(total_alerts))
    return jnp.stack([idx_lane, rules_lane, meta_lane, counts_lane])


@dataclass
class DecodedAlertLanes:
    """Host-side view of one lane array's used slots (all arrays [n])."""

    rows: np.ndarray        # int32 batch-row indices, ascending
    thr_fired: np.ndarray   # bool
    geo_fired: np.ndarray   # bool
    thr_rule: np.ndarray    # int32 (sign-extended; -1 = none)
    geo_rule: np.ndarray    # int32
    thr_level: np.ndarray   # int32 (meaningful only where thr_fired)
    geo_level: np.ndarray   # int32
    fired_rows: int         # total fired rows incl. overflow
    dropped_alerts: int     # alerts lost to lane overflow
    total_alerts: int
    prog_fired: np.ndarray = None  # bool (rule-program composite fires)
    prog_rule: np.ndarray = None   # int32 program slot (-1 = none)
    prog_level: np.ndarray = None  # int32 (meaningful under prog_fired)
    route_dropped: int = 0         # rows dropped by the on-device route
    model_fired: np.ndarray = None  # bool (anomaly-model fires)
    model_slot: np.ndarray = None   # int32 model slot (-1 = none)

    def __post_init__(self):
        n = self.rows.shape[0]
        if self.prog_fired is None:
            self.prog_fired = np.zeros(n, bool)
            self.prog_rule = np.full(n, -1, np.int32)
            self.prog_level = np.zeros(n, np.int32)
        if self.model_fired is None:
            self.model_fired = np.zeros(n, bool)
            self.model_slot = np.full(n, -1, np.int32)

    @property
    def n(self) -> int:
        return int(self.rows.shape[0])

    def head(self, n: int) -> "DecodedAlertLanes":
        """First `n` slots (max_alerts bounding; counts untouched)."""
        return DecodedAlertLanes(
            rows=self.rows[:n], thr_fired=self.thr_fired[:n],
            geo_fired=self.geo_fired[:n], thr_rule=self.thr_rule[:n],
            geo_rule=self.geo_rule[:n], thr_level=self.thr_level[:n],
            geo_level=self.geo_level[:n], fired_rows=self.fired_rows,
            dropped_alerts=self.dropped_alerts,
            total_alerts=self.total_alerts,
            prog_fired=self.prog_fired[:n], prog_rule=self.prog_rule[:n],
            prog_level=self.prog_level[:n],
            route_dropped=self.route_dropped,
            model_fired=self.model_fired[:n],
            model_slot=self.model_slot[:n])


def decode_alert_lanes(lanes: np.ndarray) -> DecodedAlertLanes:
    """Inverse of compact_alert_lanes on the fetched host copy (numpy)."""
    lanes = np.asarray(lanes)
    capacity = lanes.shape[-1]
    counts = lanes[3]
    fired_rows = int(counts[0])
    n = min(fired_rows, capacity)
    rules = lanes[1, :n]
    meta = lanes[2, :n]
    prog_fired = ((meta >> _PROG_FIRED_BIT) & 1).astype(bool)
    model_fired = meta < 0                     # sign bit IS the fire bit
    return DecodedAlertLanes(
        rows=lanes[0, :n],
        thr_fired=((meta >> _THR_FIRED_BIT) & 1).astype(bool),
        geo_fired=((meta >> _GEO_FIRED_BIT) & 1).astype(bool),
        # int32 arithmetic shifts sign-extend the int16 halves exactly
        thr_rule=(rules << 16) >> 16,
        geo_rule=rules >> 16,
        thr_level=meta & 0xF,
        geo_level=(meta >> 8) & 0xF,
        fired_rows=fired_rows,
        dropped_alerts=int(counts[1]),
        total_alerts=int(counts[2]),
        prog_fired=prog_fired,
        prog_rule=np.where(prog_fired,
                           (meta >> _PROG_RULE_SHIFT) & 0xFF,
                           -1).astype(np.int32),
        prog_level=((meta >> _PROG_LEVEL_SHIFT) & 0xF).astype(np.int32),
        route_dropped=int(counts[3]),
        model_fired=model_fired,
        model_slot=np.where(
            model_fired,
            ((meta >> _MODEL_SLOT_LO_SHIFT) & 0xF)
            | (((meta >> _MODEL_SLOT_HI_SHIFT) & 0xF) << 4),
            -1).astype(np.int32))
