"""Fused i32 state-slab primitives shared by the stateful rule kernel
(ops/stateful.py) and the anomaly-model kernel (ops/anomaly.py).

Both kernels keep all per-(device, program|model) temporal state in ONE
interleaved i32 slab [D, P, 4*S+2] so a step pulls a device's whole
state row with a single contiguous HBM gather instead of 4-6 strided
ones. Lane layout: [0:S] value f32 bits, [S:2S] aux f32 bits, [2S:3S]
ts, [3S:4S] counter, lane 4S the flag bit (root_prev / score_prev),
lane 4S+1 the per-row generation. Float planes travel as raw IEEE bit
patterns, so NaN payloads and -0.0 round-trip exactly.

This module is import-leaf on purpose (jax/numpy only): stateful.py
pulls in the rule-program compiler, whose package chain reaches
pipeline/step.py and thus ops/anomaly.py — the slab helpers living
here keep that cycle open no matter which module is imported first.
"""

from __future__ import annotations

from typing import Dict

import jax
import jax.numpy as jnp
import numpy as np


def state_slab_lanes(slots: int) -> int:
    """Lane count of a fused state slab with `slots` state slots: four
    interleaved planes (value/aux bits, ts, counter) plus the flag and
    row-generation lanes."""
    return 4 * slots + 2


def pack_state_slab_np(value: np.ndarray, aux: np.ndarray, ts: np.ndarray,
                       counter: np.ndarray, flag: np.ndarray,
                       row_gen: np.ndarray) -> np.ndarray:
    """Fuse the legacy per-field state arrays into one i32 slab along the
    last axis: lanes [0:S] value bits, [S:2S] aux bits, [2S:3S] ts,
    [3S:4S] counter, lane 4S the flag (root_prev bit / score_prev bit),
    lane 4S+1 the per-row generation.

    float planes travel as raw IEEE bit patterns (`.view(int32)`), so
    NaN payloads and -0.0 round-trip exactly. Works for any leading
    dims — canonical [D, P, S] and host-shard stacked blocks alike.
    Used by checkpoint restore to migrate pre-slab layouts in place.
    """
    def bits(a):
        a = np.asarray(a)
        if a.dtype == np.float32:
            return np.ascontiguousarray(a).view(np.int32)
        return np.ascontiguousarray(a).astype(np.int32)

    return np.concatenate([
        bits(value), bits(aux),
        np.asarray(ts, np.int32), np.asarray(counter, np.int32),
        bits(flag)[..., None], np.asarray(row_gen, np.int32)[..., None],
    ], axis=-1)


def unpack_state_slab_np(slab: np.ndarray, *, float_flag: bool = False
                         ) -> Dict[str, np.ndarray]:
    """Inverse of pack_state_slab_np. `float_flag` reinterprets the flag
    lane as f32 bits instead of a 0/1 bit (unused by the current
    kernels — both flags are booleans — but keeps the layout general)."""
    slab = np.ascontiguousarray(np.asarray(slab, np.int32))
    S = (slab.shape[-1] - 2) // 4

    def as_f32(a):
        return np.ascontiguousarray(a).view(np.float32)

    flag = slab[..., 4 * S]
    return {
        "value": as_f32(slab[..., 0:S]),
        "aux": as_f32(slab[..., S:2 * S]),
        "ts": slab[..., 2 * S:3 * S].copy(),
        "counter": slab[..., 3 * S:4 * S].copy(),
        "flag": as_f32(flag) if float_flag else flag.copy(),
        "row_gen": slab[..., 4 * S + 1].copy(),
    }


def _slab_f32(plane: jnp.ndarray) -> jnp.ndarray:
    """i32 lane plane -> f32, bit-exact (NaN payloads, -0.0)."""
    return jax.lax.bitcast_convert_type(plane, jnp.float32)


def _slab_i32(plane: jnp.ndarray) -> jnp.ndarray:
    """f32 plane -> raw i32 bits for slab storage."""
    return jax.lax.bitcast_convert_type(plane, jnp.int32)
