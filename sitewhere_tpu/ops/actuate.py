"""In-step actuation: policy evaluation + debounce + command-lane pack.

Closing the detection->actuation loop ON DEVICE (ROADMAP item 5): right
after anomaly scoring, every (batch row, policy) pair is tested against
the step's fired alert bits (threshold/geofence/program/model), matched
triggers are debounced against per-(device, policy) state carried in
HBM (the ops/slab.py bit-exact packing, one ts/counter slot per
policy), and the surviving (device, command) pairs prefix-sum-compact
(the ops/compact.py pattern) into a SECOND fixed-capacity [4, K] int32
lane the host fetches in the SAME materialize pass as the alert lanes —
the one-fetch-per-step budget grows to exactly two fixed-shape fetches
and detection->actuation never ships per-row arrays.

Step semantics (tests/test_actuation.py pins them with a NumPy oracle):

  * a policy MATCHES a batch row when any allowed source kind fired on
    that row with (match_slot < 0 or the kind's slot id == match_slot)
    and the kind's alert level >= min_level, the policy is active, and
    the row's tenant matches (tenant_idx 0 = any);
  * per device a policy TRIGGERS at most once per step, on the device's
    LAST matching row (highest batch index) — one command per
    (device, policy) per step;
  * a trigger FIRES only when the debounce window allows: never fired
    before (or the slot's epoch moved — the generation reset trick), or
    trigger_ts - last_fire_ts >= debounce_ms, both in EVENT time so the
    semantics replay deterministically; a blocked trigger counts as
    DEBOUNCED and leaves the stored last-fire ts unchanged;
  * fires pack into the command lane in (device, policy) ascending
    order; rows beyond the K capacity are counted on device (counts[1])
    and dropped loudly, never silently.

On the sharded engine each device lives on exactly one shard, so the
whole kernel is shard-local and the lane rides the shard axis like the
alert lanes — no new collectives. Device indices in lane row 2 are
shard-LOCAL; the materializer remaps to global exactly like alert rows.

Lane layout ([COMMAND_LANE_ROWS, K] int32; slot i = i-th fired
(device, policy) pair in device-major order):

  row 0 (idx):    batch-row index of the triggering row; -1 unused
  row 1 (meta):   policy slot bits 0-7 | trigger alert level bits 8-11 |
                  trigger source kind bits 12-14 (PolicySource ids)
  row 2 (dev):    shard-local device index of the fired device
  row 3 (counts): [0] = commands fired this step (INCLUDING pairs beyond
                  capacity), [1] = commands dropped by lane overflow,
                  [2] = triggers debounced this step, [3] reserved (0)
"""

from __future__ import annotations

from dataclasses import dataclass
from typing import Dict, Tuple

import jax
import jax.numpy as jnp
import numpy as np
from flax import struct

from sitewhere_tpu.actuation.compiler import (
    ActuationPolicyTable, PolicySource)
from sitewhere_tpu.ops.slab import state_slab_lanes

_NEG = -(2 ** 31)

COMMAND_LANE_ROWS = 4
# bytes each lane slot costs on the wire — the perf gate's two-fetch
# bytes budget adds capacity * this to the alert-lane term
COMMAND_LANE_BYTES_PER_SLOT = COMMAND_LANE_ROWS * 4
DEFAULT_COMMAND_LANE_CAPACITY = 64
# counts ride slots 0..2 of the counts row
MIN_COMMAND_LANE_CAPACITY = 4

_LEVEL_SHIFT = 8
_SOURCE_SHIFT = 12


@struct.dataclass
class ActuationStateTensors:
    """Per-(device, policy) debounce state, HBM-resident like
    RuleStateTensors/ModelStateTensors (sharded engines carry a leading
    shard axis on every field).

    The slab is the shared ops/slab.py layout with ONE state slot
    [D, P, 6]: lane 2 = last command-fire ts (event time, _NEG = never),
    lane 3 = per-(device, policy) cumulative fire counter, lane 5 = the
    row generation vs the policy's table epoch; lanes 0/1/4 (value/aux/
    flag) are unused and held at zero."""

    slab: jnp.ndarray            # i32 [D, P, 6] fused debounce state
    gen: jnp.ndarray             # i32 [P] counter-row generation
    fire_count: jnp.ndarray      # i32 [P] cumulative commands fired
    debounce_count: jnp.ndarray  # i32 [P] cumulative triggers debounced

    @property
    def num_policies(self) -> int:
        return self.gen.shape[-1]


def init_actuation_state_np(max_devices: int,
                            max_policies: int) -> ActuationStateTensors:
    """Numpy-leaved initial state (same contract as init_rule_state_np:
    no device buffers, so sharded engines place the tree with ONE
    device_put on their mesh)."""
    D, P = max_devices, max_policies
    slab = np.zeros((D, P, state_slab_lanes(1)), np.int32)
    slab[:, :, 2] = _NEG   # last-fire ts plane: never fired
    return ActuationStateTensors(
        slab=slab,
        gen=np.zeros((P,), np.int32),
        fire_count=np.zeros((P,), np.int32),
        debounce_count=np.zeros((P,), np.int32),
    )


def init_actuation_state(max_devices: int,
                         max_policies: int) -> ActuationStateTensors:
    return jax.tree_util.tree_map(
        jnp.asarray, init_actuation_state_np(max_devices, max_policies))


def eval_actuation_policies(
        table: ActuationPolicyTable,
        state: ActuationStateTensors,
        *,
        dev: jnp.ndarray,           # i32 [B] row device index (local)
        ts: jnp.ndarray,            # i32 [B] row relative timestamps
        tenant_row: jnp.ndarray,    # i32 [B] registry mirror per row
        thr: Dict[str, jnp.ndarray],    # eval_threshold_rules output
        geo: Dict[str, jnp.ndarray],    # eval_geofence_rules output
        prog: Dict[str, jnp.ndarray],   # rule-program row dict
        model: Dict[str, jnp.ndarray],  # anomaly-model row dict
        capacity: int,
) -> Tuple[ActuationStateTensors, jnp.ndarray]:
    """One fused-step actuation advance (jax, call under jit/shard_map).

    Returns (new_state, command_lanes [COMMAND_LANE_ROWS, capacity]).
    Works per shard under shard_map: `dev` and the state's device axis
    are shard-local, and every reduction here is per-device."""
    if capacity < MIN_COMMAND_LANE_CAPACITY:
        raise ValueError(
            f"command lane capacity {capacity} < "
            f"{MIN_COMMAND_LANE_CAPACITY}")
    B = dev.shape[0]
    D = state.slab.shape[0]
    P = table.num_policies

    # ---- per-(row, policy) matching over the step's fire bits ----------
    # (fired, slot id, level) per source family, all [B]
    families = (
        (PolicySource.THRESHOLD, thr["fired"], thr["first_rule"],
         thr["alert_level"]),
        (PolicySource.GEOFENCE, geo["fired"], geo["first_rule"],
         geo["alert_level"]),
        (PolicySource.PROGRAM, prog["fired"], prog["first_rule"],
         prog["alert_level"]),
        (PolicySource.MODEL, model["fired"], model["first_model"],
         model["alert_level"]),
    )
    tenant_ok = ((table.tenant_idx[None, :] == 0)
                 | (table.tenant_idx[None, :] == tenant_row[:, None]))
    eligible = table.active[None, :] & tenant_ok            # [B, P]

    matched = jnp.zeros((B, P), bool)
    # lowest matching source kind and max matching level per (row, policy)
    trig_src = jnp.full((B, P), 8, jnp.int32)
    trig_level = jnp.full((B, P), -1, jnp.int32)
    for kind, fired_k, slot_k, level_k in families:
        src_ok = ((table.source[None, :] == PolicySource.ANY)
                  | (table.source[None, :] == kind))
        slot_ok = ((table.match_slot[None, :] < 0)
                   | (table.match_slot[None, :] == slot_k[:, None]))
        level_ok = level_k[:, None] >= table.min_level[None, :]
        m = eligible & fired_k[:, None] & src_ok & slot_ok & level_ok
        matched = matched | m
        trig_src = jnp.where(m, jnp.minimum(trig_src, kind), trig_src)
        trig_level = jnp.where(m, jnp.maximum(trig_level, level_k[:, None]),
                               trig_level)

    # ---- per-(device, policy) trigger: LAST matching row wins ----------
    row_ids = jnp.arange(B, dtype=jnp.int32)
    slot_ids = jnp.arange(P, dtype=jnp.int32)
    keyr = dev[:, None] * P + slot_ids[None, :]             # [B, P]
    tgt = jnp.where(matched, keyr, D * P)
    last_row = (jnp.full((D * P,), -1, jnp.int32)
                .at[tgt.reshape(-1)]
                .max(jnp.broadcast_to(row_ids[:, None], (B, P)).reshape(-1),
                     mode="drop")
                .reshape(D, P))
    trig = last_row >= 0                                    # [D, P]
    safe_row = jnp.clip(last_row, 0, B - 1)
    fire_ts = ts[safe_row]                                  # [D, P]
    lvl_dp = jnp.take_along_axis(trig_level, safe_row, axis=0)
    src_dp = jnp.take_along_axis(trig_src, safe_row, axis=0)

    # ---- debounce against stored last-fire ts (generation-reset) -------
    stale = state.slab[:, :, 5] != table.epoch[None, :]     # [D, P]
    last_ts = jnp.where(stale, _NEG, state.slab[:, :, 2])
    ctr = jnp.where(stale, 0, state.slab[:, :, 3])
    allow = ((last_ts == _NEG)
             | ((fire_ts - last_ts) >= table.debounce_ms[None, :]))
    fired_dp = trig & allow
    debounced_dp = trig & ~allow

    # ---- state write-back: only TRIGGERED records persist (and destale,
    # zeroing the unused value/aux/flag lanes of a freshly reset row) ----
    slab = state.slab
    fresh = trig & stale
    zero = jnp.zeros((D, P), jnp.int32)
    slab = slab.at[:, :, 0].set(jnp.where(fresh, zero, slab[:, :, 0]))
    slab = slab.at[:, :, 1].set(jnp.where(fresh, zero, slab[:, :, 1]))
    slab = slab.at[:, :, 4].set(jnp.where(fresh, zero, slab[:, :, 4]))
    slab = slab.at[:, :, 2].set(
        jnp.where(trig, jnp.where(fired_dp, fire_ts, last_ts),
                  slab[:, :, 2]))
    slab = slab.at[:, :, 3].set(
        jnp.where(trig, ctr + fired_dp.astype(jnp.int32), slab[:, :, 3]))
    slab = slab.at[:, :, 5].set(
        jnp.where(trig, jnp.broadcast_to(table.epoch[None, :], (D, P)),
                  slab[:, :, 5]))

    epoch_moved = state.gen != table.epoch
    new_state = state.replace(
        slab=slab,
        gen=table.epoch,
        fire_count=jnp.where(epoch_moved, 0, state.fire_count)
        + jnp.sum(fired_dp, axis=0, dtype=jnp.int32),
        debounce_count=jnp.where(epoch_moved, 0, state.debounce_count)
        + jnp.sum(debounced_dp, axis=0, dtype=jnp.int32),
    )

    # ---- prefix-sum compaction into the command lane (device-major) ----
    fired_flat = fired_dp.reshape(-1)
    fired_i = fired_flat.astype(jnp.int32)
    rank = jnp.cumsum(fired_i) - 1
    keep = fired_flat & (rank < capacity)
    slot = jnp.where(keep, rank, capacity)
    idx_lane = jnp.full((capacity,), -1, jnp.int32).at[slot].set(
        last_row.reshape(-1), mode="drop")
    meta_dp = ((jnp.broadcast_to(slot_ids[None, :], (D, P)) & 0xFF)
               | ((lvl_dp & 0xF) << _LEVEL_SHIFT)
               | ((src_dp & 0x7) << _SOURCE_SHIFT))
    meta_lane = jnp.zeros((capacity,), jnp.int32).at[slot].set(
        meta_dp.reshape(-1), mode="drop")
    dev_dp = jnp.broadcast_to(
        jnp.arange(D, dtype=jnp.int32)[:, None], (D, P))
    dev_lane = jnp.full((capacity,), -1, jnp.int32).at[slot].set(
        dev_dp.reshape(-1), mode="drop")
    total = jnp.sum(fired_i)
    kept = jnp.sum(keep.astype(jnp.int32))
    counts_lane = (jnp.zeros((capacity,), jnp.int32)
                   .at[0].set(total)
                   .at[1].set(total - kept)
                   .at[2].set(jnp.sum(debounced_dp, dtype=jnp.int32)))
    lanes = jnp.stack([idx_lane, meta_lane, dev_lane, counts_lane])
    return new_state, lanes


@dataclass
class DecodedCommandLanes:
    """Host-side view of one command-lane array's used slots ([n])."""

    rows: np.ndarray         # int32 triggering batch-row indices
    policy_slot: np.ndarray  # int32 policy slot ids
    level: np.ndarray        # int32 trigger alert level
    source: np.ndarray       # int32 trigger source kind (PolicySource)
    dev: np.ndarray          # int32 shard-local device indices
    fired: int               # commands fired incl. overflow
    dropped: int             # commands lost to lane overflow
    debounced: int           # triggers blocked by the debounce window

    @property
    def n(self) -> int:
        return int(self.rows.shape[0])

    def head(self, n: int) -> "DecodedCommandLanes":
        """First `n` slots (bounding; counts untouched)."""
        return DecodedCommandLanes(
            rows=self.rows[:n], policy_slot=self.policy_slot[:n],
            level=self.level[:n], source=self.source[:n],
            dev=self.dev[:n], fired=self.fired, dropped=self.dropped,
            debounced=self.debounced)


def decode_command_lanes(lanes: np.ndarray) -> DecodedCommandLanes:
    """Inverse of the lane pack on the fetched host copy (numpy)."""
    lanes = np.asarray(lanes)
    capacity = lanes.shape[-1]
    counts = lanes[3]
    fired = int(counts[0])
    n = min(fired, capacity)
    meta = lanes[1, :n]
    return DecodedCommandLanes(
        rows=lanes[0, :n],
        policy_slot=(meta & 0xFF).astype(np.int32),
        level=((meta >> _LEVEL_SHIFT) & 0xF).astype(np.int32),
        source=((meta >> _SOURCE_SHIFT) & 0x7).astype(np.int32),
        dev=lanes[2, :n],
        fired=fired,
        dropped=int(counts[1]),
        debounced=int(counts[2]))
