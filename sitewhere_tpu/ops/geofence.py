"""Vectorized geofencing: point-in-polygon over all zones at once.

The TPU replacement for the reference's per-event JTS containment test
(ZoneTestRuleProcessor.java:47-52: cached JTS polygon per zone,
poly.contains(point) per location event): all B location events are tested
against all Z zone polygons simultaneously with the crossing-number
(even-odd) algorithm, scanning the padded vertex dimension with `lax.scan`
so the [B,Z] working set stays small (never materializing [B,Z,V]).

Zones are padded to V vertices by repeating the last vertex
(registry/tensors.py): degenerate zero-length edges satisfy y1==y2 and never
toggle crossing parity, so padding is semantically inert.
"""

from __future__ import annotations

from typing import Dict

import jax
import jax.numpy as jnp
import numpy as np
from flax import struct

from sitewhere_tpu.model.event import DeviceEventType
from sitewhere_tpu.ops.pack import EventBatch


@struct.dataclass
class ZoneTable:
    """Zone geometry + scoping, shapes [Z] / [Z,V,2]."""

    vertices: np.ndarray   # f32 [Z,V,2] (lat, lon)
    nvert: np.ndarray      # int32 [Z]
    tenant_idx: np.ndarray  # int32 [Z]
    active: np.ndarray     # bool [Z]

    @property
    def num_zones(self) -> int:
        return self.nvert.shape[0]


class GeofenceCondition:
    INSIDE = 0   # fire when the point IS in the zone
    OUTSIDE = 1  # fire when the point is NOT in the zone


@struct.dataclass
class GeofenceRuleTable:
    """Rules binding zones to alert outcomes, shapes [G].

    Mirrors ZoneTestRuleProcessor configuration: zone token + containment
    condition + alert type/level/message to fire.
    """

    active: np.ndarray       # bool
    zone_row: np.ndarray     # int32 row into ZoneTable
    condition: np.ndarray    # int32 GeofenceCondition
    alert_level: np.ndarray  # int32
    alert_type_idx: np.ndarray  # int32


def empty_geofence_table(max_rules: int) -> GeofenceRuleTable:
    zi = np.zeros(max_rules, np.int32)
    return GeofenceRuleTable(active=np.zeros(max_rules, bool), zone_row=zi,
                             condition=zi.copy(), alert_level=zi.copy(),
                             alert_type_idx=zi.copy())


def points_in_zones(lat: jnp.ndarray, lon: jnp.ndarray,
                    vertices: jnp.ndarray) -> jnp.ndarray:
    """Even-odd containment: points [B] against polygons [Z,V,2] -> bool [B,Z].

    Scans edges (v, v+1 mod V) accumulating crossing parity of a rightward ray
    from each point. Working set per step: [B,Z] booleans.
    """
    V = vertices.shape[1]
    # Edge endpoints per step: start = vertices[:, v], end = vertices[:, (v+1)%V]
    starts = vertices                                   # [Z,V,2]
    ends = jnp.roll(vertices, shift=-1, axis=1)         # [Z,V,2]
    px = lon[:, None]  # [B,1] x = longitude
    py = lat[:, None]  # [B,1] y = latitude

    def edge_step(parity, edge):
        (y1, x1, y2, x2) = edge                         # each [Z]
        y1b, y2b = y1[None, :], y2[None, :]             # [1,Z]
        x1b, x2b = x1[None, :], x2[None, :]
        straddles = (y1b > py) != (y2b > py)            # [B,Z]
        dy = y2b - y1b
        safe_dy = jnp.where(dy == 0, 1.0, dy)
        x_at_y = x1b + (x2b - x1b) * (py - y1b) / safe_dy
        crosses = straddles & (px < x_at_y)
        return parity ^ crosses, None

    edges = (starts[:, :, 0].T, starts[:, :, 1].T,      # [V,Z] each
             ends[:, :, 0].T, ends[:, :, 1].T)
    # Derive the initial parity from the points so it inherits their
    # varying-manual-axes under shard_map (a plain jnp.zeros would be
    # unvarying and fail lax.scan's carry type check).
    parity0 = jnp.broadcast_to((lat > jnp.inf)[:, None],
                               (lat.shape[0], vertices.shape[0]))
    parity, _ = jax.lax.scan(edge_step, parity0, edges)
    return parity


def resolve_geofence_impl(impl: str, platform: str) -> str:
    """Resolve an `auto` containment implementation choice for a platform.

    `pallas` (the hand-written VPU kernel in ops/pallas_geofence.py) on real
    TPUs; the XLA scan everywhere else (CPU shard meshes, interpret-less
    debugging). Explicit choices pass through.
    """
    if impl == "auto":
        return "pallas" if platform == "tpu" else "xla"
    if impl not in ("xla", "pallas", "pallas_interpret"):
        raise ValueError(
            f"geofence impl {impl!r}: expected one of "
            f"'auto', 'xla', 'pallas', 'pallas_interpret'")
    return impl


def _containment(lat: jnp.ndarray, lon: jnp.ndarray, vertices: jnp.ndarray,
                 impl: str) -> jnp.ndarray:
    # Below one lane-width of zones the pallas kernel pads Z to 128 and wastes
    # most of the VPU; the XLA scan measures faster there (v5e), so "pallas"
    # only engages at Z >= 128 (explicit "pallas_interpret" always runs the
    # kernel — that mode exists for CPU correctness tests).
    if impl == "xla" or (impl == "pallas" and vertices.shape[0] < 128):
        return points_in_zones(lat, lon, vertices)
    from sitewhere_tpu.ops.pallas_geofence import points_in_zones_pallas
    return points_in_zones_pallas(lat, lon, vertices,
                                  interpret=(impl == "pallas_interpret"))


def eval_geofence_rules(batch: EventBatch, zones: ZoneTable,
                        rules: GeofenceRuleTable,
                        impl: str = "xla") -> Dict[str, jnp.ndarray]:
    """Evaluate geofence rules against the location events of a batch.

    Returns per-event outputs (shape [B]):
      fired:       bool, any geofence rule fired
      fired_count: int32
      first_rule:  int32 lowest-index fired rule (-1 if none)
      alert_level: int32 max alert level among fired rules
    and the raw containment matrix `inside` [B,Z] (device-state / analytics
    reuse it without recomputing).
    """
    is_location = batch.event_type == DeviceEventType.LOCATION
    event_ok = batch.valid & is_location                        # [B]

    inside = _containment(batch.lat, batch.lon, zones.vertices, impl)  # [B,Z]
    zone_ok = (zones.active[None, :]
               & ((zones.tenant_idx[None, :] == 0)
                  | (zones.tenant_idx[None, :] == batch.tenant_idx[:, None])))
    inside_scoped = inside & zone_ok

    # Gather per-rule containment: [B,G]
    rule_inside = inside_scoped[:, rules.zone_row]
    rule_zone_ok = zone_ok[:, rules.zone_row]
    cond_met = jnp.where(rules.condition[None, :] == GeofenceCondition.INSIDE,
                         rule_inside, rule_zone_ok & ~rule_inside)
    fired_matrix = (rules.active[None, :] & event_ok[:, None] & cond_met)

    fired_count = jnp.sum(fired_matrix, axis=1, dtype=jnp.int32)
    fired = fired_count > 0
    G = rules.zone_row.shape[0]
    rule_ids = jnp.arange(G, dtype=jnp.int32)[None, :]
    first_rule = jnp.min(jnp.where(fired_matrix, rule_ids, G), axis=1)
    first_rule = jnp.where(fired, first_rule, -1).astype(jnp.int32)
    alert_level = jnp.max(
        jnp.where(fired_matrix, rules.alert_level[None, :], -1), axis=1
    ).astype(jnp.int32)
    return {
        "fired": fired,
        "fired_count": fired_count,
        "first_rule": first_rule,
        "alert_level": alert_level,
        "inside": inside_scoped,
    }
