"""Anomaly-model scoring inside the fused step.

Evaluates the compiled anomaly-model weight tables (ml/compiler.py)
with per-(device, model, feature) state carried in HBM across steps:
EWMA accumulators and last-value/last-ts pairs for rate features —
the same feature semantics the rule-program predicates use
(ops/stateful.py), pinned by the same kind of NumPy oracle
(tests/test_anomaly_models.py).

Work scales with the BATCH, not the device capacity: each batch row's
whole feature-state record gathers with one contiguous read from the
fused i32 slab [D, P, 4*F+2] (ops/stateful.py lane layout) and scatters
back from each device's ATTACH row (its last tracked-measurement row this
step — a unique writer, so the scatter is deterministic like every
other fold here). The model forward pass is a static unroll over the
layer bucket: one [P, H, H] einsum per layer over every (row, model)
pair — tiny matrices, batched wide, exactly the shape the MXU (or a
CPU's SIMD GEMM) wants.

Step semantics (the oracle pins them exactly):
  * a device's observation TICK is a step with >= 1 valid tracked
    measurement event (same definition as the rule programs);
  * features read the POST-FOLD last-measurement state; EWMA and rate
    features advance their state only when their measurement was
    observed this step (same equations as ops/stateful.py);
  * a model SCORES at a tick only when every used feature is ready
    (value: ever observed; ewma: >= 1 observation; rate: >= 2) and
    finite — a NaN feature never fires and never counts as scored;
  * mlp score = sigmoid(out_w . h + out_b) over tanh hidden layers;
    autoencoder score = mean squared reconstruction error of the
    normalized features (final layer linear);
  * a model FIRES on the RISING EDGE of (score > threshold) at a scored
    tick; fires attach to the device's last tracked-measurement row so
    they ride the alert-lane compaction (ops/compact.py) and delivery
    stays one fixed-shape D2H fetch per step.

Generation reset: `row_gen [D, P]` vs the table's per-slot `epoch` —
a gathered row whose generation lags its model's epoch reads as fresh
state, so installing a new model into a recycled slot resets feature
state lazily INSIDE the jit (lockstep-safe, no out-of-band device
mutation, no full-capacity sweep — rules/compiler.py's trick).
"""

from __future__ import annotations

from typing import Dict, Tuple

import jax
import jax.numpy as jnp
import numpy as np
from flax import struct

from sitewhere_tpu.ml.compiler import AnomalyModelTable, FeatureKind, \
    ModelKind
from sitewhere_tpu.ops.slab import _slab_f32, _slab_i32, state_slab_lanes

_NEG = -(2 ** 31)


@struct.dataclass
class ModelStateTensors:
    """Per-(device, model, feature) scoring state, HBM-resident like
    RuleStateTensors (sharded engines carry a leading shard axis on
    every field).

    All per-device state lives in ONE fused i32 slab [D, P, 4*F+2] with
    the same lane layout as the rule-state slab (value/aux bits, ts,
    counter planes, then the score_prev bit and the row generation), so
    a step gathers a device's whole scoring record with one contiguous
    HBM read.

    The (value, aux, ts, counter) quad is one uniform record per
    feature slot:
      VALUE  unused (the post-fold last measurement IS the state)
      EWMA   value = accumulator, counter = observation count
      RATE   value = prev observation, aux = last computed rate,
             ts = prev observation ts, counter = observation count
    """

    slab: jnp.ndarray        # i32 [D, P, 4*F+2] fused per-device state
    gen: jnp.ndarray         # i32 [P] counter-row generation
    fire_count: jnp.ndarray  # i32 [P] cumulative fires
    eval_count: jnp.ndarray  # i32 [P] cumulative scored ticks

    @property
    def num_models(self) -> int:
        return self.gen.shape[-1]

    @property
    def num_features(self) -> int:
        return (self.slab.shape[-1] - 2) // 4


def init_model_state_np(max_devices: int, max_models: int,
                        max_features: int) -> ModelStateTensors:
    """Numpy-leaved initial state (same contract as init_rule_state_np:
    no device buffers, so sharded engines place the tree with ONE
    device_put on their mesh)."""
    D, P, F = max_devices, max_models, max_features
    slab = np.zeros((D, P, state_slab_lanes(F)), np.int32)
    slab[:, :, 2 * F:3 * F] = _NEG   # ts plane; zero bits are 0.0f elsewhere
    return ModelStateTensors(
        slab=slab,
        gen=np.zeros((P,), np.int32),
        fire_count=np.zeros((P,), np.int32),
        eval_count=np.zeros((P,), np.int32),
    )


def init_model_state(max_devices: int, max_models: int,
                     max_features: int) -> ModelStateTensors:
    return jax.tree_util.tree_map(
        jnp.asarray,
        init_model_state_np(max_devices, max_models, max_features))


def eval_anomaly_models(
        table: AnomalyModelTable,
        state: ModelStateTensors,
        *,
        dev: jnp.ndarray,             # i32 [B] row device index
        attach: jnp.ndarray,          # bool [B] device's last tracked row
        obs_row: jnp.ndarray,         # bool [B, M] device observed slot m
        lm_row: jnp.ndarray,          # f32 [B, M] POST-fold last values
        lmts_row: jnp.ndarray,        # i32 [B, M] POST-fold last ts
        tenant_row: jnp.ndarray,      # i32 [B] registry mirror per row
        dtype_row: jnp.ndarray,       # i32 [B] registry mirror per row
) -> Tuple[ModelStateTensors, Dict[str, jnp.ndarray]]:
    """One fused-step advance, evaluated on the batch's rows.

    Only ATTACH rows advance state and may fire (one per ticked device);
    the returned per-row outputs feed the alert-lane compaction:
      fired:       bool [B]
      first_model: i32 [B] lowest fired model slot (-1 = none)
      alert_level: i32 [B] max level among fired models (-1 = none)
      score:       f32 [B] lowest scored slot's score (0 = none scored)
    """
    B = dev.shape[0]
    D = state.slab.shape[0]
    P, F = table.num_models, table.num_features
    H = table.width

    eligible = (
        table.active[None, :]
        & ((table.tenant_idx[None, :] == 0)
           | (table.tenant_idx[None, :] == tenant_row[:, None]))
        & ((table.device_type_idx[None, :] == 0)
           | (table.device_type_idx[None, :] == dtype_row[:, None]))
    )                                                     # [B, P]
    tick = eligible & attach[:, None]                     # [B, P]

    # ONE contiguous gather pulls each row's whole fused state record;
    # rows whose generation lags their model's epoch read as fresh
    # (lazy per-row reset)
    slab_rows = state.slab[dev]                           # [B, P, 4F+2]
    stale = slab_rows[:, :, 4 * F + 1] != table.epoch[None, :]  # [B, P]
    stale_f = stale[:, :, None]
    value_s = jnp.where(stale_f, 0.0,
                        _slab_f32(slab_rows[:, :, 0:F]))  # [B, P, F]
    aux_s = jnp.where(stale_f, 0.0, _slab_f32(slab_rows[:, :, F:2 * F]))
    ts_s = jnp.where(stale_f, _NEG, slab_rows[:, :, 2 * F:3 * F])
    ctr_s = jnp.where(stale_f, 0, slab_rows[:, :, 3 * F:4 * F])
    prev_row = jnp.where(stale, False, slab_rows[:, :, 4 * F] != 0)  # [B, P]

    # ---- feature extraction + state advance ([B, P, F] vectorized) ----
    mm = jnp.clip(table.feat_mm, 0, lm_row.shape[1] - 1)  # [P, F]
    fk = table.feat_kind[None, :, :]                      # [1, P, F]
    used = table.feat_kind > FeatureKind.UNUSED           # [P, F]

    v = lm_row[:, mm]                                     # [B, P, F]
    cur_ts = lmts_row[:, mm]                              # [B, P, F]
    known = cur_ts > _NEG                                 # [B, P, F]
    observed = obs_row[:, mm] & eligible[:, :, None]      # [B, P, F]
    obs_inc = observed.astype(jnp.int32)

    is_ewma = fk == FeatureKind.EWMA
    is_rate = fk == FeatureKind.RATE

    # EWMA advance (ops/stateful.py equations, per feature lane)
    alpha = table.feat_alpha[None, :, :]
    ewma = jnp.where(ctr_s > 0, alpha * v + (1.0 - alpha) * value_s, v)
    new_sv_ewma = jnp.where(observed, ewma, value_s)

    # rate advance: per-second delta between consecutive observations
    dt = jnp.maximum(cur_ts - ts_s, 1).astype(jnp.float32)
    rate = (v - value_s) * 1000.0 / dt
    upd_rate = observed & (ctr_s > 0)
    new_sa_rate = jnp.where(upd_rate, rate, aux_s)

    # per-kind feature value + readiness
    x = jnp.where(is_ewma, new_sv_ewma,
                  jnp.where(is_rate, new_sa_rate, v))     # [B, P, F]
    ready = jnp.where(
        is_ewma, (ctr_s + obs_inc) > 0,
        jnp.where(is_rate, (ctr_s + obs_inc) > 1, known))
    ready = ready | ~used[None]                           # pads never block

    xn = (x - table.feat_mean[None]) * table.feat_scale[None]
    xn = jnp.where(used[None], xn, 0.0)                   # [B, P, F]
    nan_any = jnp.any(jnp.isnan(xn) & used[None], axis=-1)   # [B, P]
    ready_all = jnp.all(ready, axis=-1)                   # [B, P]

    # state writes (gated per kind; scattered back from attach rows)
    new_value = jnp.where(is_ewma, new_sv_ewma,
                          jnp.where(is_rate & observed, v, value_s))
    new_aux = jnp.where(is_rate, new_sa_rate, aux_s)
    new_ts = jnp.where(is_rate & observed, cur_ts, ts_s)
    new_ctr = jnp.where(is_ewma | is_rate, ctr_s + obs_inc, ctr_s)

    # ---- forward pass: static unroll over the layer bucket ------------
    # features embed in the first F lanes of a width-H activation vector
    # (F <= H enforced by empty_model_table); rows/cols past a model's
    # true dims are zero-padded, so tanh(0) = 0 keeps the padding inert.
    if H > F:
        h0 = jnp.concatenate(
            [xn, jnp.zeros((B, P, H - F), xn.dtype)], axis=-1)
    else:
        h0 = xn
    is_ae = (table.kind == ModelKind.AUTOENCODER)         # [P]
    h = h0
    for li in range(table.num_layers):
        lin = jnp.einsum("pij,bpj->bpi", table.w[:, li], h) \
            + table.b[None, :, li]
        last = (table.n_layers - 1) == li                 # [P]
        act = jnp.where((is_ae & last)[None, :, None], lin, jnp.tanh(lin))
        live = (li < table.n_layers)[None, :, None]
        h = jnp.where(live, act, h)

    mlp_score = jnp.asarray(1.0, h.dtype) / (
        1.0 + jnp.exp(-(jnp.einsum("ph,bph->bp", table.out_w, h)
                        + table.out_b[None, :])))
    lane_used = jnp.arange(H, dtype=jnp.int32)[None, :] \
        < table.n_features[:, None]                       # [P, H]
    err = jnp.where(lane_used[None], h - h0, 0.0)
    ae_score = jnp.sum(err * err, axis=-1) \
        / jnp.maximum(table.n_features[None, :], 1).astype(h.dtype)
    score = jnp.where(is_ae[None, :], ae_score, mlp_score)   # [B, P]

    # ---- fires: rising edge of (score > threshold) at scored ticks ----
    scored = tick & ready_all & ~nan_any                  # [B, P]
    above = scored & (score > table.threshold[None, :])
    fired = above & ~prev_row
    new_prev_row = jnp.where(scored, above, prev_row)

    # fuse the updated record back into slab lanes and scatter it from
    # attach rows only (unique writer per device; other rows route to
    # the dropped pad index) — with attach-sorted rows this is a single
    # contiguous segment write per touched device
    new_rows = jnp.concatenate([
        _slab_i32(new_value), _slab_i32(new_aux),
        new_ts.astype(jnp.int32), new_ctr.astype(jnp.int32),
        new_prev_row.astype(jnp.int32)[:, :, None],
        jnp.broadcast_to(table.epoch[None, :],
                         (B, P)).astype(jnp.int32)[:, :, None],
    ], axis=-1)
    target = jnp.where(attach, dev, D)
    new_state = state.replace(
        slab=state.slab.at[target].set(new_rows, mode="drop"),
        # per-model counters reset when their slot's epoch moved
        gen=table.epoch,
        fire_count=jnp.where(state.gen != table.epoch, 0,
                             state.fire_count)
        + jnp.sum(fired, axis=0, dtype=jnp.int32),
        eval_count=jnp.where(state.gen != table.epoch, 0,
                             state.eval_count)
        + jnp.sum(scored, axis=0, dtype=jnp.int32),
    )

    any_fired = jnp.any(fired, axis=1)                    # [B]
    slot_ids = jnp.arange(P, dtype=jnp.int32)[None, :]
    first_model = jnp.min(jnp.where(fired, slot_ids, P), axis=1)
    first_model = jnp.where(any_fired, first_model, -1).astype(jnp.int32)
    level = jnp.max(
        jnp.where(fired, table.alert_level[None, :], -1), axis=1
    ).astype(jnp.int32)
    # tolerance channel for the differential oracle: the lowest SCORED
    # slot's score this row (well-defined regardless of fires)
    any_scored = jnp.any(scored, axis=1)
    first_scored = jnp.min(jnp.where(scored, slot_ids, P), axis=1)
    score_row = jnp.take_along_axis(
        score, jnp.clip(first_scored, 0, P - 1)[:, None], axis=1)[:, 0]
    score_row = jnp.where(any_scored, score_row, 0.0).astype(jnp.float32)
    return new_state, {
        "fired": any_fired,
        "first_model": first_model,
        "alert_level": level,
        "score": score_row,
    }
