"""Pallas TPU kernel for point-in-polygon geofence containment.

The hottest op of the pipeline step (see ops/geofence.py — the TPU-native
replacement for the reference's per-event JTS containment at
ZoneTestRuleProcessor.java:47-52) as a hand-written VPU kernel: the batch of
points is tiled along sublanes, the zone axis rides the 128-wide lanes, and
the edge loop runs entirely in VMEM, producing the [B, Z] parity matrix in a
single pass with no [B, Z, V] intermediate in HBM.

The XLA `lax.scan` implementation in ops/geofence.py stays as the reference
semantics (and the CPU / non-TPU path); this kernel is bit-identical on the
same inputs and is selected by the engines when their devices are TPUs.
"""

from __future__ import annotations

import functools

import jax
import jax.numpy as jnp
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu

_LANES = 128      # TPU lane width: zone axis padding quantum
_BLOCK_B = 512    # points per grid step (multiple of 8 sublanes; measured
                  # best at Z>=256 on v5e vs 256/1024)


def _round_up(n: int, m: int) -> int:
    return -(-n // m) * m


def _pip_kernel(py_ref, px_ref, y1_ref, x1_ref, y2_ref, x2_ref, out_ref,
                *, n_edges: int):
    """Crossing-number parity for one block of points against all zones.

    py/px: [Bb, 1] point coordinates (lat=y, lon=x).
    y1/x1/y2/x2: [V, Zp] edge endpoint tables (zones along lanes).
    out: [Bb, Zp] bool containment parity.
    """
    py = py_ref[:]                                   # [Bb, 1]
    px = px_ref[:]

    # Parity is carried as int32 (Mosaic cannot carry i1 vectors through
    # scf loops) and stored as int8; callers compare != 0.
    def edge_step(v, parity):
        y1 = y1_ref[pl.ds(v, 1), :]                  # [1, Zp]
        x1 = x1_ref[pl.ds(v, 1), :]
        y2 = y2_ref[pl.ds(v, 1), :]
        x2 = x2_ref[pl.ds(v, 1), :]
        straddles = (y1 > py) != (y2 > py)           # [Bb, Zp]
        dy = y2 - y1
        safe_dy = jnp.where(dy == 0.0, 1.0, dy)
        x_at_y = x1 + (x2 - x1) * (py - y1) / safe_dy
        crosses = straddles & (px < x_at_y)
        return parity ^ crosses.astype(jnp.int32)

    parity0 = jnp.zeros(out_ref.shape, jnp.int32)
    out_ref[:] = jax.lax.fori_loop(0, n_edges, edge_step, parity0)


@functools.partial(jax.jit, static_argnames=("block_b", "interpret"))
def points_in_zones_pallas(lat: jnp.ndarray, lon: jnp.ndarray,
                           vertices: jnp.ndarray, *, block_b: int = _BLOCK_B,
                           interpret: bool = False) -> jnp.ndarray:
    """Even-odd containment of points [B] in polygons [Z, V, 2] -> bool [B, Z].

    Semantically identical to ops.geofence.points_in_zones (XLA scan); padded
    zones/edges are degenerate (zero-length) so they never toggle parity.
    """
    B = lat.shape[0]
    Z, V = vertices.shape[0], vertices.shape[1]
    Bp = _round_up(max(B, 1), block_b)
    Zp = _round_up(max(Z, 1), _LANES)

    starts = vertices                                 # [Z, V, 2]
    ends = jnp.roll(vertices, shift=-1, axis=1)
    # [V, Zp] edge tables; pad zones with zero-length edges (inert).
    def table(a):
        t = a.T.astype(jnp.float32)                   # [V, Z]
        return jnp.pad(t, ((0, 0), (0, Zp - Z)))

    y1, x1 = table(starts[:, :, 0]), table(starts[:, :, 1])
    y2, x2 = table(ends[:, :, 0]), table(ends[:, :, 1])

    py = jnp.pad(lat.astype(jnp.float32), (0, Bp - B)).reshape(Bp, 1)
    px = jnp.pad(lon.astype(jnp.float32), (0, Bp - B)).reshape(Bp, 1)

    grid = (Bp // block_b,)
    point_spec = pl.BlockSpec((block_b, 1), lambda i: (i, 0),
                              memory_space=pltpu.VMEM)
    edge_spec = pl.BlockSpec(memory_space=pltpu.VMEM)
    out = pl.pallas_call(
        functools.partial(_pip_kernel, n_edges=V),
        grid=grid,
        in_specs=[point_spec, point_spec,
                  edge_spec, edge_spec, edge_spec, edge_spec],
        out_specs=pl.BlockSpec((block_b, Zp), lambda i: (i, 0),
                               memory_space=pltpu.VMEM),
        out_shape=jax.ShapeDtypeStruct((Bp, Zp), jnp.int32),
        cost_estimate=pl.CostEstimate(
            flops=8 * Bp * Zp * V,
            bytes_accessed=4 * (2 * Bp + 4 * V * Zp) + Bp * Zp,
            transcendentals=0),
        interpret=interpret,
    )(py, px, y1, x1, y2, x2)
    return out[:B, :Z] != 0
