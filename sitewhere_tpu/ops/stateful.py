"""Stateful rule-program evaluation inside the fused step.

Evaluates the compiled rule-program tables (rules/compiler.py) with
per-(device, program, state-slot) temporal state carried in HBM across
steps: EWMA accumulators, last-value/last-ts pairs for rate-of-change,
consecutive-hit counters for debounce, armed/latched bits for
hysteresis, and satisfied-since timestamps for `for_duration`.

Work scales with the BATCH, not the device capacity: the step first
reduces the batch to per-device observations with the same keyed
reductions the device-state fold uses (ops/segments.py), then evaluates
the [B, P] program matrix only on the batch's rows — state rows gather
per row from the [D, P, S] HBM tensors and scatter back from each
device's ATTACH row (its last tracked-measurement row this step, a
unique writer, so the scatter is deterministic like every other fold
here). A device with no event this step costs nothing, exactly like the
rest of the pipeline.

Step semantics (the NumPy oracle in tests/test_rule_programs.py pins
them exactly):
  * a device's observation TICK is a step in which it had >= 1 valid
    measurement event on a tracked slot (mm_idx < M);
  * predicates read the POST-FOLD last-measurement state, so composite
    conditions over measurements arriving in different events hold
    between observations;
  * temporal operators advance only on ticks; `for_duration` measures
    against the device's newest event timestamp this step;
  * a program FIRES on the rising edge of its root expression at a tick;
    a tick where the root stays true counts one suppression instead
    (per-program fire/suppress counters ride the state tensors);
  * fires attach to the device's last tracked measurement row — the row
    that completed the condition — so composite fires feed the existing
    alert-lane compaction (ops/compact.py) and delivery stays one
    fixed-shape D2H fetch per step.

Generation reset: `row_gen [D, P]` vs the table's per-slot `epoch` —
a gathered row whose generation lags its program's epoch reads as
freshly-initialized state (and writes back the current epoch), so
installing a new program into a recycled slot resets temporal state
lazily INSIDE the jit: lockstep-safe on multi-host meshes, no
out-of-band device mutation, no full-capacity sweep.
"""

from __future__ import annotations

from typing import Dict, Tuple

import jax.numpy as jnp
import numpy as np
from flax import struct

from sitewhere_tpu.rules.compiler import ProgramOp, RuleProgramTable

_NEG = -(2 ** 31)


@struct.dataclass
class RuleStateTensors:
    """Per-(device, program) temporal state, HBM-resident like
    DeviceStateTensors (sharded engines carry a leading shard axis on
    every field, exactly like the device-state group).

    The (value, aux, ts, counter) quad is one uniform state record per
    stateful node (compiler-assigned state_slot):
      EWMA          value = accumulator, counter = observation count
      RATE          value = prev observation, aux = last computed rate,
                    ts = prev observation ts, counter = observation count
      DEBOUNCE      counter = consecutive satisfied ticks
      FOR_DURATION  ts = satisfied-since timestamp (NEG = not satisfied)
      HYSTERESIS    counter = latch bit
    """

    value: jnp.ndarray     # f32 [D, P, S]
    aux: jnp.ndarray       # f32 [D, P, S]
    ts: jnp.ndarray        # i32 [D, P, S]
    counter: jnp.ndarray   # i32 [D, P, S]
    root_prev: jnp.ndarray  # bool [D, P] root output at the last tick
    row_gen: jnp.ndarray   # i32 [D, P] per-row state generation
    gen: jnp.ndarray       # i32 [P] counter-row generation
    fire_count: jnp.ndarray      # i32 [P] cumulative fires
    suppress_count: jnp.ndarray  # i32 [P] cumulative suppressions

    @property
    def num_programs(self) -> int:
        return self.gen.shape[-1]

    @property
    def num_state_slots(self) -> int:
        return self.value.shape[-1]


def init_rule_state_np(max_devices: int,
                       max_programs: int,
                       state_slots: int) -> RuleStateTensors:
    """Numpy-leaved initial state (same contract as init_device_state_np:
    no device buffers, so sharded engines place the tree with ONE
    device_put on their mesh)."""
    D, P, S = max_devices, max_programs, state_slots
    return RuleStateTensors(
        value=np.zeros((D, P, S), np.float32),
        aux=np.zeros((D, P, S), np.float32),
        ts=np.full((D, P, S), _NEG, np.int32),
        counter=np.zeros((D, P, S), np.int32),
        root_prev=np.zeros((D, P), bool),
        row_gen=np.zeros((D, P), np.int32),
        gen=np.zeros((P,), np.int32),
        fire_count=np.zeros((P,), np.int32),
        suppress_count=np.zeros((P,), np.int32),
    )


def init_rule_state(max_devices: int, max_programs: int,
                    state_slots: int) -> RuleStateTensors:
    import jax

    return jax.tree_util.tree_map(
        jnp.asarray,
        init_rule_state_np(max_devices, max_programs, state_slots))


def _slot_onehot(slots: jnp.ndarray, size: int) -> jnp.ndarray:
    """[P] slot ids -> bool [P, size] one-hot. The lane axes here are
    tiny static buckets (state slots, node slots), so dense one-hot
    select/merge beats per-element scatter/gather by orders of magnitude
    on every backend (XLA scatters with full index arrays serialize on
    CPU and tile poorly on the VPU)."""
    return slots[:, None] == jnp.arange(size, dtype=slots.dtype)[None, :]


def _gather_slot(arr: jnp.ndarray, slots: jnp.ndarray) -> jnp.ndarray:
    """arr [B, P, S], slots [P] -> [B, P] (each program's assigned lane)."""
    onehot = _slot_onehot(slots, arr.shape[2])[None]      # [1, P, S]
    if arr.dtype == jnp.bool_:
        return jnp.any(arr & onehot, axis=2)
    return jnp.sum(jnp.where(onehot, arr, 0), axis=2).astype(arr.dtype)


def _scatter_slot(arr: jnp.ndarray, slots: jnp.ndarray,
                  values: jnp.ndarray, write: jnp.ndarray) -> jnp.ndarray:
    """Write `values` [B, P] into arr[b, p, slots[p]] where `write` [P];
    programs outside `write` keep their lane untouched."""
    onehot = _slot_onehot(slots, arr.shape[2])[None]      # [1, P, S]
    mask = onehot & write[None, :, None]
    return jnp.where(mask, values[:, :, None], arr)


def eval_rule_programs(
        table: RuleProgramTable,
        state: RuleStateTensors,
        *,
        dev: jnp.ndarray,             # i32 [B] row device index
        attach: jnp.ndarray,          # bool [B] device's last tracked row
        obs_row: jnp.ndarray,         # bool [B, M] device observed slot m
        now_row: jnp.ndarray,         # i32 [B] device's newest ts this step
        lm_row: jnp.ndarray,          # f32 [B, M] POST-fold last values
        lmts_row: jnp.ndarray,        # i32 [B, M] POST-fold last ts
        tenant_row: jnp.ndarray,      # i32 [B] registry mirror per row
        dtype_row: jnp.ndarray,       # i32 [B] registry mirror per row
        node_limit: int = 0,          # static: node slots actually in use
) -> Tuple[RuleStateTensors, Dict[str, jnp.ndarray]]:
    """One fused-step advance, evaluated on the batch's rows.

    Only ATTACH rows advance state and may fire (one per ticked device);
    the returned per-row outputs feed the alert-lane compaction:
      fired:       bool [B]
      first_rule:  i32 [B] lowest fired program slot (-1 = none)
      alert_level: i32 [B] max level among fired programs (-1 = none)
    """
    from sitewhere_tpu.ops.threshold import _compare

    B = dev.shape[0]
    D = state.value.shape[0]
    P, N = table.num_programs, table.num_nodes
    # trim the unrolled node pass to the slots the COMPILED table
    # actually populates (trace-time static, threaded from the engine's
    # table compile): the bucket is a capacity, and an all-NOP tail slot
    # still costs a full op-group per unroll step — pure dispatch
    # overhead on CPU, pure pipeline bubbles on the VPU
    if node_limit:
        N = min(N, node_limit)
    S = state.num_state_slots

    eligible = (
        table.active[None, :]
        & ((table.tenant_idx[None, :] == 0)
           | (table.tenant_idx[None, :] == tenant_row[:, None]))
        & ((table.device_type_idx[None, :] == 0)
           | (table.device_type_idx[None, :] == dtype_row[:, None]))
    )                                                     # [B, P]
    tick = eligible & attach[:, None]                     # [B, P]

    # gather this batch's state rows; rows whose generation lags their
    # program's epoch read as fresh (lazy per-row reset)
    stale = state.row_gen[dev] != table.epoch[None, :]    # [B, P]
    stale_s = stale[:, :, None]
    value_s = jnp.where(stale_s, 0.0, state.value[dev])   # [B, P, S]
    aux_s = jnp.where(stale_s, 0.0, state.aux[dev])
    ts_s = jnp.where(stale_s, _NEG, state.ts[dev])
    ctr_s = jnp.where(stale_s, 0, state.counter[dev])
    prev_row = jnp.where(stale, False, state.root_prev[dev])  # [B, P]

    outs = jnp.zeros((B, P, N), bool)

    for j in range(N):  # static unroll; children sit at lower slots
        op = table.opcode[:, j]                           # [P]
        mm = jnp.clip(table.mm_idx[:, j], 0, lm_row.shape[1] - 1)
        slot = table.state_slot[:, j]                     # [P]
        cmp_op = table.cmp_op[None, :, j]                 # [1, P]
        fconst = table.fconst[None, :, j]                 # [1, P]

        v = lm_row[:, mm]                                 # [B, P]
        known = lmts_row[:, mm] > _NEG                    # [B, P]
        observed = obs_row[:, mm] & eligible              # [B, P]

        sv = _gather_slot(value_s, slot)                  # [B, P]
        sa = _gather_slot(aux_s, slot)
        st = _gather_slot(ts_s, slot)
        sc = _gather_slot(ctr_s, slot)

        is_value = op == ProgramOp.VALUE
        is_ewma = op == ProgramOp.EWMA
        is_rate = op == ProgramOp.RATE
        is_not = op == ProgramOp.NOT
        is_and = op == ProgramOp.AND
        is_or = op == ProgramOp.OR
        is_deb = op == ProgramOp.DEBOUNCE
        is_dur = op == ProgramOp.FOR_DURATION
        is_hys = op == ProgramOp.HYSTERESIS

        lhs = _gather_slot(outs, jnp.clip(table.lhs[:, j], 0, N - 1))
        rhs = _gather_slot(outs, jnp.clip(table.rhs[:, j], 0, N - 1))

        # ---- predicates ------------------------------------------------
        out_value = known & _compare(v, cmp_op, fconst)

        alpha = table.falpha[None, :, j]
        ewma = jnp.where(sc > 0, alpha * v + (1.0 - alpha) * sv, v)
        new_sv_ewma = jnp.where(observed, ewma, sv)
        out_ewma = ((sc + observed.astype(jnp.int32)) > 0) \
            & _compare(new_sv_ewma, cmp_op, fconst)

        cur_ts = lmts_row[:, mm]
        dt = jnp.maximum(cur_ts - st, 1).astype(jnp.float32)
        rate = (v - sv) * 1000.0 / dt
        upd_rate = observed & (sc > 0)
        new_sa_rate = jnp.where(upd_rate, rate, sa)
        out_rate = ((sc + observed.astype(jnp.int32)) > 1) \
            & _compare(new_sa_rate, cmp_op, fconst)

        # ---- temporal operators (advance on ticks only) ---------------
        iparam = table.iparam[None, :, j]
        new_sc_deb = jnp.where(
            tick, jnp.where(lhs, jnp.minimum(sc + 1, 2 ** 30), 0), sc)
        out_deb = new_sc_deb >= iparam

        since = jnp.where(st == _NEG, now_row[:, None], st)
        new_st_dur = jnp.where(tick, jnp.where(lhs, since, _NEG), st)
        out_dur = lhs & (new_st_dur != _NEG) \
            & (now_row[:, None] - new_st_dur >= iparam)

        latch = sc > 0
        new_latch = jnp.where(tick, (latch | lhs) & ~rhs, latch)
        out_hys = new_latch

        # ---- merge by opcode (data-independent select) ----------------
        out_j = (
            (is_value & out_value) | (is_ewma & out_ewma)
            | (is_rate & out_rate) | (is_not & ~lhs)
            | (is_and & (lhs & rhs)) | (is_or & (lhs | rhs))
            | (is_deb & out_deb) | (is_dur & out_dur)
            | (is_hys & out_hys))
        outs = outs.at[:, :, j].set(out_j)

        # ---- state writes (one lane per stateful node) ----------------
        obs_inc = observed.astype(jnp.int32)
        new_value = jnp.where(is_ewma, new_sv_ewma,
                              jnp.where(is_rate & observed, v, sv))
        new_aux = jnp.where(is_rate, new_sa_rate, sa)
        new_ts = jnp.where(is_rate & observed, cur_ts,
                           jnp.where(is_dur, new_st_dur, st))
        new_ctr = jnp.where(is_ewma | is_rate, sc + obs_inc,
                            jnp.where(is_deb, new_sc_deb,
                                      jnp.where(is_hys,
                                                new_latch.astype(jnp.int32),
                                                sc)))
        stateful = (is_ewma | is_rate | is_deb | is_dur | is_hys)
        value_s = _scatter_slot(value_s, slot, new_value, stateful)
        aux_s = _scatter_slot(aux_s, slot, new_aux, stateful)
        ts_s = _scatter_slot(ts_s, slot, new_ts, stateful)
        ctr_s = _scatter_slot(ctr_s, slot, new_ctr, stateful)

    root = _gather_slot(outs, jnp.clip(table.root, 0, N - 1)) & eligible
    fired = tick & root & ~prev_row                       # [B, P]
    suppressed = tick & root & prev_row
    new_prev_row = jnp.where(tick, root, prev_row)

    # scatter updated rows back from attach rows only (unique writer per
    # device; other rows route to the dropped pad index)
    target = jnp.where(attach, dev, D)
    def put(arr, rows):
        return arr.at[target].set(rows, mode="drop")
    new_state = state.replace(
        value=put(state.value, value_s),
        aux=put(state.aux, aux_s),
        ts=put(state.ts, ts_s),
        counter=put(state.counter, ctr_s),
        root_prev=put(state.root_prev, new_prev_row),
        row_gen=put(state.row_gen,
                    jnp.broadcast_to(table.epoch[None, :], (B, P))),
        # per-program counters reset when their slot's epoch moved
        gen=table.epoch,
        fire_count=jnp.where(state.gen != table.epoch, 0,
                             state.fire_count)
        + jnp.sum(fired, axis=0, dtype=jnp.int32),
        suppress_count=jnp.where(state.gen != table.epoch, 0,
                                 state.suppress_count)
        + jnp.sum(suppressed, axis=0, dtype=jnp.int32),
    )

    any_fired = jnp.any(fired, axis=1)                    # [B]
    slot_ids = jnp.arange(P, dtype=jnp.int32)[None, :]
    first_prog = jnp.min(jnp.where(fired, slot_ids, P), axis=1)
    first_prog = jnp.where(any_fired, first_prog, -1).astype(jnp.int32)
    level = jnp.max(
        jnp.where(fired, table.alert_level[None, :], -1), axis=1
    ).astype(jnp.int32)
    return new_state, {
        "fired": any_fired,
        "first_rule": first_prog,
        "alert_level": level,
    }


def observations_of_batch(batch, measurement_slots: int, num_devices: int
                          ) -> Tuple[jnp.ndarray, jnp.ndarray, jnp.ndarray,
                                     jnp.ndarray]:
    """Reduce a packed batch to the per-device observation view the
    program evaluator consumes: (obs_mm [D, M], touched [D], now_d [D],
    attach_row [B]).

    `attach_row` marks, per batch row, whether it is its device's LAST
    valid tracked-measurement row — the row a composite fire attaches to
    so it rides the alert lanes. Built from the same scatter reductions
    as the device-state fold (deterministic under XLA)."""
    from sitewhere_tpu.model.event import DeviceEventType
    from sitewhere_tpu.ops.segments import count_by_key, scatter_max_by_key

    D, M = num_devices, measurement_slots
    dev = batch.device_idx
    is_obs = (batch.valid
              & (batch.event_type == DeviceEventType.MEASUREMENT)
              & (batch.mm_idx > 0) & (batch.mm_idx < M))      # bool [B]
    mm_key = dev * M + batch.mm_idx
    obs_mm = (count_by_key(mm_key, is_obs, D * M) > 0).reshape(D, M)
    touched = jnp.any(obs_mm, axis=1)
    neg = jnp.full((D,), _NEG, jnp.int32)
    now_d = scatter_max_by_key(dev, batch.ts, is_obs, D, neg)
    B = dev.shape[0]
    row_ids = jnp.arange(B, dtype=jnp.int32)
    last_row = scatter_max_by_key(dev, row_ids, is_obs, D,
                                  jnp.full((D,), -1, jnp.int32))
    attach_row = is_obs & (last_row[dev] == row_ids)
    return obs_mm, touched, now_d, attach_row
