"""Stateful rule-program evaluation inside the fused step.

Evaluates the compiled rule-program tables (rules/compiler.py) with
per-(device, program, state-slot) temporal state carried in HBM across
steps: EWMA accumulators, last-value/last-ts pairs for rate-of-change,
consecutive-hit counters for debounce, armed/latched bits for
hysteresis, and satisfied-since timestamps for `for_duration`.

Work scales with the BATCH, not the device capacity: the step first
reduces the batch to per-device observations with the same keyed
reductions the device-state fold uses (ops/segments.py), then evaluates
the [B, P] program matrix only on the batch's rows — each row's whole
state record gathers with ONE contiguous read from the fused i32 slab
[D, P, 4*S+2] and scatters back from the device's ATTACH row (its last
tracked-measurement row this step, a unique writer, so the scatter is
deterministic like every other fold here). The step sorts batch rows by
device first (ops/segments.py batch_device_order), so gathers and the
attach scatter touch HBM in contiguous device segments. A device with
no event this step costs nothing, exactly like the rest of the
pipeline.

Step semantics (the NumPy oracle in tests/test_rule_programs.py pins
them exactly):
  * a device's observation TICK is a step in which it had >= 1 valid
    measurement event on a tracked slot (mm_idx < M);
  * predicates read the POST-FOLD last-measurement state, so composite
    conditions over measurements arriving in different events hold
    between observations;
  * temporal operators advance only on ticks; `for_duration` measures
    against the device's newest event timestamp this step;
  * a program FIRES on the rising edge of its root expression at a tick;
    a tick where the root stays true counts one suppression instead
    (per-program fire/suppress counters ride the state tensors);
  * fires attach to the device's last tracked measurement row — the row
    that completed the condition — so composite fires feed the existing
    alert-lane compaction (ops/compact.py) and delivery stays one
    fixed-shape D2H fetch per step.

Generation reset: `row_gen [D, P]` vs the table's per-slot `epoch` —
a gathered row whose generation lags its program's epoch reads as
freshly-initialized state (and writes back the current epoch), so
installing a new program into a recycled slot resets temporal state
lazily INSIDE the jit: lockstep-safe on multi-host meshes, no
out-of-band device mutation, no full-capacity sweep.
"""

from __future__ import annotations

from typing import Dict, Tuple

import jax
import jax.numpy as jnp
import numpy as np
from flax import struct

# slab primitives live in the import-leaf ops/slab.py (ops/anomaly.py
# needs them too, and this module's compiler import chain reaches
# anomaly); re-exported here because this is the layout's home API
from sitewhere_tpu.ops.slab import (  # noqa: F401  (re-export)
    _slab_f32, _slab_i32, pack_state_slab_np, state_slab_lanes,
    unpack_state_slab_np)
from sitewhere_tpu.rules.compiler import ProgramOp, RuleProgramTable

_NEG = -(2 ** 31)


@struct.dataclass
class RuleStateTensors:
    """Per-(device, program) temporal state, HBM-resident like
    DeviceStateTensors (sharded engines carry a leading shard axis on
    every field, exactly like the device-state group).

    All per-device state lives in ONE fused i32 slab [D, P, 4*S+2] so a
    step gathers a device's whole state row with a single contiguous
    HBM read instead of six strided ones (the structural fix for the
    small-scale offload losses). Lane layout (see pack_state_slab_np):
    value bits / aux bits / ts / counter planes of S lanes each, then
    the root_prev bit and the per-row generation.

    The (value, aux, ts, counter) quad is one uniform state record per
    stateful node (compiler-assigned state_slot):
      EWMA          value = accumulator, counter = observation count
      RATE          value = prev observation, aux = last computed rate,
                    ts = prev observation ts, counter = observation count
      DEBOUNCE      counter = consecutive satisfied ticks
      FOR_DURATION  ts = satisfied-since timestamp (NEG = not satisfied)
      HYSTERESIS    counter = latch bit
    """

    slab: jnp.ndarray      # i32 [D, P, 4*S+2] fused per-device state
    gen: jnp.ndarray       # i32 [P] counter-row generation
    fire_count: jnp.ndarray      # i32 [P] cumulative fires
    suppress_count: jnp.ndarray  # i32 [P] cumulative suppressions

    @property
    def num_programs(self) -> int:
        return self.gen.shape[-1]

    @property
    def num_state_slots(self) -> int:
        return (self.slab.shape[-1] - 2) // 4


def init_rule_state_np(max_devices: int,
                       max_programs: int,
                       state_slots: int) -> RuleStateTensors:
    """Numpy-leaved initial state (same contract as init_device_state_np:
    no device buffers, so sharded engines place the tree with ONE
    device_put on their mesh)."""
    D, P, S = max_devices, max_programs, state_slots
    slab = np.zeros((D, P, state_slab_lanes(S)), np.int32)
    slab[:, :, 2 * S:3 * S] = _NEG   # ts plane; zero bits are 0.0f elsewhere
    return RuleStateTensors(
        slab=slab,
        gen=np.zeros((P,), np.int32),
        fire_count=np.zeros((P,), np.int32),
        suppress_count=np.zeros((P,), np.int32),
    )


def init_rule_state(max_devices: int, max_programs: int,
                    state_slots: int) -> RuleStateTensors:
    return jax.tree_util.tree_map(
        jnp.asarray,
        init_rule_state_np(max_devices, max_programs, state_slots))


def _gather_slot(arr: jnp.ndarray, slots: jnp.ndarray) -> jnp.ndarray:
    """arr [B, P, S], slots [P] (in-range) -> [B, P]: each program's
    assigned lane, as one narrow take_along_axis instead of a dense
    one-hot reduction over the lane axis."""
    idx = slots.astype(jnp.int32)[None, :, None]          # [1, P, 1]
    return jnp.take_along_axis(arr, idx, axis=2)[..., 0]


def _scatter_slot(arr: jnp.ndarray, slots: jnp.ndarray,
                  values: jnp.ndarray, write: jnp.ndarray) -> jnp.ndarray:
    """Write `values` [B, P] into arr[b, p, slots[p]] where `write` [P];
    programs outside `write` keep their lane untouched (the current lane
    value is written back bit-identically, so the update is one unique-
    index scatter instead of a [B, P, S] select)."""
    cur = _gather_slot(arr, slots)
    new = jnp.where(write[None, :], values, cur)
    lanes = jnp.arange(arr.shape[1], dtype=jnp.int32)
    return arr.at[:, lanes, slots.astype(jnp.int32)].set(new)


def eval_rule_programs(
        table: RuleProgramTable,
        state: RuleStateTensors,
        *,
        dev: jnp.ndarray,             # i32 [B] row device index
        attach: jnp.ndarray,          # bool [B] device's last tracked row
        obs_row: jnp.ndarray,         # bool [B, M] device observed slot m
        now_row: jnp.ndarray,         # i32 [B] device's newest ts this step
        lm_row: jnp.ndarray,          # f32 [B, M] POST-fold last values
        lmts_row: jnp.ndarray,        # i32 [B, M] POST-fold last ts
        tenant_row: jnp.ndarray,      # i32 [B] registry mirror per row
        dtype_row: jnp.ndarray,       # i32 [B] registry mirror per row
        node_limit: int = 0,          # static: node slots actually in use
) -> Tuple[RuleStateTensors, Dict[str, jnp.ndarray]]:
    """One fused-step advance, evaluated on the batch's rows.

    Only ATTACH rows advance state and may fire (one per ticked device);
    the returned per-row outputs feed the alert-lane compaction:
      fired:       bool [B]
      first_rule:  i32 [B] lowest fired program slot (-1 = none)
      alert_level: i32 [B] max level among fired programs (-1 = none)
    """
    from sitewhere_tpu.ops.threshold import _compare

    B = dev.shape[0]
    D = state.slab.shape[0]
    P, N = table.num_programs, table.num_nodes
    # trim the unrolled node pass to the slots the COMPILED table
    # actually populates (trace-time static, threaded from the engine's
    # table compile): the bucket is a capacity, and an all-NOP tail slot
    # still costs a full op-group per unroll step — pure dispatch
    # overhead on CPU, pure pipeline bubbles on the VPU
    if node_limit:
        N = min(N, node_limit)
    S = state.num_state_slots

    eligible = (
        table.active[None, :]
        & ((table.tenant_idx[None, :] == 0)
           | (table.tenant_idx[None, :] == tenant_row[:, None]))
        & ((table.device_type_idx[None, :] == 0)
           | (table.device_type_idx[None, :] == dtype_row[:, None]))
    )                                                     # [B, P]
    tick = eligible & attach[:, None]                     # [B, P]

    # ONE contiguous gather pulls each row's whole fused state record;
    # rows whose generation lags their program's epoch read as fresh
    # (lazy per-row reset)
    slab_rows = state.slab[dev]                           # [B, P, 4S+2]
    stale = slab_rows[:, :, 4 * S + 1] != table.epoch[None, :]  # [B, P]
    stale_s = stale[:, :, None]
    value_s = jnp.where(stale_s, 0.0,
                        _slab_f32(slab_rows[:, :, 0:S]))  # [B, P, S]
    aux_s = jnp.where(stale_s, 0.0, _slab_f32(slab_rows[:, :, S:2 * S]))
    ts_s = jnp.where(stale_s, _NEG, slab_rows[:, :, 2 * S:3 * S])
    ctr_s = jnp.where(stale_s, 0, slab_rows[:, :, 3 * S:4 * S])
    prev_row = jnp.where(stale, False, slab_rows[:, :, 4 * S] != 0)  # [B, P]

    outs = jnp.zeros((B, P, N), bool)

    for j in range(N):  # static unroll; children sit at lower slots
        op = table.opcode[:, j]                           # [P]
        mm = jnp.clip(table.mm_idx[:, j], 0, lm_row.shape[1] - 1)
        slot = table.state_slot[:, j]                     # [P]
        cmp_op = table.cmp_op[None, :, j]                 # [1, P]
        fconst = table.fconst[None, :, j]                 # [1, P]

        v = lm_row[:, mm]                                 # [B, P]
        known = lmts_row[:, mm] > _NEG                    # [B, P]
        observed = obs_row[:, mm] & eligible              # [B, P]

        sv = _gather_slot(value_s, slot)                  # [B, P]
        sa = _gather_slot(aux_s, slot)
        st = _gather_slot(ts_s, slot)
        sc = _gather_slot(ctr_s, slot)

        is_value = op == ProgramOp.VALUE
        is_ewma = op == ProgramOp.EWMA
        is_rate = op == ProgramOp.RATE
        is_not = op == ProgramOp.NOT
        is_and = op == ProgramOp.AND
        is_or = op == ProgramOp.OR
        is_deb = op == ProgramOp.DEBOUNCE
        is_dur = op == ProgramOp.FOR_DURATION
        is_hys = op == ProgramOp.HYSTERESIS

        lhs = _gather_slot(outs, jnp.clip(table.lhs[:, j], 0, N - 1))
        rhs = _gather_slot(outs, jnp.clip(table.rhs[:, j], 0, N - 1))

        # ---- predicates ------------------------------------------------
        out_value = known & _compare(v, cmp_op, fconst)

        alpha = table.falpha[None, :, j]
        ewma = jnp.where(sc > 0, alpha * v + (1.0 - alpha) * sv, v)
        new_sv_ewma = jnp.where(observed, ewma, sv)
        out_ewma = ((sc + observed.astype(jnp.int32)) > 0) \
            & _compare(new_sv_ewma, cmp_op, fconst)

        cur_ts = lmts_row[:, mm]
        dt = jnp.maximum(cur_ts - st, 1).astype(jnp.float32)
        rate = (v - sv) * 1000.0 / dt
        upd_rate = observed & (sc > 0)
        new_sa_rate = jnp.where(upd_rate, rate, sa)
        out_rate = ((sc + observed.astype(jnp.int32)) > 1) \
            & _compare(new_sa_rate, cmp_op, fconst)

        # ---- temporal operators (advance on ticks only) ---------------
        iparam = table.iparam[None, :, j]
        new_sc_deb = jnp.where(
            tick, jnp.where(lhs, jnp.minimum(sc + 1, 2 ** 30), 0), sc)
        out_deb = new_sc_deb >= iparam

        since = jnp.where(st == _NEG, now_row[:, None], st)
        new_st_dur = jnp.where(tick, jnp.where(lhs, since, _NEG), st)
        out_dur = lhs & (new_st_dur != _NEG) \
            & (now_row[:, None] - new_st_dur >= iparam)

        latch = sc > 0
        new_latch = jnp.where(tick, (latch | lhs) & ~rhs, latch)
        out_hys = new_latch

        # ---- merge by opcode (data-independent select) ----------------
        out_j = (
            (is_value & out_value) | (is_ewma & out_ewma)
            | (is_rate & out_rate) | (is_not & ~lhs)
            | (is_and & (lhs & rhs)) | (is_or & (lhs | rhs))
            | (is_deb & out_deb) | (is_dur & out_dur)
            | (is_hys & out_hys))
        outs = outs.at[:, :, j].set(out_j)

        # ---- state writes (one lane per stateful node) ----------------
        obs_inc = observed.astype(jnp.int32)
        new_value = jnp.where(is_ewma, new_sv_ewma,
                              jnp.where(is_rate & observed, v, sv))
        new_aux = jnp.where(is_rate, new_sa_rate, sa)
        new_ts = jnp.where(is_rate & observed, cur_ts,
                           jnp.where(is_dur, new_st_dur, st))
        new_ctr = jnp.where(is_ewma | is_rate, sc + obs_inc,
                            jnp.where(is_deb, new_sc_deb,
                                      jnp.where(is_hys,
                                                new_latch.astype(jnp.int32),
                                                sc)))
        stateful = (is_ewma | is_rate | is_deb | is_dur | is_hys)
        value_s = _scatter_slot(value_s, slot, new_value, stateful)
        aux_s = _scatter_slot(aux_s, slot, new_aux, stateful)
        ts_s = _scatter_slot(ts_s, slot, new_ts, stateful)
        ctr_s = _scatter_slot(ctr_s, slot, new_ctr, stateful)

    root = _gather_slot(outs, jnp.clip(table.root, 0, N - 1)) & eligible
    fired = tick & root & ~prev_row                       # [B, P]
    suppressed = tick & root & prev_row
    new_prev_row = jnp.where(tick, root, prev_row)

    # fuse the updated record back into slab lanes and scatter it from
    # attach rows only (unique writer per device; other rows route to
    # the dropped pad index) — with attach-sorted rows this is a single
    # contiguous segment write per touched device
    new_rows = jnp.concatenate([
        _slab_i32(value_s), _slab_i32(aux_s),
        ts_s.astype(jnp.int32), ctr_s.astype(jnp.int32),
        new_prev_row.astype(jnp.int32)[:, :, None],
        jnp.broadcast_to(table.epoch[None, :],
                         (B, P)).astype(jnp.int32)[:, :, None],
    ], axis=-1)
    target = jnp.where(attach, dev, D)
    new_state = state.replace(
        slab=state.slab.at[target].set(new_rows, mode="drop"),
        # per-program counters reset when their slot's epoch moved
        gen=table.epoch,
        fire_count=jnp.where(state.gen != table.epoch, 0,
                             state.fire_count)
        + jnp.sum(fired, axis=0, dtype=jnp.int32),
        suppress_count=jnp.where(state.gen != table.epoch, 0,
                                 state.suppress_count)
        + jnp.sum(suppressed, axis=0, dtype=jnp.int32),
    )

    any_fired = jnp.any(fired, axis=1)                    # [B]
    slot_ids = jnp.arange(P, dtype=jnp.int32)[None, :]
    first_prog = jnp.min(jnp.where(fired, slot_ids, P), axis=1)
    first_prog = jnp.where(any_fired, first_prog, -1).astype(jnp.int32)
    level = jnp.max(
        jnp.where(fired, table.alert_level[None, :], -1), axis=1
    ).astype(jnp.int32)
    return new_state, {
        "fired": any_fired,
        "first_rule": first_prog,
        "alert_level": level,
    }


def observations_of_batch(batch, measurement_slots: int, num_devices: int
                          ) -> Tuple[jnp.ndarray, jnp.ndarray, jnp.ndarray,
                                     jnp.ndarray]:
    """Reduce a packed batch to the per-device observation view the
    program evaluator consumes: (obs_mm [D, M], touched [D], now_d [D],
    attach_row [B]).

    `attach_row` marks, per batch row, whether it is its device's LAST
    valid tracked-measurement row — the row a composite fire attaches to
    so it rides the alert lanes. Built from the same scatter reductions
    as the device-state fold (deterministic under XLA)."""
    from sitewhere_tpu.model.event import DeviceEventType
    from sitewhere_tpu.ops.segments import count_by_key, scatter_max_by_key

    D, M = num_devices, measurement_slots
    dev = batch.device_idx
    is_obs = (batch.valid
              & (batch.event_type == DeviceEventType.MEASUREMENT)
              & (batch.mm_idx > 0) & (batch.mm_idx < M))      # bool [B]
    mm_key = dev * M + batch.mm_idx
    obs_mm = (count_by_key(mm_key, is_obs, D * M) > 0).reshape(D, M)
    touched = jnp.any(obs_mm, axis=1)
    neg = jnp.full((D,), _NEG, jnp.int32)
    now_d = scatter_max_by_key(dev, batch.ts, is_obs, D, neg)
    B = dev.shape[0]
    row_ids = jnp.arange(B, dtype=jnp.int32)
    last_row = scatter_max_by_key(dev, row_ids, is_obs, D,
                                  jnp.full((D,), -1, jnp.int32))
    attach_row = is_obs & (last_row[dev] == row_ids)
    return obs_mm, touched, now_d, attach_row
