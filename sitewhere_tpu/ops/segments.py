"""Keyed per-device reductions: last-write-wins, scatter-max, counts.

The TPU replacement for the reference's per-event Mongo upserts in
service-device-state (DeviceStateProcessingLogic.java:116+ merges each event
into a DeviceState row): a whole batch of events folds into device-indexed
state tensors with sort + boundary-detection + unique-index scatter, which is
deterministic under XLA (unlike duplicate-index scatter-set).

SURVEY.md §7 hard part (d): keyed last-write-wins at scale.
"""

from __future__ import annotations

from typing import Sequence, Tuple

import jax
import jax.numpy as jnp


def _last_row_selector(keys: jnp.ndarray, ts: jnp.ndarray, valid: jnp.ndarray,
                       num_segments: int
                       ) -> Tuple[jnp.ndarray, jnp.ndarray, jnp.ndarray]:
    """Sort rows by (key, ts) with invalid rows keyed to `num_segments`, and
    compute for each sorted row whether it is the LAST row of its key segment.

    Returns (order, scatter_target, is_last_sorted):
      order[B]      permutation sorting the batch
      target[B]     key for last-of-segment rows, num_segments otherwise
                    (scatter into a [num_segments+1] padded array, drop tail)
      is_last[B]    last-of-segment mask in sorted order
    """
    B = keys.shape[0]
    sort_key = jnp.where(valid, keys, num_segments)
    # Stable two-level sort: primary key, secondary ts. jnp.lexsort sorts by
    # last key first.
    order = jnp.lexsort((ts, sort_key))
    sorted_keys = sort_key[order]
    next_keys = jnp.concatenate(
        [sorted_keys[1:], jnp.full((1,), -1, sorted_keys.dtype)])
    is_last = sorted_keys != next_keys
    target = jnp.where(is_last & (sorted_keys < num_segments),
                       sorted_keys, num_segments)
    return order, target, is_last


def last_by_key(keys: jnp.ndarray, ts: jnp.ndarray, valid: jnp.ndarray,
                num_segments: int, state_ts: jnp.ndarray,
                states: Sequence[jnp.ndarray], values: Sequence[jnp.ndarray],
                ) -> Tuple[jnp.ndarray, Tuple[jnp.ndarray, ...]]:
    """Fold a batch into last-value-wins state tensors.

    For each key k appearing in the batch (valid rows only), pick the row with
    the greatest ts; if that ts >= state_ts[k], write each values[i] into
    states[i][k] and update state_ts[k]. Rows with equal ts resolve by batch
    position (later position wins) via stable sort.

    Args:
      keys:   int32 [B] segment ids in [0, num_segments)
      ts:     int32 [B] event timestamps (rebased ms)
      valid:  bool  [B]
      num_segments: static int
      state_ts: int32 [num_segments] current last-update ts per key
      states: tensors [num_segments, ...] to update
      values: matching per-row update values [B, ...]

    Returns (new_state_ts, tuple(new_states)).
    """
    order, target, _ = _last_row_selector(keys, ts, valid, num_segments)
    sorted_ts = ts[order]
    # Only apply if batch ts is newer than (or equal to) what state holds.
    candidate_ts = jnp.zeros(num_segments + 1, ts.dtype).at[target].set(sorted_ts)
    touched = jnp.zeros(num_segments + 1, bool).at[target].set(True)[:num_segments]
    newer = touched & (candidate_ts[:num_segments] >= state_ts)
    new_state_ts = jnp.where(newer, candidate_ts[:num_segments], state_ts)

    new_states = []
    for state, value in zip(states, values):
        sorted_val = value[order]
        candidate = (jnp.zeros((num_segments + 1,) + state.shape[1:], state.dtype)
                     .at[target].set(sorted_val))[:num_segments]
        mask = newer.reshape((num_segments,) + (1,) * (state.ndim - 1))
        new_states.append(jnp.where(mask, candidate, state))
    return new_state_ts, tuple(new_states)


def batch_device_order(dev: jnp.ndarray) -> Tuple[jnp.ndarray, jnp.ndarray]:
    """Stable permutation grouping batch rows by device, plus its inverse.

    One shared argsort per step: the rule-program and anomaly-model
    kernels gather their HBM state rows at `dev[order]` so all rows of
    the same device read adjacent state, and per-row outputs are
    un-sorted with `out[inv]`. Stability preserves batch arrival order
    inside each device segment, so last-writer-wins semantics are
    untouched.

    Returns (order, inv) with `inv[order[i]] == i`.
    """
    B = dev.shape[0]
    rows = jnp.arange(B, dtype=jnp.int32)
    order = jnp.lexsort((rows, dev))
    inv = jnp.zeros((B,), order.dtype).at[order].set(rows)
    return order, inv


def bucket_ranks(keys: jnp.ndarray) -> jnp.ndarray:
    """Arrival-order rank of each row within its key bucket.

    Sort-based replacement for the one-hot × cumsum counting sort
    (O(B·S) work, [B, S] intermediate): a single stable sort by key
    plus segment-start subtraction gives the same rank in O(B log B)
    with no wide intermediates. For rows sharing a key, ranks follow
    batch position (stable sort), exactly like cumsum over arrival
    order.
    """
    B = keys.shape[0]
    rows = jnp.arange(B, dtype=jnp.int32)
    order = jnp.lexsort((rows, keys))
    sk = keys[order]
    first = jnp.concatenate(
        [jnp.ones((1,), bool), sk[1:] != sk[:-1]])
    seg_start = jax.lax.cummax(jnp.where(first, rows, 0))
    rank = rows - seg_start
    return jnp.zeros((B,), jnp.int32).at[order].set(rank)


def scatter_max_by_key(keys: jnp.ndarray, values: jnp.ndarray,
                       valid: jnp.ndarray, num_segments: int,
                       state: jnp.ndarray) -> jnp.ndarray:
    """state[k] = max(state[k], max over batch rows with key k).

    Used for last-interaction timestamps (presence tracking): duplicate-index
    scatter-max is deterministic. Invalid rows route to the dropped pad row.
    """
    target = jnp.where(valid, keys, num_segments)
    padded = jnp.concatenate([state, jnp.full((1,), -(2 ** 31), state.dtype)])
    return padded.at[target].max(values)[:num_segments]


def count_by_key(keys: jnp.ndarray, valid: jnp.ndarray, num_segments: int,
                 weights: jnp.ndarray = None) -> jnp.ndarray:
    """Per-key event counts (int32 [num_segments]) — feeds per-tenant /
    per-device throughput stats (the reference's Dropwizard meters)."""
    target = jnp.where(valid, keys, num_segments)
    ones = (weights if weights is not None
            else jnp.ones(keys.shape[0], jnp.int32))
    ones = jnp.where(valid, ones, 0)
    return jnp.zeros(num_segments + 1, jnp.int32).at[target].add(ones)[:num_segments]
