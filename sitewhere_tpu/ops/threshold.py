"""Vectorized threshold-rule evaluation.

The TPU replacement for per-event rule processor dispatch
(service-rule-processing KafkaRuleProcessorHost.java:144 switch + callbacks):
R rules are a table of columns; one batch evaluates all B x R (event, rule)
pairs as a broadcast compare on the VPU, then reduces per event.

A rule matches an event when: rule active, event valid, event is a
MEASUREMENT, tenant matches (or rule tenant = 0 = any), measurement name
matches (or 0 = any), device type matches (or 0 = any), and
`value <op> threshold` holds.
"""

from __future__ import annotations

from typing import Dict, Optional, Tuple

import jax.numpy as jnp
import numpy as np
from flax import struct

from sitewhere_tpu.model.event import DeviceEventType
from sitewhere_tpu.ops.pack import EventBatch


class ThresholdOp:
    GT = 0
    GTE = 1
    LT = 2
    LTE = 3
    EQ = 4
    NEQ = 5

    BY_NAME = {">": GT, ">=": GTE, "<": LT, "<=": LTE, "==": EQ, "!=": NEQ}


@struct.dataclass
class ThresholdRuleTable:
    """SoA rule columns, all shape [R]."""

    active: np.ndarray        # bool
    tenant_idx: np.ndarray    # int32, 0 = any tenant
    mm_idx: np.ndarray        # int32, 0 = any measurement
    device_type_idx: np.ndarray  # int32, 0 = any device type
    op: np.ndarray            # int32, ThresholdOp
    threshold: np.ndarray     # float32
    alert_level: np.ndarray   # int32 AlertLevel fired on match
    alert_type_idx: np.ndarray  # int32 interned alert type code

    @property
    def num_rules(self) -> int:
        return self.active.shape[0]


def empty_threshold_table(max_rules: int) -> ThresholdRuleTable:
    zi = np.zeros(max_rules, np.int32)
    return ThresholdRuleTable(
        active=np.zeros(max_rules, bool), tenant_idx=zi, mm_idx=zi.copy(),
        device_type_idx=zi.copy(), op=zi.copy(),
        threshold=np.zeros(max_rules, np.float32),
        alert_level=zi.copy(), alert_type_idx=zi.copy())


def _compare(value: jnp.ndarray, op: jnp.ndarray, threshold: jnp.ndarray
             ) -> jnp.ndarray:
    """value [B,1] vs op/threshold [R] -> [B,R]; selects among all six compares
    (cheap on VPU; avoids data-dependent branching).

    NaN guard: a NaN measurement value satisfies NO comparison. IEEE
    semantics already make the ordered compares false, but `!=` is TRUE
    for NaN — a corrupt/unparseable reading must never fire an alert, so
    non-firing is explicit rather than inherited per-op."""
    gt = value > threshold
    lt = value < threshold
    eq = value == threshold
    result = jnp.select(
        [op == ThresholdOp.GT, op == ThresholdOp.GTE, op == ThresholdOp.LT,
         op == ThresholdOp.LTE, op == ThresholdOp.EQ],
        [gt, gt | eq, lt, lt | eq, eq],
        default=~eq)
    return result & ~jnp.isnan(value)


def eval_threshold_rules(batch: EventBatch, table: ThresholdRuleTable,
                         device_type_idx_of_event: jnp.ndarray
                         ) -> Dict[str, jnp.ndarray]:
    """Evaluate all rules against all events.

    Returns per-event outputs (shape [B]):
      fired:          bool, any rule fired
      fired_count:    int32, number of rules fired
      first_rule:     int32, lowest-index fired rule (-1 if none)
      alert_level:    int32, max alert level among fired rules
    """
    value = batch.value[:, None]                     # [B,1]
    is_measurement = (batch.event_type == DeviceEventType.MEASUREMENT)
    event_ok = (batch.valid & is_measurement)[:, None]   # [B,1]

    tenant_ok = ((table.tenant_idx[None, :] == 0)
                 | (table.tenant_idx[None, :] == batch.tenant_idx[:, None]))
    mm_ok = ((table.mm_idx[None, :] == 0)
             | (table.mm_idx[None, :] == batch.mm_idx[:, None]))
    dtype_ok = ((table.device_type_idx[None, :] == 0)
                | (table.device_type_idx[None, :]
                   == device_type_idx_of_event[:, None]))
    predicate = _compare(value, table.op[None, :], table.threshold[None, :])

    fired_matrix = (table.active[None, :] & event_ok & tenant_ok & mm_ok
                    & dtype_ok & predicate)          # [B,R]

    fired_count = jnp.sum(fired_matrix, axis=1, dtype=jnp.int32)
    fired = fired_count > 0
    R = table.num_rules
    rule_ids = jnp.arange(R, dtype=jnp.int32)[None, :]
    first_rule = jnp.min(jnp.where(fired_matrix, rule_ids, R), axis=1)
    first_rule = jnp.where(fired, first_rule, -1).astype(jnp.int32)
    alert_level = jnp.max(
        jnp.where(fired_matrix, table.alert_level[None, :], -1), axis=1
    ).astype(jnp.int32)
    return {
        "fired": fired,
        "fired_count": fired_count,
        "first_rule": first_rule,
        "alert_level": alert_level,
    }
