"""Event packing: API events -> fixed-width SoA tensors.

The wire/API view of events is the dataclass family in model/event.py; the
device view is `EventBatch`: one fixed-width column per field, shape [B], with
a validity mask for padding. Variable-rate ingest never changes shapes — the
host packs whatever arrived into the next fixed-size batch and pads
(SURVEY.md §7 hard part (a): bucketed shapes + padding masks, no recompiles).

Timestamps are int32 milliseconds relative to a host-held `epoch_base_ms` so
they fit TPU-friendly 32-bit lanes; the host rebases periodically (int32 ms
covers ±24 days per base).

Columns are a strict superset of what each event type needs; unused columns
for a given event type are zero. This wastes HBM bytes but keeps a single
batch schema for the whole pipeline — the same trade the reference's
GDeviceEventPayload protobuf union makes, resolved SoA instead of AoS.

Reference: model fields from IDeviceMeasurement/IDeviceLocation/IDeviceAlert
(sitewhere-core-api spi/device/event/); packing replaces the per-event protobuf
decode at InboundPayloadProcessingLogic.java:141.
"""

from __future__ import annotations

import time
from typing import List, Optional, Sequence, Tuple

import numpy as np
from flax import struct

from sitewhere_tpu.model.event import (
    DeviceAlert, DeviceEvent, DeviceEventType, DeviceLocation, DeviceMeasurement,
)
from sitewhere_tpu.registry.interning import TokenInterner


@struct.dataclass
class EventBatch:
    """SoA columns, all shape [B]. A jax pytree (works under jit/shard_map)."""

    device_idx: np.ndarray   # int32, interned device token (0 = unknown)
    tenant_idx: np.ndarray   # int32, interned tenant (filled by validation)
    event_type: np.ndarray   # int32, DeviceEventType value
    ts: np.ndarray           # int32, ms since epoch_base
    mm_idx: np.ndarray       # int32, interned measurement name
    value: np.ndarray        # float32, measurement value
    lat: np.ndarray          # float32
    lon: np.ndarray          # float32
    elevation: np.ndarray    # float32
    alert_type_idx: np.ndarray  # int32, interned alert type code
    alert_level: np.ndarray  # int32, AlertLevel value
    valid: np.ndarray        # bool, False for padding rows

    @property
    def batch_size(self) -> int:
        return self.device_idx.shape[0]


def empty_batch(batch_size: int) -> EventBatch:
    zi = np.zeros(batch_size, np.int32)
    zf = np.zeros(batch_size, np.float32)
    return EventBatch(
        device_idx=zi, tenant_idx=zi.copy(), event_type=zi.copy(), ts=zi.copy(),
        mm_idx=zi.copy(), value=zf, lat=zf.copy(), lon=zf.copy(),
        elevation=zf.copy(), alert_type_idx=zi.copy(), alert_level=zi.copy(),
        valid=np.zeros(batch_size, bool))


# Wire-blob layout v2: the host->device staging format is ONE contiguous
# int32 array of WIRE_ROWS rows per batch ([5, B]; [S, 5, B] routed).
# Host->device bandwidth is the pipeline's hard ceiling (HBM/PCIe/tunnel —
# SURVEY.md north star analysis), so the wire format is minimized:
# 20 B/event instead of the 48 B of one row per EventBatch column. The two
# payload rows are unions discriminated by event_type — a measurement's
# (value, mm_idx), a location's (lat, lon) and an alert's alert_type_idx
# are mutually exclusive, so they share rows with no precision loss.
# tenant_idx never crosses (validation re-derives it from the registry
# mirror on device, pipeline/step.py stage 1).
#   row 0: device_idx (bits 0-21) | event_type (22-24) |
#          alert_level (25-27) | valid (28)
#   row 1: ts (int32 ms, relative)
#   row 2: payload A — value f32 bits (measurement) | lat f32 bits (location)
#   row 3: payload B — mm_idx (measurement) | lon f32 bits (location) |
#          alert_type_idx (alert)
#   row 4: elevation f32 bits (carried for every type; zero unless set)
#
# COMPACT variant (v3): when no row of a batch carries a nonzero
# elevation — the common case for measurement/alert traffic and 2-D
# location fixes — row 4 is omitted entirely: 16 B/event instead of 20.
# The unpackers derive the variant from the blob's row dimension
# (elevation reads as 0 for 4-row blobs); jit compiles one program per
# shape, both cached. On a transfer-bound link (step_breakdown shows H2D
# dominating the step) this is a direct ~20% throughput lift.
#
# PACKED variant (v4): measurement/alert-only batches whose timestamps
# span < 65.536 s (any real-time ingest window) drop to THREE rows —
# 12 B/event. ts travels as a 16-bit delta against a per-batch base;
# mm_idx/alert_type_idx (12 bits) shares row 1 with the delta; the f32
# payload keeps full precision in row 2. The 32-bit ts base rides the
# 3 spare bits of row 0 across lanes 0..10 (3 bits/lane, two's
# complement), so no side-channel scalar transfer and no extra bytes.
# Location events still need lat+lon at full precision -> those batches
# stay on the 4/5-row layouts; the unpackers keep dispatching on the
# row dimension (one cached jit program per variant).
WIRE_ROWS = 5
WIRE_ROWS_COMPACT = 4
WIRE_ROWS_PACKED = 3
_TS_DELTA_BITS = 16
_TS_DELTA_MASK = (1 << _TS_DELTA_BITS) - 1
_PKIDX_SHIFT = 16
_BASE_SHIFT = 29     # row-0 bits 29..31 carry the ts base, lanes 0..10
_BASE_LANES = 11
WIRE_DEV_BITS = 22
WIRE_DEV_MAX = 1 << WIRE_DEV_BITS   # 4.19M interned devices per wire batch
_ET_SHIFT = 22
_LEVEL_SHIFT = 25
_VALID_SHIFT = 28
_META_MAX_IDX = 1 << 12  # mm_idx / alert_type_idx interner width (unchanged)

_ET_MEASUREMENT = int(DeviceEventType.MEASUREMENT)
_ET_LOCATION = int(DeviceEventType.LOCATION)
_ET_ALERT = int(DeviceEventType.ALERT)


def wire_variant_for(batch: EventBatch) -> Tuple[int, int]:
    """(wire_rows, ts_base) for a flat batch. The checks are full-column
    numpy reductions (~0.1 ms at bench scale) buying 25-40% off a
    transfer-bound step: packed 3-row when the batch has no elevation, no
    location events, and a ts span under 2^16 ms; compact 4-row when only
    the elevation is absent; full 5-row otherwise. ts_base is meaningful
    for the packed variant only."""
    if np.any(np.asarray(batch.elevation)):
        return WIRE_ROWS, 0
    valid = np.asarray(batch.valid)
    if valid.shape[-1] >= _BASE_LANES \
            and not np.any(np.asarray(batch.event_type) == _ET_LOCATION):
        ts = np.asarray(batch.ts)
        lo = int(ts.min(where=valid, initial=2 ** 31 - 1))
        hi = int(ts.max(where=valid, initial=-(2 ** 31)))
        if hi < lo:  # no valid rows
            return WIRE_ROWS_PACKED, 0
        if hi - lo <= _TS_DELTA_MASK:
            return WIRE_ROWS_PACKED, lo
    return WIRE_ROWS_COMPACT, 0


def wire_rows_for(batch: EventBatch) -> int:
    """Wire variant row count only (callers that cannot use the packed
    layout's ts base, e.g. the multi-host fixed-rows pin)."""
    return wire_variant_for(batch)[0]


def _embed_ts_base(row0: np.ndarray, ts_base: int) -> None:
    """Scatter the 32-bit ts base over row 0's spare bits, 3 per lane
    (lane 10 carries the top 2). row0 may be [B] or [S, B] (routed: the
    same base lands in every shard's lanes). Bit work happens on a
    uint32 view so bit 31 never trips int32 overflow handling."""
    lanes = row0[..., :_BASE_LANES].view(np.uint32)
    base = np.uint32(int(ts_base) & 0xFFFFFFFF)
    for lane in range(_BASE_LANES):
        lanes[..., lane] |= ((base >> np.uint32(3 * lane)) & np.uint32(7)) \
            << np.uint32(_BASE_SHIFT)


def _extract_ts_base_np(row0: np.ndarray) -> np.ndarray:
    """Inverse of _embed_ts_base; returns an int32 of row0's leading
    shape (scalar for flat blobs, [S] for routed)."""
    base = np.zeros(row0.shape[:-1], np.uint32)
    for lane in range(_BASE_LANES):
        base |= ((row0[..., lane].astype(np.uint32) >> _BASE_SHIFT) & 7) \
            << np.uint32(3 * lane)
    return base.astype(np.int32)


def batch_to_blob(batch: EventBatch,
                  out: Optional[np.ndarray] = None,
                  wire_rows: Optional[int] = None) -> np.ndarray:
    """Pack an EventBatch into the compact wire blob (host side, numpy).

    A single transfer instead of 12 (remote/tunneled runtimes pay a
    round-trip per device_put), at 20 B/event instead of 48. Payload
    fields are preserved per event type (see layout comment); a
    well-formed batch — anything the packer/decoders produce — round-trips
    exactly.

    `out` (flat batches only) is an optional preallocated [WIRE_ROWS, B]
    int32 buffer — engines pass a rotating staging buffer so the hot path
    does not pay a fresh 2.6 MB mmap-backed allocation (page faults) per
    step. Every element is overwritten; no pre-zeroing needed. When the
    batch carries no elevation, only the first WIRE_ROWS_COMPACT rows are
    written and that contiguous view is returned (16 B/event on the
    wire).
    """
    lead = batch.device_idx.shape[:-1]   # () flat, (S,) routed
    B = batch.device_idx.shape[-1]
    # routed blobs always carry the full layout; flat batches pick the
    # smallest variant the content allows — unless the caller pins one
    # (`wire_rows` >= 4 forces a classic layout: the multi-host lockstep
    # pin must not take the packed path, whose 3-row layout is not a
    # prefix of the 4/5-row one). Pinning the PACKED layout is only legal
    # when the content is eligible — the ts base cannot be zero-guessed.
    if wire_rows == WIRE_ROWS_PACKED:
        rows, ts_base = wire_variant_for(batch)
        if rows != WIRE_ROWS_PACKED:
            raise ValueError(
                "batch is not packed-eligible (carries locations, "
                "elevation, or a ts span over 2^16 ms); pack with a "
                "classic layout")
    elif wire_rows is not None:
        rows, ts_base = wire_rows, 0
    elif lead:
        rows, ts_base = WIRE_ROWS, 0
    else:
        rows, ts_base = wire_variant_for(batch)
    if not lead:
        from sitewhere_tpu import native

        if native.available():
            if out is None or out.shape[-1] != B or out.shape[0] < rows:
                out = np.empty((rows, B), np.int32)
            view = out[:rows]
            if native.pack_blob(batch, view, ts_base=ts_base):
                return view
            # fall through: the numpy range check below raises the
            # (single, shared) diagnostic for the out-of-range device_idx
    dev = np.asarray(batch.device_idx, np.int32)
    if dev.size and (int(dev.max()) >= WIRE_DEV_MAX or int(dev.min()) < 0):
        raise ValueError(
            f"device_idx out of wire-blob device field range "
            f"[0, {WIRE_DEV_MAX}): min {int(dev.min())}, "
            f"max {int(dev.max())}")
    et = np.asarray(batch.event_type, np.int32) & 7
    is_loc = et == _ET_LOCATION
    is_alert = et == _ET_ALERT
    if out is not None and out.shape[-1] == B \
            and out.shape[:-2] == lead and out.shape[-2] >= rows:
        blob = out[..., :rows, :]
    else:
        blob = np.empty(lead + (rows, B), np.int32)
    valid = np.asarray(batch.valid)
    blob[..., 0, :] = (
        dev
        | (et << _ET_SHIFT)
        | (np.asarray(batch.alert_level, np.int32) & 7) << _LEVEL_SHIFT
        | valid.astype(np.int32) << _VALID_SHIFT)
    # mm_idx/alert_type_idx keep the v1 12-bit wire mask: a negative or
    # oversized index (reachable via un-validated pack_columns input) must
    # not reach the device-side `idx < M` guards as a negative — a negative
    # index would wrap Python-style in the keyed scatter and corrupt a
    # NEIGHBORING device's state slot.
    idx_mask = _META_MAX_IDX - 1
    if rows == WIRE_ROWS_PACKED:
        delta = np.where(valid,
                         np.asarray(batch.ts, np.int32) - np.int32(ts_base),
                         0) & _TS_DELTA_MASK
        idx = np.where(is_alert,
                       np.asarray(batch.alert_type_idx, np.int32),
                       np.asarray(batch.mm_idx, np.int32)) & idx_mask
        blob[..., 1, :] = delta | (idx << _PKIDX_SHIFT)
        blob[..., 2, :] = np.asarray(batch.value, np.float32).view(np.int32)
        _embed_ts_base(blob[..., 0, :], ts_base)
        return blob
    blob[..., 1, :] = batch.ts
    blob[..., 2, :] = np.where(
        is_loc, np.asarray(batch.lat, np.float32).view(np.int32),
        np.asarray(batch.value, np.float32).view(np.int32))
    blob[..., 3, :] = np.where(
        is_loc, np.asarray(batch.lon, np.float32).view(np.int32),
        np.where(is_alert,
                 np.asarray(batch.alert_type_idx, np.int32) & idx_mask,
                 np.asarray(batch.mm_idx, np.int32) & idx_mask))
    if rows >= WIRE_ROWS:
        blob[..., 4, :] = np.asarray(batch.elevation,
                                     np.float32).view(np.int32)
    return blob


def blob_to_batch_np(blob: np.ndarray) -> EventBatch:
    """Host-side inverse of batch_to_blob (native one-pass when available,
    numpy views/bit ops otherwise). Used to materialize a routed blob back
    into columns for alert materialization without keeping a second routed
    copy around."""
    blob = np.asarray(blob, np.int32)
    from sitewhere_tpu import native

    if native.available():
        shape = blob.shape[:-2] + blob.shape[-1:]   # [n] flat, [S, B] routed
        cols = {name: np.empty(shape, np.int32) for name in
                ("device_idx", "event_type", "ts", "mm_idx",
                 "alert_type_idx", "alert_level")}
        cols.update({name: np.empty(shape, np.float32) for name in
                     ("value", "lat", "lon", "elevation")})
        cols["valid"] = np.empty(shape, np.uint8)
        if blob.ndim == 2:
            native.unpack_blob(blob, cols)
        else:
            flat = blob.reshape((-1,) + blob.shape[-2:])
            for s in range(flat.shape[0]):
                native.unpack_blob(
                    flat[s], {k: v.reshape(-1, shape[-1])[s]
                              for k, v in cols.items()})
        return EventBatch(
            device_idx=cols["device_idx"],
            tenant_idx=np.zeros(shape, np.int32),
            event_type=cols["event_type"], ts=cols["ts"],
            mm_idx=cols["mm_idx"], value=cols["value"], lat=cols["lat"],
            lon=cols["lon"], elevation=cols["elevation"],
            alert_type_idx=cols["alert_type_idx"],
            alert_level=cols["alert_level"],
            valid=cols["valid"].view(bool))  # 0/1 uint8 -> bool, no copy
    if blob.shape[-2] == WIRE_ROWS_PACKED:
        return _packed_blob_to_batch_np(blob)
    r0 = blob[..., 0, :]
    et = (r0 >> _ET_SHIFT) & 7
    is_meas = et == _ET_MEASUREMENT
    is_loc = et == _ET_LOCATION
    pa = blob[..., 2, :]
    pb = blob[..., 3, :]
    zf = np.float32(0)
    if blob.shape[-2] >= WIRE_ROWS:
        elevation = np.ascontiguousarray(blob[..., 4, :]).view(np.float32)
    else:  # compact variant: elevation row omitted, reads as 0
        elevation = np.zeros(r0.shape, np.float32)
    return EventBatch(
        device_idx=r0 & (WIRE_DEV_MAX - 1),
        tenant_idx=np.zeros_like(r0),
        event_type=et,
        ts=blob[..., 1, :],
        mm_idx=np.where(is_meas, pb, 0).astype(np.int32),
        value=np.where(is_meas, pa.view(np.float32), zf),
        lat=np.where(is_loc, pa.view(np.float32), zf),
        lon=np.where(is_loc, pb.view(np.float32), zf),
        elevation=elevation,
        alert_type_idx=np.where(et == _ET_ALERT, pb, 0).astype(np.int32),
        alert_level=(r0 >> _LEVEL_SHIFT) & 7,
        valid=(r0 & (1 << _VALID_SHIFT)) != 0)


def _packed_blob_to_batch_np(blob: np.ndarray) -> EventBatch:
    """Host-side decode of the 3-row packed variant (numpy)."""
    r0 = blob[..., 0, :]
    r1 = blob[..., 1, :]
    et = (r0 >> _ET_SHIFT) & 7
    is_meas = et == _ET_MEASUREMENT
    base = _extract_ts_base_np(r0)
    ts = (np.expand_dims(base, -1)
          + (r1 & _TS_DELTA_MASK)).astype(np.int32)
    idx = (r1 >> _PKIDX_SHIFT) & (_META_MAX_IDX - 1)
    value_bits = np.ascontiguousarray(blob[..., 2, :]).view(np.float32)
    zf32 = np.zeros(r0.shape, np.float32)
    return EventBatch(
        device_idx=r0 & (WIRE_DEV_MAX - 1),
        tenant_idx=np.zeros_like(r0),
        event_type=et, ts=ts,
        mm_idx=np.where(is_meas, idx, 0).astype(np.int32),
        value=np.where(is_meas, value_bits, np.float32(0)),
        lat=zf32, lon=zf32.copy(), elevation=zf32.copy(),
        alert_type_idx=np.where(et == _ET_ALERT, idx, 0).astype(np.int32),
        alert_level=(r0 >> _LEVEL_SHIFT) & 7,
        valid=(r0 & (1 << _VALID_SHIFT)) != 0)


def blob_to_batch(blob) -> EventBatch:
    """Inverse of batch_to_blob on-device (jax ops; call under jit — XLA
    fuses the unpack + selects into the step's first consumers). Variant
    dispatch is on the (static) row dimension: one cached program per
    wire layout."""
    import jax
    import jax.numpy as jnp

    if blob.shape[-2] == WIRE_ROWS_PACKED:
        r0 = blob[..., 0, :]
        r1 = blob[..., 1, :]
        et = (r0 >> _ET_SHIFT) & 7
        is_meas = et == _ET_MEASUREMENT
        spare = (r0[..., :_BASE_LANES] >> _BASE_SHIFT) & 7
        base = spare[..., 0]
        for lane in range(1, _BASE_LANES):
            # int32 shifts wrap mod 2^32: lane 10's bits land on 30/31,
            # reconstructing the base's two's complement exactly
            base = base | (spare[..., lane] << (3 * lane))
        ts = jnp.expand_dims(base, -1) + (r1 & _TS_DELTA_MASK)
        idx = (r1 >> _PKIDX_SHIFT) & (_META_MAX_IDX - 1)
        value = jax.lax.bitcast_convert_type(blob[..., 2, :], jnp.float32)
        zf32 = jnp.zeros(r0.shape, jnp.float32)
        return EventBatch(
            device_idx=r0 & (WIRE_DEV_MAX - 1),
            tenant_idx=jnp.zeros_like(r0),
            event_type=et, ts=ts,
            mm_idx=jnp.where(is_meas, idx, 0),
            value=jnp.where(is_meas, value, jnp.float32(0)),
            lat=zf32, lon=zf32, elevation=zf32,
            alert_type_idx=jnp.where(et == _ET_ALERT, idx, 0),
            alert_level=(r0 >> _LEVEL_SHIFT) & 7,
            valid=(r0 & (1 << _VALID_SHIFT)) != 0)
    r0 = blob[..., 0, :]
    et = (r0 >> _ET_SHIFT) & 7
    is_meas = et == _ET_MEASUREMENT
    is_loc = et == _ET_LOCATION
    pa = blob[..., 2, :]
    pb = blob[..., 3, :]
    fa = jax.lax.bitcast_convert_type(pa, jnp.float32)
    fb = jax.lax.bitcast_convert_type(pb, jnp.float32)
    zf = jnp.float32(0)
    if blob.shape[-2] >= WIRE_ROWS:  # static shape: resolved at trace time
        elevation = jax.lax.bitcast_convert_type(blob[..., 4, :],
                                                 jnp.float32)
    else:  # compact variant: elevation row omitted, reads as 0
        elevation = jnp.zeros(r0.shape, jnp.float32)
    return EventBatch(
        device_idx=r0 & (WIRE_DEV_MAX - 1),
        tenant_idx=jnp.zeros_like(r0),
        event_type=et,
        ts=blob[..., 1, :],
        mm_idx=jnp.where(is_meas, pb, 0),
        value=jnp.where(is_meas, fa, zf),
        lat=jnp.where(is_loc, fa, zf),
        lon=jnp.where(is_loc, fb, zf),
        elevation=elevation,
        alert_type_idx=jnp.where(et == _ET_ALERT, pb, 0),
        alert_level=(r0 >> _LEVEL_SHIFT) & 7,
        valid=(r0 & (1 << _VALID_SHIFT)) != 0)


class EventPacker:
    """Host-side packer: Python event objects / raw column arrays -> EventBatch.

    Owns the measurement-name and alert-type interners; device tokens are
    interned against the shared registry interner so packed indices line up
    with the registry lookup tensors.
    """

    def __init__(self, batch_size: int, device_interner: TokenInterner,
                 max_measurement_names: int = 1024, max_alert_types: int = 1024,
                 epoch_base_ms: Optional[int] = None):
        if max_measurement_names > _META_MAX_IDX or \
                max_alert_types > _META_MAX_IDX:
            raise ValueError(
                f"measurement/alert-type interner capacity is limited to "
                f"{_META_MAX_IDX} by the wire-blob meta field width")
        self.batch_size = batch_size
        self.devices = device_interner
        self.measurements = TokenInterner(max_measurement_names, "measurements")
        self.alert_types = TokenInterner(max_alert_types, "alert_types")
        self.epoch_base_ms = (epoch_base_ms if epoch_base_ms is not None
                              else int(time.time() * 1000))

    # int32 range minus a margin for the -2^31 "never" sentinel in state tensors
    _REL_MIN = -(2 ** 31) + 2
    _REL_MAX = 2 ** 31 - 1

    def rel_ts(self, ts_ms: int) -> int:
        # Events dated before epoch_base are legitimate (delayed delivery,
        # replay): rebased ts may be negative. Clamp to int32 range.
        rel = int(ts_ms - self.epoch_base_ms)
        return max(self._REL_MIN, min(self._REL_MAX, rel))

    def abs_ts(self, rel: int) -> int:
        return self.epoch_base_ms + int(rel)

    def pack_events(self, events: Sequence[DeviceEvent],
                    device_tokens: Sequence[str]) -> List[EventBatch]:
        """Pack API-level events (paired with their device tokens) into one or
        more fixed-size batches."""
        batches: List[EventBatch] = []
        for start in range(0, max(len(events), 1), self.batch_size):
            chunk = events[start:start + self.batch_size]
            tokens = device_tokens[start:start + self.batch_size]
            if not chunk:
                break
            batches.append(self._pack_chunk(chunk, tokens))
        return batches

    def _pack_chunk(self, events: Sequence[DeviceEvent],
                    tokens: Sequence[str]) -> EventBatch:
        B = self.batch_size
        batch = empty_batch(B)
        n = len(events)
        device_idx = np.zeros(B, np.int32)
        event_type = np.zeros(B, np.int32)
        ts = np.zeros(B, np.int32)
        mm_idx = np.zeros(B, np.int32)
        value = np.zeros(B, np.float32)
        lat = np.zeros(B, np.float32)
        lon = np.zeros(B, np.float32)
        elevation = np.zeros(B, np.float32)
        alert_type_idx = np.zeros(B, np.int32)
        alert_level = np.zeros(B, np.int32)
        valid = np.zeros(B, bool)
        for i, (event, token) in enumerate(zip(events, tokens)):
            device_idx[i] = self.devices.lookup(token)
            event_type[i] = int(event.event_type)
            ts[i] = self.rel_ts(event.event_date)
            valid[i] = True
            if isinstance(event, DeviceMeasurement):
                mm_idx[i] = self.measurements.intern(event.name)
                value[i] = event.value
            elif isinstance(event, DeviceLocation):
                lat[i] = event.latitude
                lon[i] = event.longitude
                elevation[i] = event.elevation
            elif isinstance(event, DeviceAlert):
                alert_type_idx[i] = self.alert_types.intern(event.type)
                alert_level[i] = int(event.level)
        return EventBatch(
            device_idx=device_idx, tenant_idx=batch.tenant_idx,
            event_type=event_type, ts=ts, mm_idx=mm_idx, value=value,
            lat=lat, lon=lon, elevation=elevation,
            alert_type_idx=alert_type_idx, alert_level=alert_level, valid=valid)

    def pack_columns(self, device_idx: np.ndarray, event_type: np.ndarray,
                     ts_ms_abs: np.ndarray, *, mm_idx: Optional[np.ndarray] = None,
                     value: Optional[np.ndarray] = None,
                     lat: Optional[np.ndarray] = None,
                     lon: Optional[np.ndarray] = None,
                     elevation: Optional[np.ndarray] = None,
                     alert_type_idx: Optional[np.ndarray] = None,
                     alert_level: Optional[np.ndarray] = None) -> EventBatch:
        """Zero-copy-ish fast path for bulk synthetic/replayed columns; pads or
        rejects to exactly one batch."""
        n = len(device_idx)
        if n > self.batch_size:
            raise ValueError(f"{n} events > batch size {self.batch_size}")
        B = self.batch_size

        def col(arr: Optional[np.ndarray], dtype) -> np.ndarray:
            out = np.zeros(B, dtype)
            if arr is not None:
                out[:n] = arr
            return out

        ts_rel = np.clip(np.asarray(ts_ms_abs, np.int64) - self.epoch_base_ms,
                         self._REL_MIN, self._REL_MAX).astype(np.int32)
        valid = np.zeros(B, bool)
        valid[:n] = True
        return EventBatch(
            device_idx=col(device_idx, np.int32),
            tenant_idx=np.zeros(B, np.int32),
            event_type=col(event_type, np.int32),
            ts=col(ts_rel, np.int32),
            mm_idx=col(mm_idx, np.int32), value=col(value, np.float32),
            lat=col(lat, np.float32), lon=col(lon, np.float32),
            elevation=col(elevation, np.float32),
            alert_type_idx=col(alert_type_idx, np.int32),
            alert_level=col(alert_level, np.int32), valid=valid)
