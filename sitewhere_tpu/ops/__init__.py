"""TPU compute ops: the kernels of the hot event path.

Everything here is shape-static, jit-safe, and free of per-event Python — the
replacement for the reference's per-event JVM work (decode, validate, JTS
containment, Mongo upserts) described in SURVEY.md §3.2-3.3.
"""

from sitewhere_tpu.ops.pack import EventBatch, EventPacker
from sitewhere_tpu.ops.threshold import ThresholdRuleTable, eval_threshold_rules
from sitewhere_tpu.ops.geofence import ZoneTable, points_in_zones, eval_geofence_rules, GeofenceRuleTable
from sitewhere_tpu.ops.segments import last_by_key, scatter_max_by_key, count_by_key

__all__ = [name for name in dir() if not name.startswith("_")]
