"""On-device shard routing: radix bucketing + one ICI all_to_all.

The host arena router (parallel/router.py) pays a full host-CPU pass —
stable bucketing sort plus a 5-row gather/scatter — before a single byte
reaches the mesh, and round 5 measured that pass drifting 1.2 -> 6.6 ms
per step under host CPU steal. Routing belongs where the bandwidth is
(the tf.data lesson applied to the shard edge): the feeder ships the
UNROUTED packed blob split into contiguous lane chunks (one chunk per
shard, `P(None, shard)` — pack + one H2D, nothing else on the host), and
the mesh routes it itself inside the same shard_map as the fused step:

  1. bucket: each shard computes its chunk rows' destination shard
     (`dev % S` — the same hash partition the host router and the
     registry interner use), then stable-sorts them into S
     fixed-capacity per-destination lanes via shared sort-rank
     arithmetic (ops/segments.py bucket_ranks): O(B log B), no [B, S]
     one-hot intermediate — the same in-bucket arrival order the old
     one-hot prefix-sum produced, bit for bit.
  2. exchange: ONE `all_to_all` over ICI transposes the [S_dest, C]
     lanes so every shard holds the [S_src, C] buckets destined to it,
     source-major — i.e. flat-batch arrival order.
  3. compact: a prefix-sum over the received candidates' valid bits
     packs them into the local [rows, B] routed blob.

Because the bucketing is stable and the exchange is source-major, the
compacted result is BIT-IDENTICAL to the host arena router's output for
any batch that fits the lanes — every downstream contract (state fold
order, alert-lane contents and order, checkpoint parity) holds exactly,
and the differential tests pin it (tests/test_device_route.py).

Overflow contract: lane capacity is `route_lane_capacity(B, S)` —
2x the uniform per-(source, destination) expectation, capped at B. The
host feeder runs `host_fits_device_route` (two bincount passes, no sort,
no scatter — the cheap 1% of the old host route) before staging; a batch
that would overflow any lane, or any shard's total capacity, spills to
the existing host arena path for that step (bounded fallback, counted on
`device_route_fallbacks` — same philosophy as alert-lane drops: degrade
loudly, never silently). The device kernel still counts any row it had
to drop (belt and braces; zero whenever the guard ran) and rides the
count out on the alert lanes' spare counts slot — no extra D2H fetch.

The packed 3-row wire variant embeds its ts base by LANE POSITION in
row 0 (ops/pack.py): only chunk 0 carries it, so the kernel extracts it
there, broadcasts it with a scalar psum, strips the spare bits before
bucketing (exactly like the host router), and re-embeds per shard after
compaction — bit-for-bit the host `_embed_ts_base` layout.
"""

from __future__ import annotations

from typing import Optional

import numpy as np

from sitewhere_tpu.ops.pack import (
    _BASE_LANES, _BASE_SHIFT, _VALID_SHIFT, WIRE_DEV_MAX, WIRE_ROWS_PACKED)

# low-29-bit mask: strips the packed variant's spare ts-base bits from a
# routed head (a no-op on classic 4/5-row blobs, whose spares are zero)
_SPARE_CLEAR = (1 << _BASE_SHIFT) - 1

# the on-device route's defensive drop count rides the alert lanes'
# counts row at this slot (slots 0..2 hold the fired/dropped/total alert
# counts — ops/compact.py; capacity >= MIN_ALERT_LANE_CAPACITY == 4
# guarantees the slot exists). Zero whenever the host guard ran.
ROUTE_DROPPED_SLOT = 3


def route_lane_capacity(per_shard_batch: int, n_shards: int) -> int:
    """Per-(source, destination) lane capacity: 2x the uniform
    expectation ceil(B/S), capped at B. Uniform hash traffic loads each
    lane with mean B/S rows; 2x slack absorbs Poisson fluctuation and
    moderate tenant skew, while the transient lane tensor stays at most
    2x the blob itself ([rows, S, C] vs [rows, B]). Heavier skew is the
    host guard's job (spill the step to the host arena path)."""
    if n_shards <= 1:
        return per_shard_batch
    return min(per_shard_batch, -(-2 * per_shard_batch // n_shards))


def host_fits_device_route(device_idx: np.ndarray, valid: np.ndarray,
                           n_shards: int, per_shard_batch: int,
                           capacity: int) -> bool:
    """Cheap host-side guard: can the device route carry this flat batch
    without dropping a row? True iff every (source chunk, destination)
    bucket fits its lane AND every destination's total fits the
    per-shard batch. Two bincount passes over the shard ids — no sort,
    no scatter; the flat positions are implicit in the chunk slicing, so
    the check costs ~1% of the host arena route it gates."""
    S, B, C = n_shards, per_shard_batch, capacity
    dev = np.asarray(device_idx)
    val = np.asarray(valid)
    n = dev.shape[0]
    shard = (dev % S).astype(np.int64)
    all_valid = bool(val.all())
    totals = np.zeros(S, np.int64)
    for c in range(0, n, B):
        sl = shard[c:c + B]
        if all_valid:
            counts = np.bincount(sl, minlength=S)
        else:
            counts = np.bincount(sl, weights=val[c:c + B],
                                 minlength=S).astype(np.int64)
        if int(counts.max(initial=0)) > C:
            return False
        totals += counts
    return int(totals.max(initial=0)) <= B


# -- jax kernel (call under shard_map) --------------------------------------


def _extract_ts_base(head):
    """jnp mirror of ops.pack._extract_ts_base_np: lift the 32-bit ts
    base from row 0's spare bits, 3 per lane across lanes 0..10 (int32
    shift-left wrap reconstructs lane 10's top bits exactly)."""
    import jax.numpy as jnp

    spare = (head[:_BASE_LANES] >> _BASE_SHIFT) & 7
    base = spare[0]
    for lane in range(1, _BASE_LANES):
        base = base | (spare[lane] << (3 * lane))
    return base.astype(jnp.int32)


def _embed_ts_base(row0, base):
    """jnp mirror of ops.pack._embed_ts_base — bit-identical layout: the
    base is scattered over lanes 0..10 on a uint32 view (LOGICAL shifts,
    so lane 10 carries exactly the top 2 bits, matching the host's
    numpy-uint32 embed even for negative bases)."""
    import jax
    import jax.numpy as jnp

    ubase = jax.lax.bitcast_convert_type(base, jnp.uint32)
    lanes = jax.lax.bitcast_convert_type(row0[:_BASE_LANES], jnp.uint32)
    shifts = jnp.uint32(3) * jnp.arange(_BASE_LANES, dtype=jnp.uint32)
    bits = (ubase >> shifts) & jnp.uint32(7)
    lanes = lanes | (bits << jnp.uint32(_BASE_SHIFT))
    return jnp.concatenate(
        [jax.lax.bitcast_convert_type(lanes, jnp.int32),
         row0[_BASE_LANES:]])


def device_route_chunk(chunk, n_shards: int, per_shard_batch: int,
                       capacity: int, axis_name: str):
    """Route this shard's unrouted lane chunk to its owner shards.

    `chunk` is the [wire_rows, B] contiguous slice of the flat wire blob
    this shard received (flat lanes [i*B, (i+1)*B) for shard i — flat
    arrival order). Returns (routed [wire_rows, B] blob for THIS shard,
    rows this shard had to drop), where the blob is bit-identical to the
    host arena router's per-shard output whenever nothing dropped. Call
    under shard_map on `axis_name`; contains one all_to_all (plus one
    scalar psum for the packed wire variant's ts base).
    """
    import jax
    import jax.numpy as jnp

    S, B, C = n_shards, per_shard_batch, capacity
    rows = chunk.shape[0]
    packed = rows == WIRE_ROWS_PACKED
    head = chunk[0]
    if packed:
        # only chunk 0 carries the lane-embedded base: lift it there and
        # broadcast (a 4-byte psum — noise next to the row exchange)
        base_local = jnp.where(
            jax.lax.axis_index(axis_name) == 0, _extract_ts_base(head),
            jnp.int32(0))
        base = jax.lax.psum(base_local, axis_name)
    from sitewhere_tpu.ops.segments import bucket_ranks

    valid = (head >> _VALID_SHIFT) & 1
    dev = head & (WIRE_DEV_MAX - 1)
    dest = jnp.where(valid == 1, dev % S, S)          # S = padding sentinel
    # stable sort-based bucketing: rank of each row within its
    # destination bucket via one shared stable sort + segment-start
    # subtraction — O(B log B), no [B, S] one-hot intermediate. Invalid
    # rows (sentinel bucket S) get real ranks but `keep` masks them out
    # exactly like the old counting sort's rank -1.
    pos = bucket_ranks(dest)
    keep = (valid == 1) & (pos < C)
    slot = jnp.where(keep, dest * C + pos, S * C)      # OOB -> dropped
    # routed heads carry LOCAL device indices with spare bits stripped,
    # exactly like the host router's head rewrite
    local_head = ((head & _SPARE_CLEAR & ~jnp.int32(WIRE_DEV_MAX - 1))
                  | (dev // S))
    # one [rows, B] -> [rows, S*C] scatter builds every wire row's lane
    # at once (unique slots; OOB rows drop)
    vals = jnp.concatenate([local_head[None], chunk[1:]], axis=0)
    lanes = jnp.zeros((rows, S * C), jnp.int32).at[:, slot].set(
        vals, mode="drop")                             # [rows, S*C]
    dropped = jnp.sum(((valid == 1) & ~keep).astype(jnp.int32))
    # ONE collective: transpose the per-destination lanes so this shard
    # holds every source's bucket for it, source-major (= arrival order)
    recv = jax.lax.all_to_all(lanes.reshape(rows, S, C), axis_name,
                              split_axis=1, concat_axis=1)
    cand = recv.reshape(rows, S * C)
    cvalid = (cand[0] >> _VALID_SHIFT) & 1
    crank = jnp.cumsum(cvalid) - cvalid                # exclusive rank
    ckeep = (cvalid == 1) & (crank < B)
    cslot = jnp.where(ckeep, crank, B)                 # OOB -> dropped
    blob = jnp.zeros((rows, B), jnp.int32).at[:, cslot].set(
        cand, mode="drop")
    dropped = dropped + jnp.sum(((cvalid == 1) & ~ckeep).astype(jnp.int32))
    if packed:
        blob = blob.at[0].set(_embed_ts_base(blob[0], base))
    return blob, dropped


def build_device_route_program(mesh, n_shards: int, per_shard_batch: int,
                               capacity: Optional[int] = None):
    """Standalone jitted route-only program over `mesh`: flat wire blob
    [wire_rows, S*B] (lane-sharded `P(None, shard)`) -> (routed
    [S, wire_rows, B] global array, per-shard dropped counts [S]).

    The differential tests compare its output against
    `ShardRouter.route_blob` bit for bit, and the bench's pinned
    `router_offload_speedup_x` micro-bench times it against the host
    arena route at full batch. The engine's fused step uses
    `device_route_chunk` directly inside its own shard_map instead."""
    import jax
    from jax.sharding import PartitionSpec as P

    try:
        from jax import shard_map as _shard_map
    except ImportError:  # pragma: no cover
        from jax.experimental.shard_map import shard_map as _shard_map

    from sitewhere_tpu.parallel.mesh import SHARD_AXIS

    cap = capacity or route_lane_capacity(per_shard_batch, n_shards)

    def route(flat_blob):
        blob, dropped = device_route_chunk(
            flat_blob, n_shards, per_shard_batch, cap, SHARD_AXIS)
        return blob[None], dropped[None]

    specs = dict(mesh=mesh, in_specs=P(None, SHARD_AXIS),
                 out_specs=(P(SHARD_AXIS), P(SHARD_AXIS)))
    try:
        mapped = _shard_map(route, check_vma=False, **specs)
    except TypeError:  # older jax spells it check_rep
        mapped = _shard_map(route, check_rep=False, **specs)
    return jax.jit(mapped)
