"""Inbound event receivers: transport listeners feeding an event source.

Reference: service-event-sources receiver zoo — MQTT
(mqtt/MqttInboundEventReceiver.java:39, subscribe :100), raw sockets
(socket/SocketInboundEventReceiver.java), WebSocket, CoAP
(coap/CoapServerEventReceiver.java), HTTP polling. Each receiver binds to
an InboundEventSource and forwards raw payloads to
`on_encoded_event_received` (same contract as IInboundEventReceiver).

All asyncio transports run on one shared background event-loop thread so a
tenant with many receivers costs one thread, mirroring the reference's
shared executor pools.
"""

from __future__ import annotations

import asyncio
import threading
import time
from typing import Dict, List, Optional


class EventLoopThread:
    """A dedicated asyncio loop on a daemon thread; receivers submit
    coroutines with `run(coro)`."""

    _shared: Optional["EventLoopThread"] = None
    _shared_lock = threading.Lock()

    def __init__(self) -> None:
        self.loop = asyncio.new_event_loop()
        self._thread = threading.Thread(target=self._run, daemon=True,
                                        name="receiver-loop")
        self._thread.start()

    def _run(self) -> None:
        asyncio.set_event_loop(self.loop)
        self.loop.run_forever()

    def run(self, coro, timeout_s: float = 10.0):
        return asyncio.run_coroutine_threadsafe(coro, self.loop).result(
            timeout_s)

    @classmethod
    def shared(cls) -> "EventLoopThread":
        with cls._shared_lock:
            if cls._shared is None or not cls._shared._thread.is_alive():
                cls._shared = cls()
            return cls._shared


class _ReceiverBase:
    def __init__(self, loop_thread: Optional[EventLoopThread] = None):
        self._loop_thread = loop_thread
        self.source = None

    @property
    def loop_thread(self) -> EventLoopThread:
        if self._loop_thread is None:
            self._loop_thread = EventLoopThread.shared()
        return self._loop_thread

    def bind(self, source) -> None:
        self.source = source

    async def _forward(self, payload: bytes,
                       metadata: Optional[Dict[str, str]] = None) -> None:
        # decode + bus publish are cheap/non-blocking; run inline on the loop
        metadata = metadata or {}
        # ingest-edge age stamp: one monotonic clock read per DELIVERY
        # (a payload of N events shares it) — the open edge of the
        # ingest->effect age waterfall (runtime/eventage.py). Kept as a
        # float; the ingest service pops it before metadata reaches
        # decoders.
        metadata.setdefault("received_at", time.perf_counter())
        self.source.on_encoded_event_received(payload, metadata)


class MqttEventReceiver(_ReceiverBase):
    """Subscribes to a topic filter on an MQTT broker (the in-proc
    MqttBroker or any external one) — MqttInboundEventReceiver."""

    def __init__(self, host: str, port: int, topic: str = "SW/+/input/#",
                 qos: int = 1, client_id: str = "",
                 loop_thread: Optional[EventLoopThread] = None):
        super().__init__(loop_thread)
        self.host = host
        self.port = port
        self.topic = topic
        self.qos = qos
        self.client_id = client_id
        self._client = None

    def start(self) -> None:
        from sitewhere_tpu.transport.mqtt import MqttClient

        async def go():
            self._client = MqttClient(self.host, self.port, self.client_id)
            await self._client.connect()

            async def on_message(topic: str, payload: bytes):
                await self._forward(payload, {"mqtt.topic": topic})

            await self._client.subscribe(self.topic, on_message, qos=self.qos)

        self.loop_thread.run(go())

    def stop(self) -> None:
        if self._client is not None:
            self.loop_thread.run(self._client.disconnect())
            self._client = None


class StompBrokerEventReceiver(_ReceiverBase):
    """EMBEDDED-broker STOMP receiver: hosts an in-process STOMP broker
    (transport/stomp.py) and consumes device events from one of its
    destinations — the ActiveMQBrokerEventReceiver role
    (service-event-sources activemq/ActiveMQBrokerEventReceiver.java:42
    hosts an in-JVM ActiveMQ broker the devices connect TO). The
    client-side adapters (receivers_ext.StompEventReceiver, AMQP) cover
    the EXTERNAL-broker slot; this closes the embedded one with no
    middleware dependency."""

    def __init__(self, host: str = "127.0.0.1", port: int = 0,
                 destination: str = "/queue/sitewhere",
                 loop_thread: Optional[EventLoopThread] = None):
        super().__init__(loop_thread)
        self.host = host
        self.port = port
        self.destination = destination
        self._broker = None
        self._consumer = None

    def start(self) -> None:
        from sitewhere_tpu.transport.stomp import StompBroker, StompClient

        async def go():
            self._broker = StompBroker(self.host, self.port)
            await self._broker.start()
            self.port = self._broker.port
            # in-proc consumer rides the same public protocol the
            # devices use — nothing broker-internal to maintain. Connect
            # on the broker's bind address (loopback only when bound to
            # the wildcard, where 127.0.0.1 is always reachable).
            connect_host = (self.host if self.host not in ("", "0.0.0.0",
                                                           "::")
                            else "127.0.0.1")
            self._consumer = StompClient(connect_host, self.port)
            await self._consumer.connect()

            async def on_message(headers, body: bytes):
                await self._forward(body, {
                    "stomp.destination": headers.get("destination",
                                                     self.destination)})

            await self._consumer.subscribe(self.destination, on_message)

        self.loop_thread.run(go())

    def stop(self) -> None:
        async def go():
            if self._consumer is not None:
                await self._consumer.disconnect()
            if self._broker is not None:
                await self._broker.stop()

        self.loop_thread.run(go())
        self._consumer = None
        self._broker = None


class SocketEventReceiver(_ReceiverBase):
    """TCP wire-frame listener (SocketInboundEventReceiver)."""

    def __init__(self, host: str = "127.0.0.1", port: int = 0,
                 loop_thread: Optional[EventLoopThread] = None):
        super().__init__(loop_thread)
        self.host = host
        self.port = port
        self._server = None

    def start(self) -> None:
        from sitewhere_tpu.transport.servers import SocketEventServer

        async def go():
            self._server = SocketEventServer(self._forward, self.host,
                                             self.port)
            await self._server.start()
            self.port = self._server.port

        self.loop_thread.run(go())

    def stop(self) -> None:
        if self._server is not None:
            self.loop_thread.run(self._server.stop())
            self._server = None


class WebSocketEventReceiver(_ReceiverBase):
    def __init__(self, host: str = "127.0.0.1", port: int = 0,
                 loop_thread: Optional[EventLoopThread] = None):
        super().__init__(loop_thread)
        self.host = host
        self.port = port
        self._server = None

    def start(self) -> None:
        from sitewhere_tpu.transport.servers import WebSocketEventServer

        async def go():
            self._server = WebSocketEventServer(self._forward, self.host,
                                                self.port)
            await self._server.start()
            self.port = self._server.port

        self.loop_thread.run(go())

    def stop(self) -> None:
        if self._server is not None:
            self.loop_thread.run(self._server.stop())
            self._server = None


class HttpEventReceiver(_ReceiverBase):
    def __init__(self, host: str = "127.0.0.1", port: int = 0,
                 path: str = "/events",
                 loop_thread: Optional[EventLoopThread] = None):
        super().__init__(loop_thread)
        self.host = host
        self.port = port
        self.path = path
        self._server = None

    def start(self) -> None:
        from sitewhere_tpu.transport.servers import HttpEventServer

        async def go():
            self._server = HttpEventServer(self._forward, self.host,
                                           self.port, self.path)
            await self._server.start()
            self.port = self._server.port

        self.loop_thread.run(go())

    def stop(self) -> None:
        if self._server is not None:
            self.loop_thread.run(self._server.stop())
            self._server = None


class CoapEventReceiver(_ReceiverBase):
    """CoAP POST/PUT listener (CoapServerEventReceiver)."""

    def __init__(self, host: str = "127.0.0.1", port: int = 0,
                 loop_thread: Optional[EventLoopThread] = None):
        super().__init__(loop_thread)
        self.host = host
        self.port = port
        self._server = None

    def start(self) -> None:
        from sitewhere_tpu.transport.coap import CoapServer

        def handler(path: str, payload: bytes, method: int):
            self.source.on_encoded_event_received(
                payload, {"coap.path": path,
                          "received_at": time.perf_counter()})
            return b""

        async def go():
            self._server = CoapServer(handler, self.host, self.port)
            await self._server.start()
            self.port = self._server.port

        self.loop_thread.run(go())

    def stop(self) -> None:
        if self._server is not None:
            self.loop_thread.run(self._server.stop())
            self._server = None
