"""Extended inbound receivers: polling REST + external-broker adapters.

Reference: service-event-sources ships receiver implementations for every
transport its users run — ActiveMQ broker/client, RabbitMQ, Azure EventHub,
polling REST (PollingRestInboundEventReceiver) alongside MQTT/CoAP/sockets.
The in-image equivalents:

- `PollingRestReceiver` — fully functional (stdlib urllib): polls an HTTP
  endpoint on an interval and forwards the body as an encoded payload.
- `AmqpEventReceiver` / `EventHubEventReceiver` / `StompEventReceiver` —
  adapters over the respective client libraries (pika / azure-eventhub /
  stomp.py). The libraries are optional dependencies: construction succeeds
  (config can be parsed/validated anywhere), `start()` raises a clear
  SiteWhereError when the client library is absent. The adapter pattern —
  client thread consuming deliveries into `on_encoded_event_received` — is
  identical to the reference's receiver classes.
"""

from __future__ import annotations

import importlib
import logging
import threading
import urllib.error
import urllib.request
from typing import Callable, Dict, Optional

from sitewhere_tpu.errors import SiteWhereError
from sitewhere_tpu.sources.receivers import _ReceiverBase

LOGGER = logging.getLogger("sitewhere.sources.ext")


def require_optional(import_name: str, human_name: str):
    """Import an optional client library or raise a clear 501 gating error
    (shared by the broker receivers here and connectors/sinks.py)."""
    try:
        return importlib.import_module(import_name)
    except ImportError as exc:
        raise SiteWhereError(
            f"this component requires the optional {human_name} client "
            f"library ('{import_name}'), which is not installed in this "
            f"image; use the MQTT/CoAP/socket/HTTP transports or install "
            f"it in your deployment", http_status=501) from exc


class PollingRestReceiver(_ReceiverBase):
    """Periodically GETs a URL and forwards non-empty response bodies
    (PollingRestInboundEventReceiver). An `ETag`/`Last-Modified` aware
    variant is unnecessary here: servers that support conditional GETs
    return 304 with an empty body, which is dropped."""

    def __init__(self, url: str, interval_s: float = 10.0,
                 headers: Optional[Dict[str, str]] = None,
                 timeout_s: float = 10.0):
        super().__init__()
        self.url = url
        self.interval_s = interval_s
        self.headers = dict(headers or {})
        self.timeout_s = timeout_s
        self.poll_errors = 0
        self._stop = threading.Event()
        self._thread: Optional[threading.Thread] = None

    def start(self) -> None:
        self._stop.clear()
        self._thread = threading.Thread(target=self._run, daemon=True,
                                        name=f"poll-rest:{self.url}")
        self._thread.start()

    def stop(self) -> None:
        self._stop.set()
        if self._thread is not None:
            self._thread.join(timeout=5)
            self._thread = None

    def poll_once(self) -> Optional[bytes]:
        """One poll cycle (public so tests/ops can drive it synchronously).
        Any failure — network, protocol, downstream handler — is counted,
        never raised: the polling loop must survive a misbehaving endpoint."""
        request = urllib.request.Request(self.url, headers=self.headers)
        try:
            with urllib.request.urlopen(request,
                                        timeout=self.timeout_s) as resp:
                body = resp.read()
        except Exception:
            self.poll_errors += 1
            return None
        if body:
            try:
                self.source.on_encoded_event_received(
                    body, {"rest.url": self.url})
            except Exception:
                self.poll_errors += 1
                LOGGER.exception("polling-REST delivery failed for %s",
                                 self.url)
        return body

    def _run(self) -> None:
        while not self._stop.is_set():
            self.poll_once()
            self._stop.wait(self.interval_s)


class _OptionalClientReceiver(_ReceiverBase):
    """Base for receivers whose client library is an optional dependency."""

    #: override: (import name, human name)
    _LIB: tuple = ("", "")

    def _require_lib(self):
        return require_optional(self._LIB[0], self._LIB[1])


class AmqpEventReceiver(_OptionalClientReceiver):
    """RabbitMQ/AMQP queue consumer (RabbitMqInboundEventReceiver) over the
    `pika` client when available."""

    _LIB = ("pika", "AMQP (RabbitMQ)")

    def __init__(self, url: str = "amqp://localhost", queue: str = "sitewhere",
                 durable: bool = True):
        super().__init__()
        self.url = url
        self.queue = queue
        self.durable = durable
        self._conn = None
        self._thread: Optional[threading.Thread] = None

    def start(self) -> None:
        pika = self._require_lib()
        params = pika.URLParameters(self.url)
        self._conn = pika.BlockingConnection(params)
        self._channel = self._conn.channel()
        self._channel.queue_declare(queue=self.queue, durable=self.durable)

        def on_message(ch, method, properties, body):
            self.source.on_encoded_event_received(
                body, {"amqp.queue": self.queue})
            ch.basic_ack(delivery_tag=method.delivery_tag)

        self._channel.basic_consume(queue=self.queue,
                                    on_message_callback=on_message)
        self._thread = threading.Thread(target=self._channel.start_consuming,
                                        daemon=True, name="amqp-receiver")
        self._thread.start()

    def stop(self) -> None:
        if self._conn is not None:
            # pika's BlockingConnection is single-threaded: the consumer
            # thread owns it, so stop via its thread-safe callback and join
            try:
                self._conn.add_callback_threadsafe(
                    self._channel.stop_consuming)
                if self._thread is not None:
                    self._thread.join(timeout=5)
                self._conn.close()
            except Exception:
                pass
            self._conn = None


class EventHubEventReceiver(_OptionalClientReceiver):
    """Azure EventHub consumer (EventHubInboundEventReceiver) over
    `azure.eventhub` when available."""

    _LIB = ("azure.eventhub", "Azure EventHub")

    def __init__(self, connection_str: str, eventhub_name: str,
                 consumer_group: str = "$Default"):
        super().__init__()
        self.connection_str = connection_str
        self.eventhub_name = eventhub_name
        self.consumer_group = consumer_group
        self._client = None
        self._thread: Optional[threading.Thread] = None

    def start(self) -> None:
        eventhub = self._require_lib()
        self._client = eventhub.EventHubConsumerClient.from_connection_string(
            self.connection_str, consumer_group=self.consumer_group,
            eventhub_name=self.eventhub_name)

        def on_event(partition_context, event):
            self.source.on_encoded_event_received(
                event.body_as_bytes(),
                {"eventhub.partition": partition_context.partition_id})
            partition_context.update_checkpoint(event)

        self._thread = threading.Thread(
            target=lambda: self._client.receive(on_event=on_event),
            daemon=True, name="eventhub-receiver")
        self._thread.start()

    def stop(self) -> None:
        if self._client is not None:
            self._client.close()
            self._client = None


class StompEventReceiver(_OptionalClientReceiver):
    """ActiveMQ/STOMP subscriber (ActiveMQInboundEventReceiver) over
    `stomp.py` when available."""

    _LIB = ("stomp", "STOMP (ActiveMQ)")

    def __init__(self, host: str = "localhost", port: int = 61613,
                 destination: str = "/queue/sitewhere"):
        super().__init__()
        self.host = host
        self.port = port
        self.destination = destination
        self._conn = None

    def start(self) -> None:
        stomp = self._require_lib()
        receiver = self

        class Listener(stomp.ConnectionListener):
            def on_message(self, frame):
                receiver.source.on_encoded_event_received(
                    frame.body if isinstance(frame.body, bytes)
                    else frame.body.encode(),
                    {"stomp.destination": receiver.destination})

        self._conn = stomp.Connection([(self.host, self.port)])
        self._conn.set_listener("sitewhere", Listener())
        self._conn.connect(wait=True)
        self._conn.subscribe(destination=self.destination, id="sitewhere",
                             ack="auto")

    def stop(self) -> None:
        if self._conn is not None:
            try:
                self._conn.disconnect()
            except Exception:
                pass
            self._conn = None
