"""Event deduplicators (IDeviceEventDeduplicator).

Reference: deduplicator/AlternateIdDeduplicator.java — checks the event
store for an existing event with the same alternate id — and
GroovyEventDeduplicator.java (scripted predicate). Here the alternate-id
check is a bounded in-memory set backed by an optional event-management
lookup, so the hot path stays off the store for recent duplicates.
"""

from __future__ import annotations

from collections import OrderedDict
from typing import Callable, Dict, Optional

from sitewhere_tpu.model.event import DeviceEventBatch
from sitewhere_tpu.sources.decoders import DecodedRequest


class AlternateIdDeduplicator:
    """Duplicate if any event in the request carries an alternate_id seen
    before (recent-window LRU, then the event store)."""

    def __init__(self, event_management=None, window: int = 100_000):
        self.event_management = event_management
        self._seen: "OrderedDict[str, None]" = OrderedDict()
        self._window = window

    def _alternate_ids(self, request: DecodedRequest):
        req = request.request
        if isinstance(req, DeviceEventBatch):
            for ev in req.all_events():
                if ev.alternate_id:
                    yield ev.alternate_id
        elif getattr(req, "alternate_id", ""):
            yield req.alternate_id

    def is_duplicate(self, request: DecodedRequest) -> bool:
        """Pure check — does NOT record the request's ids. Callers must
        invoke remember() only after the request is accepted; otherwise a
        rejected mixed batch would poison the window and a later retry of
        its never-persisted events would be dropped."""
        for alt in self._alternate_ids(request):
            if alt in self._seen:
                return True
            if (self.event_management is not None and
                    self.event_management.get_event_by_alternate_id(alt)
                    is not None):
                self._remember(alt)  # store-confirmed duplicate: cache it
                return True
        return False

    def remember(self, request: DecodedRequest) -> None:
        """Record an ACCEPTED request's alternate ids."""
        for alt in self._alternate_ids(request):
            self._remember(alt)

    def _remember(self, alt: str) -> None:
        self._seen[alt] = None
        self._seen.move_to_end(alt)
        while len(self._seen) > self._window:
            self._seen.popitem(last=False)


class ScriptedDeduplicator:
    """Predicate-callable deduplicator (GroovyEventDeduplicator):
    `fn(request) -> True if duplicate`."""

    def __init__(self, fn: Callable[[DecodedRequest], bool]):
        self.fn = fn

    def is_duplicate(self, request: DecodedRequest) -> bool:
        return bool(self.fn(request))

    def remember(self, request: DecodedRequest) -> None:
        pass  # scripted predicates carry their own state
