"""Event deduplicators (IDeviceEventDeduplicator).

Reference: deduplicator/AlternateIdDeduplicator.java — checks the event
store for an existing event with the same alternate id — and
GroovyEventDeduplicator.java (scripted predicate). Here the alternate-id
check is a bounded in-memory set backed by an optional event-management
lookup, so the hot path stays off the store for recent duplicates.
"""

from __future__ import annotations

from collections import OrderedDict
from typing import Callable, Dict, Iterable, List, Optional, Tuple

from sitewhere_tpu.model.event import DeviceEventBatch
from sitewhere_tpu.sources.decoders import DecodedRequest


class AlternateIdDeduplicator:
    """Duplicate if any event in the request carries an alternate_id seen
    before (recent-window LRU, then the event store)."""

    def __init__(self, event_management=None, window: int = 100_000):
        self.event_management = event_management
        self._seen: "OrderedDict[str, None]" = OrderedDict()
        self._window = window

    def _alternate_ids(self, request: DecodedRequest):
        req = request.request
        if isinstance(req, DeviceEventBatch):
            for ev in req.all_events():
                if ev.alternate_id:
                    yield ev.alternate_id
        elif getattr(req, "alternate_id", ""):
            yield req.alternate_id

    def is_duplicate(self, request: DecodedRequest) -> bool:
        """Pure check — does NOT record the request's ids. Callers must
        invoke remember() only after the request is accepted; otherwise a
        rejected mixed batch would poison the window and a later retry of
        its never-persisted events would be dropped."""
        for alt in self._alternate_ids(request):
            if alt in self._seen:
                return True
            if (self.event_management is not None and
                    self.event_management.get_event_by_alternate_id(alt)
                    is not None):
                self._remember(alt)  # store-confirmed duplicate: cache it
                return True
        return False

    def remember(self, request: DecodedRequest) -> None:
        """Record an ACCEPTED request's alternate ids."""
        for alt in self._alternate_ids(request):
            self._remember(alt)

    def _remember(self, alt: str) -> None:
        self._seen[alt] = None
        self._seen.move_to_end(alt)
        while len(self._seen) > self._window:
            self._seen.popitem(last=False)

    # -- checkpoint ride-along -----------------------------------------
    # The LRU window is process-local; without carrying it through the
    # instance checkpoint, every crash forgets the recent-duplicate set
    # and re-admits duplicates the store lookup is too slow to catch.
    def export_window(self, limit: Optional[int] = None) -> List[str]:
        """Oldest-first recent-id window, optionally truncated to the
        NEWEST `limit` entries (bounded checkpoint payload)."""
        ids = list(self._seen)
        if limit is not None and len(ids) > limit:
            ids = ids[-limit:]
        return ids

    def restore_window(self, ids: Iterable[str]) -> None:
        """Re-seed the window (oldest-first order preserves LRU age)."""
        for alt in ids:
            self._remember(alt)


class SequenceWatermarkDeduplicator:
    """Duplicate if the request carries a replayed `(id_prefix, id_seq)`
    at-or-below a per-prefix high-watermark.

    The eventlog stamps every persisted row with a process-unique
    `id_prefix` and a monotonic `id_seq`; the instance checkpoint
    captures the per-prefix maxima. After a crash-replay, stragglers
    that slipped past the replay barrier (a partial batch at the budget
    boundary) still identify themselves by a watermarked source row —
    this deduplicator drops them, the post-replay half of the
    exactly-once-effects contract. Requests without sequence metadata
    (live traffic from a new incarnation) always pass."""

    def __init__(self,
                 watermarks: Optional[Dict[str, int]] = None):
        self._marks: Dict[str, int] = {
            p: int(s) for p, s in (watermarks or {}).items()}

    def _sequence_of(self, request: DecodedRequest
                     ) -> Optional[Tuple[str, int]]:
        meta = getattr(request, "metadata", None) or {}
        prefix = meta.get("id_prefix")
        seq = meta.get("id_seq")
        if prefix is None or seq is None:
            return None
        return str(prefix), int(seq)

    def is_duplicate(self, request: DecodedRequest) -> bool:
        seq = self._sequence_of(request)
        if seq is None:
            return False
        return self.is_duplicate_row(*seq)

    def is_duplicate_row(self, prefix: str, seq: int) -> bool:
        mark = self._marks.get(prefix)
        return mark is not None and int(seq) <= mark

    def observe(self, prefix: str, seq: int) -> None:
        if int(seq) > self._marks.get(prefix, -1):
            self._marks[prefix] = int(seq)

    def merge(self, watermarks: Dict[str, int]) -> None:
        for prefix, seq in watermarks.items():
            self.observe(prefix, seq)

    def export(self) -> Dict[str, int]:
        return dict(self._marks)

    def remember(self, request: DecodedRequest) -> None:
        seq = self._sequence_of(request)
        if seq is not None:
            self.observe(*seq)


class ScriptedDeduplicator:
    """Predicate-callable deduplicator (GroovyEventDeduplicator):
    `fn(request) -> True if duplicate`."""

    def __init__(self, fn: Callable[[DecodedRequest], bool]):
        self.fn = fn

    def is_duplicate(self, request: DecodedRequest) -> bool:
        return bool(self.fn(request))

    def remember(self, request: DecodedRequest) -> None:
        pass  # scripted predicates carry their own state
