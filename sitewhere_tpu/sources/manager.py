"""Inbound event sources: receivers -> decode -> dedup -> bus topics.

Reference flow (InboundEventSource.java:189-210 / :247-294):
  onEncodedEventReceived -> decodePayload -> [deduplicator] ->
  handleDecodedRequest: events -> DecodedEventsProducer,
  registrations -> deviceRegistrationProducer,
  decode failures -> onFailedDecode -> failed-decode topic.

Here the producers publish msgpack-serialized requests onto the in-proc bus
(runtime/bus.py) keyed by device token, preserving per-device ordering into
the TPU packing stage downstream (pipeline/inbound.py; bulk alternative:
sources/fastlane.py).
"""

from __future__ import annotations

import threading
from typing import Any, Dict, List, Optional

import msgpack

from sitewhere_tpu.errors import SiteWhereError
from sitewhere_tpu.model.common import _asdict
from sitewhere_tpu.model.event import (
    DeviceCommandResponse, DeviceEventBatch, DeviceRegistrationRequest,
    DeviceStreamData)
from sitewhere_tpu.runtime.bus import EventBus, TopicNaming
from sitewhere_tpu.runtime.flight import GLOBAL_FLIGHT
from sitewhere_tpu.runtime.lifecycle import LifecycleComponent
from sitewhere_tpu.runtime.metrics import GLOBAL_METRICS, MetricsRegistry
from sitewhere_tpu.runtime.recovery import take_dedup_seed
from sitewhere_tpu.sources.decoders import DecodedRequest, DecodeError


class IngestShedError(SiteWhereError):
    """Client-visible NACK for an ingest request shed under overload —
    maps to HTTP 429 through the REST error path, and to a counted drop
    for fire-and-forget receivers (MQTT-style QoS contract)."""

    def __init__(self, message: str = "ingest shed: pipeline over budget"):
        super().__init__(message, http_status=429)


class AdmissionController:
    """Front-door overload shedding for event ingest.

    The reference gets backpressure for free from Kafka's bounded producer
    buffer; the in-proc bus is unbounded, so without a front door a slow
    fused step lets the decoded-events backlog (and its memory) grow
    without limit while client latency silently rots. This controller
    sheds AT ADMISSION — a counted, client-visible 429/NACK — when either
    budget is breached:

      * ``step_budget_ms``   — the flight recorder's mean per-step sync
        cost (``sync_total_ms.sum_of_stages`` over the last ``window``
        steps) exceeds the budget: the pipeline itself is too slow.
      * ``queue_depth_budget`` — the pluggable ``queue_depth`` provider
        (typically the decoded-events topic backlog) exceeds the budget:
        the pipeline is fine but ingest is outrunning it.

    ``admit()`` amortizes the rollup read by caching the decision for
    ``check_every`` admissions; disabled (both budgets zero — the
    default) it is two attribute loads, cheap enough for the perf gate's
    ``fault_injection_overhead`` pin. Module singleton ``GLOBAL_ADMISSION``
    mirrors GLOBAL_METRICS/GLOBAL_FLIGHT: sources are built deep inside
    tenant engines with no instance handle to thread a controller
    through."""

    def __init__(self, flight=None, step_budget_ms: float = 0.0,
                 queue_depth_budget: int = 0, queue_depth=None,
                 check_every: int = 64, window: int = 32):
        self._flight = flight
        self.step_budget_ms = float(step_budget_ms)
        self.queue_depth_budget = int(queue_depth_budget)
        self.queue_depth = queue_depth
        self.check_every = max(1, int(check_every))
        self.window = int(window)
        self._lock = threading.Lock()
        self._admits = 0
        self._shedding = False
        self._last_step_ms = 0.0
        self._last_depth = 0
        self._shed_counter = GLOBAL_METRICS.counter("admission.shed")
        self._remote_shed_counter = GLOBAL_METRICS.counter(
            "admission.shed_remote")

    @property
    def enabled(self) -> bool:
        return self.step_budget_ms > 0.0 or self.queue_depth_budget > 0

    def configure(self, step_budget_ms: Optional[float] = None,
                  queue_depth_budget: Optional[int] = None,
                  queue_depth=None, check_every: Optional[int] = None
                  ) -> None:
        """Rewire budgets (instance boot / tests). Passing None leaves a
        field unchanged; the cached decision resets either way."""
        with self._lock:
            if step_budget_ms is not None:
                self.step_budget_ms = float(step_budget_ms)
            if queue_depth_budget is not None:
                self.queue_depth_budget = int(queue_depth_budget)
            if queue_depth is not None:
                self.queue_depth = queue_depth
            if check_every is not None:
                self.check_every = max(1, int(check_every))
            self._admits = 0
            self._shedding = False

    def _refresh(self) -> None:
        breach = False
        if self.step_budget_ms > 0.0:
            flight = self._flight or GLOBAL_FLIGHT
            roll = flight.export(last_n=self.window).get("rollups", {})
            if roll.get("steps", 0):
                self._last_step_ms = float(
                    roll["sync_total_ms"]["sum_of_stages"])
                breach = self._last_step_ms > self.step_budget_ms
        if not breach and self.queue_depth_budget > 0 \
                and self.queue_depth is not None:
            try:
                self._last_depth = int(self.queue_depth())
            except Exception:
                self._last_depth = 0
            breach = self._last_depth > self.queue_depth_budget
        self._shedding = breach

    def admit(self) -> bool:
        """One admission decision; False means shed (the caller counts it
        per-source and raises IngestShedError). Refreshes from the flight
        rollups every ``check_every`` calls."""
        if not self.enabled:
            return True
        with self._lock:
            if self._admits % self.check_every == 0:
                self._refresh()
            self._admits += 1
            if self._shedding:
                self._shed_counter.inc()
                return False
            return True

    def admit_remote(self) -> bool:
        """Admission decision for a REMOTE producer (a feeder shipping a
        packed blob over busnet, feeders/service.py). Same budgets and
        cadence as admit(); a shed is additionally counted under
        `admission.shed_remote` so operators can tell propagated
        structured-429 refusals from local front-door sheds — the remote
        refusal happens before the payload is even decoded, where the
        local path sheds before pack."""
        ok = self.admit()
        if not ok:
            self._remote_shed_counter.inc()
        return ok

    def report(self) -> Dict[str, Any]:
        with self._lock:
            return {
                "enabled": self.enabled,
                "shedding": self._shedding,
                "step_budget_ms": self.step_budget_ms,
                "last_step_ms": round(self._last_step_ms, 3),
                "queue_depth_budget": self.queue_depth_budget,
                "last_queue_depth": self._last_depth,
                "shed_total": self._shed_counter.value,
                "check_every": self.check_every,
            }


GLOBAL_ADMISSION = AdmissionController()


def _pack_request(source_id: str, request: DecodedRequest) -> bytes:
    req = request.request
    kind = type(req).__name__
    return msgpack.packb({
        "sourceId": source_id,
        "deviceToken": request.device_token,
        "kind": kind,
        "request": _asdict(req),
        "metadata": request.metadata,
    }, use_bin_type=True)


class InboundEventSource(LifecycleComponent):
    """One configured event source: N receivers + decoder (+ deduplicator).

    Receivers call `on_encoded_event_received(payload, metadata)` from any
    thread; routing onto the bus is thread-safe.
    """

    def __init__(self, source_id: str, decoder, receivers: List[Any],
                 bus: EventBus, naming: Optional[TopicNaming] = None,
                 tenant: str = "default", deduplicator=None,
                 metrics: Optional[MetricsRegistry] = None):
        super().__init__(f"event-source:{source_id}")
        self.source_id = source_id
        self.decoder = decoder
        self.receivers = receivers
        self.deduplicator = deduplicator
        self.bus = bus
        self.naming = naming or TopicNaming()
        self.tenant = tenant
        m = (metrics or MetricsRegistry()).scoped(f"source.{source_id}")
        self.decoded_meter = m.meter("decoded")
        self.failed_counter = m.counter("failed_decode")
        self.duplicate_counter = m.counter("duplicates")
        self.shed_counter = m.counter("shed")

    # -- lifecycle ---------------------------------------------------------
    def on_start(self, monitor) -> None:
        # a boot restore may have staged this source's checkpointed
        # recent-duplicate window (runtime/recovery.py): claim it before
        # receivers deliver, or the first post-crash duplicates slip by
        restore = getattr(self.deduplicator, "restore_window", None)
        if restore is not None:
            seed = take_dedup_seed(self.tenant, self.source_id)
            if seed:
                restore(seed)
        for receiver in self.receivers:
            receiver.bind(self)
            receiver.start()

    def on_stop(self, monitor) -> None:
        for receiver in self.receivers:
            receiver.stop()

    # -- ingest ------------------------------------------------------------
    def on_encoded_event_received(self, payload: bytes,
                                  metadata: Optional[Dict[str, str]] = None
                                  ) -> None:
        """Receiver entry point (InboundEventSource.onEncodedEventReceived)."""
        try:
            requests = self.decoder.decode(payload, metadata)
        except DecodeError as exc:
            self.failed_counter.inc()
            self.bus.publish(
                self.naming.event_source_failed_decode_events(self.tenant),
                b"", msgpack.packb({"sourceId": self.source_id,
                                    "error": str(exc), "payload": payload},
                                   use_bin_type=True))
            return
        for request in requests:
            if metadata:  # receiver context (e.g. mqtt.topic) rides along
                request.metadata = {**metadata, **request.metadata}
            try:
                self.handle_decoded_request(request)
            except IngestShedError:
                # fire-and-forget receiver threads (MQTT-style) have no
                # reply channel: the shed is already counted per-source
                # and globally; swallowing keeps the receiver loop alive
                pass

    def handle_decoded_request(self, request: DecodedRequest) -> None:
        if isinstance(request.request, (DeviceEventBatch,
                                        DeviceCommandResponse,
                                        DeviceStreamData)) \
                and not GLOBAL_ADMISSION.admit():
            # event traffic only — registrations are rare control-plane
            # requests and always admit
            self.shed_counter.inc()
            raise IngestShedError(
                f"ingest shed at source '{self.source_id}': "
                "pipeline over budget")
        if self.deduplicator is not None:
            if self.deduplicator.is_duplicate(request):
                self.duplicate_counter.inc()
                return
        key = request.device_token.encode()
        payload = _pack_request(self.source_id, request)
        req = request.request
        if isinstance(req, DeviceRegistrationRequest):
            topic = self.naming.inbound_device_registration_events(self.tenant)
        elif isinstance(req, (DeviceEventBatch, DeviceCommandResponse,
                              DeviceStreamData)):
            topic = self.naming.event_source_decoded_events(self.tenant)
            self.decoded_meter.mark(
                len(req.all_events()) if isinstance(req, DeviceEventBatch)
                else 1)
        else:
            raise TypeError(f"undecodable request type {type(req).__name__}")
        self.bus.publish(topic, key, payload)
        if self.deduplicator is not None:
            self.deduplicator.remember(request)  # only after acceptance


class EventSourcesManager(LifecycleComponent):
    """Hosts all event sources of one tenant (EventSourcesManager.java)."""

    def __init__(self, sources: Optional[List[InboundEventSource]] = None):
        super().__init__("event-sources-manager")
        self.sources: List[InboundEventSource] = []
        for source in sources or []:
            self.add_source(source)

    def add_source(self, source: InboundEventSource) -> None:
        self.sources.append(source)
        self.add_nested(source)

    def source(self, source_id: str) -> Optional[InboundEventSource]:
        for s in self.sources:
            if s.source_id == source_id:
                return s
        return None
