"""Inbound event sources: receivers -> decode -> dedup -> bus topics.

Reference flow (InboundEventSource.java:189-210 / :247-294):
  onEncodedEventReceived -> decodePayload -> [deduplicator] ->
  handleDecodedRequest: events -> DecodedEventsProducer,
  registrations -> deviceRegistrationProducer,
  decode failures -> onFailedDecode -> failed-decode topic.

Here the producers publish msgpack-serialized requests onto the in-proc bus
(runtime/bus.py) keyed by device token, preserving per-device ordering into
the TPU packing stage downstream (pipeline/inbound.py; bulk alternative:
sources/fastlane.py).
"""

from __future__ import annotations

import threading
from typing import Any, Dict, List, Optional

import msgpack

from sitewhere_tpu.model.common import _asdict
from sitewhere_tpu.model.event import (
    DeviceCommandResponse, DeviceEventBatch, DeviceRegistrationRequest,
    DeviceStreamData)
from sitewhere_tpu.runtime.bus import EventBus, TopicNaming
from sitewhere_tpu.runtime.lifecycle import LifecycleComponent
from sitewhere_tpu.runtime.metrics import MetricsRegistry
from sitewhere_tpu.sources.decoders import DecodedRequest, DecodeError


def _pack_request(source_id: str, request: DecodedRequest) -> bytes:
    req = request.request
    kind = type(req).__name__
    return msgpack.packb({
        "sourceId": source_id,
        "deviceToken": request.device_token,
        "kind": kind,
        "request": _asdict(req),
        "metadata": request.metadata,
    }, use_bin_type=True)


class InboundEventSource(LifecycleComponent):
    """One configured event source: N receivers + decoder (+ deduplicator).

    Receivers call `on_encoded_event_received(payload, metadata)` from any
    thread; routing onto the bus is thread-safe.
    """

    def __init__(self, source_id: str, decoder, receivers: List[Any],
                 bus: EventBus, naming: Optional[TopicNaming] = None,
                 tenant: str = "default", deduplicator=None,
                 metrics: Optional[MetricsRegistry] = None):
        super().__init__(f"event-source:{source_id}")
        self.source_id = source_id
        self.decoder = decoder
        self.receivers = receivers
        self.deduplicator = deduplicator
        self.bus = bus
        self.naming = naming or TopicNaming()
        self.tenant = tenant
        m = (metrics or MetricsRegistry()).scoped(f"source.{source_id}")
        self.decoded_meter = m.meter("decoded")
        self.failed_counter = m.counter("failed_decode")
        self.duplicate_counter = m.counter("duplicates")

    # -- lifecycle ---------------------------------------------------------
    def on_start(self, monitor) -> None:
        for receiver in self.receivers:
            receiver.bind(self)
            receiver.start()

    def on_stop(self, monitor) -> None:
        for receiver in self.receivers:
            receiver.stop()

    # -- ingest ------------------------------------------------------------
    def on_encoded_event_received(self, payload: bytes,
                                  metadata: Optional[Dict[str, str]] = None
                                  ) -> None:
        """Receiver entry point (InboundEventSource.onEncodedEventReceived)."""
        try:
            requests = self.decoder.decode(payload, metadata)
        except DecodeError as exc:
            self.failed_counter.inc()
            self.bus.publish(
                self.naming.event_source_failed_decode_events(self.tenant),
                b"", msgpack.packb({"sourceId": self.source_id,
                                    "error": str(exc), "payload": payload},
                                   use_bin_type=True))
            return
        for request in requests:
            if metadata:  # receiver context (e.g. mqtt.topic) rides along
                request.metadata = {**metadata, **request.metadata}
            self.handle_decoded_request(request)

    def handle_decoded_request(self, request: DecodedRequest) -> None:
        if self.deduplicator is not None:
            if self.deduplicator.is_duplicate(request):
                self.duplicate_counter.inc()
                return
        key = request.device_token.encode()
        payload = _pack_request(self.source_id, request)
        req = request.request
        if isinstance(req, DeviceRegistrationRequest):
            topic = self.naming.inbound_device_registration_events(self.tenant)
        elif isinstance(req, (DeviceEventBatch, DeviceCommandResponse,
                              DeviceStreamData)):
            topic = self.naming.event_source_decoded_events(self.tenant)
            self.decoded_meter.mark(
                len(req.all_events()) if isinstance(req, DeviceEventBatch)
                else 1)
        else:
            raise TypeError(f"undecodable request type {type(req).__name__}")
        self.bus.publish(topic, key, payload)
        if self.deduplicator is not None:
            self.deduplicator.remember(request)  # only after acceptance


class EventSourcesManager(LifecycleComponent):
    """Hosts all event sources of one tenant (EventSourcesManager.java)."""

    def __init__(self, sources: Optional[List[InboundEventSource]] = None):
        super().__init__("event-sources-manager")
        self.sources: List[InboundEventSource] = []
        for source in sources or []:
            self.add_source(source)

    def add_source(self, source: InboundEventSource) -> None:
        self.sources.append(source)
        self.add_nested(source)

    def source(self, source_id: str) -> Optional[InboundEventSource]:
        for s in self.sources:
            if s.source_id == source_id:
                return s
        return None
