"""Event sources: ingest + decode (reference service-event-sources).

An `InboundEventSource` binds receivers (transport listeners) to a decoder
chain and emits decoded requests onto the bus topics
(event-source-decoded-events / failed-decode / registration), exactly the
flow of InboundEventSource.onEncodedEventReceived
(service-event-sources/…/InboundEventSource.java:189-210). The
`EventSourcesManager` hosts N sources per tenant.
"""

from sitewhere_tpu.sources.decoders import (
    CompositeDecoder, DecodedRequest, DecodeError, JsonBatchDecoder,
    JsonRequestDecoder, ScriptedDecoder, WireDecoder)
from sitewhere_tpu.transport.protobuf_compat import ProtobufCompatDecoder
from sitewhere_tpu.sources.dedup import (
    AlternateIdDeduplicator, ScriptedDeduplicator,
    SequenceWatermarkDeduplicator)
from sitewhere_tpu.sources.manager import (
    EventSourcesManager, InboundEventSource)
from sitewhere_tpu.sources.receivers import (
    CoapEventReceiver, HttpEventReceiver, MqttEventReceiver,
    StompBrokerEventReceiver,
    SocketEventReceiver, WebSocketEventReceiver)

__all__ = [
    "CompositeDecoder", "DecodedRequest", "DecodeError", "JsonBatchDecoder",
    "JsonRequestDecoder", "ProtobufCompatDecoder", "ScriptedDecoder",
    "WireDecoder",
    "AlternateIdDeduplicator", "ScriptedDeduplicator",
    "SequenceWatermarkDeduplicator",
    "EventSourcesManager", "InboundEventSource",
    "CoapEventReceiver", "HttpEventReceiver", "MqttEventReceiver",
    "StompBrokerEventReceiver",
    "SocketEventReceiver", "WebSocketEventReceiver",
]
