"""Bulk wire-frame ingest lane: bytes -> packed EventBatch, no per-event
Python objects.

The reference decodes every event payload into Java POJOs and hands them
through Kafka stage by stage (InboundEventSource.onEncodedEventReceived ->
ProtobufDeviceEventDecoder -> DecodedEventsProducer, InboundEventSource.java
:189-294); sustaining 1M events/sec on the host requires never touching a
per-event object. This lane is the batch alternative: a native single-pass
frame decode (sitewhere_tpu/native, with a pure-Python fallback), vectorized
token interning straight off the decoder's (bytes, offsets) columns, and
`EventPacker`-compatible column packing.

Control frames (registration, acks, stream data) are surfaced to the caller
for the normal object path — they are rare and not throughput-critical.
"""

from __future__ import annotations

from dataclasses import dataclass, field
from typing import List, Optional, Tuple

import numpy as np

from sitewhere_tpu.model.event import DeviceEventType
from sitewhere_tpu.ops.pack import EventBatch, EventPacker
from sitewhere_tpu.runtime.bus import TopicNaming
from sitewhere_tpu.runtime.eventage import (AgeSidecar, age_histogram,
                                            observe_summary)
from sitewhere_tpu.runtime.lifecycle import LifecycleComponent
from sitewhere_tpu.runtime.metrics import GLOBAL_METRICS, MetricsRegistry
from sitewhere_tpu.runtime.tracing import GLOBAL_TRACER
from sitewhere_tpu.transport.wire import (
    MessageType, WireError, decode_event_frames_to_columns, decode_frames,
    encode_frame)


@dataclass
class FastIngestResult:
    batches: List[EventBatch] = field(default_factory=list)
    n_events: int = 0
    # control frames for the object path: (MessageType value, payload bytes)
    control_frames: List[Tuple[int, bytes]] = field(default_factory=list)
    # bytes of a trailing partial frame the caller must keep buffered
    remainder: bytes = b""
    # device tokens of all hot events as (joined bytes, offsets[n+1]);
    # row i of the concatenated batches is tokens[offsets[i]:offsets[i+1]]
    # (kept in columnar form so the rare consumers — unregistered-device
    # routing — pay the string cost, not the hot path)
    tokens: Tuple[bytes, np.ndarray] = (b"", None)

    def token_at(self, row: int) -> str:
        buf, off = self.tokens
        return buf[int(off[row]):int(off[row + 1])].decode(
            errors="surrogateescape")


class FastWireIngest:
    """Turn concatenated wire frames into ready-to-submit EventBatches.

    Device tokens are looked up (NOT interned — unknown devices must stay
    index 0 so the pipeline flags them unregistered, pipeline/step.py
    stage 1); measurement names and alert types are interned on the fly like
    `EventPacker.pack_events` does.
    """

    def __init__(self, packer: EventPacker):
        self.packer = packer
        from sitewhere_tpu import native
        self._nat = native if native.available() else None

    def ingest(self, data: bytes) -> FastIngestResult:
        if self._nat is not None:
            return self._ingest_native(data)
        return self._ingest_python(data)

    # -- native path --------------------------------------------------------

    def _ingest_native(self, data: bytes) -> FastIngestResult:
        cols = self._nat.decode_hot_frames(data)
        res = FastIngestResult(control_frames=cols.others,
                               remainder=data[cols.consumed:],
                               n_events=cols.n, tokens=cols.tokens)
        if cols.n == 0:
            return res
        tok_buf, tok_off = cols.tokens
        device_idx = self.packer.devices.lookup_offsets(tok_buf, tok_off)
        name_buf, name_off = cols.names
        mm_idx = self.packer.measurements.intern_offsets(
            name_buf, name_off, skip_empty=True)
        at_buf, at_off = cols.alert_types
        alert_type_idx = self.packer.alert_types.intern_offsets(
            at_buf, at_off, skip_empty=True)
        res.batches = self._pack(
            device_idx, cols.event_type, cols.ts_ms, mm_idx, cols.value,
            cols.lat, cols.lon, cols.elevation, alert_type_idx,
            cols.alert_level)
        return res

    # -- pure-Python fallback ----------------------------------------------

    def _ingest_python(self, data: bytes) -> FastIngestResult:
        frames, rest = decode_frames(data)
        hot = decode_event_frames_to_columns(frames)
        others = [(int(t), p) for t, p in frames
                  if t not in (MessageType.MEASUREMENT, MessageType.LOCATION,
                               MessageType.ALERT)]
        n = len(hot["tokens"])
        enc = [t.encode(errors="surrogateescape") for t in hot["tokens"]]
        off = np.zeros(n + 1, np.int64)
        np.cumsum([len(t) for t in enc], out=off[1:])
        res = FastIngestResult(control_frames=others, remainder=rest,
                               n_events=n, tokens=(b"".join(enc), off))
        if n == 0:
            return res
        device_idx = self.packer.devices.lookup_batch(hot["tokens"])
        # empty names/types map to UNKNOWN without interning — same contract
        # as the native lane's intern_offsets(skip_empty=True)
        is_mm = hot["event_type"] == int(DeviceEventType.MEASUREMENT)
        mm_idx = np.zeros(n, np.int32)
        for i in np.nonzero(is_mm)[0]:
            if hot["names"][i]:
                mm_idx[i] = self.packer.measurements.intern(hot["names"][i])
        is_alert = hot["event_type"] == int(DeviceEventType.ALERT)
        alert_type_idx = np.zeros(n, np.int32)
        for i in np.nonzero(is_alert)[0]:
            if hot["alert_types"][i]:
                alert_type_idx[i] = self.packer.alert_types.intern(
                    hot["alert_types"][i])
        res.batches = self._pack(
            device_idx, hot["event_type"], hot["ts_ms"], mm_idx,
            hot["value"], hot["lat"], hot["lon"], hot["elevation"],
            alert_type_idx, hot["alert_level"])
        return res

    # -- shared packing -----------------------------------------------------

    def _pack(self, device_idx, event_type, ts_ms, mm_idx, value, lat, lon,
              elevation, alert_type_idx, alert_level) -> List[EventBatch]:
        B = self.packer.batch_size
        out: List[EventBatch] = []
        for s in range(0, len(device_idx), B):
            e = s + B
            out.append(self.packer.pack_columns(
                device_idx[s:e], event_type[s:e], ts_ms[s:e],
                mm_idx=mm_idx[s:e], value=value[s:e], lat=lat[s:e],
                lon=lon[s:e], elevation=elevation[s:e],
                alert_type_idx=alert_type_idx[s:e],
                alert_level=alert_level[s:e]))
        return out


class BulkWireIngestService(LifecycleComponent):
    """A receiver sink that runs the bulk lane end-to-end.

    Receivers deliver raw wire bytes here (same `on_encoded_event_received`
    contract as InboundEventSource); each delivery is decoded in bulk,
    submitted to the fused pipeline step, and appended to the columnar event
    log — the high-rate alternative to the object pipeline
    (sources/manager.py -> bus -> pipeline/inbound.py), the way the
    reference's BulkEventStorageStrategy is the alternative to
    UnaryEventStorageStrategy (service-inbound-processing).

    Control frames (registration etc.) are re-framed and handed to
    `control_sink` — typically InboundEventSource.on_encoded_event_received
    of a normal source, so registration/acks flow the standard path.
    Unregistered hot events route their tokens to the unregistered topic.
    """

    def __init__(self, engine, eventlog=None, events=None, bus=None,
                 tenant: str = "default", naming=None, control_sink=None,
                 persist_rule_alerts: bool = True, registry=None,
                 metrics=None, persist_async: bool = False,
                 persist_depth: int = 8, trace_sample_n: int = 0):
        super().__init__(f"bulk-wire-ingest:{tenant}")
        self.engine = engine
        self.lane = FastWireIngest(engine.packer)
        self.eventlog = eventlog
        # persist_async moves the columnar append onto a writer thread
        # (persist/worker.py, the DeviceEventBuffer role) so the durable
        # append overlaps the next delivery's decode+step instead of
        # serializing after it; the bounded queue backpressures ingest
        # when the datastore is the bottleneck.
        self.persister = None
        if persist_async and eventlog is not None:
            from sitewhere_tpu.persist.worker import AsyncEventPersister
            self.persister = self.add_nested(AsyncEventPersister(
                eventlog, engine.packer, tenant=tenant, bus=bus,
                naming=naming, registry=registry, depth=persist_depth,
                metrics=metrics))
        self.events = events
        self.registry = registry
        self.bus = bus
        self.tenant = tenant
        self.naming = naming or TopicNaming()
        self.control_sink = control_sink
        self.persist_rule_alerts = persist_rule_alerts
        m = (metrics or MetricsRegistry()).scoped("bulk_ingest")
        self.events_meter = m.meter("events")
        self.unregistered_counter = m.counter("unregistered")
        self.failed_counter = m.counter("failed_decode")
        self._remainder = b""
        # ingest->effect age telemetry (runtime/eventage.py): the age
        # histogram lives on the SCRAPED registry (global by default)
        # under labels (engine, edge); journey tracing samples one
        # delivery in trace_sample_n with a span whose traceparent rides
        # any busnet RPC issued while processing it (0 = off).
        self._age_hist = age_histogram(metrics if metrics is not None
                                       else GLOBAL_METRICS)
        self._engine_label = getattr(engine, "name", "pipeline")
        self.trace_sample_n = int(trace_sample_n)
        self._delivery_seq = 0

    def on_encoded_event_received(self, payload: bytes,
                                  metadata=None) -> None:
        # one ingest stamp per delivery (sources/receivers.py); popped so
        # decoders never see the float. Direct callers without a stamp
        # age from "now" (ages ~0 — still counted).
        received_at = None
        if metadata is not None:
            received_at = metadata.pop("received_at", None)
        self._delivery_seq += 1
        n = self.trace_sample_n
        if n > 0 and self._delivery_seq % n == 0:
            with GLOBAL_TRACER.span("ingest.journey", tenant=self.tenant,
                                    delivery=str(self._delivery_seq)):
                self._handle_delivery(payload, metadata, received_at)
        else:
            self._handle_delivery(payload, metadata, received_at)

    def _handle_delivery(self, payload: bytes, metadata,
                         received_at) -> None:
        data = self._remainder + payload if self._remainder else payload
        try:
            res = self.lane.ingest(data)
        except (WireError, ValueError) as exc:
            # corrupt delivery: drop buffered bytes so the stream re-syncs at
            # the next delivery, and route to the failed-decode topic like
            # the object path (InboundEventSource.onFailedDecode)
            self._remainder = b""
            self.failed_counter.inc()
            if self.bus is not None:
                self.bus.publish(
                    self.naming.event_source_failed_decode_events(self.tenant),
                    str(exc).encode(), payload)
            return
        self._remainder = res.remainder
        if res.control_frames and self.control_sink is not None:
            for mtype, body in res.control_frames:
                try:
                    frame = encode_frame(MessageType(mtype), body)
                except ValueError:  # unknown control msg_type: skip
                    self.failed_counter.inc()
                    continue
                self.control_sink(frame, metadata)
        row = 0
        for batch in res.batches:
            age = AgeSidecar()
            age.add(received_at, min(batch.batch_size, res.n_events - row))
            alert_batch, outputs = self.engine.submit_routed(batch, age=age)
            persisted = True
            if self.persister is not None:
                self.persister.submit(batch, self.tenant)
            elif self.eventlog is not None:
                self.eventlog.append_batch(self.tenant, batch,
                                           self.engine.packer,
                                           registry=self.registry)
            else:
                persisted = False
            if persisted:
                # persist edge: durable append handed off (close() is
                # pure — the engine separately closed the materialize
                # edge on the same sidecar)
                observe_summary(self._age_hist, age.close(),
                                engine=self._engine_label, edge="persist")
            self._route_unregistered(res, batch, row)
            self._persist_alerts(alert_batch, outputs, age=age)
            row += batch.batch_size
        self.events_meter.mark(res.n_events)

    def _route_unregistered(self, res: FastIngestResult, batch: EventBatch,
                            row0: int) -> None:
        """Route events whose device has no active assignment to the
        unregistered-device topic (flat host-side check against the registry
        mirror, so it works identically for single-chip and sharded engines
        whose outputs are in routed [S, B] layout)."""
        snap = self._registry_snapshot()
        device_idx = np.asarray(batch.device_idx)
        valid = np.asarray(batch.valid)
        status = snap.assignment_status[device_idx]
        rows = np.nonzero(valid & (status != 1))[0]
        if rows.size == 0:
            return
        self.unregistered_counter.inc(int(rows.size))
        if self.bus is None:
            return
        topic = self.naming.inbound_unregistered_device_events(self.tenant)
        for r in rows:
            if row0 + int(r) < res.n_events:
                token = res.token_at(row0 + int(r))
                self.bus.publish(topic, token.encode(), token.encode())

    def _registry_snapshot(self):
        tensors = self.engine.registry
        cached = getattr(self, "_snap", None)
        if cached is None or cached.version != tensors.version:
            self._snap = tensors.snapshot()
        return self._snap

    def _persist_alerts(self, batch, outputs, age=None) -> None:
        if not self.persist_rule_alerts or self.events is None \
                or self.registry is None:
            return
        alerts = list(self.engine.materialize_alerts(batch, outputs))
        for alert in alerts:
            device = self.registry.get_device_by_token(alert.device_id)
            if device is None:
                continue
            assignment = self.registry.get_active_assignment(device.id)
            if assignment is not None:
                self.events.add_alerts(assignment.token, alert)
        if alerts and age is not None:
            # alert edge: rule alerts reached the event store
            observe_summary(self._age_hist, age.close(),
                            engine=self._engine_label, edge="alert")
