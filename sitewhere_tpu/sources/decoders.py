"""Inbound payload decoders: raw bytes -> decoded device requests.

Reference: IDeviceEventDecoder implementations in service-event-sources —
protobuf (decoder/protobuf/ProtobufDeviceEventDecoder.java), JSON batch +
JSON request (decoder/json/JsonBatchEventDecoder.java /
JsonDeviceRequestDecoder.java), Groovy scripted (GroovyEventDecoder.java),
and composite per-device-type routing (decoder/composite/*).

A decoder returns a list of `DecodedRequest`s: (device_token, request),
where request is a DeviceEventBatch, a DeviceRegistrationRequest, a
DeviceCommandResponse, or a DeviceStreamData chunk. The scripted decoder
takes a plain Python callable — the Groovy-script extension point without a
JVM.
"""

from __future__ import annotations

import json
from dataclasses import dataclass, field
from typing import Any, Callable, Dict, List, Optional, Protocol

from sitewhere_tpu.model.event import (
    AlertLevel, AlertSource, DeviceAlert, DeviceCommandResponse,
    DeviceEventBatch, DeviceLocation, DeviceMeasurement,
    DeviceRegistrationRequest, DeviceStreamData)
from sitewhere_tpu.transport.wire import (
    MessageType, WireCodec, WireError, decode_frames)


class DecodeError(Exception):
    """Raised for undecodable payloads; routes to the failed-decode topic
    (EventSourcesManager.onFailedDecode)."""


@dataclass
class DecodedRequest:
    """One decoded unit (IDecodedDeviceRequest<?>)."""

    device_token: str
    request: Any  # DeviceEventBatch | DeviceRegistrationRequest | ...
    metadata: Dict[str, str] = field(default_factory=dict)


class Decoder(Protocol):
    def decode(self, payload: bytes,
               metadata: Optional[Dict[str, str]] = None
               ) -> List[DecodedRequest]: ...


class WireDecoder:
    """Decode wire-protocol frames (transport/wire.py) — the equivalent of
    ProtobufDeviceEventDecoder over sitewhere.proto messages. A payload may
    carry many frames; events group per device into DeviceEventBatches."""

    def decode(self, payload: bytes,
               metadata: Optional[Dict[str, str]] = None
               ) -> List[DecodedRequest]:
        try:
            frames, rest = decode_frames(payload)
        except WireError as exc:
            raise DecodeError(str(exc)) from exc
        if rest:
            raise DecodeError(f"trailing {len(rest)} bytes after frames")
        if not frames:
            raise DecodeError("no frames in payload")
        out: List[DecodedRequest] = []
        batches: Dict[str, DeviceEventBatch] = {}
        for mtype, body in frames:
            try:
                self._one(mtype, body, out, batches)
            except (IndexError, KeyError, ValueError) as exc:
                raise DecodeError(f"bad {mtype.name} payload") from exc
        out.extend(DecodedRequest(tok, b) for tok, b in batches.items())
        return out

    @staticmethod
    def _one(mtype: MessageType, body: bytes, out: List[DecodedRequest],
             batches: Dict[str, DeviceEventBatch]) -> None:
        if mtype == MessageType.MEASUREMENT:
            ev = WireCodec.decode_event(mtype, body)
            batch = batches.setdefault(ev["token"],
                                       DeviceEventBatch(ev["token"]))
            batch.measurements.append(DeviceMeasurement(
                name=ev["name"], value=ev["value"], event_date=ev["ts_ms"]))
        elif mtype == MessageType.LOCATION:
            ev = WireCodec.decode_event(mtype, body)
            batch = batches.setdefault(ev["token"],
                                       DeviceEventBatch(ev["token"]))
            batch.locations.append(DeviceLocation(
                latitude=ev["lat"], longitude=ev["lon"],
                elevation=ev["elevation"], event_date=ev["ts_ms"]))
        elif mtype == MessageType.ALERT:
            ev = WireCodec.decode_event(mtype, body)
            batch = batches.setdefault(ev["token"],
                                       DeviceEventBatch(ev["token"]))
            batch.alerts.append(DeviceAlert(
                type=ev["type"], level=AlertLevel(ev["level"]),
                message=ev["message"], source=AlertSource.DEVICE,
                event_date=ev["ts_ms"]))
        elif mtype == MessageType.REGISTER:
            c = WireCodec.decode_control(body)
            out.append(DecodedRequest(c["token"], DeviceRegistrationRequest(
                device_token=c["token"], device_type_token=c["deviceType"],
                area_token=c.get("area", ""),
                customer_token=c.get("customer", ""),
                metadata=c.get("metadata", {}))))
        elif mtype == MessageType.COMMAND_RESPONSE:
            c = WireCodec.decode_control(body)
            out.append(DecodedRequest(c["token"], DeviceCommandResponse(
                originating_event_id=c["invocationId"],
                response=c["response"])))
        elif mtype == MessageType.STREAM_DATA:
            c = WireCodec.decode_control(body)
            out.append(DecodedRequest(c["token"], DeviceStreamData(
                stream_id=c["streamId"], sequence_number=c["sequence"],
                data=c["data"])))
        else:
            raise DecodeError(f"unexpected inbound type {mtype.name}")


class JsonBatchDecoder:
    """JSON event batch (JsonBatchEventDecoder):
    {"deviceToken": "...", "measurements": [{"name","value","eventDate"?}],
     "locations": [...], "alerts": [...]}"""

    def decode(self, payload: bytes,
               metadata: Optional[Dict[str, str]] = None
               ) -> List[DecodedRequest]:
        try:
            doc = json.loads(payload)
            token = doc["deviceToken"]
            batch = DeviceEventBatch(device_token=token)
            for m in doc.get("measurements", []):
                batch.measurements.append(DeviceMeasurement(
                    name=m["name"], value=float(m["value"]),
                    **_dates(m)))
            for l in doc.get("locations", []):
                batch.locations.append(DeviceLocation(
                    latitude=float(l["latitude"]),
                    longitude=float(l["longitude"]),
                    elevation=float(l.get("elevation", 0.0)), **_dates(l)))
            for a in doc.get("alerts", []):
                batch.alerts.append(DeviceAlert(
                    type=a["type"], message=a.get("message", ""),
                    level=AlertLevel[a.get("level", "INFO").upper()],
                    **_dates(a)))
        except (ValueError, KeyError, TypeError) as exc:
            raise DecodeError(f"bad JSON batch: {exc}") from exc
        return [DecodedRequest(token, batch)]


def _dates(doc: Dict) -> Dict:
    out = {}
    if "eventDate" in doc:
        out["event_date"] = int(doc["eventDate"])
    if "alternateId" in doc:
        out["alternate_id"] = str(doc["alternateId"])
    return out


class JsonRequestDecoder:
    """Typed JSON request (JsonDeviceRequestDecoder):
    {"deviceToken": "...", "type": "RegisterDevice"|"DeviceMeasurement"|...,
     "request": {...}}"""

    def decode(self, payload: bytes,
               metadata: Optional[Dict[str, str]] = None
               ) -> List[DecodedRequest]:
        try:
            doc = json.loads(payload)
            token = doc["deviceToken"]
            rtype = doc["type"]
            req = doc.get("request", {})
            if rtype == "RegisterDevice":
                return [DecodedRequest(token, DeviceRegistrationRequest(
                    device_token=token,
                    device_type_token=req.get("deviceTypeToken", ""),
                    area_token=req.get("areaToken", ""),
                    metadata=req.get("metadata", {})))]
            batch = DeviceEventBatch(device_token=token)
            if rtype == "DeviceMeasurement":
                batch.measurements.append(DeviceMeasurement(
                    name=req["name"], value=float(req["value"]),
                    **_dates(req)))
            elif rtype == "DeviceLocation":
                batch.locations.append(DeviceLocation(
                    latitude=float(req["latitude"]),
                    longitude=float(req["longitude"]),
                    elevation=float(req.get("elevation", 0.0)),
                    **_dates(req)))
            elif rtype == "DeviceAlert":
                batch.alerts.append(DeviceAlert(
                    type=req["type"], message=req.get("message", ""),
                    level=AlertLevel[req.get("level", "INFO").upper()],
                    **_dates(req)))
            else:
                raise DecodeError(f"unknown request type {rtype}")
            return [DecodedRequest(token, batch)]
        except DecodeError:
            raise
        except (ValueError, KeyError, TypeError) as exc:
            raise DecodeError(f"bad JSON request: {exc}") from exc


class ScriptedDecoder:
    """User-code decoder (GroovyEventDecoder equivalent): wraps a Python
    callable `(payload: bytes, metadata: dict) -> List[DecodedRequest]`.
    Registered scripts come from the script manager (runtime.scripts)."""

    def __init__(self, fn: Callable[[bytes, Dict[str, str]],
                                    List[DecodedRequest]]):
        self.fn = fn

    @classmethod
    def from_manager(cls, manager, script_id: str, scope: str = "global",
                     entry: str = "decode") -> "ScriptedDecoder":
        """Bind to a managed script's active version (hot-swaps on
        activation — runtime/scripts.py)."""
        return cls(manager.resolve(scope, script_id, entry))

    def decode(self, payload: bytes,
               metadata: Optional[Dict[str, str]] = None
               ) -> List[DecodedRequest]:
        try:
            return self.fn(payload, metadata or {})
        except Exception as exc:
            raise DecodeError(f"script decoder failed: {exc}") from exc


class CompositeDecoder:
    """Per-device-type decoder routing (decoder/composite/*): a metadata
    extractor pulls the device token from the payload, the device's type
    selects the sub-decoder."""

    def __init__(self, registry,
                 extractor: Callable[[bytes], str],
                 choices: Dict[str, Decoder],
                 default: Optional[Decoder] = None):
        self.registry = registry
        self.extractor = extractor
        self.choices = choices
        self.default = default

    def decode(self, payload: bytes,
               metadata: Optional[Dict[str, str]] = None
               ) -> List[DecodedRequest]:
        token = self.extractor(payload)
        device = self.registry.get_device_by_token(token)
        decoder = self.default
        if device is not None:
            dtype = self.registry.device_types.get(device.device_type_id)
            if dtype is not None and dtype.token in self.choices:
                decoder = self.choices[dtype.token]
        if decoder is None:
            raise DecodeError(f"no decoder for device {token}")
        return decoder.decode(payload, metadata)
