"""Device registry: CRUD store + token interning + device-indexed tensors.

Replaces the reference's service-device-management (gRPC registry with 84 rpcs,
Hazelcast near-caches) with an in-process store whose hot-path view is a set of
device-indexed lookup tensors resident in HBM — the per-event gRPC
getDeviceByToken of InboundPayloadProcessingLogic.java:156-193 becomes a dense
int32 gather inside the fused pipeline step.
"""

from sitewhere_tpu.registry.interning import TokenInterner
from sitewhere_tpu.registry.store import DeviceManagement, SqliteStore, InMemoryStore
from sitewhere_tpu.registry.tensors import RegistryTensors

__all__ = ["TokenInterner", "DeviceManagement", "SqliteStore", "InMemoryStore",
           "RegistryTensors"]
