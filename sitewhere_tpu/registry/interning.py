"""Token interning: string identifiers -> dense int32 indices.

The hot path cannot touch Python strings: device tokens, measurement names,
alert types and tenant tokens are interned once on the host into dense indices
that index HBM lookup tensors. This replaces the reference's per-event
device-token -> Device gRPC lookup + Hazelcast near-cache
(InboundPayloadProcessingLogic.java:156, NearCacheManager.java:42).

The native C++ batch interner (sitewhere_tpu/native/host_runtime.cc)
accelerates bulk interning; this module transparently uses it when the shared
library is available (it is mirrored entry-for-entry from the Python side,
which stays authoritative for token_of/snapshot/restore) and falls back to
pure Python otherwise (SITEWHERE_TPU_NO_NATIVE=1 forces the fallback).
"""

from __future__ import annotations

import threading
from typing import Dict, Iterable, List, Optional, Sequence

import numpy as np


def _native():
    from sitewhere_tpu import native
    return native if native.available() else None


class TokenInterner:
    """Bidirectional string<->int32 mapping with a fixed capacity.

    Index 0 is reserved as UNKNOWN so that lookup tensors can keep a sentinel
    row and failed lookups stay in-band on device.

    ``shard_classes`` > 1 turns on SHARD-CONGRUENT allocation: a token's
    index is chosen within the congruence class ``crc32(token) % classes``
    (the same keying the bus uses for partitions), so the engine's
    structural shard mapping ``idx % S`` depends only on the token — NEVER
    on per-host creation order. That is what lets cluster hosts provision
    in different orders yet agree on device ownership
    (parallel/cluster.py owner_process). The index table becomes sparse
    (gap slots are None; the native mirror holds un-lookupable
    placeholders overwritten in place via set_at); capacity is effectively
    per class (capacity/classes devices per shard family). classes == 1
    is the exact sequential behavior every other interner uses.
    """

    UNKNOWN = 0

    def __init__(self, capacity: int, name: str = "tokens",
                 shard_classes: int = 1):
        if capacity < 2:
            raise ValueError("capacity must be >= 2")
        if shard_classes < 1 or shard_classes >= capacity:
            raise ValueError("shard_classes must be in [1, capacity)")
        self.capacity = capacity
        self.name = name
        self.shard_classes = shard_classes
        self._to_index: Dict[str, int] = {}
        self._to_token: List[Optional[str]] = [None]  # index 0 = UNKNOWN
        self._lock = threading.Lock()
        # per-class next-candidate index (class 0 starts past the
        # reserved UNKNOWN slot)
        self._class_next: Dict[int, int] = {}
        # Bumped on every mutation INCLUDING restore(): length alone is not
        # a valid cache key for snapshot consumers — a checkpoint restore
        # can swap same-length contents.
        self.version = 0
        # Append-only assignment journal for replica feeders (feeders/
        # replica.py): one (idx, token) entry per slot ASSIGNMENT — gap
        # slots journal as (idx, None), a later in-place gap fill journals
        # the same idx again with its token. Replaying the journal from 0
        # reproduces _to_token exactly, so a remote replica that applies
        # deltas in order packs bit-identical indices. restore() swaps
        # table contents wholesale — the journal is rebuilt and
        # journal_epoch bumped so replicas discard and resync from 0.
        self._journal: List[tuple] = []
        self.journal_epoch = 0
        # cached dense index -> token array (token_array); rebuilt lazily
        # when version moves — hot-path materialization fancy-indexes it
        # instead of calling token_of per row
        self._token_array: Optional[np.ndarray] = None
        self._token_array_version = -1
        nat = _native()
        self._nat = nat.NativeInterner(capacity) if nat else None

    def __len__(self) -> int:
        return len(self._to_token)

    def _raise_capacity(self, congruence_class: Optional[int] = None):
        from sitewhere_tpu.errors import ErrorCode, SiteWhereError
        if congruence_class is not None:
            # per-class exhaustion can hit while the table is mostly
            # empty (crc32 skew): name the real limit so operators don't
            # chase the global capacity number
            per_class = self.capacity // self.shard_classes
            raise SiteWhereError(
                f"interner '{self.name}' congruence class "
                f"{congruence_class} exhausted (~{per_class} slots per "
                f"class = capacity {self.capacity} / {self.shard_classes} "
                f"shard classes; raise max_devices)",
                ErrorCode.CAPACITY_EXCEEDED)
        raise SiteWhereError(
            f"interner '{self.name}' capacity {self.capacity} exceeded",
            ErrorCode.CAPACITY_EXCEEDED)

    def _mirror_sync_error(self, nidx: int, idx: int):
        # survives `python -O`, unlike an assert: a silent native/Python
        # desync would corrupt every later native-path lookup
        from sitewhere_tpu.errors import ErrorCode, SiteWhereError
        raise SiteWhereError(
            f"interner '{self.name}' native mirror out of sync "
            f"(native {nidx} != {idx})", ErrorCode.GENERIC)

    def _class_of(self, token: str) -> int:
        import zlib

        return zlib.crc32(token.encode(errors="surrogateescape")) \
            % self.shard_classes

    def _intern_congruent(self, token: str) -> int:
        """Assign within the token's congruence class (caller holds lock)."""
        cls = self._class_of(token)
        start = cls if cls != 0 else self.shard_classes
        idx = self._class_next.get(cls, start)
        # skip slots already occupied (e.g. restored snapshots)
        while idx < len(self._to_token) and self._to_token[idx] is not None:
            idx += self.shard_classes
        if idx >= self.capacity:
            self._raise_capacity(congruence_class=cls)
        if idx < len(self._to_token):
            # a gap slot left by another class growing past: overwrite in
            # place (native set_at replaces the placeholder)
            self._to_token[idx] = token
            if self._nat is not None:
                if self._nat.set_at(idx, token) != 0:
                    self._mirror_sync_error(-1, idx)
        else:
            while len(self._to_token) < idx:
                gap = len(self._to_token)
                self._to_token.append(None)
                self._journal.append((gap, None))
                if self._nat is not None:
                    # gap slots never enter the native hash: unfindable by
                    # construction, no byte pattern is reserved
                    if self._nat.add_gap() != gap:
                        self._mirror_sync_error(-1, gap)
            self._to_token.append(token)
            if self._nat is not None:
                nidx = self._nat.add(token)
                if nidx != idx:
                    self._mirror_sync_error(nidx, idx)
        self._journal.append((idx, token))
        self._to_index[token] = idx
        self._class_next[cls] = idx + self.shard_classes
        self.version += 1
        return idx

    def intern(self, token: str) -> int:
        """Get-or-assign the index for a token."""
        idx = self._to_index.get(token)
        if idx is not None:
            return idx
        with self._lock:
            idx = self._to_index.get(token)
            if idx is not None:
                return idx
            if self.shard_classes > 1:
                return self._intern_congruent(token)
            idx = len(self._to_token)
            if idx >= self.capacity:
                self._raise_capacity()
            self._to_token.append(token)
            self._to_index[token] = idx
            self._journal.append((idx, token))
            self.version += 1
            if self._nat is not None:
                nidx = self._nat.add(token)
                if nidx != idx:
                    self._mirror_sync_error(nidx, idx)
            return idx

    def lookup(self, token: str) -> int:
        """Index for a token, UNKNOWN (0) if absent. Never allocates."""
        return self._to_index.get(token, self.UNKNOWN)

    def token_of(self, index: int) -> Optional[str]:
        if 0 < index < len(self._to_token):
            return self._to_token[index]
        return None

    def token_array(self) -> np.ndarray:
        """Dense [capacity] object array: index -> token, "" for UNKNOWN,
        gaps, and never-assigned slots. Cached and rebuilt only when the
        interner version moves, so hot paths (alert materialization,
        presence sweeps) resolve many indices with one fancy-index
        instead of a per-row Python `token_of` loop. The returned array
        is shared — callers must not mutate it."""
        with self._lock:
            if (self._token_array is not None
                    and self._token_array_version == self.version):
                return self._token_array
            arr = np.empty(self.capacity, object)
            arr[:] = ""
            for i in range(1, len(self._to_token)):
                token = self._to_token[i]
                if token is not None:
                    arr[i] = token
            self._token_array = arr
            self._token_array_version = self.version
            return arr

    def lookup_batch(self, tokens: Sequence[str]) -> np.ndarray:
        """Vectorized lookup of many tokens -> int32 array (no allocation)."""
        if self._nat is not None:
            return self._nat.lookup_batch(tokens)
        get = self._to_index.get
        return np.fromiter((get(t, 0) for t in tokens), dtype=np.int32,
                           count=len(tokens))

    def lookup_offsets(self, buf: bytes, off: np.ndarray) -> np.ndarray:
        """Lookup tokens given as a (joined bytes, offsets[n+1]) pair — the
        zero-copy contract of the native wire decoder (native/__init__.py
        DecodedColumns). Falls back through Python slicing."""
        if self._nat is not None:
            return self._nat.lookup_offsets(buf, off)
        get = self._to_index.get
        n = len(off) - 1
        return np.fromiter(
            (get(buf[off[i]:off[i + 1]].decode(errors="surrogateescape"), 0)
             for i in range(n)),
            dtype=np.int32, count=n)

    def intern_batch(self, tokens: Iterable[str]) -> np.ndarray:
        if self._nat is None or self.shard_classes > 1:
            # congruent allocation goes token-by-token (the native bulk
            # assign is sequential-only); no current congruent interner
            # uses the bulk path on a hot loop
            return np.fromiter((self.intern(t) for t in tokens),
                               dtype=np.int32)
        tokens = list(tokens)
        with self._lock:
            idx, ok = self._nat.intern_batch(tokens)
            self._sync_from_native()
        if not ok:
            self._raise_capacity()
        return idx

    def intern_offsets(self, buf: bytes, off: np.ndarray,
                       skip_empty: bool = False) -> np.ndarray:
        """intern_batch over a (joined bytes, offsets) pair. skip_empty maps
        zero-length tokens to UNKNOWN without interning (absent fields in
        decoded columns)."""
        if self._nat is None or self.shard_classes > 1:
            n = len(off) - 1

            def one(i):
                if skip_empty and off[i + 1] == off[i]:
                    return 0
                return self.intern(
                    buf[off[i]:off[i + 1]].decode(errors="surrogateescape"))

            return np.fromiter((one(i) for i in range(n)), dtype=np.int32,
                               count=n)
        with self._lock:
            idx, ok = self._nat.intern_offsets(buf, off, skip_empty)
            self._sync_from_native()
        if not ok:
            self._raise_capacity()
        return idx

    def _sync_from_native(self) -> None:
        """Mirror tokens the native table assigned that Python hasn't seen.
        Caller holds self._lock."""
        n = len(self._nat)
        if len(self._to_token) < n:
            self.version += 1
        while len(self._to_token) < n:
            idx = len(self._to_token)
            token = self._nat.token_at(idx)
            self._to_token.append(token)
            self._to_index[token] = idx
            self._journal.append((idx, token))

    # -- replica journal (feeders/replica.py) -------------------------------

    def journal_len(self) -> int:
        with self._lock:
            return len(self._journal)

    def journal_since(self, n: int) -> tuple:
        """(journal_epoch, entries[n:]) — the delta a replica at journal
        position ``n`` needs to catch up. A replica whose remembered
        epoch differs must discard its table and resync from 0 (the
        authoritative interner was checkpoint-restored)."""
        with self._lock:
            return self.journal_epoch, list(self._journal[n:])

    def apply_delta(self, entries: Sequence[tuple], base: int) -> int:
        """Replay journal entries [base, base+len) onto THIS interner (a
        replica). Applies are by explicit index — append-with-gaps plus
        in-place gap fills reproduce the authoritative table exactly, so
        a replica's lookups return bit-identical indices. Raises on a
        positional mismatch or slot conflict (the replica must resync).
        Returns the new journal length."""
        from sitewhere_tpu.errors import ErrorCode, SiteWhereError
        with self._lock:
            if base != len(self._journal):
                raise SiteWhereError(
                    f"interner '{self.name}' delta base {base} != replica "
                    f"journal {len(self._journal)} (resync required)",
                    ErrorCode.GENERIC)
            mutated = False
            for idx, token in entries:
                idx = int(idx)
                if idx >= self.capacity:
                    self._raise_capacity()
                while len(self._to_token) <= idx:
                    self._to_token.append(None)
                    if self._nat is not None:
                        if self._nat.add_gap() != len(self._to_token) - 1:
                            self._mirror_sync_error(
                                -1, len(self._to_token) - 1)
                cur = self._to_token[idx]
                if token is None:
                    if cur is not None:
                        raise SiteWhereError(
                            f"interner '{self.name}' delta gap at occupied "
                            f"slot {idx} ({cur!r})", ErrorCode.GENERIC)
                elif cur is None:
                    self._to_token[idx] = token
                    self._to_index[token] = idx
                    if self._nat is not None:
                        if self._nat.set_at(idx, token) != 0:
                            self._mirror_sync_error(-1, idx)
                    if self.shard_classes > 1:
                        cls = idx % self.shard_classes
                        self._class_next[cls] = max(
                            self._class_next.get(cls, 0),
                            idx + self.shard_classes)
                    mutated = True
                elif cur != token:
                    raise SiteWhereError(
                        f"interner '{self.name}' delta conflict at slot "
                        f"{idx}: {cur!r} != {token!r} (resync required)",
                        ErrorCode.GENERIC)
                self._journal.append((idx, token))
            if mutated:
                self.version += 1
            return len(self._journal)

    def snapshot(self) -> List[Optional[str]]:
        with self._lock:
            return list(self._to_token)

    def restore(self, tokens: Sequence[Optional[str]]) -> None:
        """Rebuild from a snapshot (checkpoint restore)."""
        with self._lock:
            incoming = list(tokens) if tokens else [None]
            if not incoming or incoming[0] is not None:
                incoming.insert(0, None)
            # validate BEFORE mutating: raising mid-swap would leave
            # _to_token and _to_index answering from different snapshots
            if len(incoming) > self.capacity:
                self._raise_capacity()
            if self.shard_classes > 1:
                # a snapshot from a sequential (pre-congruent) or
                # different-S layout would silently break the ownership
                # contract (idx % S must equal crc32(token) % S for every
                # device) — refuse loudly instead of misrouting forever
                bad = [t for i, t in enumerate(incoming)
                       if t is not None and i > 0
                       and i % self.shard_classes != self._class_of(t)]
                if bad:
                    raise ValueError(
                        f"interner '{self.name}' snapshot is not "
                        f"congruent with {self.shard_classes} shard "
                        f"classes ({len(bad)} tokens at non-congruent "
                        f"indices, e.g. {bad[0]!r}); it was taken on a "
                        f"different shard layout — restore it onto the "
                        f"original layout, or re-provision")
            self._to_token = incoming
            self._to_index = {t: i for i, t in enumerate(self._to_token)
                              if t is not None}
            # the journal no longer describes the table: rebuild it as the
            # snapshot's slot assignments and bump journal_epoch so
            # replica feeders discard their copy and resync from 0
            self._journal = [(i, t) for i, t in
                             enumerate(self._to_token) if i > 0]
            self.journal_epoch += 1
            # congruent allocator: resume each class past its restored max
            self._class_next = {}
            if self.shard_classes > 1:
                for idx, token in enumerate(self._to_token):
                    if token is not None and idx > 0:
                        cls = idx % self.shard_classes
                        self._class_next[cls] = max(
                            self._class_next.get(cls, 0),
                            idx + self.shard_classes)
            self.version += 1
            if self._nat is not None:
                nat = _native()
                self._nat = nat.NativeInterner(self.capacity)
                for i, t in enumerate(self._to_token[1:], start=1):
                    # snapshots may hold None gaps (never valid mid-stream);
                    # keep native slot numbering aligned with a hash-less
                    # (un-lookupable) placeholder
                    if (self._nat.add(t) if t is not None
                            else self._nat.add_gap()) == -1:
                        from sitewhere_tpu.errors import (
                            ErrorCode, SiteWhereError)
                        raise SiteWhereError(
                            f"interner '{self.name}' native rebuild failed "
                            f"at slot {i}", ErrorCode.GENERIC)
