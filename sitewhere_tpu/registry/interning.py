"""Token interning: string identifiers -> dense int32 indices.

The hot path cannot touch Python strings: device tokens, measurement names,
alert types and tenant tokens are interned once on the host into dense indices
that index HBM lookup tensors. This replaces the reference's per-event
device-token -> Device gRPC lookup + Hazelcast near-cache
(InboundPayloadProcessingLogic.java:156, NearCacheManager.java:42).

The native C++ batch interner (sitewhere_tpu/native/host_runtime.cc)
accelerates bulk interning; this module transparently uses it when the shared
library is available (it is mirrored entry-for-entry from the Python side,
which stays authoritative for token_of/snapshot/restore) and falls back to
pure Python otherwise (SITEWHERE_TPU_NO_NATIVE=1 forces the fallback).
"""

from __future__ import annotations

import threading
from typing import Dict, Iterable, List, Optional, Sequence

import numpy as np


def _native():
    from sitewhere_tpu import native
    return native if native.available() else None


class TokenInterner:
    """Bidirectional string<->int32 mapping with a fixed capacity.

    Index 0 is reserved as UNKNOWN so that lookup tensors can keep a sentinel
    row and failed lookups stay in-band on device.
    """

    UNKNOWN = 0

    def __init__(self, capacity: int, name: str = "tokens"):
        if capacity < 2:
            raise ValueError("capacity must be >= 2")
        self.capacity = capacity
        self.name = name
        self._to_index: Dict[str, int] = {}
        self._to_token: List[Optional[str]] = [None]  # index 0 = UNKNOWN
        self._lock = threading.Lock()
        # Bumped on every mutation INCLUDING restore(): length alone is not
        # a valid cache key for snapshot consumers — a checkpoint restore
        # can swap same-length contents.
        self.version = 0
        nat = _native()
        self._nat = nat.NativeInterner(capacity) if nat else None

    def __len__(self) -> int:
        return len(self._to_token)

    def _raise_capacity(self):
        from sitewhere_tpu.errors import ErrorCode, SiteWhereError
        raise SiteWhereError(
            f"interner '{self.name}' capacity {self.capacity} exceeded",
            ErrorCode.CAPACITY_EXCEEDED)

    def intern(self, token: str) -> int:
        """Get-or-assign the index for a token."""
        idx = self._to_index.get(token)
        if idx is not None:
            return idx
        with self._lock:
            idx = self._to_index.get(token)
            if idx is not None:
                return idx
            idx = len(self._to_token)
            if idx >= self.capacity:
                self._raise_capacity()
            self._to_token.append(token)
            self._to_index[token] = idx
            self.version += 1
            if self._nat is not None:
                nidx = self._nat.add(token)
                if nidx != idx:
                    # survives `python -O`, unlike an assert: a silent
                    # native/Python desync would corrupt every later
                    # native-path lookup
                    from sitewhere_tpu.errors import ErrorCode, SiteWhereError
                    raise SiteWhereError(
                        f"interner '{self.name}' native mirror out of sync "
                        f"(native {nidx} != {idx})", ErrorCode.GENERIC)
            return idx

    def lookup(self, token: str) -> int:
        """Index for a token, UNKNOWN (0) if absent. Never allocates."""
        return self._to_index.get(token, self.UNKNOWN)

    def token_of(self, index: int) -> Optional[str]:
        if 0 < index < len(self._to_token):
            return self._to_token[index]
        return None

    def lookup_batch(self, tokens: Sequence[str]) -> np.ndarray:
        """Vectorized lookup of many tokens -> int32 array (no allocation)."""
        if self._nat is not None:
            return self._nat.lookup_batch(tokens)
        get = self._to_index.get
        return np.fromiter((get(t, 0) for t in tokens), dtype=np.int32,
                           count=len(tokens))

    def lookup_offsets(self, buf: bytes, off: np.ndarray) -> np.ndarray:
        """Lookup tokens given as a (joined bytes, offsets[n+1]) pair — the
        zero-copy contract of the native wire decoder (native/__init__.py
        DecodedColumns). Falls back through Python slicing."""
        if self._nat is not None:
            return self._nat.lookup_offsets(buf, off)
        get = self._to_index.get
        n = len(off) - 1
        return np.fromiter(
            (get(buf[off[i]:off[i + 1]].decode(errors="surrogateescape"), 0)
             for i in range(n)),
            dtype=np.int32, count=n)

    def intern_batch(self, tokens: Iterable[str]) -> np.ndarray:
        if self._nat is None:
            return np.fromiter((self.intern(t) for t in tokens),
                               dtype=np.int32)
        tokens = list(tokens)
        with self._lock:
            idx, ok = self._nat.intern_batch(tokens)
            self._sync_from_native()
        if not ok:
            self._raise_capacity()
        return idx

    def intern_offsets(self, buf: bytes, off: np.ndarray,
                       skip_empty: bool = False) -> np.ndarray:
        """intern_batch over a (joined bytes, offsets) pair. skip_empty maps
        zero-length tokens to UNKNOWN without interning (absent fields in
        decoded columns)."""
        if self._nat is None:
            n = len(off) - 1

            def one(i):
                if skip_empty and off[i + 1] == off[i]:
                    return 0
                return self.intern(
                    buf[off[i]:off[i + 1]].decode(errors="surrogateescape"))

            return np.fromiter((one(i) for i in range(n)), dtype=np.int32,
                               count=n)
        with self._lock:
            idx, ok = self._nat.intern_offsets(buf, off, skip_empty)
            self._sync_from_native()
        if not ok:
            self._raise_capacity()
        return idx

    def _sync_from_native(self) -> None:
        """Mirror tokens the native table assigned that Python hasn't seen.
        Caller holds self._lock."""
        n = len(self._nat)
        if len(self._to_token) < n:
            self.version += 1
        while len(self._to_token) < n:
            idx = len(self._to_token)
            token = self._nat.token_at(idx)
            self._to_token.append(token)
            self._to_index[token] = idx

    def snapshot(self) -> List[Optional[str]]:
        with self._lock:
            return list(self._to_token)

    def restore(self, tokens: Sequence[Optional[str]]) -> None:
        """Rebuild from a snapshot (checkpoint restore)."""
        with self._lock:
            incoming = list(tokens) if tokens else [None]
            if not incoming or incoming[0] is not None:
                incoming.insert(0, None)
            # validate BEFORE mutating: raising mid-swap would leave
            # _to_token and _to_index answering from different snapshots
            if len(incoming) > self.capacity:
                self._raise_capacity()
            self._to_token = incoming
            self._to_index = {t: i for i, t in enumerate(self._to_token)
                              if t is not None}
            self.version += 1
            if self._nat is not None:
                nat = _native()
                self._nat = nat.NativeInterner(self.capacity)
                for i, t in enumerate(self._to_token[1:], start=1):
                    # snapshots may hold None gaps (never valid mid-stream);
                    # keep native slot numbering aligned with an
                    # un-lookupable placeholder
                    if self._nat.add(t if t is not None else f"\x00gap{i}") \
                            == -1:
                        from sitewhere_tpu.errors import (
                            ErrorCode, SiteWhereError)
                        raise SiteWhereError(
                            f"interner '{self.name}' native rebuild failed "
                            f"at slot {i}", ErrorCode.GENERIC)
