"""Token interning: string identifiers -> dense int32 indices.

The hot path cannot touch Python strings: device tokens, measurement names,
alert types and tenant tokens are interned once on the host into dense indices
that index HBM lookup tensors. This replaces the reference's per-event
device-token -> Device gRPC lookup + Hazelcast near-cache
(InboundPayloadProcessingLogic.java:156, NearCacheManager.java:42).

A native C++ batch interner (sitewhere_tpu/native) accelerates bulk interning;
this module transparently uses it when the shared library is built and falls
back to pure Python otherwise.
"""

from __future__ import annotations

import threading
from typing import Dict, Iterable, List, Optional, Sequence

import numpy as np


class TokenInterner:
    """Bidirectional string<->int32 mapping with a fixed capacity.

    Index 0 is reserved as UNKNOWN so that lookup tensors can keep a sentinel
    row and failed lookups stay in-band on device.
    """

    UNKNOWN = 0

    def __init__(self, capacity: int, name: str = "tokens"):
        if capacity < 2:
            raise ValueError("capacity must be >= 2")
        self.capacity = capacity
        self.name = name
        self._to_index: Dict[str, int] = {}
        self._to_token: List[Optional[str]] = [None]  # index 0 = UNKNOWN
        self._lock = threading.Lock()

    def __len__(self) -> int:
        return len(self._to_token)

    def intern(self, token: str) -> int:
        """Get-or-assign the index for a token."""
        idx = self._to_index.get(token)
        if idx is not None:
            return idx
        with self._lock:
            idx = self._to_index.get(token)
            if idx is not None:
                return idx
            idx = len(self._to_token)
            if idx >= self.capacity:
                from sitewhere_tpu.errors import ErrorCode, SiteWhereError
                raise SiteWhereError(
                    f"interner '{self.name}' capacity {self.capacity} exceeded",
                    ErrorCode.CAPACITY_EXCEEDED)
            self._to_token.append(token)
            self._to_index[token] = idx
            return idx

    def lookup(self, token: str) -> int:
        """Index for a token, UNKNOWN (0) if absent. Never allocates."""
        return self._to_index.get(token, self.UNKNOWN)

    def token_of(self, index: int) -> Optional[str]:
        if 0 < index < len(self._to_token):
            return self._to_token[index]
        return None

    def lookup_batch(self, tokens: Sequence[str]) -> np.ndarray:
        """Vectorized lookup of many tokens -> int32 array (no allocation)."""
        get = self._to_index.get
        return np.fromiter((get(t, 0) for t in tokens), dtype=np.int32,
                           count=len(tokens))

    def intern_batch(self, tokens: Iterable[str]) -> np.ndarray:
        return np.fromiter((self.intern(t) for t in tokens), dtype=np.int32)

    def snapshot(self) -> List[Optional[str]]:
        with self._lock:
            return list(self._to_token)

    def restore(self, tokens: Sequence[Optional[str]]) -> None:
        """Rebuild from a snapshot (checkpoint restore)."""
        with self._lock:
            self._to_token = list(tokens) if tokens else [None]
            if not self._to_token or self._to_token[0] is not None:
                self._to_token.insert(0, None)
            self._to_index = {t: i for i, t in enumerate(self._to_token)
                              if t is not None}
