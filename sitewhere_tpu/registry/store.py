"""Registry CRUD store: the IDeviceManagement surface.

Reference: sitewhere-core-api spi/device/IDeviceManagement.java (device types,
commands, statuses, devices, assignments, areas/area types, zones, customers/
customer types, device groups, alarms — the 84-rpc device-management surface)
with pluggable persistence like the reference's mongodb/hbase choice
(service-device-management/persistence/*). Backends here: InMemoryStore
(dict-of-dicts) and SqliteStore (stdlib sqlite3, one row per entity, JSON
payload, token/id indexed) — write-through from the in-memory maps.

All reads the hot path needs are mirrored into RegistryTensors
(registry/tensors.py); this store is control-plane only.
"""

from __future__ import annotations

import dataclasses
import json
import sqlite3
import threading
from contextlib import contextmanager
from typing import Any, Callable, Dict, Generic, Iterable, List, Optional, Type, TypeVar

from sitewhere_tpu.errors import DuplicateTokenError, ErrorCode, NotFoundError, SiteWhereError
from sitewhere_tpu.model import (
    Area, AreaType, Customer, CustomerType, Device, DeviceAlarm, DeviceAssignment,
    DeviceAssignmentStatus, DeviceCommand, DeviceGroup, DeviceGroupElement,
    DeviceStatus, DeviceType, Zone,
)
from sitewhere_tpu.model.common import (
    SearchCriteria, SearchResults, new_id, now_ms, page)
from sitewhere_tpu.model.device import CommandParameter, DeviceElementMapping, ParameterType

T = TypeVar("T")


# ---------------------------------------------------------------------------
# (de)serialization helpers
# ---------------------------------------------------------------------------

def _entity_to_json(entity: Any) -> str:
    def default(obj: Any) -> Any:
        if dataclasses.is_dataclass(obj) and not isinstance(obj, type):
            return dataclasses.asdict(obj)
        if hasattr(obj, "value"):
            return obj.value
        raise TypeError(type(obj))
    return json.dumps(dataclasses.asdict(entity), default=default)


def _element_schema_from_dict(data: dict):
    """Recursive unit/slot tree decode (IDeviceElementSchema)."""
    from sitewhere_tpu.model.device import (
        DeviceElementSchema, DeviceSlot, DeviceUnit)

    def unit(d: dict, cls):
        return cls(
            name=d.get("name", ""), path=d.get("path", ""),
            device_slots=[DeviceSlot(name=s.get("name", ""),
                                     path=s.get("path", ""))
                          for s in d.get("device_slots", [])],
            device_units=[unit(u, DeviceUnit)
                          for u in d.get("device_units", [])])

    return unit(data, DeviceElementSchema)


_NESTED_FIELDS: Dict[Type, Dict[str, Callable[[dict], Any]]] = {
    Device: {"device_element_mappings": lambda d: DeviceElementMapping(**d)},
    DeviceCommand: {"parameters": lambda d: CommandParameter(
        name=d["name"], type=ParameterType(d["type"]), required=d["required"])},
    DeviceType: {"device_element_schema": _element_schema_from_dict},
}


def _entity_from_json(cls: Type[T], payload: str) -> T:
    data = json.loads(payload)
    fields = {f.name: f for f in dataclasses.fields(cls)}
    kwargs: Dict[str, Any] = {}
    nested = _NESTED_FIELDS.get(cls, {})
    for key, val in data.items():
        if key not in fields:
            continue
        ftype = fields[key].type
        if key in nested and isinstance(val, list):
            val = [nested[key](v) for v in val]
        elif key in nested and isinstance(val, dict):
            val = nested[key](val)
        elif isinstance(ftype, str):
            # enum-typed fields are stored by value
            resolved = _ENUM_TYPES.get(ftype)
            if resolved is not None and val is not None:
                val = resolved(val)
        kwargs[key] = val
    # Location lists come back as dicts
    if cls in (Area, Zone) and "bounds" in kwargs:
        from sitewhere_tpu.model.common import Location
        kwargs["bounds"] = [Location(**b) if isinstance(b, dict) else b
                            for b in kwargs["bounds"]]
    return cls(**kwargs)


from sitewhere_tpu.model.device import DeviceContainerPolicy
from sitewhere_tpu.model.device import DeviceAlarmState
from sitewhere_tpu.model.asset import AssetCategory
from sitewhere_tpu.model.batch import (
    BatchOperationStatus, ElementProcessingStatus)
from sitewhere_tpu.model.schedule import (
    ScheduledJobState, ScheduledJobType, TriggerType)

_ENUM_TYPES = {
    "DeviceAssignmentStatus": DeviceAssignmentStatus,
    "DeviceContainerPolicy": DeviceContainerPolicy,
    "DeviceAlarmState": DeviceAlarmState,
    "AssetCategory": AssetCategory,
    "BatchOperationStatus": BatchOperationStatus,
    "ElementProcessingStatus": ElementProcessingStatus,
    "TriggerType": TriggerType,
    "ScheduledJobType": ScheduledJobType,
    "ScheduledJobState": ScheduledJobState,
}


# ---------------------------------------------------------------------------
# storage backends
# ---------------------------------------------------------------------------

class InMemoryStore:
    """No-op durable backend: everything lives in DeviceManagement's maps."""

    def save(self, kind: str, entity_id: str, token: str, payload: str) -> None:
        pass

    def delete(self, kind: str, entity_id: str) -> None:
        pass

    def load_all(self, kind: str) -> Iterable[tuple]:
        return []

    def close(self) -> None:
        pass


class SqliteStore:
    """Durable backend on stdlib sqlite3 (reference analogue: the MongoDB
    persistence tier, MongoDeviceManagement)."""

    def __init__(self, path: str):
        self._conn = sqlite3.connect(path, check_same_thread=False)
        self._lock = threading.Lock()
        self._conn.execute(
            "CREATE TABLE IF NOT EXISTS entities ("
            " kind TEXT NOT NULL, id TEXT NOT NULL, token TEXT NOT NULL,"
            " payload TEXT NOT NULL, PRIMARY KEY (kind, id))")
        self._conn.execute(
            "CREATE INDEX IF NOT EXISTS idx_entities_token ON entities (kind, token)")
        self._conn.commit()

    def save(self, kind: str, entity_id: str, token: str, payload: str) -> None:
        with self._lock:
            self._conn.execute(
                "INSERT OR REPLACE INTO entities (kind, id, token, payload)"
                " VALUES (?, ?, ?, ?)", (kind, entity_id, token, payload))
            self._conn.commit()

    def delete(self, kind: str, entity_id: str) -> None:
        with self._lock:
            self._conn.execute("DELETE FROM entities WHERE kind=? AND id=?",
                               (kind, entity_id))
            self._conn.commit()

    def load_all(self, kind: str) -> Iterable[tuple]:
        with self._lock:
            rows = self._conn.execute(
                "SELECT id, token, payload FROM entities WHERE kind=?", (kind,)
            ).fetchall()
        return rows

    def close(self) -> None:
        with self._lock:
            self._conn.close()


# ---------------------------------------------------------------------------
# generic collection
# ---------------------------------------------------------------------------

class _Collection(Generic[T]):
    """Token+id indexed entity map with write-through persistence.

    ``replicating`` (a nullary callable) marks threads applying
    PEER-REPLICATED mutations (parallel/cluster.py RegistryGossip): a
    replicated create of an existing token is idempotent (at-least-once
    redelivery), a fresh replicated create marks its token so a later
    IDENTICAL local create merges into it instead of raising — cluster
    hosts provision the same world in any order, the way the reference's
    shared store makes creates race-free across processes
    (service-device-management persistence/mongodb/MongoDeviceManagement.java).
    """

    # identity + provenance fields a local create never overwrites when
    # claiming a replicated entity
    _MERGE_SKIP = frozenset({"id", "token", "created_date", "created_by"})

    def __init__(self, kind: str, cls: Type[T], store: Any,
                 not_found: ErrorCode,
                 replicating: Optional[Callable[[], bool]] = None,
                 on_mutation: Optional[Callable[[str, str, T], None]] = None):
        self.kind = kind
        self.cls = cls
        self.store = store
        self.not_found = not_found
        self.by_id: Dict[str, T] = {}
        self.by_token: Dict[str, T] = {}
        self._lock = threading.RLock()
        self._is_replicating = replicating or (lambda: False)
        # complete (kind, op, entity) feed across every mutation path —
        # what the cluster replicates; fired OUTSIDE the collection lock
        # (the callback may do network I/O)
        self._on_mutation = on_mutation
        # unclaimed-replica markers persist under a reserved kind (load_all
        # is always kind-filtered) so the claim contract survives the gang
        # restarts that rebuild every host from durable state
        self._replica_kind = f"{kind}#replica"
        self._replicated_tokens: set = {
            tok for _, tok, _ in store.load_all(self._replica_kind)}
        for _id, _token, payload in store.load_all(kind):
            entity = _entity_from_json(cls, payload)
            self.by_id[_id] = entity
            if _token:
                self.by_token[_token] = entity

    def _emit(self, op: str, entity: T) -> None:
        if self._on_mutation is not None:
            self._on_mutation(self.kind, op, entity)

    def create(self, entity: T) -> T:
        with self._lock:
            token = getattr(entity, "token", "")
            if not token:
                # reference behavior: token auto-assigned when not provided
                # (Persistence.java entityCreateLogic UUID fallback)
                token = new_id()
                entity.token = token
            existing = self.by_token.get(token)
            if existing is not None:
                if self._is_replicating():
                    return existing  # peer redelivery: idempotent
                merged = self._merge_replicated_locked(entity, existing)
                if merged is None:
                    raise DuplicateTokenError(
                        f"{self.kind} token '{token}' already exists")
            else:
                if self._is_replicating():
                    self._replicated_tokens.add(token)
                    self.store.save(self._replica_kind, token, token, "{}")
                self.by_id[entity.id] = entity
                self.by_token[token] = entity
                self.store.save(self.kind, entity.id, token,
                                _entity_to_json(entity))
        if existing is not None:
            self._emit("update", existing)  # claimed replica
            return existing
        self._emit("create", entity)
        return entity

    def claimable_replica(self, token: str) -> bool:
        """True when `token` names an unclaimed replicated entity a local
        create may merge into (callers peek before mutating their input)."""
        with self._lock:
            return token in self._replicated_tokens

    def merge_replicated(self, entity: T) -> Optional[T]:
        """Claim an unclaimed replica for a colliding local create; None
        when the existing entity is a genuine duplicate (or absent)."""
        with self._lock:
            existing = self.by_token.get(getattr(entity, "token", ""))
            if existing is None:
                return None
            merged = self._merge_replicated_locked(entity, existing)
        if merged is not None:
            self._emit("update", merged)
        return merged

    def _merge_replicated_locked(self, entity: T, existing: T) -> Optional[T]:
        token = getattr(entity, "token", "")
        if token not in self._replicated_tokens:
            return None
        # the replica keeps its (peer-adopted) id so references already
        # bound to it stay valid; the local create intent wins the fields
        self._discard_replica_locked(token)
        for field in dataclasses.fields(existing):
            if field.name not in self._MERGE_SKIP:
                setattr(existing, field.name, getattr(entity, field.name))
        # the claim is a NEW write: stamp past the replica's so the
        # emitted update wins last-writer-wins on every peer (without
        # this, it would tie the original create's stamp and the digest
        # could keep the pre-claim content on other hosts)
        existing.touch()
        self.store.save(self.kind, existing.id, token,
                        _entity_to_json(existing))
        return existing

    def _discard_replica_locked(self, token: str) -> None:
        if token in self._replicated_tokens:
            self._replicated_tokens.discard(token)
            self.store.delete(self._replica_kind, token)

    def get(self, entity_id: str) -> Optional[T]:
        return self.by_id.get(entity_id)

    def get_by_token(self, token: str) -> Optional[T]:
        return self.by_token.get(token)

    def require(self, entity_id: str) -> T:
        entity = self.by_id.get(entity_id)
        if entity is None:
            raise NotFoundError(f"{self.kind} id '{entity_id}' not found",
                                self.not_found)
        return entity

    def require_by_token(self, token: str) -> T:
        entity = self.by_token.get(token)
        if entity is None:
            raise NotFoundError(f"{self.kind} token '{token}' not found",
                                self.not_found)
        return entity

    def update(self, entity_id: str, updates: Dict[str, Any],
               username: str = "") -> T:
        with self._lock:
            entity = self.require(entity_id)
            old_token = getattr(entity, "token", "")
            # validate every key before mutating, so a bad update leaves the
            # entity untouched (and in-memory state consistent with storage)
            for key in updates:
                if not hasattr(entity, key):
                    raise SiteWhereError(f"unknown field '{key}' on {self.kind}")
            nested = _NESTED_FIELDS.get(self.cls, {})
            for key, val in updates.items():
                # REST updates carry nested structures as plain dicts:
                # coerce through the same decoders the load path uses so
                # in-memory state always holds typed objects (internal
                # callers pass dataclasses and skip this)
                if key in nested:
                    if isinstance(val, dict):
                        val = nested[key](val)
                    elif isinstance(val, list):
                        val = [nested[key](v) if isinstance(v, dict) else v
                               for v in val]
                setattr(entity, key, val)
            if not self._is_replicating():
                entity.touch(username)
            # else: a replicated update carries the WRITER's updated_date in
            # `updates` — adopting it (not re-stamping) is what makes
            # last-writer-wins comparisons agree on every host
            # Any update ends the claim window: a late local create of this
            # token must now raise on EVERY host (the claim-merge contract
            # covers boot-time provisioning races only, not clobbering an
            # entity that has since moved on — e.g. a released assignment)
            self._discard_replica_locked(old_token)
            new_token = getattr(entity, "token", "")
            if new_token != old_token:
                if new_token in self.by_token:
                    raise DuplicateTokenError(
                        f"{self.kind} token '{new_token}' already exists")
                self.by_token.pop(old_token, None)
                self._discard_replica_locked(old_token)
                if new_token:
                    self.by_token[new_token] = entity
            self.store.save(self.kind, entity.id, new_token, _entity_to_json(entity))
        self._emit("update", entity)
        return entity

    def delete(self, entity_id: str) -> T:
        with self._lock:
            entity = self.require(entity_id)
            del self.by_id[entity_id]
            token = getattr(entity, "token", "")
            if token:
                self.by_token.pop(token, None)
                self._discard_replica_locked(token)
            self.store.delete(self.kind, entity_id)
        self._emit("delete", entity)
        return entity

    def save(self, entity: T) -> None:
        """Persist in-place mutations."""
        token = getattr(entity, "token", "")
        with self._lock:
            self.store.save(self.kind, entity.id, token,
                            _entity_to_json(entity))
            self._discard_replica_locked(token)  # mutation ends the claim
        self._emit("update", entity)

    def persist_quietly(self, entity: T) -> None:
        """Persist WITHOUT firing listeners or ending a claim window —
        for metadata-only normalization (the gossip publish side stamps a
        resurrecting create past its tombstone AFTER create() already
        saved; the durable row must carry the same stamp or a restart
        rehydrates a weaker one and a redelivered delete wins here
        alone)."""
        with self._lock:
            self.store.save(self.kind, entity.id,
                            getattr(entity, "token", ""),
                            _entity_to_json(entity))

    def list(self, criteria: Optional[SearchCriteria] = None,
             where: Optional[Callable[[T], bool]] = None) -> SearchResults[T]:
        with self._lock:
            items = [e for e in self.by_id.values() if where is None or where(e)]
        items.sort(key=lambda e: getattr(e, "created_date", 0))
        return page(items, criteria or SearchCriteria(page_size=10 ** 9))

    def all(self) -> List[T]:
        with self._lock:
            return list(self.by_id.values())

    def __len__(self) -> int:
        return len(self.by_id)


# ---------------------------------------------------------------------------
# the IDeviceManagement surface
# ---------------------------------------------------------------------------

class DeviceManagement:
    """Full registry API (IDeviceManagement.java). One instance per tenant
    engine, like the reference's per-tenant store delegates.

    Mutations invalidate listeners (pipeline mirrors subscribe via
    `add_listener` — the reference's DeviceManagementTriggers Kafka
    notifications, collapsed to an in-proc callback)."""

    def __init__(self, store: Any = None, tenant_id: str = "default"):
        store = store or InMemoryStore()
        self.tenant_id = tenant_id
        self.store = store
        self._replication = threading.local()
        E = ErrorCode

        def coll(kind: str, cls: Type, err: ErrorCode) -> _Collection:
            return _Collection(kind, cls, store, err,
                               replicating=self._replicating,
                               on_mutation=self._emit_mutation)

        self.device_types: _Collection[DeviceType] = coll(
            "device_type", DeviceType, E.INVALID_DEVICE_TYPE_TOKEN)
        self.device_commands: _Collection[DeviceCommand] = coll(
            "device_command", DeviceCommand, E.INVALID_COMMAND_TOKEN)
        self.device_statuses: _Collection[DeviceStatus] = coll(
            "device_status", DeviceStatus, E.INVALID_DEVICE_TOKEN)
        self.devices: _Collection[Device] = coll(
            "device", Device, E.INVALID_DEVICE_TOKEN)
        self.assignments: _Collection[DeviceAssignment] = coll(
            "assignment", DeviceAssignment, E.INVALID_ASSIGNMENT_TOKEN)
        self.area_types: _Collection[AreaType] = coll(
            "area_type", AreaType, E.INVALID_AREA_TOKEN)
        self.areas: _Collection[Area] = coll(
            "area", Area, E.INVALID_AREA_TOKEN)
        self.zones: _Collection[Zone] = coll(
            "zone", Zone, E.INVALID_ZONE_TOKEN)
        self.customer_types: _Collection[CustomerType] = coll(
            "customer_type", CustomerType, E.INVALID_CUSTOMER_TOKEN)
        self.customers: _Collection[Customer] = coll(
            "customer", Customer, E.INVALID_CUSTOMER_TOKEN)
        self.device_groups: _Collection[DeviceGroup] = coll(
            "device_group", DeviceGroup, E.INVALID_GROUP_TOKEN)
        self.group_elements: _Collection[DeviceGroupElement] = coll(
            "group_element", DeviceGroupElement, E.INVALID_GROUP_TOKEN)
        self.alarms: _Collection[DeviceAlarm] = coll(
            "alarm", DeviceAlarm, E.INVALID_DEVICE_TOKEN)
        self._listeners: List[Callable[[str, Any], None]] = []
        self._mutation_listeners: List[Callable[[str, str, Any], None]] = []
        # serializes composite-mapping create/delete: the validate + two-
        # update sequence must not interleave across threads (two
        # concurrent creates could both pass the unmapped/unparented
        # checks and double-map a child or a slot path)
        self._mapping_lock = threading.Lock()
        # device_id -> active assignment (the hot lookup of
        # InboundPayloadProcessingLogic.validateAssignment:179)
        self._active_assignment: Dict[str, DeviceAssignment] = {}
        for assignment in self.assignments.all():
            if assignment.status == DeviceAssignmentStatus.ACTIVE:
                self._active_assignment[assignment.device_id] = assignment

    # -- replication context --------------------------------------------------

    def _replicating(self) -> bool:
        return getattr(self._replication, "active", False)

    @contextmanager
    def replication(self):
        """Mark this thread as applying peer-replicated mutations
        (parallel/cluster.py RegistryGossip): creates become idempotent
        get-or-create and their entities stay claimable by a later
        identical local create, so cluster hosts can provision the same
        world in any order relative to gossip arrival. Reentrant: nested
        contexts restore the prior flag, not False."""
        prev = getattr(self._replication, "active", False)
        self._replication.active = True
        try:
            yield
        finally:
            self._replication.active = prev

    # -- change notification --------------------------------------------------

    def add_listener(self, callback: Callable[[str, Any], None]) -> None:
        self._listeners.append(callback)

    def _notify(self, kind: str, entity: Any) -> None:
        for callback in list(self._listeners):
            callback(kind, entity)

    def add_mutation_listener(
            self, callback: Callable[[str, str, Any], None]) -> None:
        """Subscribe to the COMPLETE (kind, op, entity) mutation feed —
        every create/update/delete on every collection, fired from the
        collections themselves so no wrapper can forget to notify. This is
        what cluster replication rides (parallel/cluster.py RegistryGossip,
        the role of the reference's DeviceManagementTriggers Kafka
        notifications, sitewhere-microservice DeviceManagementTriggers)."""
        self._mutation_listeners.append(callback)

    def _emit_mutation(self, kind: str, op: str, entity: Any) -> None:
        for callback in list(self._mutation_listeners):
            callback(kind, op, entity)

    # -- kind dispatch (replication appliers) ----------------------------------

    def collection_of(self, kind: str) -> _Collection:
        return {
            "device_type": self.device_types,
            "device_command": self.device_commands,
            "device_status": self.device_statuses,
            "device": self.devices,
            "assignment": self.assignments,
            "area_type": self.area_types,
            "area": self.areas,
            "zone": self.zones,
            "customer_type": self.customer_types,
            "customer": self.customers,
            "device_group": self.device_groups,
            "group_element": self.group_elements,
            "alarm": self.alarms,
        }[kind]

    def create_by_kind(self, kind: str, entity: Any) -> Any:
        """Create through the kind's wrapper (side effects: active-
        assignment index, mirror notifications) — the uniform entry the
        replication applier uses for every entity kind."""
        wrapper = {
            "device_type": self.create_device_type,
            "device_command": self.create_device_command,
            "device_status": self.create_device_status,
            "device": self.create_device,
            "assignment": self.create_device_assignment,
            "area_type": self.create_area_type,
            "area": self.create_area,
            "zone": self.create_zone,
            "customer_type": self.create_customer_type,
            "customer": self.create_customer,
            "device_group": self.create_device_group,
            "alarm": self.create_device_alarm,
        }.get(kind)
        if wrapper is not None:
            return wrapper(entity)
        return self.collection_of(kind).create(entity)

    def update_by_kind(self, kind: str, token: str, updates: Dict) -> Any:
        """Update by token through the kind's wrapper where one exists
        (mirror notifications), the collection otherwise."""
        wrapper = {
            "device_type": self.update_device_type,
            "device": self.update_device,
            "zone": self.update_zone,
        }.get(kind)
        if wrapper is not None:
            return wrapper(token, updates)
        collection = self.collection_of(kind)
        result = collection.update(collection.require_by_token(token).id,
                                   updates)
        self._notify(kind, result)
        return result

    def delete_by_kind(self, kind: str, token: str) -> Any:
        """Delete by token through the kind's wrapper where one exists
        (referential validation + index upkeep), the collection otherwise."""
        wrapper = {
            "device_type": self.delete_device_type,
            "device": self.delete_device,
            "zone": self.delete_zone,
            "assignment": self.delete_device_assignment,
        }.get(kind)
        if wrapper is not None:
            return wrapper(token)
        collection = self.collection_of(kind)
        result = collection.delete(collection.require_by_token(token).id)
        self._notify(kind, result)
        return result

    # -- device types / commands / statuses -----------------------------------

    def create_device_type(self, device_type: DeviceType) -> DeviceType:
        result = self.device_types.create(device_type)
        self._notify("device_type", result)
        return result

    def get_device_type(self, device_type_id: str) -> Optional[DeviceType]:
        return self.device_types.get(device_type_id)

    def get_device_type_by_token(self, token: str) -> DeviceType:
        return self.device_types.require_by_token(token)

    def update_device_type(self, token: str, updates: Dict) -> DeviceType:
        entity = self.device_types.require_by_token(token)
        result = self.device_types.update(entity.id, updates)
        self._notify("device_type", result)
        return result

    def delete_device_type(self, token: str) -> DeviceType:
        entity = self.device_types.require_by_token(token)
        in_use = any(d.device_type_id == entity.id for d in self.devices.all())
        if in_use:
            raise SiteWhereError("device type in use",
                                 ErrorCode.DEVICE_TYPE_IN_USE)
        result = self.device_types.delete(entity.id)
        self._notify("device_type", result)
        return result

    def list_device_types(self, criteria: Optional[SearchCriteria] = None
                          ) -> SearchResults[DeviceType]:
        return self.device_types.list(criteria)

    def create_device_command(self, command: DeviceCommand) -> DeviceCommand:
        return self.device_commands.create(command)

    def get_device_command_by_token(self, token: str) -> DeviceCommand:
        return self.device_commands.require_by_token(token)

    def list_device_commands(self, device_type_token: Optional[str] = None
                             ) -> SearchResults[DeviceCommand]:
        type_id = (self.device_types.require_by_token(device_type_token).id
                   if device_type_token else None)
        return self.device_commands.list(
            where=(lambda c: c.device_type_id == type_id) if type_id else None)

    def create_device_status(self, status: DeviceStatus) -> DeviceStatus:
        return self.device_statuses.create(status)

    def list_device_statuses(self, device_type_token: Optional[str] = None
                             ) -> SearchResults[DeviceStatus]:
        type_id = (self.device_types.require_by_token(device_type_token).id
                   if device_type_token else None)
        return self.device_statuses.list(
            where=(lambda s: s.device_type_id == type_id) if type_id else None)

    # -- devices ---------------------------------------------------------------

    def create_device(self, device: Device) -> Device:
        if device.device_type_id:
            self.device_types.require(device.device_type_id)
        result = self.devices.create(device)
        self._notify("device", result)
        return result

    def get_device(self, device_id: str) -> Device:
        return self.devices.require(device_id)

    def get_device_by_token(self, token: str) -> Optional[Device]:
        return self.devices.get_by_token(token)

    def update_device(self, token: str, updates: Dict) -> Device:
        entity = self.devices.require_by_token(token)
        result = self.devices.update(entity.id, updates)
        self._notify("device", result)
        return result

    def delete_device(self, token: str) -> Device:
        entity = self.devices.require_by_token(token)
        active = self._active_assignment.get(entity.id)
        if active is not None:
            raise SiteWhereError("device has an active assignment",
                                 ErrorCode.DEVICE_ALREADY_ASSIGNED)
        # deleting a composite gateway releases its children (clear the
        # parent backreferences so nesting lookups can't dangle); a
        # mapped CHILD must be unmapped first (the parent still lists
        # it). A DANGLING backreference — live parent gone or no longer
        # listing the mapping (replicated tombstone orderings) — must
        # not block deletion forever.
        if entity.parent_device_id:
            parent = self.devices.get(entity.parent_device_id)
            if parent is not None and any(
                    m.device_token == token
                    for m in parent.device_element_mappings):
                raise SiteWhereError(
                    f"device '{token}' is mapped into a composite "
                    f"parent; delete the mapping first", ErrorCode.GENERIC,
                    http_status=409)
        for mapping in entity.device_element_mappings:
            child = self.devices.get_by_token(mapping.device_token)
            if child is not None and child.parent_device_id == entity.id:
                self.update_device(child.token, {"parent_device_id": ""})
        result = self.devices.delete(entity.id)
        self._notify("device", result)
        return result

    def list_devices(self, criteria: Optional[SearchCriteria] = None,
                     device_type_token: Optional[str] = None,
                     assigned: Optional[bool] = None) -> SearchResults[Device]:
        type_id = (self.device_types.require_by_token(device_type_token).id
                   if device_type_token else None)

        def where(d: Device) -> bool:
            if type_id and d.device_type_id != type_id:
                return False
            if assigned is not None:
                if assigned != (d.id in self._active_assignment):
                    return False
            return True

        return self.devices.list(criteria, where)

    # -- composite-device element mappings -------------------------------------

    def create_device_element_mapping(self, device_token: str,
                                      mapping: "DeviceElementMapping"
                                      ) -> Device:
        """Map a child device into a slot of a composite parent
        (DeviceManagementPersistence.deviceElementMappingCreateLogic:657):
        the child must exist and be unparented, the path must resolve to a
        DeviceSlot in the parent TYPE's element schema, and the path must
        be unmapped. Sets the child's parent backreference; both updates
        ride the normal mutation feed (replicated, durable).

        The whole validate + two-update sequence runs under the registry
        mapping mutex (two concurrent creates must not both pass the
        unmapped checks), and a failure of the parent-list update rolls
        the child's parent backreference back — no half-applied mapping
        survives."""
        from sitewhere_tpu.model.device import find_device_slot

        with self._mapping_lock:
            return self._create_device_element_mapping_locked(
                device_token, mapping, find_device_slot)

    def _create_device_element_mapping_locked(self, device_token: str,
                                              mapping, find_device_slot
                                              ) -> Device:
        device = self.devices.require_by_token(device_token)
        mapped = self.devices.get_by_token(mapping.device_token)
        if mapped is None:
            raise NotFoundError(
                f"mapping references unknown device "
                f"'{mapping.device_token}'", ErrorCode.INVALID_DEVICE_TOKEN)
        if mapped.parent_device_id:
            raise SiteWhereError(
                f"device '{mapped.token}' is already mapped into another "
                f"composite device", ErrorCode.GENERIC, http_status=409)
        # no self-mapping and no cycles: the child may not appear on the
        # gateway's own parent chain (A->A, or A->B when B is already an
        # ancestor of A, would make nesting resolution circular)
        ancestor = device
        while ancestor is not None:
            if ancestor.id == mapped.id:
                raise SiteWhereError(
                    f"mapping '{mapped.token}' into '{device.token}' "
                    f"would create a composite cycle", ErrorCode.GENERIC,
                    http_status=409)
            ancestor = (self.devices.get(ancestor.parent_device_id)
                        if ancestor.parent_device_id else None)
        dtype = self.device_types.get(device.device_type_id)
        slot = find_device_slot(
            dtype.device_element_schema if dtype else None,
            mapping.device_element_schema_path)
        if slot is None:
            raise SiteWhereError(
                f"path '{mapping.device_element_schema_path}' does not "
                f"name a device slot in type "
                f"'{dtype.token if dtype else '?'}'s element schema",
                ErrorCode.GENERIC, http_status=400)
        existing = device.device_element_mappings
        if any(m.device_element_schema_path ==
               mapping.device_element_schema_path for m in existing):
            raise SiteWhereError(
                f"path '{mapping.device_element_schema_path}' already has "
                f"a device mapped", ErrorCode.DUPLICATE_TOKEN,
                http_status=409)
        # parent backreference first (the reference's order, :688-694)
        self.update_device(mapped.token, {"parent_device_id": device.id})
        try:
            return self.update_device(device_token, {
                "device_element_mappings": existing + [mapping]})
        except BaseException:
            # second update failed (listener raise, replicated-tombstone
            # race, ...): un-parent the child so the failed mapping
            # leaves no dangling backreference
            try:
                self.update_device(mapped.token, {"parent_device_id": ""})
            except Exception:
                pass  # child row vanished mid-rollback: nothing dangles
            raise

    def delete_device_element_mapping(self, device_token: str,
                                      path: str) -> Device:
        """Remove the mapping at `path` and clear the child's parent
        backreference (deviceElementMappingDeleteLogic:709). Serialized
        under the same mapping mutex as create — a delete interleaving
        with a concurrent create's validate window could otherwise free a
        slot both see as mapped/unmapped at once."""
        with self._mapping_lock:
            device = self.devices.require_by_token(device_token)
            match = next((m for m in device.device_element_mappings
                          if m.device_element_schema_path == path), None)
            if match is None:
                raise NotFoundError(
                    f"no device mapping at path '{path}'", ErrorCode.GENERIC)
            mapped = self.devices.get_by_token(match.device_token)
            if mapped is not None and mapped.parent_device_id == device.id:
                self.update_device(mapped.token, {"parent_device_id": ""})
            remaining = [m for m in device.device_element_mappings
                         if m.device_element_schema_path != path]
            return self.update_device(device_token, {
                "device_element_mappings": remaining})

    # -- assignments -----------------------------------------------------------

    def create_device_assignment(self, assignment: DeviceAssignment
                                 ) -> DeviceAssignment:
        device = self.devices.require(assignment.device_id)
        if not assignment.device_type_id:
            assignment.device_type_id = device.device_type_id
        active = self._active_assignment.get(device.id)
        if active is not None:
            token = getattr(assignment, "token", "")
            if active.token == token:
                if self._replicating():
                    return active  # peer redelivery: idempotent
                # the replication applier may have installed this very
                # assignment before the operator's own provisioning ran:
                # claim it instead of refusing (peek first — the genuine-
                # duplicate path must raise without mutating the input)
                if self.assignments.claimable_replica(token):
                    assignment.status = DeviceAssignmentStatus.ACTIVE
                    assignment.active_date = active.active_date
                    merged = self.assignments.merge_replicated(assignment)
                    if merged is not None:
                        self._notify("assignment", merged)
                        return merged
            raise SiteWhereError(
                f"device '{device.token}' already has an active assignment",
                ErrorCode.DEVICE_ALREADY_ASSIGNED)
        assignment.status = DeviceAssignmentStatus.ACTIVE
        # a replicated create carries the CREATING host's activation time —
        # keep it so replicas agree on active_date
        if not (self._replicating() and assignment.active_date):
            assignment.active_date = now_ms()
        result = self.assignments.create(assignment)
        self._active_assignment[device.id] = result
        self._notify("assignment", result)
        return result

    def get_device_assignment(self, assignment_id: str) -> DeviceAssignment:
        return self.assignments.require(assignment_id)

    def get_device_assignment_by_token(self, token: str) -> Optional[DeviceAssignment]:
        return self.assignments.get_by_token(token)

    def get_active_assignment(self, device_id: str) -> Optional[DeviceAssignment]:
        """The per-event validation lookup (hot in the reference, tensorized
        here via RegistryTensors)."""
        return self._active_assignment.get(device_id)

    def release_device_assignment(self, token: str) -> DeviceAssignment:
        assignment = self.assignments.require_by_token(token)
        assignment.status = DeviceAssignmentStatus.RELEASED
        assignment.released_date = now_ms()
        assignment.touch()
        self.assignments.save(assignment)
        if self._active_assignment.get(assignment.device_id) is assignment:
            del self._active_assignment[assignment.device_id]
        self._notify("assignment", assignment)
        return assignment

    def reconcile_active_assignment(self, assignment: DeviceAssignment) -> None:
        """Re-derive the active-assignment index entry for one assignment
        after a replicated field update (the replication applier mutates
        status through the generic diff path, not the lifecycle methods)."""
        if assignment.status == DeviceAssignmentStatus.ACTIVE:
            self._active_assignment[assignment.device_id] = assignment
        elif self._active_assignment.get(assignment.device_id) is assignment:
            del self._active_assignment[assignment.device_id]

    def delete_device_assignment(self, token: str) -> DeviceAssignment:
        assignment = self.assignments.require_by_token(token)
        result = self.assignments.delete(assignment.id)
        if self._active_assignment.get(assignment.device_id) is assignment:
            del self._active_assignment[assignment.device_id]
        self._notify("assignment", result)
        return result

    def mark_assignment_missing(self, assignment_id: str) -> DeviceAssignment:
        assignment = self.assignments.require(assignment_id)
        assignment.status = DeviceAssignmentStatus.MISSING
        assignment.touch()
        self.assignments.save(assignment)
        self._notify("assignment", assignment)
        return assignment

    def list_assignments(self, criteria: Optional[SearchCriteria] = None,
                         device_token: Optional[str] = None,
                         customer_token: Optional[str] = None,
                         area_token: Optional[str] = None
                         ) -> SearchResults[DeviceAssignment]:
        device_id = (self.devices.require_by_token(device_token).id
                     if device_token else None)
        customer_id = (self.customers.require_by_token(customer_token).id
                       if customer_token else None)
        area_id = (self.areas.require_by_token(area_token).id
                   if area_token else None)

        def where(a: DeviceAssignment) -> bool:
            if device_id and a.device_id != device_id:
                return False
            if customer_id and a.customer_id != customer_id:
                return False
            if area_id and a.area_id != area_id:
                return False
            return True

        return self.assignments.list(criteria, where)

    # -- areas / zones / customers --------------------------------------------

    def create_area_type(self, area_type: AreaType) -> AreaType:
        return self.area_types.create(area_type)

    def create_area(self, area: Area) -> Area:
        result = self.areas.create(area)
        self._notify("area", result)
        return result

    def get_area_by_token(self, token: str) -> Area:
        return self.areas.require_by_token(token)

    def list_areas(self, criteria: Optional[SearchCriteria] = None
                   ) -> SearchResults[Area]:
        return self.areas.list(criteria)

    def create_zone(self, zone: Zone) -> Zone:
        result = self.zones.create(zone)
        self._notify("zone", result)
        return result

    def get_zone_by_token(self, token: str) -> Zone:
        return self.zones.require_by_token(token)

    def update_zone(self, token: str, updates: Dict) -> Zone:
        entity = self.zones.require_by_token(token)
        result = self.zones.update(entity.id, updates)
        self._notify("zone", result)
        return result

    def delete_zone(self, token: str) -> Zone:
        entity = self.zones.require_by_token(token)
        result = self.zones.delete(entity.id)
        self._notify("zone", result)
        return result

    def list_zones(self, area_token: Optional[str] = None,
                   criteria: Optional[SearchCriteria] = None
                   ) -> SearchResults[Zone]:
        area_id = self.areas.require_by_token(area_token).id if area_token else None
        return self.zones.list(
            criteria, (lambda z: z.area_id == area_id) if area_id else None)

    def create_customer_type(self, customer_type: CustomerType) -> CustomerType:
        return self.customer_types.create(customer_type)

    def create_customer(self, customer: Customer) -> Customer:
        return self.customers.create(customer)

    def get_customer_by_token(self, token: str) -> Customer:
        return self.customers.require_by_token(token)

    def list_customers(self, criteria: Optional[SearchCriteria] = None
                       ) -> SearchResults[Customer]:
        return self.customers.list(criteria)

    # -- device groups ---------------------------------------------------------

    def create_device_group(self, group: DeviceGroup) -> DeviceGroup:
        return self.device_groups.create(group)

    def get_device_group_by_token(self, token: str) -> DeviceGroup:
        return self.device_groups.require_by_token(token)

    def add_device_group_elements(self, group_token: str,
                                  elements: List[DeviceGroupElement]
                                  ) -> List[DeviceGroupElement]:
        group = self.device_groups.require_by_token(group_token)
        out = []
        for element in elements:
            element.group_id = group.id
            out.append(self.group_elements.create(element))
        return out

    def list_device_group_elements(self, group_token: str
                                   ) -> SearchResults[DeviceGroupElement]:
        group = self.device_groups.require_by_token(group_token)
        return self.group_elements.list(where=lambda e: e.group_id == group.id)

    def expand_group_devices(self, group_token: str) -> List[Device]:
        """Recursively resolve a group to its device list (used by batch ops)."""
        seen_groups: set = set()
        devices: Dict[str, Device] = {}

        def walk(token: str) -> None:
            group = self.device_groups.require_by_token(token)
            if group.id in seen_groups:
                return
            seen_groups.add(group.id)
            for element in self.group_elements.all():
                if element.group_id != group.id:
                    continue
                if element.device_id:
                    device = self.devices.get(element.device_id)
                    if device:
                        devices[device.id] = device
                elif element.nested_group_id:
                    nested = self.device_groups.get(element.nested_group_id)
                    if nested:
                        walk(nested.token)

        walk(group_token)
        return list(devices.values())

    # -- alarms ----------------------------------------------------------------

    def create_device_alarm(self, alarm: DeviceAlarm) -> DeviceAlarm:
        alarm.triggered_date = alarm.triggered_date or now_ms()
        return self.alarms.create(alarm)

    def list_device_alarms(self, device_token: Optional[str] = None,
                           criteria: Optional[SearchCriteria] = None
                           ) -> SearchResults[DeviceAlarm]:
        device_id = (self.devices.require_by_token(device_token).id
                     if device_token else None)
        return self.alarms.list(
            criteria, (lambda a: a.device_id == device_id) if device_id else None)

    def get_device_alarm(self, alarm_id: str) -> Optional[DeviceAlarm]:
        return self.alarms.get(alarm_id)

    def update_device_alarm(self, alarm_id: str,
                            updates: Dict) -> DeviceAlarm:
        """State transitions stamp their dates (the reference's
        DeviceAlarmMarshalHelper behavior for acknowledge/resolve)."""
        from sitewhere_tpu.model.device import DeviceAlarmState

        updates = dict(updates)
        state = updates.get("state")
        if state is not None and not isinstance(state, DeviceAlarmState):
            updates["state"] = state = DeviceAlarmState(state)
        if state == DeviceAlarmState.ACKNOWLEDGED:
            updates.setdefault("acknowledged_date", now_ms())
        elif state == DeviceAlarmState.RESOLVED:
            updates.setdefault("resolved_date", now_ms())
        return self.alarms.update(alarm_id, updates)

    def delete_device_alarm(self, alarm_id: str) -> DeviceAlarm:
        return self.alarms.delete(alarm_id)
