"""Device-indexed registry lookup tensors: the HBM mirror of the registry.

This is the TPU replacement for hot-path gRPC lookup #1 (SURVEY.md §3.2):
instead of `getDeviceByToken` + assignment validation per event over gRPC +
Hazelcast near-cache, the registry is mirrored into fixed-capacity int32/f32
arrays indexed by interned device index. Validation inside the fused pipeline
step is then a gather + compare.

Columns (capacity D = max_devices, index = TokenInterner index, row 0 =
UNKNOWN sentinel):
  assignment_status  int32[D]  0 = unregistered/no active assignment,
                               else DeviceAssignmentStatus value
  tenant_idx         int32[D]  interned tenant of the device's assignment
  area_idx           int32[D]  interned area token of the active assignment
  device_type_idx    int32[D]  interned device type token
  assignment_idx     int32[D]  interned assignment token (for mapping back)

Zone geometry for the geofence kernel lives here too (compiled from
Zone.bounds, reference analogue: ZoneTestRuleProcessor's cached JTS polygons,
ZoneTestRuleProcessor.java:72-83):
  zone_vertices f32[Z, V, 2]  (lat, lon), padded by repeating the last vertex
  zone_nvert    int32[Z]      actual vertex count
  zone_tenant   int32[Z], zone_area int32[Z], zone_active bool[Z]
"""

from __future__ import annotations

import threading
from dataclasses import dataclass
from typing import Dict, Optional

import numpy as np

from sitewhere_tpu.model import DeviceAssignmentStatus, Zone
from sitewhere_tpu.registry.interning import TokenInterner
from sitewhere_tpu.registry.store import DeviceManagement


@dataclass
class RegistrySnapshot:
    """Immutable numpy view handed to the pipeline step. All int32/f32."""

    assignment_status: np.ndarray
    tenant_idx: np.ndarray
    area_idx: np.ndarray
    device_type_idx: np.ndarray
    assignment_idx: np.ndarray
    zone_vertices: np.ndarray
    zone_nvert: np.ndarray
    zone_tenant: np.ndarray
    zone_area: np.ndarray
    zone_active: np.ndarray
    version: int


class RegistryTensors:
    """Maintains the tensor mirror of one-or-more tenants' DeviceManagement.

    Subscribes to registry mutations and rebuilds incrementally (device-level
    changes touch single rows; zone changes recompile the zone table).
    Thread-safe: `snapshot()` returns a consistent frozen view with a version
    counter so the pipeline can detect staleness cheaply.
    """

    def __init__(self, max_devices: int, max_zones: int, max_zone_vertices: int,
                 device_interner: Optional[TokenInterner] = None,
                 tenant_interner: Optional[TokenInterner] = None,
                 shard_classes: int = 1):
        # shard_classes = the device mesh size: device indices allocate
        # within crc32(token) % S congruence classes so shard ownership
        # (idx % S) depends only on the token, never on creation order —
        # cluster hosts provisioned in different orders still agree on
        # which host owns which device (registry/interning.py)
        self.devices = device_interner or TokenInterner(
            max_devices, "devices", shard_classes=shard_classes)
        self.tenants = tenant_interner or TokenInterner(64, "tenants")
        self.areas = TokenInterner(4096, "areas")
        self.device_types = TokenInterner(4096, "device_types")
        self.assignments = TokenInterner(max_devices, "assignments")
        self.zones_interner = TokenInterner(max_zones + 1, "zones")
        self.max_zones = max_zones
        self.max_zone_vertices = max_zone_vertices

        D = max_devices
        self._assignment_status = np.zeros(D, np.int32)
        self._tenant_idx = np.zeros(D, np.int32)
        self._area_idx = np.zeros(D, np.int32)
        self._device_type_idx = np.zeros(D, np.int32)
        self._assignment_idx = np.zeros(D, np.int32)

        Z, V = max_zones, max_zone_vertices
        self._zone_vertices = np.zeros((Z, V, 2), np.float32)
        self._zone_nvert = np.zeros(Z, np.int32)
        self._zone_tenant = np.zeros(Z, np.int32)
        self._zone_area = np.zeros(Z, np.int32)
        self._zone_active = np.zeros(Z, bool)

        self._version = 0
        self._lock = threading.Lock()
        self._managements: Dict[str, DeviceManagement] = {}
        # device entity id -> interned token index, to retire stale rows when
        # a device's token is renamed (the old token's row must stop
        # validating events)
        self._idx_by_device_id: Dict[str, int] = {}

    # -- wiring ---------------------------------------------------------------

    def attach(self, management: DeviceManagement, tenant_token: str) -> None:
        """Mirror a tenant's registry; subscribes to its mutations."""
        tenant_idx = self.tenants.intern(tenant_token)
        self._managements[tenant_token] = management
        management.add_listener(
            lambda kind, entity: self._on_change(management, tenant_idx, kind, entity))
        self._full_rebuild(management, tenant_idx)

    def _on_change(self, management: DeviceManagement, tenant_idx: int,
                   kind: str, entity) -> None:
        if kind in ("device", "assignment"):
            with self._lock:
                if kind == "assignment":
                    device = management.devices.get(entity.device_id)
                else:
                    device = entity if entity.id in management.devices.by_id else None
                    if device is None:  # deleted device
                        idx = self.devices.lookup(entity.token)
                        if idx:
                            self._assignment_status[idx] = 0
                        self._idx_by_device_id.pop(entity.id, None)
                        self._version += 1
                        return
                if device is not None:
                    self._mirror_device(management, tenant_idx, device)
                self._version += 1
        elif kind == "zone":
            with self._lock:
                self._mirror_zone(tenant_idx, entity,
                                  active=entity.id in management.zones.by_id)
                self._version += 1

    # -- mirroring ------------------------------------------------------------

    def _mirror_device(self, management: DeviceManagement, tenant_idx: int,
                       device) -> None:
        idx = self.devices.intern(device.token)
        prior = self._idx_by_device_id.get(device.id)
        if prior is not None and prior != idx:
            # token renamed: the retired token's row must stop validating
            self._assignment_status[prior] = 0
            self._assignment_idx[prior] = 0
        self._idx_by_device_id[device.id] = idx
        assignment = management.get_active_assignment(device.id)
        if assignment is None:
            self._assignment_status[idx] = 0
            self._tenant_idx[idx] = tenant_idx
            self._assignment_idx[idx] = 0
            return
        self._assignment_status[idx] = int(assignment.status)
        self._tenant_idx[idx] = tenant_idx
        area = management.areas.get(assignment.area_id)
        self._area_idx[idx] = self.areas.intern(area.token) if area else 0
        dtype = management.device_types.get(device.device_type_id)
        self._device_type_idx[idx] = (
            self.device_types.intern(dtype.token) if dtype else 0)
        self._assignment_idx[idx] = self.assignments.intern(assignment.token)

    def _mirror_zone(self, tenant_idx: int, zone: Zone, active: bool = True) -> None:
        zidx = self.zones_interner.intern(zone.token) - 1  # row 0 of table = zone idx 1
        if not (0 <= zidx < self.max_zones):
            return
        verts = [(b.latitude, b.longitude) for b in zone.bounds]
        n = min(len(verts), self.max_zone_vertices)
        self._zone_active[zidx] = active and n >= 3
        self._zone_nvert[zidx] = n
        self._zone_tenant[zidx] = tenant_idx
        if verts:
            arr = np.asarray(verts[:n], np.float32)
            self._zone_vertices[zidx, :n] = arr
            # pad by repeating last vertex: degenerate edges never toggle the
            # crossing-number parity in the geofence kernel
            self._zone_vertices[zidx, n:] = arr[-1]
        management = self._managements.get(self.tenants.token_of(tenant_idx) or "")
        if management is not None:
            area = management.areas.get(zone.area_id)
            self._zone_area[zidx] = self.areas.intern(area.token) if area else 0

    def rebuild(self) -> None:
        """Re-mirror every attached tenant's registry. Needed after a
        checkpoint restore replaces the device interner assignment (the
        elastic cross-layout path re-interns tokens in snapshot order, so
        rows built at attach time may have moved)."""
        for tenant_token, management in self._managements.items():
            self._full_rebuild(management, self.tenants.intern(tenant_token))

    def _full_rebuild(self, management: DeviceManagement, tenant_idx: int) -> None:
        with self._lock:
            for device in management.devices.all():
                self._mirror_device(management, tenant_idx, device)
            for zone in management.zones.all():
                self._mirror_zone(tenant_idx, zone)
            self._version += 1

    # -- reads ----------------------------------------------------------------

    def tenant_of_device(self, token: str) -> Optional[str]:
        """Tenant token owning a device token (host-side reverse lookup —
        the cluster alert-persistence path resolves which tenant engine's
        event management stores a rule-fired alert)."""
        idx = self.devices.lookup(token)
        if idx <= 0:
            return None
        with self._lock:
            tenant_idx = int(self._tenant_idx[idx])
        return self.tenants.token_of(tenant_idx)

    @property
    def version(self) -> int:
        return self._version

    def snapshot(self) -> RegistrySnapshot:
        with self._lock:
            return RegistrySnapshot(
                assignment_status=self._assignment_status.copy(),
                tenant_idx=self._tenant_idx.copy(),
                area_idx=self._area_idx.copy(),
                device_type_idx=self._device_type_idx.copy(),
                assignment_idx=self._assignment_idx.copy(),
                zone_vertices=self._zone_vertices.copy(),
                zone_nvert=self._zone_nvert.copy(),
                zone_tenant=self._zone_tenant.copy(),
                zone_area=self._zone_area.copy(),
                zone_active=self._zone_active.copy(),
                version=self._version,
            )
