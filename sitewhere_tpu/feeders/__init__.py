"""Disaggregated feeder fleet: pack anywhere, step on the mesh host.

The mesh host's per-event work (decode -> intern -> pack -> route guard)
is what caps the headline rate at a fraction of the device ceiling
(flight recorder + age waterfall diagnosis, docs/PERF.md). tf.data
service (Audibert et al.) makes the case for disaggregating input
processing onto a worker fleet; this package applies it to the event
pipeline with the platform's own primitives:

* feeders own TTL-leased source partitions (runtime/recovery.py
  LeaseTable + EpochFence — fenced takeover at epoch+1, exactly-once
  replay via per-partition watermarks),
* interner replicas stay bit-identical through an append-only token
  journal replicated over busnet (registry/interning.py journal ops),
* ready-to-stage wire blobs ship with their age sidecar and traceparent,
  and the mesh host does only H2D-into-StagingRing + step.

See docs/FEEDERS.md for the architecture and protocol walkthrough.
"""

from sitewhere_tpu.feeders.protocol import (
    blob_message, decode_blob, feeder_fence_key, partition_resource)
from sitewhere_tpu.feeders.replica import ReplicaPacker
from sitewhere_tpu.feeders.service import FeederService
from sitewhere_tpu.feeders.worker import FeederWorker

__all__ = [
    "FeederService", "FeederWorker", "ReplicaPacker",
    "blob_message", "decode_blob", "feeder_fence_key",
    "partition_resource",
]
