"""Feeder worker: lease-owned partitions -> ready-to-stage wire blobs.

One worker (thread in-proc, process under ``serve --feeder``) owns a set
of TTL-leased source partitions and runs the whole per-event pipeline
locally — decode, interner-replica token resolution, pack, host-route
guard — then ships each blob to the mesh host's ``feeder_blob`` endpoint
and commits the covered offsets ONLY after the ack. The commit-after-ack
order is the exactly-once half the mesh-side watermark needs: a worker
that dies mid-blob leaves its offsets uncommitted, the successor (fenced
at a strictly higher epoch) replays the extent, and the watermark drops
what already stepped.

Blob grouping is record-ALIGNED (protocol.count_hot_events header walk):
an offset commit can never split a bus record, so replayed extents are
whole blobs. The `feeder_process_death` fault point fires mid-blob —
between ship and commit — and kills the worker the hard way (os._exit
under ``serve --feeder``; an abandoned thread in the in-proc drill), the
exact window where exactly-once is hardest.

A structured 429 from the mesh host (AdmissionController shed propagated
over busnet) is counted at THIS receiver (`feeder.shed_received`) and
backs the partition off without committing — the events redeliver when
admission reopens, instead of being dropped after the transfer was paid.

Every consume-side op (poll / commit_at / seek_committed) is stamped
with the per-partition lease fences, so a fenced-out zombie cannot move
the shared server-side cursor — records it would silently skip past
could otherwise never redeliver to the successor. And ANY failure mid-
cycle — shed, fence, or a raw transport error — takes the same exit:
commit what was acked, rewind the partition to committed so the polled-
but-unshipped records redeliver.
"""

from __future__ import annotations

import os
import threading
import time
from typing import Dict, List, Optional, Sequence

import numpy as np

from sitewhere_tpu.feeders import protocol
from sitewhere_tpu.feeders.replica import ReplicaPacker
from sitewhere_tpu.ops.pack import batch_to_blob, wire_variant_for
from sitewhere_tpu.runtime.busnet import BusClient, StaleEpochBusError
from sitewhere_tpu.runtime.eventage import AgeSidecar
from sitewhere_tpu.runtime.faults import FaultError, fault_point
from sitewhere_tpu.runtime.metrics import GLOBAL_METRICS


class FeederWorker:
    """One feeder: hello -> lease -> (sync, poll, pack, ship, commit)*.

    ``epoch`` is the worker's fencing epoch (runtime/recovery.py
    mint_epoch in ``serve --feeder``; explicit in drills). A successor
    taking over a dead worker's partitions MUST run at a strictly higher
    epoch — the lease steal and the fence raise are one decision
    (feeders/service.py)."""

    def __init__(self, host: str, port: int, name: str, epoch: int,
                 partitions: Optional[Sequence[int]] = None,
                 poll_max_records: int = 4096,
                 poll_timeout_s: float = 0.25,
                 shed_backoff_s: float = 0.25,
                 hard_exit: bool = False,
                 metrics=GLOBAL_METRICS):
        self.name = str(name)
        self.epoch = int(epoch)
        self.client = BusClient(host, port)
        self.configured_partitions = (list(partitions)
                                      if partitions is not None else None)
        self.poll_max_records = int(poll_max_records)
        self.poll_timeout_s = float(poll_timeout_s)
        self.shed_backoff_s = float(shed_backoff_s)
        # serve --feeder: an injected process death must not unwind
        # through handlers that could commit — leave no trace, like
        # SIGKILL would
        self.hard_exit = bool(hard_exit)
        self._metrics = metrics
        self._blob_counter = metrics.counter("feeder.blobs_shipped")
        self._shed_counter = metrics.counter("feeder.shed_received")
        self._error_counter = metrics.counter("feeder.cycle_errors")
        self._fenced_counter = metrics.counter("feeder.fenced")
        self._takeover_counter = metrics.counter("feeder.takeovers")
        self.hello: Optional[dict] = None
        self.replica: Optional[ReplicaPacker] = None
        self.owned: Dict[int, float] = {}   # partition -> last renew ts
        self.seq = 0
        self.events_shipped = 0
        self.blobs_shipped = 0
        self._stop = threading.Event()
        self._thread: Optional[threading.Thread] = None
        self._dead = False

    # -- lifecycle ----------------------------------------------------------

    def connect(self) -> dict:
        """Hello handshake + replica bootstrap (idempotent)."""
        if self.hello is None:
            self.hello = self.client.call(protocol.OP_HELLO)
            self.replica = ReplicaPacker(self.hello, self.client,
                                         metrics=self._metrics)
            self.replica.sync()
        return self.hello

    def acquire_leases(self) -> List[int]:
        """Try to lease every configured partition (all partitions when
        none were configured). Grants out of another owner's lapsed or
        fenced lease count as takeovers."""
        hello = self.connect()
        wanted = (self.configured_partitions
                  if self.configured_partitions is not None
                  else list(range(int(hello["partitions"]))))
        now = time.monotonic()
        fresh: List[int] = []
        for p in wanted:
            if p in self.owned:
                continue
            resp = self.client.call(
                protocol.OP_LEASE,
                **protocol.lease_request("acquire", p, self.name,
                                         self.epoch,
                                         hello["lease_ttl_s"]))
            if resp.get("granted"):
                self.owned[p] = now
                fresh.append(p)
                if resp.get("took_over"):
                    self._takeover_counter.inc()
        if fresh:
            # a takeover inherits its predecessor's polled-but-uncommitted
            # tail: rewind exactly the granted partitions to their last
            # COMMITTED offsets so those records redeliver (the mesh
            # watermark drops whatever was already applied)
            self.client.seek_committed(
                hello["topic"], hello["group"], partitions=fresh,
                fences=protocol.consume_fences(fresh, self.epoch))
        return sorted(self.owned)

    def release_leases(self) -> None:
        for p in list(self.owned):
            try:
                self.client.call(
                    protocol.OP_LEASE,
                    **protocol.lease_request("release", p, self.name,
                                             self.epoch))
            except Exception:
                pass
            self.owned.pop(p, None)

    def _renew_leases(self) -> None:
        hello = self.hello or {}
        ttl = float(hello.get("lease_ttl_s", 5.0))
        now = time.monotonic()
        for p, last in list(self.owned.items()):
            if now - last < ttl / 3.0:
                continue
            resp = self.client.call(
                protocol.OP_LEASE,
                **protocol.lease_request("renew", p, self.name, self.epoch))
            if resp.get("renewed"):
                self.owned[p] = now
            else:
                # lost the lease (lapsed + stolen): the partition is no
                # longer ours — its uncommitted tail replays on the owner
                self.owned.pop(p, None)

    # -- one ship cycle -----------------------------------------------------

    def run_once(self, timeout_s: Optional[float] = None) -> int:
        """One sync -> poll -> pack -> ship -> commit cycle over the
        owned partitions. Returns events shipped (0 on an idle poll)."""
        if self._dead:
            return 0
        self.connect()
        if not self.owned:
            self.acquire_leases()
            if not self.owned:
                return 0
        self._renew_leases()
        if not self.owned:
            return 0
        self.replica.sync()
        parts = sorted(self.owned)
        try:
            records = self.client.poll(
                self.hello["topic"], self.hello["group"],
                max_records=self.poll_max_records,
                timeout_s=self.poll_timeout_s if timeout_s is None
                else timeout_s,
                partitions=parts,
                fences=protocol.consume_fences(parts, self.epoch))
        except StaleEpochBusError as exc:
            # consume-side fencing: a successor's takeover raised a
            # partition's floor past our epoch — the rejected poll moved
            # NO cursor, so nothing was skipped. Drop the named
            # partition; the next cycle polls the survivors.
            self._fenced_counter.inc()
            stale = protocol.fence_key_partition(exc.resource)
            if stale is not None:
                self.owned.pop(stale, None)
            else:
                self.owned.clear()
            return 0
        if not records:
            return 0
        shipped = 0
        by_part: Dict[int, List] = {}
        for rec in records:
            by_part.setdefault(rec.partition, []).append(rec)
        for p, recs in sorted(by_part.items()):
            if self._dead:
                break
            if p not in self.owned:
                continue
            shipped += self._ship_partition(p, recs)
        return shipped

    def _ship_partition(self, partition: int, records: List) -> int:
        """Pack one partition's polled records into record-aligned blobs
        and ship them; commit after the last ack. ANY exit before the
        commit — shed, fence, or a raw transport error unwinding out of
        a ship — leaves the unacked tail uncommitted AND rewinds the
        partition to committed, so the polled-but-unshipped records
        redeliver instead of sitting forever past the server-side
        cursor. At-least-once upstream, deduplicated downstream by the
        mesh watermark."""
        B = int(self.hello["batch_size"])
        # record-aligned groups: greedily accumulate whole records up to
        # the batch width so an offset commit never splits a record
        groups: List[List] = []
        group: List = []
        group_events = 0
        for rec in records:
            n = protocol.count_hot_events(rec.value)
            if group and group_events + n > B:
                groups.append(group)
                group, group_events = [], 0
            group.append(rec)
            group_events += n
        if group:
            groups.append(group)
        shipped = 0
        committed_through: Optional[int] = None
        rewind = False
        try:
            for group in groups:
                age = AgeSidecar()
                data = b"".join(rec.value for rec in group)
                batches, n_events, _rest = self.replica.pack_bytes(data)
                age.add(None, n_events)
                extent = (group[0].offset, group[-1].offset + 1)
                ok, skip_to = self._ship_blobs(partition, batches,
                                               n_events, extent, age)
                if self._dead:
                    # injected death: commit NOTHING — acked-but-
                    # uncommitted extents must replay through the
                    # successor, exactly like a SIGKILL before the
                    # commit_at went out (the finally below skips too)
                    return shipped
                if skip_to is not None:
                    # mesh overlap verdict: everything below the
                    # watermark IS applied — advance the commit to it so
                    # the rewound re-poll regroups from exactly the
                    # first unapplied record
                    committed_through = max(committed_through
                                            if committed_through is not None
                                            else -1, skip_to)
                if not ok:
                    rewind = True
                    break  # shed/fenced/overlap: nothing past this point
                shipped += n_events
                committed_through = extent[1]
        except Exception:
            # a transport (or any other) failure mid-ship takes the SAME
            # exit as shed/fenced — without the rewind, the polled-but-
            # unshipped records sit past the server-side cursor, later
            # extents advance the mesh watermark over them, and their
            # eventual redelivery is dropped as a false replay (loss)
            rewind = True
            raise
        finally:
            if not self._dead and partition in self.owned:
                self._commit_and_rewind(partition, committed_through,
                                        rewind)
        return shipped

    def _commit_and_rewind(self, partition: int,
                           committed_through: Optional[int],
                           rewind: bool) -> None:
        """Best-effort cycle exit: commit the acked extents, then rewind
        to committed when the cycle stopped early. Both ops are fenced —
        a takeover between ship and commit bounces them (the successor
        replays; the watermark dedupes) — and both may fail on a dead
        transport, which only costs redelivery (at-least-once)."""
        fences = protocol.consume_fences([partition], self.epoch)
        try:
            if committed_through is not None:
                self.client.commit_at(
                    self.hello["topic"], self.hello["group"],
                    {partition: committed_through},
                    partitions=[partition], fences=fences)
            if rewind:
                self.client.seek_committed(self.hello["topic"],
                                           self.hello["group"],
                                           partitions=[partition],
                                           fences=fences)
        except StaleEpochBusError:
            self._fenced_counter.inc()
            self.owned.pop(partition, None)
        except Exception:
            pass

    def _ship_blobs(self, partition: int, batches, n_events: int,
                    extent, age: AgeSidecar):
        """Pack each batch into its wire blob and ship. A single record
        group normally yields one batch; an oversized record chunks into
        several — each stamped with its chunk index, only the last
        advancing the mesh watermark (see protocol.blob_message).
        Returns ``(ok, skip_to)``: ok False stops the cycle before any
        commit past this group; skip_to (the watermark from an overlap
        verdict) tells the caller to advance the partition's commit to
        it before rewinding."""
        sharded = self.hello.get("engine") == "sharded"
        for i, batch in enumerate(batches):
            final = i == len(batches) - 1
            blob, fits = self._pack_blob(batch, sharded)
            n = int(np.asarray(batch.valid).sum())
            self.seq += 1
            try:
                resp = self.client.call(protocol.OP_BLOB, **protocol.blob_message(
                    blob, n_events=n, partition=partition, seq=self.seq,
                    extent=extent, epoch=self.epoch,
                    fits_device_route=fits, age=age, advance=final,
                    chunk=i))
            except StaleEpochBusError:
                # fenced: a successor took this partition over — drop the
                # lease and never commit (our rows land via its replay)
                self._fenced_counter.inc()
                self.owned.pop(partition, None)
                return False, None
            if resp.get("shed"):
                # the propagated AdmissionController 429: counted here at
                # the receiver, partition backs off uncommitted
                self._shed_counter.inc()
                time.sleep(self.shed_backoff_s)
                return False, None
            if resp.get("overlap"):
                # the extent straddles the mesh watermark (a regrouped
                # replay after new records widened the greedy group):
                # its applied prefix must NOT step again — skip the
                # commit to the watermark and re-poll from there
                return False, int(resp["watermark"])
            # the kill drill's window: the blob is ACKED (applied on the
            # mesh host) but the offsets behind it are not yet committed —
            # the successor replays this extent and exactly-once must
            # come from the watermark, not from us
            try:
                fault_point("feeder_process_death")
            except FaultError:
                self._die()
                return False, None
            self._blob_counter.inc()
            self.blobs_shipped += 1
            self.events_shipped += n
        return True, None

    def _pack_blob(self, batch, sharded: bool):
        """Batch -> the exact wire layout the engine would have packed
        inline, plus the host-route guard verdict (sharded only)."""
        if not sharded:
            return batch_to_blob(batch), True
        S = int(self.hello["n_shards"])
        per_shard = int(self.hello["per_shard_batch"])
        G = S * per_shard
        fits = True
        if self.hello.get("device_routing"):
            from sitewhere_tpu.ops.route import host_fits_device_route

            valid = np.asarray(batch.valid)
            fits = bool(host_fits_device_route(
                np.asarray(batch.device_idx), valid, S, per_shard,
                int(self.hello["route_lane_capacity"])))
        rows, ts_base = wire_variant_for(batch)
        rows, ts_base = _routable_variant(rows, ts_base, per_shard)
        fixed = int(self.hello.get("fixed_wire_rows") or 0)
        if fixed:
            rows = fixed
        small = batch_to_blob(batch, wire_rows=rows)
        n = batch.device_idx.shape[0]
        if n == G:
            return small, fits
        buf = np.zeros((small.shape[0], G), np.int32)
        buf[:, :n] = small
        return buf, fits

    def _die(self) -> None:
        """The injected process death: no commits, no lease release, no
        cleanup — indistinguishable from SIGKILL to everyone else."""
        self._dead = True
        self._stop.set()
        if self.hard_exit:
            os._exit(9)

    @property
    def dead(self) -> bool:
        return self._dead

    # -- background thread --------------------------------------------------

    def start(self) -> None:
        if self._thread is not None:
            return
        self._stop.clear()
        self._thread = threading.Thread(target=self._run,
                                        name=f"feeder-{self.name}",
                                        daemon=True)
        self._thread.start()

    def _run(self) -> None:
        while not self._stop.is_set():
            try:
                if self.run_once() == 0:
                    # idle poll already long-polled server-side
                    continue
            except FaultError:
                self._die()
                return
            except Exception:
                if self._stop.is_set() or self._dead:
                    return
                # safe to swallow-and-retry ONLY because _ship_partition
                # already rewound the partition to committed on its way
                # out — the failed cycle's records redeliver; counted so
                # a flapping transport is visible, not silent
                self._error_counter.inc()
                time.sleep(0.2)

    def stop(self) -> None:
        self._stop.set()
        if self._thread is not None:
            self._thread.join(timeout=10.0)
            self._thread = None
        if not self._dead:
            self.release_leases()
        self.client.close()


def _routable_variant(rows: int, ts_base: int, per_shard_batch: int):
    """Mirror of ShardRouter._routable_variant for the remote pack: the
    packed 3-row layout embeds its ts base across 11 lanes of row 0 —
    per-shard widths below that cannot carry it after the on-device
    route, so downgrade to compact exactly like the inline path."""
    from sitewhere_tpu.ops.pack import (_BASE_LANES, WIRE_ROWS_COMPACT,
                                        WIRE_ROWS_PACKED)

    if rows == WIRE_ROWS_PACKED and per_shard_batch < _BASE_LANES:
        return WIRE_ROWS_COMPACT, 0
    return rows, ts_base
