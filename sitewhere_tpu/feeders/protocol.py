"""Feeder <-> mesh-host wire protocol (busnet subsystem ops).

Five ops, mounted on the mesh host's BusServer via ``register_op``
(feeders/service.py):

``feeder_hello``
    Feeder bootstrap: the mesh host describes the engine's packing
    contract (batch width, wire-variant policy, interner capacities and
    the packer's ``epoch_base_ms``) so a remote pack is bit-identical to
    an inline one, plus the frames topic and lease TTL.

``feeder_lease``
    Lease lifecycle against the mesh host's LeaseTable: acquire / renew /
    release one source partition. A steal of a live lease requires a
    strictly higher epoch — the takeover path; grants out of a takeover
    are counted (`takeover.count`).

``feeder_journal`` / ``feeder_intern``
    The interner-delta replication protocol: a replica pulls the
    append-only token journal from its last position, and allocates NEW
    measurement/alert-type tokens authoritatively on the mesh host (the
    only per-TOKEN — never per-event — round trip). Device tokens are
    lookup-only on both sides (unknown must stay 0).

``feeder_blob``
    One ready-to-stage wire blob: raw int32 bytes + shape, the partition
    offset extent it covers (the exactly-once watermark), the age
    sidecar in cross-process form (age-so-far entries, re-stamped at the
    receiver — perf_counter stamps never cross a process boundary raw),
    and the feeder's host-route guard verdict. Epoch-fenced per
    partition: the request carries ``fence=feeder:p<N>`` so a zombie
    feeder's blobs bounce off the raised floor after takeover.

Blobs travel as raw ``tobytes()`` payloads inside the existing
length-prefixed msgpack busnet frames — no new framing layer.

Consume-side ops (the built-in busnet poll / commit_at /
seek_committed) are not in this table but carry the same per-partition
fencing: ``consume_fences`` stamps them with ``[fence_key, epoch]``
pairs so a fenced-out zombie cannot move the shared server-side cursor
(records it silently skipped would otherwise be lost, not duplicated).
"""

from __future__ import annotations

from typing import List, Optional, Sequence

import numpy as np

from sitewhere_tpu.runtime.eventage import AgeSidecar, sidecar_to_wire

# busnet op names (BusServer.register_op keys)
OP_HELLO = "feeder_hello"
OP_LEASE = "feeder_lease"
OP_JOURNAL = "feeder_journal"
OP_INTERN = "feeder_intern"
OP_BLOB = "feeder_blob"

# consumer group the fleet commits under: one group, partitions pinned
# explicitly per lease (busnet poll `partitions` override) — ownership
# follows the lease, not the TCP connection
FEEDER_GROUP = "feeder-fleet"


def feeder_fence_key(partition: int) -> str:
    """EpochFence resource for one source partition's write stream."""
    return f"feeder:p{int(partition)}"


def fence_key_partition(key: str) -> Optional[int]:
    """Inverse of feeder_fence_key: the partition a stale_epoch rejection
    names, or None for a non-feeder fence resource."""
    key = str(key)
    if not key.startswith("feeder:p"):
        return None
    try:
        return int(key[len("feeder:p"):])
    except ValueError:
        return None


def consume_fences(partitions: Sequence[int], epoch: int) -> List[list]:
    """Per-partition [fence_key, epoch] stamps for consume-side busnet
    ops (poll / commit_at / seek_committed). A fenced-out zombie feeder
    must bounce with stale_epoch BEFORE its request can move the shared
    server-side cursor: an unfenced zombie poll skips records it will
    never ship, and once the successor's later extents advance the mesh
    watermark those skipped records redeliver as false 'replays' —
    silent loss, not duplicates."""
    return [[feeder_fence_key(p), int(epoch)] for p in partitions]


def partition_resource(partition: int) -> str:
    """LeaseTable resource name for one source partition."""
    return f"feeder-partition-{int(partition)}"


def blob_message(blob: np.ndarray, *, n_events: int, partition: int,
                 seq: int, extent: Sequence[int], epoch: int,
                 fits_device_route: bool = True,
                 age: Optional[AgeSidecar] = None,
                 advance: bool = True, chunk: int = 0) -> dict:
    """Build the ``feeder_blob`` request body. ``extent`` is the
    [start, end) partition offset range the blob covers — the mesh
    host's replay watermark judges duplicates by it. A record too large
    for one batch ships as chunks: ``chunk`` is the 0-based index within
    the extent and ``advance=False`` marks every chunk but the last.
    The watermark only moves on the LAST chunk, but the mesh host also
    remembers the highest applied (extent, chunk) of an in-progress
    record, so a replay after a mid-record shed/fence/crash dedupes the
    already-applied chunks instead of double-stepping them — chunking is
    deterministic (greedy record grouping + fixed batch width), so a
    re-pack of the same extent reproduces the same chunk boundaries."""
    blob = np.ascontiguousarray(blob, np.int32)
    return {
        "blob": blob.tobytes(),
        "rows": int(blob.shape[0]),
        "width": int(blob.shape[1]),
        "n_events": int(n_events),
        "partition": int(partition),
        "seq": int(seq),
        "extent": [int(extent[0]), int(extent[1])],
        "fits_device_route": bool(fits_device_route),
        "age": sidecar_to_wire(age),
        "advance": bool(advance),
        "chunk": int(chunk),
        "fence": feeder_fence_key(partition),
        "epoch": int(epoch),
    }


def count_hot_events(data: bytes) -> int:
    """Hot-event frame count of one bus record's payload — a header-only
    walk (8 bytes/frame), no payload decode. Lets the feeder group
    records into record-ALIGNED blobs (extent commits can never split a
    record) without decoding twice."""
    from sitewhere_tpu.transport.wire import _HEADER, HOT_TYPES, MAGIC

    hot = {int(t) for t in HOT_TYPES}
    pos, n, count = 0, len(data), 0
    while pos + _HEADER.size <= n:
        magic, _version, mtype, length = _HEADER.unpack_from(data, pos)
        if magic != MAGIC:
            break
        if pos + _HEADER.size + length > n:
            break
        if mtype in hot:
            count += 1
        pos += _HEADER.size + length
    return count


def decode_blob(msg: dict) -> np.ndarray:
    """Reconstruct the wire blob from a ``feeder_blob`` request. The
    frombuffer view is read-only; staging copies it to the device (or
    the spill path copies columns), so no writable copy is made here."""
    rows, width = int(msg["rows"]), int(msg["width"])
    blob = np.frombuffer(msg["blob"], np.int32)
    if blob.size != rows * width:
        raise ValueError(
            f"blob payload {blob.size} int32s != shape [{rows}, {width}]")
    return blob.reshape(rows, width)


def lease_request(action: str, partition: int, owner: str, epoch: int,
                  ttl_s: Optional[float] = None) -> dict:
    req = {"action": str(action), "partition": int(partition),
           "owner": str(owner), "epoch": int(epoch)}
    if ttl_s is not None:
        req["ttl_s"] = float(ttl_s)
    return req


def partitions_of(leases: dict) -> List[int]:
    """Sorted partition list from a {partition: epoch} ownership map."""
    return sorted(int(p) for p in leases)
