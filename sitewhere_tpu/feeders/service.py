"""Mesh-host blob-ingest endpoint: the feeder fleet's landing zone.

Mounts the ``feeder_*`` op family on the serve process's BusServer
(runtime/busnet.py ``register_op``). With feeders attached, the mesh
host's per-step work on this path is H2D-into-StagingRing + dispatch —
no decode, no interning, no pack, no route guard; the flight records it
opens carry only ``h2d``/``dispatch`` (and ``stage_wait``/``guard``
backpressure) segments, which is exactly what the bench's
``mesh_host_cpu_per_step`` attribution checks.

Exactly-once across takeover: every blob names the [start, end)
partition-offset extent it covers. The service keeps a per-partition
watermark (max applied end offset) that OUTLIVES any feeder; a blob
fully at-or-under the watermark is a replay — dropped, counted
(`feeder.replay_dropped`), its rows handed to the armed ReplayBarrier
as suppressed effects. Feeders commit offsets only after the ack, so
extents are blob-aligned: a replayed extent is either fully duplicate
or fully new. Two refinements close the partial cases:

* Chunked records (one record wider than a batch) dedupe per CHUNK: the
  highest applied (extent, chunk) of an in-progress record is kept
  alongside the watermark, so a replay after a mid-record shed/fence/
  kill suppresses the chunks that already stepped instead of
  double-applying them.
* An extent that STRADDLES the watermark (start < watermark < end — a
  regrouped replay after new records extended the greedy group
  boundary) is refused with a structured ``overlap`` verdict carrying
  the watermark; the feeder advances its commit to the watermark and
  re-polls, so the straddling blob's already-applied prefix is never
  stepped twice. Counted on `feeder.extent_overlap`.

The replay check runs lock-free as a fast path and AGAIN under the step
lock before stepping: blob handlers run on concurrent busnet threads,
so a zombie's in-flight duplicate that passed the first check while the
successor's replay held the lock is caught by the in-lock re-check
after the watermark advanced.

Zombie fencing: blob requests are stamped ``fence=feeder:p<N>`` and
epoch-checked by busnet dispatch BEFORE this service sees them; a
takeover raises the partition's floor so the dead feeder's in-flight
blobs bounce with ``stale_epoch`` instead of landing twice. Feeders
stamp the same per-partition fences on their consume-side ops (poll /
commit / seek), so a fenced zombie cannot move the shared server-side
cursor either.

Admission: the shed decision propagates to the SOURCE — a shedding
AdmissionController turns the blob ack into a structured 429 the
feeder's receiver counts and backs off on, instead of the blob landing
first and shedding after the transfer was already paid.
"""

from __future__ import annotations

import threading
import time
from typing import Callable, Optional

import numpy as np

from sitewhere_tpu.feeders import protocol
from sitewhere_tpu.ops.pack import _VALID_SHIFT, blob_to_batch_np
from sitewhere_tpu.runtime.eventage import (age_histogram, observe_summary,
                                            sidecar_from_wire)
from sitewhere_tpu.runtime.metrics import GLOBAL_METRICS
from sitewhere_tpu.runtime.recovery import GLOBAL_REPLAY_BARRIER, LeaseTable

# age-waterfall edge recorded when a feeder blob crosses onto the mesh
# host (cumulative age at the handoff; per-hop = difference against the
# downstream persist/alert edges)
FEEDER_EDGE = "feeder_to_mesh"


class FeederService:
    """Serve-process side of the feeder fleet: lease authority, interner
    journal authority, and the blob-ingest endpoint feeding the engine's
    staging ring directly."""

    def __init__(self, engine, server, frames_topic: str,
                 lease_ttl_s: float = 5.0, tenant: str = "default",
                 admission=None, metrics=GLOBAL_METRICS,
                 replay_barrier=GLOBAL_REPLAY_BARRIER,
                 on_outputs: Optional[Callable] = None,
                 submitter=None):
        self.engine = engine
        self.server = server
        # optional pipeline/feed.py PipelinedSubmitter: blobs from
        # concurrent feeders then stage (H2D) in parallel on its stager
        # threads while the step thread dispatches in order — the ack
        # still waits for dispatch so the watermark never outruns a step
        self.submitter = submitter
        self.frames_topic = frames_topic
        self.lease_ttl_s = float(lease_ttl_s)
        self.tenant = tenant
        self.admission = admission
        self.replay_barrier = replay_barrier
        self.on_outputs = on_outputs
        self.leases = LeaseTable(metrics=metrics)
        self._metrics = metrics
        self._blob_counter = metrics.counter("feeder.blobs")
        self._events_meter = metrics.meter("feeder.events")
        self._shed_counter = metrics.counter("feeder.shed")
        self._replay_counter = metrics.counter("feeder.replay_dropped")
        self._spill_counter = metrics.counter("feeder.guard_spills")
        self._overlap_counter = metrics.counter("feeder.extent_overlap")
        self._takeover_counter = metrics.counter("takeover.count")
        self._age_hist = age_histogram(metrics)
        # per-partition exclusive end offset of applied extents — the
        # exactly-once watermark; survives any feeder's death
        self._watermarks: dict = {}
        # per-partition (extent_end, max applied chunk) of the ONE
        # in-progress chunked record, cleared when its final chunk
        # advances the watermark — the sub-extent half of exactly-once
        self._partials: dict = {}
        # blob staging order + the engine step are serialized: the step
        # is not concurrent-safe, and a single arrival order keeps the
        # staging ring's ordered grant meaningful across feeders
        self._step_lock = threading.Lock()
        self._order = 0
        # receiver-side accounting (read by bench/perf_gate): wall spent
        # handling blobs, the engine-step part of it, and actual thread
        # CPU (thread_time stops during lock waits and device blocks) —
        # handoff overhead per blob = (handle - step) / blobs
        self.blob_handle_s = 0.0
        self.blob_step_s = 0.0
        self.blob_cpu_s = 0.0
        from sitewhere_tpu.parallel.engine import ShardedPipelineEngine
        self._sharded = isinstance(engine, ShardedPipelineEngine)
        for op, fn in ((protocol.OP_HELLO, self._op_hello),
                       (protocol.OP_LEASE, self._op_lease),
                       (protocol.OP_JOURNAL, self._op_journal),
                       (protocol.OP_INTERN, self._op_intern),
                       (protocol.OP_BLOB, self._op_blob)):
            server.register_op(op, fn)

    # -- op: hello ----------------------------------------------------------

    def _op_hello(self, req: dict) -> dict:
        """The packing contract a feeder needs for a bit-identical remote
        pack, plus fleet wiring (topic, group, lease TTL)."""
        engine = self.engine
        packer = engine.packer
        n_parts = len(self.server.bus.topic(self.frames_topic).partitions)
        resp = {
            "ok": True,
            "engine": "sharded" if self._sharded else "single",
            "batch_size": packer.batch_size,
            "epoch_base_ms": packer.epoch_base_ms,
            "dev_capacity": packer.devices.capacity,
            "dev_shard_classes": packer.devices.shard_classes,
            "mm_capacity": packer.measurements.capacity,
            "at_capacity": packer.alert_types.capacity,
            "topic": self.frames_topic,
            "group": protocol.FEEDER_GROUP,
            "partitions": n_parts,
            "lease_ttl_s": self.lease_ttl_s,
            "shedding": bool(self.admission is not None
                             and getattr(self.admission, "_shedding",
                                         False)),
        }
        if self._sharded:
            resp.update(
                n_shards=engine.n_shards,
                per_shard_batch=engine.batch_size,
                device_routing=bool(engine.device_routing),
                route_lane_capacity=int(engine.route_lane_capacity),
                fixed_wire_rows=int(getattr(engine.router,
                                            "fixed_wire_rows", 0) or 0))
        return resp

    # -- op: lease ----------------------------------------------------------

    def _op_lease(self, req: dict) -> dict:
        action = req.get("action")
        partition = int(req["partition"])
        owner = str(req["owner"])
        epoch = int(req.get("epoch", 0))
        resource = protocol.partition_resource(partition)
        if action == "acquire":
            previous = self.leases.holder(resource)
            ttl = float(req.get("ttl_s", self.lease_ttl_s))
            granted = self.leases.acquire(resource, owner, epoch, ttl)
            if granted and previous is not None and previous != owner:
                # a live lease changed hands — only possible via the
                # strictly-higher-epoch steal: this is a takeover
                self._takeover_counter.inc()
            if granted:
                # the new owner's epoch fences the old one: raise the
                # partition floor so the previous incarnation's in-flight
                # blobs are rejected (same decision as the steal)
                self.server.fence.fence(
                    protocol.feeder_fence_key(partition), epoch)
            return {"ok": True, "granted": bool(granted),
                    "ttl_s": ttl, "holder": self.leases.holder(resource),
                    "took_over": bool(granted and previous is not None
                                      and previous != owner)}
        if action == "renew":
            renewed = self.leases.renew(resource, owner, epoch)
            return {"ok": True, "renewed": bool(renewed)}
        if action == "release":
            return {"ok": True,
                    "released": bool(self.leases.release(resource, owner))}
        return {"ok": False, "error": f"unknown lease action {action!r}"}

    # -- ops: interner journal ----------------------------------------------

    def _journal_interner(self, name: str):
        packer = self.engine.packer
        table = {"devices": packer.devices,
                 "measurements": packer.measurements,
                 "alert_types": packer.alert_types}
        return table.get(name)

    def _op_journal(self, req: dict) -> dict:
        interner = self._journal_interner(str(req.get("interner")))
        if interner is None:
            return {"ok": False,
                    "error": f"unknown interner {req.get('interner')!r}"}
        since = int(req.get("since", 0))
        epoch, entries = interner.journal_since(since)
        return {"ok": True, "journal_epoch": epoch, "base": since,
                "entries": [[i, t] for i, t in entries]}

    def _op_intern(self, req: dict) -> dict:
        """Authoritative allocation for NEW meta tokens a feeder saw
        mid-stream. Devices are refused — ingest never allocates device
        tokens on either side (unknown must stay 0)."""
        name = str(req.get("interner"))
        if name == "devices":
            return {"ok": False,
                    "error": "devices are lookup-only for ingest"}
        interner = self._journal_interner(name)
        if interner is None:
            return {"ok": False, "error": f"unknown interner {name!r}"}
        since = int(req.get("since", 0))
        for token in req.get("tokens", []):
            interner.intern(str(token))
        epoch, entries = interner.journal_since(since)
        return {"ok": True, "journal_epoch": epoch, "base": since,
                "entries": [[i, t] for i, t in entries]}

    # -- op: blob -----------------------------------------------------------

    def _extent_disposition(self, partition: int, start: int, end: int,
                            chunk: int):
        """'dup' (fully applied — drop and suppress), 'overlap' (the
        extent straddles the watermark — the shipper must re-group from
        it), or None (fresh). Consulted lock-free as a fast path and
        AGAIN under ``_step_lock`` before stepping; only the in-lock
        answer is authoritative."""
        wm = self._watermarks.get(partition, -1)
        if end <= wm:
            return "dup"
        if start < wm:
            return "overlap"
        partial = self._partials.get(partition)
        if partial is not None and partial[0] == end and chunk <= partial[1]:
            return "dup"
        return None

    def _dup_reply(self, n_events: int) -> dict:
        self._replay_counter.inc()
        suppressed = self.replay_barrier.take(self.tenant, n_events) \
            if self.replay_barrier is not None else 0
        # report what the barrier actually suppressed — 0 when disarmed
        # (no durable rows to protect), never a fabricated n_events
        return {"ok": True, "dup": True, "events": 0,
                "suppressed": int(suppressed)}

    def _overlap_reply(self, partition: int) -> dict:
        self._overlap_counter.inc()
        return {"ok": True, "overlap": True, "events": 0,
                "watermark": int(self._watermarks.get(partition, -1))}

    def _op_blob(self, req: dict) -> dict:
        partition = int(req["partition"])
        start, end = (int(x) for x in req["extent"])
        n_events = int(req["n_events"])
        chunk = int(req.get("chunk", 0))
        # 1. replay watermark FIRST: a duplicate is dropped for free —
        # were shedding checked first, an overloaded mesh host would
        # 429 takeover replays and the feeder would re-ship the same
        # already-applied extents forever instead of converging
        verdict = self._extent_disposition(partition, start, end, chunk)
        if verdict == "dup":
            return self._dup_reply(n_events)
        if verdict == "overlap":
            return self._overlap_reply(partition)
        # 2. front-door shedding: an overloaded mesh host refuses fresh
        # work before doing anything with the payload
        admit = getattr(self.admission, "admit_remote", None) \
            or getattr(self.admission, "admit", None)
        if admit is not None and not admit():
            self._shed_counter.inc()
            # transport-level ok (the socket and request were fine), app-
            # level structured 429: the feeder's receiver branches on
            # `shed`, backs off, and does NOT commit the extent
            return {"ok": True, "shed": True, "http_status": 429,
                    "events": 0}
        t0 = time.perf_counter()
        c0 = time.thread_time()
        blob = protocol.decode_blob(req)
        age = sidecar_from_wire(req.get("age") or [])
        # cumulative age at the feeder->mesh handoff (per-hop p50/p99 =
        # this edge minus the feeder's ingest edge downstream dashboards
        # already chart)
        observe_summary(self._age_hist, age.close(),
                        engine=self.engine.name, edge=FEEDER_EDGE)
        with self._step_lock:
            # 3. authoritative re-check: blob handlers run on concurrent
            # busnet threads, so a duplicate that passed the fast path
            # while another handler (the successor's replay of the same
            # extent) held this lock must be caught here, AFTER that
            # handler advanced the watermark — or it would step twice
            verdict = self._extent_disposition(partition, start, end,
                                               chunk)
            if verdict == "dup":
                return self._dup_reply(n_events)
            if verdict == "overlap":
                return self._overlap_reply(partition)
            order = self._order
            self._order += 1
            s0 = time.perf_counter()
            if self._sharded:
                events = self._step_sharded(blob, req, age, order)
            else:
                events = self._step_single(blob, n_events, age, order)
            s1 = time.perf_counter()
            if req.get("advance", True):
                # compute from the fresh in-lock value — never from a
                # pre-lock read, which could regress the watermark and
                # re-admit replays a concurrent handler already applied
                wm = max(self._watermarks.get(partition, -1), end)
                self._watermarks[partition] = wm
                partial = self._partials.get(partition)
                if partial is not None and partial[0] <= wm:
                    del self._partials[partition]
            else:
                # non-final chunk: remember the sub-extent so a replay
                # of this in-progress record dedupes its applied chunks
                partial = self._partials.get(partition)
                prev = partial[1] if partial is not None \
                    and partial[0] == end else -1
                self._partials[partition] = (end, max(prev, chunk))
            self.blob_step_s += s1 - s0
            self.blob_handle_s += s1 - t0
            self.blob_cpu_s += time.thread_time() - c0
        self._blob_counter.inc()
        self._events_meter.mark(events)
        return {"ok": True, "events": int(events), "seq": int(req["seq"])}

    def _step_single(self, blob: np.ndarray, n_events: int, age,
                     order: int) -> int:
        engine = self.engine
        if self.submitter is not None:
            fut = self.submitter.submit_blob(
                np.ascontiguousarray(blob), n_events, age=age)
            fut.result(timeout=120.0)
            return n_events
        rec = engine.flight.begin_step(engine=engine.name)
        rec.age = age
        staged = engine.stage_blob(np.ascontiguousarray(blob),
                                   flight_rec=rec, order=order)
        outputs = engine.submit_blob(staged, n_events=n_events,
                                     flight_rec=rec)
        if self.on_outputs is not None:
            self.on_outputs(engine, outputs, rec)
        return n_events

    def _step_sharded(self, blob: np.ndarray, req: dict, age,
                      order: int) -> int:
        """Sharded landing: the feeder's guard verdict picks the path.
        Fits -> the blob IS the device-routing flat layout; stage it
        through the ring and dispatch (zero per-event host work). Doesn't
        fit (skew past lane capacity) -> the loudly-counted spill: unpack
        to columns and take the host-arena route via submit()."""
        from sitewhere_tpu.parallel.engine import _PreparedStep

        engine = self.engine
        fits = bool(req.get("fits_device_route", True)) \
            and engine.device_routing
        if not fits:
            self._spill_counter.inc()
            batch = blob_to_batch_np(np.ascontiguousarray(blob))
            valid = np.asarray(batch.valid)
            n = int(valid.sum())
            engine.submit(batch, age=age)
            return n
        params = engine._ensure_params()
        rec = engine.flight.begin_step(engine=engine.name)
        rec.age = age
        prepared = _PreparedStep("device", np.ascontiguousarray(blob),
                                 flight=rec)
        staged = engine.stage_prepared(prepared, order=order)
        view, outputs = engine.dispatch_staged(params, staged)
        if self.on_outputs is not None:
            self.on_outputs(engine, outputs, rec)
        n = int(((blob[0, :] >> _VALID_SHIFT) & 1).sum())
        return n

    # -- introspection ------------------------------------------------------

    def watermark(self, partition: int) -> int:
        return int(self._watermarks.get(int(partition), -1))
