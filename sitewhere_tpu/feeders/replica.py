"""Interner replicas + remote packing: the feeder's half of bit-identity.

A feeder packs with REPLICAS of the mesh host's three interners, kept
in lockstep through the append-only token journal
(registry/interning.py): ``sync()`` pulls the journal delta since the
replica's position (``feeder_journal``), and NEW measurement/alert-type
tokens are allocated authoritatively on the mesh host
(``feeder_intern`` — one round trip per new TOKEN, never per event).
Replaying the journal reproduces the authoritative table slot-for-slot
(including congruence gaps), so a replica lookup returns the same index
the mesh host's would — the whole bit-identity argument.

Device tokens are never interned by ingest on either side: an unknown
device must stay index 0 so the pipeline flags it unregistered
(pipeline/step.py stage 1). A device MISS on the replica is ambiguous —
genuinely unregistered, or registered since the last sync — so the
packer re-syncs the device journal once per miss batch before
conceding UNKNOWN; replica lag then costs one catch-up round trip, not
a divergent pack.

A checkpoint restore on the mesh host swaps interner contents wholesale;
the journal epoch bumps and the replica rebuilds from zero on the next
sync (``journal_epoch`` mismatch).
"""

from __future__ import annotations

from typing import Dict, List, Optional, Tuple

import numpy as np

from sitewhere_tpu.feeders.protocol import OP_INTERN, OP_JOURNAL
from sitewhere_tpu.ops.pack import EventBatch, EventPacker
from sitewhere_tpu.registry.interning import TokenInterner
from sitewhere_tpu.runtime.metrics import GLOBAL_METRICS
from sitewhere_tpu.transport.wire import (
    decode_event_frames_to_columns, decode_frames)


def _offsets(tokens: List[str]) -> Tuple[bytes, np.ndarray]:
    enc = [t.encode(errors="surrogateescape") for t in tokens]
    off = np.zeros(len(enc) + 1, np.int64)
    np.cumsum([len(t) for t in enc], out=off[1:])
    return b"".join(enc), off


class ReplicaPacker:
    """Decode raw wire frames and pack EventBatches with replica
    interners — the remote twin of sources/fastlane.py FastWireIngest,
    bit-identical to it by construction (same decode, same lookup/intern
    contract against journal-synced tables, same EventPacker with the
    mesh host's ``epoch_base_ms``)."""

    _NAMES = ("devices", "measurements", "alert_types")

    def __init__(self, hello: dict, client, metrics=GLOBAL_METRICS):
        self.client = client
        self.hello = dict(hello)
        self._metrics = metrics
        self._sync_counter = metrics.counter("feeder.journal_syncs")
        self._intern_counter = metrics.counter("feeder.interned_tokens")
        # server journal epochs as of the replica's last sync; a mismatch
        # means the authoritative table was checkpoint-restored — rebuild
        self._epochs: Dict[str, Optional[int]] = {n: None
                                                  for n in self._NAMES}
        self._build_interners()
        self.packer = EventPacker(
            int(hello["batch_size"]), self.devices,
            max_measurement_names=int(hello["mm_capacity"]),
            max_alert_types=int(hello["at_capacity"]),
            epoch_base_ms=int(hello["epoch_base_ms"]))
        # swap the packer's private meta interners for the replicas
        self.packer.measurements = self.measurements
        self.packer.alert_types = self.alert_types
        from sitewhere_tpu import native
        self._nat = native if native.available() else None

    def _build_interners(self) -> None:
        h = self.hello
        self.devices = TokenInterner(
            int(h["dev_capacity"]), "devices",
            shard_classes=int(h.get("dev_shard_classes", 1)))
        self.measurements = TokenInterner(
            int(h["mm_capacity"]), "measurements")
        self.alert_types = TokenInterner(
            int(h["at_capacity"]), "alert_types")

    def _interner(self, name: str) -> TokenInterner:
        return {"devices": self.devices, "measurements": self.measurements,
                "alert_types": self.alert_types}[name]

    # -- journal sync -------------------------------------------------------

    def _rebuild(self, name: str) -> TokenInterner:
        h = self.hello
        if name == "devices":
            self.devices = TokenInterner(
                int(h["dev_capacity"]), "devices",
                shard_classes=int(h.get("dev_shard_classes", 1)))
            self.packer.devices = self.devices
            return self.devices
        if name == "measurements":
            self.measurements = TokenInterner(
                int(h["mm_capacity"]), "measurements")
            self.packer.measurements = self.measurements
            return self.measurements
        self.alert_types = TokenInterner(int(h["at_capacity"]),
                                         "alert_types")
        self.packer.alert_types = self.alert_types
        return self.alert_types

    def _apply(self, name: str, resp: dict) -> TokenInterner:
        """Fold one feeder_journal/feeder_intern reply into the replica,
        rebuilding from zero on a journal-epoch change (the server-side
        interner was checkpoint-restored)."""
        interner = self._interner(name)
        epoch = int(resp["journal_epoch"])
        if self._epochs[name] is not None and self._epochs[name] != epoch:
            interner = self._rebuild(name)
            resp = self.client.call(OP_JOURNAL, interner=name, since=0)
            epoch = int(resp["journal_epoch"])
        self._epochs[name] = epoch
        base = int(resp["base"])
        if base != interner.journal_len():
            # positional drift (e.g. replica rebuilt above): refetch flat
            resp = self.client.call(OP_JOURNAL, interner=name,
                                    since=interner.journal_len())
            base = int(resp["base"])
        interner.apply_delta(
            [(int(i), t) for i, t in resp["entries"]], base)
        return interner

    def sync(self, names: Optional[Tuple[str, ...]] = None) -> None:
        """Pull journal deltas for the named replicas (all by default)."""
        for name in names or self._NAMES:
            interner = self._interner(name)
            resp = self.client.call(OP_JOURNAL, interner=name,
                                    since=interner.journal_len())
            self._apply(name, resp)
            self._sync_counter.inc()

    # -- token resolution ---------------------------------------------------

    def _resolve_meta(self, name: str, buf: bytes, off: np.ndarray
                      ) -> np.ndarray:
        """measurement/alert-type indices: replica lookup, then one
        authoritative allocation round trip for tokens the replica has
        never seen (new-token-mid-stream). Empty tokens stay UNKNOWN."""
        interner = self._interner(name)
        idx = interner.lookup_offsets(buf, off)
        nonempty = np.asarray(off[1:]) > np.asarray(off[:-1])
        miss_rows = np.nonzero((idx == 0) & nonempty)[0]
        if len(miss_rows) == 0:
            return idx
        seen = set()
        tokens: List[str] = []
        for r in miss_rows:
            t = buf[int(off[r]):int(off[r + 1])].decode(
                errors="surrogateescape")
            if t not in seen:
                seen.add(t)
                tokens.append(t)
        resp = self.client.call(OP_INTERN, interner=name, tokens=tokens,
                                since=interner.journal_len())
        interner = self._apply(name, resp)
        self._intern_counter.inc(len(tokens))
        return interner.lookup_offsets(buf, off)

    def _resolve_devices(self, buf: bytes, off: np.ndarray) -> np.ndarray:
        """Device indices: lookup-only (ingest NEVER allocates devices),
        but a miss re-syncs the journal once — replica lag must not turn
        a freshly registered device into an unregistered event when the
        inline path would have packed its real index."""
        idx = self.devices.lookup_offsets(buf, off)
        nonempty = np.asarray(off[1:]) > np.asarray(off[:-1])
        if np.any((idx == 0) & nonempty):
            self.sync(("devices",))
            idx = self.devices.lookup_offsets(buf, off)
        return idx

    # -- decode + pack ------------------------------------------------------

    def pack_bytes(self, data: bytes) -> Tuple[List[EventBatch], int, bytes]:
        """Raw concatenated wire frames -> packed batches. Returns
        (batches, n_events, undecodable remainder). Control frames are
        dropped here — feeders carry the hot-event stream; control
        traffic stays on the standard source path."""
        if self._nat is not None:
            cols = self._nat.decode_hot_frames(data)
            rest = data[cols.consumed:]
            if cols.n == 0:
                return [], 0, rest
            tok_buf, tok_off = cols.tokens
            device_idx = self._resolve_devices(tok_buf, tok_off)
            name_buf, name_off = cols.names
            mm_idx = self._resolve_meta("measurements", name_buf, name_off)
            at_buf, at_off = cols.alert_types
            alert_type_idx = self._resolve_meta("alert_types", at_buf,
                                                at_off)
            batches = self._pack(
                device_idx, cols.event_type, cols.ts_ms, mm_idx,
                cols.value, cols.lat, cols.lon, cols.elevation,
                alert_type_idx, cols.alert_level)
            return batches, int(cols.n), rest
        frames, rest = decode_frames(data)
        hot = decode_event_frames_to_columns(frames)
        n = len(hot["tokens"])
        if n == 0:
            return [], 0, rest
        tok_buf, tok_off = _offsets(hot["tokens"])
        device_idx = self._resolve_devices(tok_buf, tok_off)
        # blank out names/types that inline interning would skip: only
        # measurement rows intern names, only alert rows intern types
        # (decoders already leave the other rows empty; this mirrors
        # skip_empty=True)
        name_buf, name_off = _offsets(hot["names"])
        mm_idx = self._resolve_meta("measurements", name_buf, name_off)
        at_buf, at_off = _offsets(hot["alert_types"])
        alert_type_idx = self._resolve_meta("alert_types", at_buf, at_off)
        batches = self._pack(
            device_idx, hot["event_type"], hot["ts_ms"], mm_idx,
            hot["value"], hot["lat"], hot["lon"], hot["elevation"],
            alert_type_idx, hot["alert_level"])
        return batches, n, rest

    def _pack(self, device_idx, event_type, ts_ms, mm_idx, value, lat, lon,
              elevation, alert_type_idx, alert_level) -> List[EventBatch]:
        B = self.packer.batch_size
        out: List[EventBatch] = []
        for s in range(0, len(device_idx), B):
            e = s + B
            out.append(self.packer.pack_columns(
                device_idx[s:e], event_type[s:e], ts_ms[s:e],
                mm_idx=mm_idx[s:e], value=value[s:e], lat=lat[s:e],
                lon=lon[s:e], elevation=elevation[s:e],
                alert_type_idx=alert_type_idx[s:e],
                alert_level=alert_level[s:e]))
        return out
