"""On-TPU anomaly inference: tiny learned models compiled into fixed-
shape weight tables (ml/compiler.py), scored inside the fused step
(ops/anomaly.py), installed through a durable LWW store (ml/store.py).
See docs/ANOMALY_MODELS.md."""

from sitewhere_tpu.ml.compiler import (
    AnomalyModelError, AnomalyModelTable, FeatureKind, ModelKind,
    model_from_dict)
from sitewhere_tpu.ml.store import ModelStore

__all__ = ["AnomalyModelError", "AnomalyModelTable", "FeatureKind",
           "ModelKind", "ModelStore", "model_from_dict"]
