"""Anomaly-model compiler: tiny learned scorers -> fixed-shape weight tables.

ROADMAP item 3 (predictive maintenance): the fused step already maintains
per-device last-value/EWMA/rate feature state (pipeline/step.py,
ops/stateful.py); this module compiles per-device-type TINY models over
those features — learned-threshold MLPs and autoencoder
reconstruction-error scorers — into static SoA weight tables that
ops/anomaly.py evaluates for every (batch row, model) pair INSIDE the
fused step. Kafka-ML (PAPERS.md) bolts model serving onto the stream
with extra network hops per event; here the weights live replicated in
HBM next to the rule tables and scoring is one more fused stage — zero
hops, the TensorFlow fuse-state-and-compute argument applied to
inference.

Like rules/compiler.py, everything pads to static buckets (models,
feature slots, layers, layer width) so there is ONE cached jit program
per bucket shape; installing or removing a model only rewrites table
rows (and bumps the slot's epoch so per-device model state lazily
resets inside the jit — same generation trick as the rule programs).

Spec shape (JSON):

    {"token": "bearing-wear", "tenant_token": "", "device_type_token": "",
     "kind": "mlp",                      # or "autoencoder"
     "alert_type": "anomaly.model", "alert_level": "WARNING",
     "alert_message": "...", "active": true,
     "threshold": 0.8,                   # fire when score > threshold
     "features": [
         {"feature": "value", "measurement": "temp",
          "mean": 70.0, "std": 5.0},
         {"feature": "ewma", "measurement": "vibration", "alpha": 0.3},
         {"feature": "rate", "measurement": "temp"}],
     "layers": [{"weights": [[...], ...], "bias": [...]}, ...],
     "output": {"weights": [...], "bias": -0.5}}   # mlp only

Feature kinds read the SAME state the rule-program predicates read
(post-fold last measurement; EWMA accumulator; per-second rate), with
per-feature standardization ((x - mean) / std) baked into the table as
(mean, 1/std). Scoring semantics (ops/anomaly.py pins them with a NumPy
oracle in tests/test_anomaly_models.py):

  mlp          hidden layers tanh; score = sigmoid(out_w . h + out_b)
  autoencoder  hidden layers tanh, FINAL layer linear (must reconstruct
               the n_features inputs); score = mean squared
               reconstruction error over the normalized features

A model fires on the RISING EDGE of (score > threshold) at a device's
observation tick, and only when every used feature is ready and finite
(NaN never fires). Fires ride the spare alert-lane meta bits
(ops/compact.py) so delivery stays one fixed-shape D2H fetch per step.

Validation is structural and loud: an invalid spec raises
AnomalyModelError (a 409 SiteWhereError) naming the offending field
path ("features[1].alpha"), never a stack trace — on both the REST and
the replicated-apply paths.
"""

from __future__ import annotations

import math
from typing import Dict, List

import numpy as np
from flax import struct

from sitewhere_tpu.errors import ErrorCode, SiteWhereError

# static buckets: one cached jit program per (bucket, batch) shape.
DEFAULT_MAX_MODELS = 8
MAX_MODEL_BUCKET = 64          # model slot id travels in 8 lane bits
DEFAULT_MODEL_FEATURES = 4
DEFAULT_MODEL_LAYERS = 2
DEFAULT_MODEL_WIDTH = 8
MAX_MODEL_ALERT_LEVEL = 15


class ModelKind:
    MLP = 0
    AUTOENCODER = 1

    BY_NAME = {"mlp": MLP, "autoencoder": AUTOENCODER}


class FeatureKind:
    """Feature-slot opcodes; 0 marks an unused padded slot."""

    UNUSED = 0
    VALUE = 1      # post-fold last measurement
    EWMA = 2       # per-(device, model, feature) EWMA accumulator
    RATE = 3       # per-second rate of change between observations

    BY_NAME = {"value": VALUE, "ewma": EWMA, "rate": RATE}


class AnomalyModelError(SiteWhereError):
    """Invalid anomaly-model spec: names the offending field so the 409
    is actionable on REST and replicated-apply paths alike."""

    def __init__(self, message: str, field_path: str = "spec"):
        super().__init__(f"invalid anomaly model at {field_path}: {message}",
                         ErrorCode.GENERIC, http_status=409)
        self.field_path = field_path


@struct.dataclass
class AnomalyModelTable:
    """SoA weight tables; per-model columns [P], per-feature [P, F],
    stacked zero-padded weights [P, L, H, H] / [P, L, H] / [P, H].

    `epoch` is a per-slot generation number: the scoring kernel zeroes a
    slot's ModelStateTensors lanes when its stored generation differs,
    so installing a new model into a recycled slot resets feature state
    INSIDE the fused step (rules/compiler.py's lockstep-safe trick)."""

    active: np.ndarray           # bool [P]
    tenant_idx: np.ndarray       # int32 [P], 0 = any tenant
    device_type_idx: np.ndarray  # int32 [P], 0 = any device type
    alert_level: np.ndarray      # int32 [P]
    alert_type_idx: np.ndarray   # int32 [P]
    kind: np.ndarray             # int32 [P] ModelKind
    n_features: np.ndarray       # int32 [P] used feature slots
    n_layers: np.ndarray         # int32 [P] used layers
    threshold: np.ndarray        # float32 [P] fire when score > threshold
    out_b: np.ndarray            # float32 [P] mlp output bias
    epoch: np.ndarray            # int32 [P] state generation

    feat_kind: np.ndarray        # int32 [P, F] FeatureKind
    feat_mm: np.ndarray          # int32 [P, F] measurement slot (< M)
    feat_alpha: np.ndarray       # float32 [P, F] ewma alpha
    feat_mean: np.ndarray        # float32 [P, F] standardization mean
    feat_scale: np.ndarray       # float32 [P, F] 1 / std

    w: np.ndarray                # float32 [P, L, H, H] layer weights
    b: np.ndarray                # float32 [P, L, H] layer biases
    out_w: np.ndarray            # float32 [P, H] mlp output weights

    @property
    def num_models(self) -> int:
        return self.active.shape[0]

    @property
    def num_features(self) -> int:
        return self.feat_kind.shape[1]

    @property
    def num_layers(self) -> int:
        return self.w.shape[1]

    @property
    def width(self) -> int:
        return self.w.shape[2]


def empty_model_table(max_models: int = DEFAULT_MAX_MODELS,
                      max_features: int = DEFAULT_MODEL_FEATURES,
                      max_layers: int = DEFAULT_MODEL_LAYERS,
                      width: int = DEFAULT_MODEL_WIDTH) -> AnomalyModelTable:
    P, F, L, H = max_models, max_features, max_layers, width
    if F > H:
        raise ValueError(
            f"model feature bucket {F} exceeds layer width {H}: features "
            f"embed into the first F lanes of a width-H activation vector")
    zp = np.zeros(P, np.int32)
    zf = np.zeros((P, F), np.int32)
    return AnomalyModelTable(
        active=np.zeros(P, bool), tenant_idx=zp, device_type_idx=zp.copy(),
        alert_level=zp.copy(), alert_type_idx=zp.copy(), kind=zp.copy(),
        n_features=zp.copy(), n_layers=zp.copy(),
        threshold=np.zeros(P, np.float32), out_b=np.zeros(P, np.float32),
        epoch=zp.copy(),
        feat_kind=zf, feat_mm=zf.copy(),
        feat_alpha=np.zeros((P, F), np.float32),
        feat_mean=np.zeros((P, F), np.float32),
        feat_scale=np.ones((P, F), np.float32),
        w=np.zeros((P, L, H, H), np.float32),
        b=np.zeros((P, L, H), np.float32),
        out_w=np.zeros((P, H), np.float32))


# ---------------------------------------------------------------------------
# spec validation / normalization (wire + store form)
# ---------------------------------------------------------------------------

def _require(cond: bool, message: str, path: str) -> None:
    if not cond:
        raise AnomalyModelError(message, path)


def _finite_number(value, message: str, path: str) -> float:
    _require(isinstance(value, (int, float))
             and not isinstance(value, bool), message, path)
    value = float(value)
    _require(math.isfinite(value), message, path)
    return value


def _validate_vector(vec, path: str) -> List[float]:
    _require(isinstance(vec, list) and len(vec) >= 1,
             "must be a non-empty list of numbers", path)
    return [_finite_number(v, "must be a finite number", f"{path}[{i}]")
            for i, v in enumerate(vec)]


def _validate_matrix(mat, path: str) -> List[List[float]]:
    _require(isinstance(mat, list) and len(mat) >= 1,
             "must be a non-empty list of rows", path)
    rows = [_validate_vector(row, f"{path}[{i}]")
            for i, row in enumerate(mat)]
    widths = {len(row) for row in rows}
    _require(len(widths) == 1, "rows must all have the same length", path)
    return rows


def _validate_feature(node, path: str) -> Dict:
    _require(isinstance(node, dict), "feature must be an object", path)
    kind = node.get("feature")
    _require(kind in FeatureKind.BY_NAME,
             f"unknown feature kind {kind!r} (one of "
             f"{sorted(FeatureKind.BY_NAME)})", f"{path}.feature")
    name = node.get("measurement")
    _require(isinstance(name, str) and bool(name),
             "feature requires a 'measurement' name", f"{path}.measurement")
    out = {"feature": kind, "measurement": name}
    if kind == "ewma":
        alpha = node.get("alpha", 0.2)
        _require(isinstance(alpha, (int, float))
                 and not isinstance(alpha, bool)
                 and 0.0 < float(alpha) <= 1.0,
                 "ewma 'alpha' must be in (0, 1]", f"{path}.alpha")
        out["alpha"] = float(alpha)
    mean = node.get("mean", 0.0)
    out["mean"] = _finite_number(mean, "'mean' must be a finite number",
                                 f"{path}.mean")
    std = node.get("std", 1.0)
    std = _finite_number(std, "'std' must be a finite number > 0",
                         f"{path}.std")
    _require(std > 0.0, "'std' must be a finite number > 0", f"{path}.std")
    out["std"] = std
    return out


def model_from_dict(data: Dict) -> Dict:
    """Validate + normalize a wire/store spec into its canonical dict.
    Raises AnomalyModelError (409, names the field) on anything a
    compile could not turn into table rows. Layer dimension chaining is
    validated here too (input dim of layer i must equal output dim of
    layer i-1; layer 0 consumes the feature vector; an autoencoder's
    final layer must reconstruct all n_features)."""
    from sitewhere_tpu.model.event import AlertLevel

    _require(isinstance(data, dict), "spec must be an object", "spec")
    token = data.get("token")
    _require(isinstance(token, str) and bool(token),
             "model requires a string token", "spec.token")
    kind = data.get("kind", "mlp")
    _require(kind in ModelKind.BY_NAME,
             f"unknown model kind {kind!r} (one of "
             f"{sorted(ModelKind.BY_NAME)})", "spec.kind")
    level = data.get("alert_level", int(AlertLevel.WARNING))
    try:
        level = (AlertLevel[level]
                 if isinstance(level, str) and not level.lstrip("-").isdigit()
                 else AlertLevel(int(level)))
    except (KeyError, ValueError, TypeError):
        raise AnomalyModelError(f"invalid alert_level {level!r}",
                                "spec.alert_level")
    _require(0 <= int(level) <= MAX_MODEL_ALERT_LEVEL,
             f"alert_level must fit {MAX_MODEL_ALERT_LEVEL}",
             "spec.alert_level")
    for field in ("tenant_token", "device_type_token", "alert_type",
                  "alert_message"):
        value = data.get(field, "")
        _require(isinstance(value, str),
                 f"'{field}' must be a string", f"spec.{field}")
    threshold = _finite_number(data.get("threshold"),
                               "model requires a finite numeric 'threshold'",
                               "spec.threshold")

    features = data.get("features")
    _require(isinstance(features, list) and len(features) >= 1,
             "model requires a non-empty 'features' list", "spec.features")
    features = [_validate_feature(f, f"features[{i}]")
                for i, f in enumerate(features)]
    n_features = len(features)

    layers_in = data.get("layers")
    _require(isinstance(layers_in, list) and len(layers_in) >= 1,
             "model requires a non-empty 'layers' list", "spec.layers")
    layers = []
    dims = n_features
    for i, layer in enumerate(layers_in):
        path = f"layers[{i}]"
        _require(isinstance(layer, dict), "layer must be an object", path)
        weights = _validate_matrix(layer.get("weights"), f"{path}.weights")
        bias = _validate_vector(layer.get("bias"), f"{path}.bias")
        _require(len(weights[0]) == dims,
                 f"layer input dim {len(weights[0])} != previous output "
                 f"dim {dims}", f"{path}.weights")
        _require(len(bias) == len(weights),
                 f"bias length {len(bias)} != layer output dim "
                 f"{len(weights)}", f"{path}.bias")
        layers.append({"weights": weights, "bias": bias})
        dims = len(weights)

    out = None
    if kind == "mlp":
        out_in = data.get("output")
        _require(isinstance(out_in, dict),
                 "mlp model requires an 'output' {weights, bias} object",
                 "spec.output")
        out_weights = _validate_vector(out_in.get("weights"),
                                       "spec.output.weights")
        _require(len(out_weights) == dims,
                 f"output weights length {len(out_weights)} != last layer "
                 f"output dim {dims}", "spec.output.weights")
        out = {"weights": out_weights,
               "bias": _finite_number(out_in.get("bias", 0.0),
                                      "'bias' must be a finite number",
                                      "spec.output.bias")}
    else:
        _require(dims == n_features,
                 f"autoencoder final layer output dim {dims} must "
                 f"reconstruct all {n_features} features",
                 f"layers[{len(layers) - 1}].weights")

    normalized = {
        "token": token,
        "kind": kind,
        "tenant_token": data.get("tenant_token", "") or "",
        "device_type_token": data.get("device_type_token", "") or "",
        "alert_type": data.get("alert_type", "") or "anomaly.model",
        "alert_level": int(level),
        "alert_message": data.get("alert_message", "") or "",
        "active": bool(data.get("active", True)),
        "threshold": threshold,
        "features": features,
        "layers": layers,
    }
    if out is not None:
        normalized["output"] = out
    return normalized


# ---------------------------------------------------------------------------
# compilation: normalized spec -> weight rows at one model slot
# ---------------------------------------------------------------------------

def compile_model_into(table: AnomalyModelTable, slot: int, spec: Dict,
                       epoch: int, *, intern_measurement,
                       intern_alert_type, lookup_tenant,
                       lookup_device_type, measurement_slots: int) -> None:
    """Compile one normalized spec into model slot `slot` of `table`.

    The intern/lookup callables bind the spec's names to the engine's
    interners (pipeline/engine.py passes its packer + registry). A
    scoping token that does not resolve deactivates the model rather
    than silently widening to "any" — the same rule every other rule
    compiler applies. Bucket overflows (features/layers/width past the
    table's static shape) raise AnomalyModelError naming the field."""
    spec = model_from_dict(spec)  # idempotent; applies on every path
    F, L, H = table.num_features, table.num_layers, table.width

    features = spec["features"]
    if len(features) > F:
        raise AnomalyModelError(
            f"model over the static bucket: {len(features)} features > "
            f"{F} slots", "spec.features")
    layers = spec["layers"]
    if len(layers) > L:
        raise AnomalyModelError(
            f"model over the static bucket: {len(layers)} layers > {L}",
            "spec.layers")
    for i, layer in enumerate(layers):
        if len(layer["weights"]) > H:
            raise AnomalyModelError(
                f"layer output dim {len(layer['weights'])} > width "
                f"bucket {H}", f"layers[{i}].weights")

    mm_slots = []
    for i, feature in enumerate(features):
        mm = intern_measurement(feature["measurement"])
        if not (0 < mm < measurement_slots):
            raise AnomalyModelError(
                f"operand slot out of range: measurement "
                f"{feature['measurement']!r} interned to slot {mm}, "
                f"tracked slots are 1..{measurement_slots - 1}",
                f"features[{i}].measurement")
        mm_slots.append(mm)

    active = spec["active"]
    tenant_idx = dtype_idx = 0
    if spec["tenant_token"]:
        tenant_idx = lookup_tenant(spec["tenant_token"])
        active = active and tenant_idx > 0
    if spec["device_type_token"]:
        dtype_idx = lookup_device_type(spec["device_type_token"])
        active = active and dtype_idx > 0

    # clear the slot before writing (a recycled slot keeps no stale rows)
    table.feat_kind[slot, :] = FeatureKind.UNUSED
    table.feat_mm[slot, :] = 0
    table.feat_alpha[slot, :] = 0.0
    table.feat_mean[slot, :] = 0.0
    table.feat_scale[slot, :] = 1.0
    table.w[slot] = 0.0
    table.b[slot] = 0.0
    table.out_w[slot, :] = 0.0

    for i, feature in enumerate(features):
        table.feat_kind[slot, i] = FeatureKind.BY_NAME[feature["feature"]]
        table.feat_mm[slot, i] = mm_slots[i]
        table.feat_alpha[slot, i] = feature.get("alpha", 0.0)
        table.feat_mean[slot, i] = feature["mean"]
        table.feat_scale[slot, i] = 1.0 / feature["std"]
    for li, layer in enumerate(layers):
        wmat = np.asarray(layer["weights"], np.float32)
        table.w[slot, li, :wmat.shape[0], :wmat.shape[1]] = wmat
        table.b[slot, li, :wmat.shape[0]] = np.asarray(
            layer["bias"], np.float32)
    if "output" in spec:
        out_w = np.asarray(spec["output"]["weights"], np.float32)
        table.out_w[slot, :out_w.shape[0]] = out_w
        table.out_b[slot] = spec["output"]["bias"]
    else:
        table.out_b[slot] = 0.0

    table.active[slot] = active
    table.tenant_idx[slot] = tenant_idx
    table.device_type_idx[slot] = dtype_idx
    table.alert_level[slot] = spec["alert_level"]
    table.alert_type_idx[slot] = intern_alert_type(spec["alert_type"])
    table.kind[slot] = ModelKind.BY_NAME[spec["kind"]]
    table.n_features[slot] = len(features)
    table.n_layers[slot] = len(layers)
    table.threshold[slot] = spec["threshold"]
    table.epoch[slot] = epoch


def dry_run_compile(spec: Dict, *, measurement_slots: int,
                    max_features: int = DEFAULT_MODEL_FEATURES,
                    max_layers: int = DEFAULT_MODEL_LAYERS,
                    width: int = DEFAULT_MODEL_WIDTH,
                    intern_measurement=None) -> Dict:
    """Full validation WITHOUT touching a live table: used by the REST
    create and the replicated-apply paths so a bad spec 409s before any
    store/engine mutation. Returns the normalized spec. When no interner
    is supplied, measurement names validate structurally only (slot 1
    assumed) — the engine-side compile still enforces the range."""
    normalized = model_from_dict(spec)
    table = empty_model_table(1, max_features, max_layers, width)
    compile_model_into(
        table, 0, normalized, epoch=1,
        intern_measurement=intern_measurement or (lambda name: 1),
        intern_alert_type=lambda name: 0,
        lookup_tenant=lambda token: 1,
        lookup_device_type=lambda token: 1,
        measurement_slots=measurement_slots)
    return normalized
