"""ctypes bindings for the native host runtime (host_runtime.cc).

The shared library is compiled on first import with the toolchain g++ (no
external build system, no pybind11 — plain `extern "C"` + ctypes) and cached
next to the source; a stale cache (source newer than .so) rebuilds. Import
never fails: if the compiler or the build is unavailable the module exposes
``LIB = None`` and callers fall back to their pure-Python paths.

Set SITEWHERE_TPU_NO_NATIVE=1 to force the fallback (used by tests to cover
both paths).
"""

from __future__ import annotations

import ctypes
import os
import subprocess
from typing import List, Optional, Tuple

import numpy as np

_DIR = os.path.dirname(os.path.abspath(__file__))
_SRC = os.path.join(_DIR, "host_runtime.cc")
_SO = os.path.join(_DIR, "libswt_host.so")

LIB: Optional[ctypes.CDLL] = None
_build_error: Optional[str] = None


def _build() -> Optional[str]:
    """Compile the shared library if missing/stale; returns error or None."""
    try:
        if (os.path.exists(_SO)
                and os.path.getmtime(_SO) >= os.path.getmtime(_SRC)):
            return None
        tmp = f"{_SO}.{os.getpid()}.tmp"  # unique per process: concurrent
        cmd = ["g++", "-O3", "-std=c++17", "-shared", "-fPIC",  # first imports
               "-o", tmp, _SRC]                # must not interleave writes
        proc = subprocess.run(cmd, capture_output=True, text=True, timeout=120)
        if proc.returncode != 0:
            return proc.stderr[-2000:]
        os.replace(tmp, _SO)
        return None
    except (OSError, subprocess.SubprocessError) as exc:
        return str(exc)


def _load(_retry: bool = True) -> None:
    global LIB, _build_error
    if os.environ.get("SITEWHERE_TPU_NO_NATIVE") == "1":
        _build_error = "disabled by SITEWHERE_TPU_NO_NATIVE"
        return
    _build_error = _build()
    if _build_error is not None:
        return
    try:
        lib = ctypes.CDLL(_SO)
    except OSError as exc:
        _build_error = str(exc)
        return
    c = ctypes
    i32, i64, vp = c.c_int32, c.c_int64, c.c_void_p
    p_i32 = np.ctypeslib.ndpointer(np.int32, flags="C_CONTIGUOUS")
    p_i64 = np.ctypeslib.ndpointer(np.int64, flags="C_CONTIGUOUS")
    p_f32 = np.ctypeslib.ndpointer(np.float32, flags="C_CONTIGUOUS")
    # ABI gate FIRST: a stale cached .so (mtime-preserving deploys defeat
    # the staleness check) must not crash the import when a newer binding
    # looks up a symbol the old library doesn't export. The condition the
    # gate detects is also repairable: delete the stale cache and rebuild
    # from source once.
    try:
        lib.swt_version.restype = i32
        stale = lib.swt_version() != 9
    except AttributeError:
        stale = True
    if stale:
        if _retry:
            try:
                # dlopen dedupes by pathname: the stale mapping must be
                # dlclose'd or the rebuilt library would never be loaded
                import _ctypes

                _ctypes.dlclose(lib._handle)
                # missing_ok: a concurrent process may have repaired the
                # cache already — that's success, proceed to reload
                try:
                    os.remove(_SO)
                except FileNotFoundError:
                    pass
            except OSError as exc:
                _build_error = f"stale libswt_host.so (unremovable: {exc})"
                return
            _load(_retry=False)
            return
        _build_error = "version mismatch persists after rebuild"
        return
    lib.swt_interner_create.argtypes = [i32]
    lib.swt_interner_create.restype = vp
    lib.swt_interner_destroy.argtypes = [vp]
    lib.swt_interner_size.argtypes = [vp]
    lib.swt_interner_size.restype = i32
    lib.swt_interner_add.argtypes = [vp, c.c_char_p, i32]
    lib.swt_interner_add.restype = i32
    lib.swt_interner_add_gap.argtypes = [vp]
    lib.swt_interner_add_gap.restype = i32
    lib.swt_interner_token_at.argtypes = [vp, i32, c.c_char_p, i32]
    lib.swt_interner_token_at.restype = i32
    lib.swt_interner_set_at.argtypes = [vp, i32, c.c_char_p, i32]
    lib.swt_interner_set_at.restype = i32
    lib.swt_interner_lookup_offsets.argtypes = [vp, c.c_char_p, p_i64, i32,
                                                p_i32]
    lib.swt_interner_lookup_offsets.restype = i32
    lib.swt_interner_intern_offsets.argtypes = [vp, c.c_char_p, p_i64, i32,
                                                p_i32, i32]
    lib.swt_interner_intern_offsets.restype = i32
    lib.swt_decode_hot_frames.argtypes = [
        c.c_char_p, i64, i32,
        p_i32, p_i64, p_f32, p_f32, p_f32, p_f32, p_i32,
        c.c_char_p, i64, p_i64,
        c.c_char_p, i64, p_i64,
        c.c_char_p, i64, p_i64,
        p_i32, p_i64, p_i64, i32, p_i64]
    lib.swt_decode_hot_frames.restype = i32
    lib.swt_route_blob.argtypes = [p_i32, i64, i32, i32, i32, p_i32, p_i64,
                                   i64]
    lib.swt_route_blob.restype = i32
    p_u8 = np.ctypeslib.ndpointer(np.uint8, flags="C_CONTIGUOUS")
    lib.swt_pack_route_blob.argtypes = [p_i32, p_i32, p_i32, p_i32, p_f32,
                                        p_f32, p_f32, p_f32, p_i32, p_i32,
                                        p_u8, i64, i32, i32, i32, i32,
                                        p_i32, p_i64, i64]
    lib.swt_pack_route_blob.restype = i32
    lib.swt_pack_blob.argtypes = [p_i32, p_i32, p_i32, p_i32, p_f32, p_f32,
                                  p_f32, p_f32, p_i32, p_i32, p_u8, i64,
                                  i32, i32, p_i32]
    lib.swt_pack_blob.restype = i32
    lib.swt_unpack_blob.argtypes = [p_i32, i64, i32, p_i32, p_i32, p_i32,
                                    p_i32, p_f32, p_f32, p_f32, p_f32, p_i32,
                                    p_i32, p_u8]
    lib.swt_unpack_blob.restype = None
    LIB = lib


_load()


def available() -> bool:
    return LIB is not None


def build_error() -> Optional[str]:
    return _build_error


def join_tokens(tokens) -> Tuple[bytes, np.ndarray]:
    """Encode a sequence of str/bytes tokens into (joined buffer, offsets)."""
    enc = [t.encode(errors="surrogateescape") if isinstance(t, str) else t
           for t in tokens]
    off = np.zeros(len(enc) + 1, np.int64)
    np.cumsum([len(t) for t in enc], out=off[1:])
    return b"".join(enc), off


class NativeInterner:
    """Thin RAII wrapper over swt_interner_* (index 0 = UNKNOWN)."""

    def __init__(self, capacity: int):
        assert LIB is not None
        self._h = LIB.swt_interner_create(capacity)
        if not self._h:
            raise MemoryError("swt_interner_create failed")

    def __del__(self):
        h, self._h = getattr(self, "_h", None), None
        if h and LIB is not None:
            LIB.swt_interner_destroy(h)

    def __len__(self) -> int:
        return LIB.swt_interner_size(self._h)

    def add(self, token: str) -> int:
        """Get-or-assign; -1 signals capacity exceeded."""
        raw = token.encode(errors="surrogateescape")
        return LIB.swt_interner_add(self._h, raw, len(raw))

    def add_gap(self) -> int:
        """Append an unfindable gap-placeholder slot (the shard-congruent
        allocator); returns its index, -1 on capacity exceeded."""
        return LIB.swt_interner_add_gap(self._h)

    def set_at(self, idx: int, token: str) -> int:
        """Overwrite a gap-placeholder slot with a real token (the
        shard-congruent allocator). 0 ok, -1 bad index, -2 token exists
        at a different index."""
        raw = token.encode(errors="surrogateescape")
        return LIB.swt_interner_set_at(self._h, idx, raw, len(raw))

    def token_at(self, idx: int) -> Optional[str]:
        cap = 1024
        while True:
            buf = ctypes.create_string_buffer(cap)
            n = LIB.swt_interner_token_at(self._h, idx, buf, cap)
            if n >= 0:
                # tokens are raw wire bytes; surrogateescape keeps non-UTF-8
                # byte sequences round-trippable through the str mirror
                return buf.raw[:n].decode(errors="surrogateescape")
            if n == -1:
                return None
            cap = -n - 2  # buffer was too small; retry at the exact size

    def lookup_offsets(self, buf: bytes, off: np.ndarray) -> np.ndarray:
        n = len(off) - 1
        out = np.empty(n, np.int32)
        LIB.swt_interner_lookup_offsets(self._h, buf, off, n, out)
        return out

    def intern_offsets(self, buf: bytes, off: np.ndarray,
                       skip_empty: bool = False) -> Tuple[np.ndarray, bool]:
        """Returns (indices, capacity_ok). skip_empty maps zero-length
        tokens to UNKNOWN without interning them."""
        n = len(off) - 1
        out = np.empty(n, np.int32)
        rc = LIB.swt_interner_intern_offsets(self._h, buf, off, n, out,
                                             1 if skip_empty else 0)
        return out, rc == 0

    def lookup_batch(self, tokens) -> np.ndarray:
        buf, off = join_tokens(tokens)
        return self.lookup_offsets(buf, off)

    def intern_batch(self, tokens) -> Tuple[np.ndarray, bool]:
        buf, off = join_tokens(tokens)
        return self.intern_offsets(buf, off)


class DecodedColumns:
    """Output of decode_hot_frames: SoA columns + string buffers + control
    frames. String columns stay as (bytes, offsets) so they can feed the
    native interner without materializing Python strings."""

    __slots__ = ("n", "event_type", "ts_ms", "value", "lat", "lon",
                 "elevation", "alert_level", "tokens", "names", "alert_types",
                 "others", "consumed")

    def __init__(self, n, event_type, ts_ms, value, lat, lon, elevation,
                 alert_level, tokens, names, alert_types, others, consumed):
        self.n = n
        self.event_type = event_type
        self.ts_ms = ts_ms
        self.value = value
        self.lat = lat
        self.lon = lon
        self.elevation = elevation
        self.alert_level = alert_level
        self.tokens = tokens            # (bytes, offsets[n+1])
        self.names = names              # (bytes, offsets[n+1])
        self.alert_types = alert_types  # (bytes, offsets[n+1])
        self.others = others            # [(msg_type, payload bytes)]
        self.consumed = consumed

    def token_list(self) -> List[str]:
        buf, off = self.tokens
        return [buf[off[i]:off[i + 1]].decode(errors="surrogateescape")
                for i in range(self.n)]


from sitewhere_tpu.transport.wire import WireError as _WireError


class WireDecodeError(_WireError):
    """Raised on malformed wire streams; subclasses transport.wire.WireError
    so `except WireError` handlers cover both ingest lanes."""


def decode_hot_frames(data: bytes, max_events: Optional[int] = None
                      ) -> DecodedColumns:
    """Single-pass native decode of a wire byte stream (see host_runtime.cc).

    Raises WireDecodeError on malformed input; a trailing partial frame is
    returned via `consumed` (callers keep the remainder buffered).
    """
    assert LIB is not None
    cap = max_events if max_events is not None else max(len(data) // 13, 1)
    et = np.empty(cap, np.int32)
    ts = np.empty(cap, np.int64)
    val = np.empty(cap, np.float32)
    lat = np.empty(cap, np.float32)
    lon = np.empty(cap, np.float32)
    ele = np.empty(cap, np.float32)
    lvl = np.empty(cap, np.int32)
    tok_cap = len(data)
    tok_buf = ctypes.create_string_buffer(tok_cap or 1)
    name_buf = ctypes.create_string_buffer(tok_cap or 1)
    atype_buf = ctypes.create_string_buffer(tok_cap or 1)
    tok_off = np.zeros(cap + 1, np.int64)
    name_off = np.zeros(cap + 1, np.int64)
    atype_off = np.zeros(cap + 1, np.int64)
    other_cap = max(len(data) // 8, 1)
    other_type = np.empty(other_cap, np.int32)
    other_off = np.empty(other_cap, np.int64)
    other_len = np.empty(other_cap, np.int64)
    counts = np.zeros(4, np.int64)
    LIB.swt_decode_hot_frames(
        data, len(data), cap, et, ts, val, lat, lon, ele, lvl,
        tok_buf, tok_cap, tok_off, name_buf, tok_cap, name_off,
        atype_buf, tok_cap, atype_off,
        other_type, other_off, other_len, other_cap, counts)
    n, m, consumed, err = (int(counts[0]), int(counts[1]), int(counts[2]),
                           int(counts[3]))
    if err == 1:
        raise WireDecodeError("bad magic/version")
    if err == 3:
        raise WireDecodeError("malformed frame payload")
    if err == 2:
        raise WireDecodeError("decode capacity exceeded")
    others = [(int(other_type[i]),
               data[int(other_off[i]):int(other_off[i]) + int(other_len[i])])
              for i in range(m)]
    return DecodedColumns(
        n, et[:n], ts[:n], val[:n], lat[:n], lon[:n], ele[:n], lvl[:n],
        (tok_buf.raw[:int(tok_off[n])], tok_off[:n + 1]),
        (name_buf.raw[:int(name_off[n])], name_off[:n + 1]),
        (atype_buf.raw[:int(atype_off[n])], atype_off[:n + 1]),
        others, consumed)


def route_blob(blob: np.ndarray, n_shards: int, per_shard: int
               ) -> Tuple[np.ndarray, np.ndarray]:
    """Shard-route a flat wire blob [wire_rows, n] -> ([S, wire_rows, B]
    routed blob, flat-row indices of overflow); wire_rows follows the
    input blob (4 = compact). Requires available(); callers fall back to
    the numpy router otherwise."""
    blob = np.ascontiguousarray(blob, np.int32)
    rows, n = blob.shape
    out = np.zeros((n_shards, rows, per_shard), np.int32)
    overflow = np.empty(max(n, 1), np.int64)
    n_over = LIB.swt_route_blob(blob.reshape(-1), n, n_shards, per_shard,
                                rows, out.reshape(-1), overflow,
                                len(overflow))
    if n_over < 0:  # cannot happen with overflow_cap=n; defensive
        raise RuntimeError("route_blob overflow capacity exceeded")
    return out, overflow[:n_over]


def pack_route_blob(batch, n_shards: int, per_shard: int,
                    out: Optional[np.ndarray] = None,
                    wire_rows: Optional[int] = None,
                    ts_base: int = 0
                    ) -> Optional[Tuple[np.ndarray, np.ndarray]]:
    """Fused pack+route: EventBatch columns -> routed [S, wire_rows, B]
    blob + overflow flat-row indices in ONE native pass (see
    swt_pack_route_blob). wire_rows 5, or 4 for the compact no-elevation
    variant; derived from `out` when a buffer is supplied. `out` may be a
    reused staging buffer — it does NOT need to be zeroed (the kernel
    clears exactly the head-row tails whose valid bits must read 0).
    Returns None when a device_idx is out of wire range (caller raises
    the shared diagnostic). Requires available()."""
    from sitewhere_tpu.ops.pack import WIRE_ROWS

    n = batch.device_idx.shape[0]
    if out is not None:
        wire_rows = out.shape[1]
    elif wire_rows is None:
        wire_rows = WIRE_ROWS
    if out is None:
        out = np.empty((n_shards, wire_rows, per_shard), np.int32)

    def i32(a):
        return np.ascontiguousarray(a, np.int32)

    def f32(a):
        return np.ascontiguousarray(a, np.float32)

    overflow = np.empty(max(n, 1), np.int64)
    rc = LIB.swt_pack_route_blob(
        i32(batch.device_idx), i32(batch.event_type), i32(batch.ts),
        i32(batch.mm_idx), f32(batch.value), f32(batch.lat), f32(batch.lon),
        f32(batch.elevation), i32(batch.alert_type_idx),
        i32(batch.alert_level),
        np.ascontiguousarray(batch.valid, np.uint8), n, n_shards, per_shard,
        wire_rows, ts_base, out.reshape(-1), overflow, len(overflow))
    if rc == -2:
        return None
    if rc < 0:  # cannot happen with overflow_cap=n; defensive
        raise RuntimeError("pack_route_blob overflow capacity exceeded")
    return out, overflow[:rc]


def pack_blob(batch, out: np.ndarray, ts_base: int = 0) -> bool:
    """One-pass EventBatch columns -> [wire_rows, n] wire blob (flat
    batches only; leading-axis batches use the numpy path; wire_rows from
    out.shape[0] — 4 = compact no-elevation variant). Returns False when
    a device_idx is out of wire range (caller raises with detail).
    Requires available()."""
    n = batch.device_idx.shape[0]

    def i32(a):
        return np.ascontiguousarray(a, np.int32)

    def f32(a):
        return np.ascontiguousarray(a, np.float32)

    rc = LIB.swt_pack_blob(
        i32(batch.device_idx), i32(batch.event_type), i32(batch.ts),
        i32(batch.mm_idx), f32(batch.value), f32(batch.lat), f32(batch.lon),
        f32(batch.elevation), i32(batch.alert_type_idx),
        i32(batch.alert_level),
        np.ascontiguousarray(batch.valid, np.uint8), n, out.shape[0],
        ts_base, out.reshape(-1))
    return rc == 0


def unpack_blob(blob: np.ndarray, cols: dict) -> None:
    """One-pass [wire_rows, n] wire blob -> preallocated column arrays
    (keys: device_idx..valid; 4-row compact blobs unpack with elevation
    0). Requires available()."""
    n = blob.shape[-1]
    LIB.swt_unpack_blob(
        np.ascontiguousarray(blob, np.int32).reshape(-1), n, blob.shape[-2],
        cols["device_idx"], cols["event_type"], cols["ts"], cols["mm_idx"],
        cols["value"], cols["lat"], cols["lon"], cols["elevation"],
        cols["alert_type_idx"], cols["alert_level"], cols["valid"])
