// Native host runtime for sitewhere_tpu: the pieces of the ingest path that
// must run at millions of events/sec on the host CPU, ahead of the TPU step.
//
// The reference implements this tier on the JVM (per-event protobuf decode in
// sitewhere-communication ProtobufDeviceEventDecoder.java + per-event device
// lookups, InboundPayloadProcessingLogic.java:156); here it is a small C++
// library driven through ctypes:
//
//   1. swt_interner_*: string token -> dense int32 index table
//      (SURVEY.md §7 hard part (c): token interning at 1M+/s). FNV-1a hash,
//      open addressing, shared_mutex (concurrent receiver threads).
//   2. swt_decode_hot_frames: one pass over a wire-protocol byte stream
//      (transport/wire.py frame layout) producing SoA columns for the hot
//      event types and an index of control frames for the Python side.
//
// Built with: g++ -O3 -std=c++17 -shared -fPIC (see native/__init__.py).

#include <atomic>
#include <cstdint>
#include <cstring>
#include <mutex>
#include <shared_mutex>
#include <string>
#include <vector>

namespace {

constexpr uint64_t kFnvOffset = 1469598103934665603ull;
constexpr uint64_t kFnvPrime = 1099511628211ull;

inline uint64_t fnv1a(const char* data, int64_t len) {
  uint64_t h = kFnvOffset;
  for (int64_t i = 0; i < len; ++i) {
    h ^= static_cast<uint8_t>(data[i]);
    h *= kFnvPrime;
  }
  return h;
}

inline size_t next_pow2(size_t v) {
  size_t p = 1;
  while (p < v) p <<= 1;
  return p;
}

struct Interner {
  // 4x capacity hash slots: at most `capacity` tokens are ever hashed
  // (gap placeholders from swt_interner_add_gap never enter the hash),
  // so the load factor stays <= 0.25 and open-addressing probes short.
  explicit Interner(int32_t capacity)
      : capacity(capacity), mask(next_pow2(static_cast<size_t>(capacity) * 4) - 1),
        slots(mask + 1, -1), hashes(mask + 1, 0) {
    tokens.reserve(capacity);
    tokens.emplace_back();  // index 0 = UNKNOWN sentinel, never matched
  }

  int32_t capacity;
  size_t mask;
  std::vector<int32_t> slots;     // slot -> token index, -1 empty
  std::vector<uint64_t> hashes;   // slot -> full hash (cheap reject)
  std::vector<std::string> tokens;  // index -> bytes
  mutable std::shared_mutex mu;

  // Requires at least a shared lock. Gap placeholders (shard-congruent
  // allocator) are appended via add_gap WITHOUT a hash entry, so they can
  // never satisfy a lookup — no byte pattern is reserved, and arbitrary
  // wire tokens (including NUL-prefixed ones) intern normally.
  int32_t find(const char* tok, int64_t len, uint64_t h) const {
    size_t slot = h & mask;
    while (true) {
      int32_t idx = slots[slot];
      if (idx < 0) return -1;
      if (hashes[slot] == h) {
        const std::string& s = tokens[static_cast<size_t>(idx)];
        if (static_cast<int64_t>(s.size()) == len &&
            std::memcmp(s.data(), tok, static_cast<size_t>(len)) == 0)
          return idx;
      }
      slot = (slot + 1) & mask;
    }
  }

  // Requires the unique lock.
  int32_t add(const char* tok, int64_t len, uint64_t h) {
    int32_t idx = find(tok, len, h);
    if (idx >= 0) return idx;
    if (static_cast<int32_t>(tokens.size()) >= capacity) return -1;
    idx = static_cast<int32_t>(tokens.size());
    tokens.emplace_back(tok, static_cast<size_t>(len));
    size_t slot = h & mask;
    while (slots[slot] >= 0) slot = (slot + 1) & mask;
    slots[slot] = idx;
    hashes[slot] = h;
    return idx;
  }

  // Requires the unique lock. Append a gap placeholder: occupies the next
  // index in the token table but is NOT inserted into the hash, so no
  // lookup can ever return it. set_at later fills it with a real token.
  int32_t add_gap() {
    if (static_cast<int32_t>(tokens.size()) >= capacity) return -1;
    int32_t idx = static_cast<int32_t>(tokens.size());
    tokens.emplace_back();
    return idx;
  }
};

}  // namespace

extern "C" {

int32_t swt_version() { return 9; }

void* swt_interner_create(int32_t capacity) {
  if (capacity < 2) return nullptr;
  return new Interner(capacity);
}

void swt_interner_destroy(void* h) { delete static_cast<Interner*>(h); }

int32_t swt_interner_size(void* h) {
  Interner* in = static_cast<Interner*>(h);
  std::shared_lock<std::shared_mutex> lock(in->mu);
  return static_cast<int32_t>(in->tokens.size());
}

// Get-or-assign one token; returns its index, or -1 when capacity exceeded.
int32_t swt_interner_add(void* h, const char* tok, int32_t len) {
  Interner* in = static_cast<Interner*>(h);
  uint64_t hash = fnv1a(tok, len);
  {
    std::shared_lock<std::shared_mutex> lock(in->mu);
    int32_t idx = in->find(tok, len, hash);
    if (idx >= 0) return idx;
  }
  std::unique_lock<std::shared_mutex> lock(in->mu);
  return in->add(tok, len, hash);
}

// Append a gap placeholder slot (shard-congruent allocator —
// registry/interning.py): takes the next index without a hash entry, so
// it is unfindable by construction. Returns the new index, or -1 when
// capacity is exceeded.
int32_t swt_interner_add_gap(void* h) {
  Interner* in = static_cast<Interner*>(h);
  std::unique_lock<std::shared_mutex> lock(in->mu);
  return in->add_gap();
}

// Overwrite the token at an EXISTING index (a gap placeholder from the
// shard-congruent allocator — registry/interning.py). The real token is
// inserted into the hash pointing at idx; the placeholder had no hash
// entry, so nothing dangles, and the token table slot is replaced so
// token_at/snapshot read the real token. Returns 0, -1 for an
// out-of-range idx, -2 when the token already exists at a DIFFERENT
// index (caller bug).
int32_t swt_interner_set_at(void* h, int32_t idx, const char* tok,
                            int32_t len) {
  Interner* in = static_cast<Interner*>(h);
  uint64_t hash = fnv1a(tok, len);
  std::unique_lock<std::shared_mutex> lock(in->mu);
  if (idx <= 0 || idx >= static_cast<int32_t>(in->tokens.size())) return -1;
  int32_t existing = in->find(tok, len, hash);
  if (existing >= 0) return existing == idx ? 0 : -2;
  in->tokens[static_cast<size_t>(idx)].assign(tok, static_cast<size_t>(len));
  size_t slot = hash & in->mask;
  while (in->slots[slot] >= 0) slot = (slot + 1) & in->mask;
  in->slots[slot] = idx;
  in->hashes[slot] = hash;
  return 0;
}

// Copy token bytes for index `idx` into out (cap bytes); returns byte
// length, -1 if idx is out of range, or -(2 + needed_len) when the buffer
// is too small (so callers can retry with a bigger one).
int32_t swt_interner_token_at(void* h, int32_t idx, char* out, int32_t cap) {
  Interner* in = static_cast<Interner*>(h);
  std::shared_lock<std::shared_mutex> lock(in->mu);
  if (idx <= 0 || idx >= static_cast<int32_t>(in->tokens.size())) return -1;
  const std::string& s = in->tokens[static_cast<size_t>(idx)];
  if (static_cast<int32_t>(s.size()) > cap)
    return -(2 + static_cast<int32_t>(s.size()));
  std::memcpy(out, s.data(), s.size());
  return static_cast<int32_t>(s.size());
}

// Batch lookup: n tokens in `buf` delimited by offsets [n+1]; unknown -> 0.
int32_t swt_interner_lookup_offsets(void* h, const char* buf,
                                    const int64_t* off, int32_t n,
                                    int32_t* out_idx) {
  Interner* in = static_cast<Interner*>(h);
  std::shared_lock<std::shared_mutex> lock(in->mu);
  for (int32_t i = 0; i < n; ++i) {
    const char* tok = buf + off[i];
    int64_t len = off[i + 1] - off[i];
    int32_t idx = in->find(tok, len, fnv1a(tok, len));
    out_idx[i] = idx < 0 ? 0 : idx;
  }
  return 0;
}

// Batch get-or-assign. Returns 0, or -1 if capacity was exceeded (out_idx is
// filled with 0 for the tokens that no longer fit). With skip_empty != 0,
// zero-length tokens map to 0 without interning (an "absent" field in a
// decoded column, e.g. measurement names on location events).
int32_t swt_interner_intern_offsets(void* h, const char* buf,
                                    const int64_t* off, int32_t n,
                                    int32_t* out_idx, int32_t skip_empty) {
  Interner* in = static_cast<Interner*>(h);
  int32_t rc = 0;
  // Fast pass under the shared lock: most tokens already exist.
  std::vector<int32_t> missing;
  {
    std::shared_lock<std::shared_mutex> lock(in->mu);
    for (int32_t i = 0; i < n; ++i) {
      const char* tok = buf + off[i];
      int64_t len = off[i + 1] - off[i];
      if (skip_empty && len == 0) {
        out_idx[i] = 0;
        continue;
      }
      out_idx[i] = in->find(tok, len, fnv1a(tok, len));
      if (out_idx[i] < 0) missing.push_back(i);
    }
  }
  if (!missing.empty()) {
    std::unique_lock<std::shared_mutex> lock(in->mu);
    for (int32_t i : missing) {
      const char* tok = buf + off[i];
      int64_t len = off[i + 1] - off[i];
      int32_t idx = in->add(tok, len, fnv1a(tok, len));
      if (idx < 0) {
        out_idx[i] = 0;
        rc = -1;
      } else {
        out_idx[i] = idx;
      }
    }
  }
  return rc;
}

// ---------------------------------------------------------------------------
// Wire-protocol hot-frame decoder (layout doc: transport/wire.py).
//
// Frame: "SW" u8 version u8 msg_type u32 payload_len payload.
// Hot payloads (msg_type 3/4/5): u8 token_len, token, i64 ts_ms, then
//   MEASUREMENT(3): u8 name_len, name, f32 value
//   LOCATION(4):    f32 lat, f32 lon, f32 elevation
//   ALERT(5):       u8 type_len, type, u8 level, u16 msg_len, msg
//
// Event-type codes written to `event_type` are the model enum values
// (model/event.py DeviceEventType): MEASUREMENT=0, LOCATION=1, ALERT=2.
//
// counts[0]=n_hot, counts[1]=n_other, counts[2]=consumed_bytes,
// counts[3]=error (0 ok; 1 bad magic/version; 2 capacity; 3 malformed).
// A trailing partial frame is not an error: it is left unconsumed.
// ---------------------------------------------------------------------------

namespace {
inline uint32_t rd_u32(const uint8_t* p) {
  uint32_t v;
  std::memcpy(&v, p, 4);
  return v;
}
inline int64_t rd_i64(const uint8_t* p) {
  int64_t v;
  std::memcpy(&v, p, 8);
  return v;
}
inline float rd_f32(const uint8_t* p) {
  float v;
  std::memcpy(&v, p, 4);
  return v;
}
}  // namespace

int32_t swt_decode_hot_frames(
    const uint8_t* buf, int64_t len, int32_t cap,
    int32_t* event_type, int64_t* ts, float* value, float* lat, float* lon,
    float* elevation, int32_t* alert_level,
    char* tok_buf, int64_t tok_cap, int64_t* tok_off,
    char* name_buf, int64_t name_cap, int64_t* name_off,
    char* atype_buf, int64_t atype_cap, int64_t* atype_off,
    int32_t* other_type, int64_t* other_off, int64_t* other_len,
    int32_t other_cap, int64_t* counts) {
  int64_t pos = 0;
  int32_t n = 0, m = 0;
  int64_t tok_pos = 0, name_pos = 0, atype_pos = 0;
  tok_off[0] = name_off[0] = atype_off[0] = 0;
  counts[0] = counts[1] = counts[2] = counts[3] = 0;
  constexpr int64_t kMaxPayload = 16ll * 1024 * 1024;  // wire.MAX_FRAME_PAYLOAD

  while (len - pos >= 8) {
    const uint8_t* hdr = buf + pos;
    if (hdr[0] != 'S' || hdr[1] != 'W' || hdr[2] != 1) {
      counts[3] = 1;
      break;
    }
    uint8_t mtype = hdr[3];
    int64_t plen = static_cast<int64_t>(rd_u32(hdr + 4));
    if (plen > kMaxPayload) {
      counts[3] = 3;
      break;
    }
    if (len - pos - 8 < plen) break;  // partial frame: stop, not an error
    const uint8_t* p = buf + pos + 8;
    if (mtype < 3 || mtype > 5) {   // control frame: index for Python
      if (m >= other_cap) {
        counts[3] = 2;
        break;
      }
      other_type[m] = mtype;
      other_off[m] = pos + 8;
      other_len[m] = plen;
      ++m;
      pos += 8 + plen;
      continue;
    }
    if (n >= cap) {
      counts[3] = 2;
      break;
    }
    // hot event payload
    const uint8_t* end = p + plen;
    if (p >= end) {
      counts[3] = 3;
      break;
    }
    int64_t tlen = *p++;
    if (p + tlen + 8 > end || tok_pos + tlen > tok_cap) {
      counts[3] = tok_pos + tlen > tok_cap ? 2 : 3;
      break;
    }
    std::memcpy(tok_buf + tok_pos, p, static_cast<size_t>(tlen));
    tok_pos += tlen;
    p += tlen;
    int64_t ets = rd_i64(p);
    p += 8;
    int32_t etype;
    float ev = 0, ela = 0, elo = 0, eel = 0;
    int32_t elev = 0;
    int64_t nlen = 0, alen = 0;
    bool ok = true;
    if (mtype == 3) {  // MEASUREMENT
      etype = 0;
      ok = p < end;
      if (ok) {
        nlen = *p++;
        ok = p + nlen + 4 <= end && name_pos + nlen <= name_cap;
      }
      if (ok) {
        std::memcpy(name_buf + name_pos, p, static_cast<size_t>(nlen));
        p += nlen;
        ev = rd_f32(p);
      }
    } else if (mtype == 4) {  // LOCATION
      etype = 1;
      ok = p + 12 <= end;
      if (ok) {
        ela = rd_f32(p);
        elo = rd_f32(p + 4);
        eel = rd_f32(p + 8);
      }
    } else {  // ALERT
      etype = 2;
      ok = p < end;
      if (ok) {
        alen = *p++;
        ok = p + alen + 3 <= end && atype_pos + alen <= atype_cap;
      }
      if (ok) {
        std::memcpy(atype_buf + atype_pos, p, static_cast<size_t>(alen));
        p += alen;
        elev = *p;
      }
    }
    if (!ok) {
      counts[3] = 3;
      break;
    }
    event_type[n] = etype;
    ts[n] = ets;
    value[n] = ev;
    lat[n] = ela;
    lon[n] = elo;
    elevation[n] = eel;
    alert_level[n] = elev;
    name_pos += nlen;
    atype_pos += alen;
    ++n;
    tok_off[n] = tok_pos;
    name_off[n] = name_pos;
    atype_off[n] = atype_pos;
    pos += 8 + plen;
  }
  counts[0] = n;
  counts[1] = m;
  counts[2] = pos;
  return counts[3] == 0 ? 0 : -1;
}

// Shard routing of the wire blob (ops/pack.py v2 layout: 5 rows
// [dev|type|level|valid packed, ts, payloadA, payloadB, elevation];
// row 0 bits 0-21 = device_idx, bit 28 = valid).
// One pass with per-shard cursors replaces the Python router's argsort +
// 12 column gather/scatters. `out` is [S, 5, B] and must arrive zeroed
// (row-0 valid bit 0 == invalid). Valid rows beyond a shard's capacity
// report their flat-row indices through `overflow_rows` (stable order).
// The device field of the routed row 0 is rewritten to the LOCAL index
// dev / S (type/level/valid bits preserved). Returns the overflow count,
// or -1 when overflow_cap is too small.
static constexpr int kWireRows = 5;
static constexpr int32_t kWireDevMask = (1 << 22) - 1;
static constexpr int32_t kWireValidBit = 1 << 28;
static constexpr int32_t kIdxMask = (1 << 12) - 1;  // mm/alert-type width
static constexpr int32_t kEtMeasurement = 0;  // model/event.py DeviceEventType
static constexpr int32_t kEtLocation = 1;
static constexpr int32_t kEtAlert = 2;
// PACKED 3-row variant (ops/pack.py WIRE_ROWS_PACKED): ts travels as a
// 16-bit delta against a per-batch base embedded in row 0's spare bits
// (3 per lane, lanes 0..10); mm/alert idx shares row 1 with the delta.
static constexpr int32_t kTsDeltaMask = (1 << 16) - 1;
static constexpr int32_t kPkIdxShift = 16;
static constexpr int32_t kBaseShift = 29;
static constexpr int32_t kBaseLanes = 11;

// OR the 32-bit ts base into row0's spare bits (row0 has >= kBaseLanes
// lanes — enforced by the packed-variant eligibility check host-side).
static inline void embed_ts_base(int32_t* row0, int32_t ts_base) {
  uint32_t base = static_cast<uint32_t>(ts_base);
  for (int32_t lane = 0; lane < kBaseLanes; ++lane) {
    uint32_t bits = (base >> (3 * lane)) & 7u;
    row0[lane] |= static_cast<int32_t>(bits << kBaseShift);
  }
}

static inline int32_t extract_ts_base(const int32_t* row0) {
  uint32_t base = 0;
  for (int32_t lane = 0; lane < kBaseLanes; ++lane) {
    uint32_t bits =
        (static_cast<uint32_t>(row0[lane]) >> kBaseShift) & 7u;
    base |= bits << (3 * lane);
  }
  return static_cast<int32_t>(base);
}

namespace {
inline int32_t f32_bits(float v) {
  int32_t out;
  std::memcpy(&out, &v, 4);
  return out;
}
inline float bits_f32(int32_t v) {
  float out;
  std::memcpy(&out, &v, 4);
  return out;
}
}  // namespace

// Pack EventBatch columns into the wire blob (ops/pack.py layout doc)
// in one pass — replaces 8 numpy full-column passes (3 of them np.where
// selects) on the hottest host path. `out` is [wire_rows, n]; wire_rows
// is 5, or 4 for the COMPACT variant that omits the elevation row (the
// caller chooses it when no row carries a nonzero elevation — 16 B/event
// instead of 20 on a transfer-bound path). Returns 0, or -1 when a
// device_idx is outside [0, 2^22) (caller raises).
int32_t swt_pack_blob(const int32_t* device_idx, const int32_t* event_type,
                      const int32_t* ts, const int32_t* mm_idx,
                      const float* value, const float* lat, const float* lon,
                      const float* elevation, const int32_t* alert_type_idx,
                      const int32_t* alert_level, const uint8_t* valid,
                      int64_t n, int32_t wire_rows, int32_t ts_base,
                      int32_t* out) {
  int32_t* head = out;
  int32_t* ts_row = out + n;
  int32_t* pa = out + 2 * n;
  if (wire_rows == 3) {  // packed: delta ts | idx, value bits, no location
    for (int64_t i = 0; i < n; ++i) {
      int32_t dev = device_idx[i];
      if (dev < 0 || dev > kWireDevMask) return -1;
      int32_t et = event_type[i] & 7;
      head[i] = dev | (et << 22) | ((alert_level[i] & 7) << 25) |
                ((valid[i] ? 1 : 0) << 28);
      int32_t delta = valid[i] ? (ts[i] - ts_base) & kTsDeltaMask : 0;
      int32_t idx =
          (et == kEtAlert ? alert_type_idx[i] : mm_idx[i]) & kIdxMask;
      ts_row[i] = delta | (idx << kPkIdxShift);
      pa[i] = f32_bits(value[i]);
    }
    embed_ts_base(head, ts_base);
    return 0;
  }
  int32_t* pb = out + 3 * n;
  int32_t* elev = wire_rows >= 5 ? out + 4 * n : nullptr;
  for (int64_t i = 0; i < n; ++i) {
    int32_t dev = device_idx[i];
    if (dev < 0 || dev > kWireDevMask) return -1;
    int32_t et = event_type[i] & 7;
    head[i] = dev | (et << 22) | ((alert_level[i] & 7) << 25) |
              ((valid[i] ? 1 : 0) << 28);
    ts_row[i] = ts[i];
    if (et == kEtLocation) {
      pa[i] = f32_bits(lat[i]);
      pb[i] = f32_bits(lon[i]);
    } else {
      pa[i] = f32_bits(value[i]);
      pb[i] = (et == kEtAlert ? alert_type_idx[i] : mm_idx[i]) & kIdxMask;
    }
    if (elev) elev[i] = f32_bits(elevation[i]);
  }
  return 0;
}

// Inverse of swt_pack_blob (one pass; `blob` is [wire_rows, n]; a 4-row
// compact blob unpacks with elevation 0). tenant_idx is not on the wire —
// the caller zero-fills it.
void swt_unpack_blob(const int32_t* blob, int64_t n, int32_t wire_rows,
                     int32_t* device_idx,
                     int32_t* event_type, int32_t* ts, int32_t* mm_idx,
                     float* value, float* lat, float* lon, float* elevation,
                     int32_t* alert_type_idx, int32_t* alert_level,
                     uint8_t* valid) {
  const int32_t* head = blob;
  const int32_t* ts_row = blob + n;
  const int32_t* pa = blob + 2 * n;
  if (wire_rows == 3) {  // packed variant
    int32_t base = extract_ts_base(head);
    for (int64_t i = 0; i < n; ++i) {
      int32_t h = head[i];
      int32_t et = (h >> 22) & 7;
      device_idx[i] = h & kWireDevMask;
      event_type[i] = et;
      alert_level[i] = (h >> 25) & 7;
      valid[i] = (h & kWireValidBit) ? 1 : 0;
      ts[i] = base + (ts_row[i] & kTsDeltaMask);
      int32_t idx = (ts_row[i] >> kPkIdxShift) & kIdxMask;
      mm_idx[i] = et == kEtMeasurement ? idx : 0;
      alert_type_idx[i] = et == kEtAlert ? idx : 0;
      value[i] = et == kEtMeasurement ? bits_f32(pa[i]) : 0.0f;
      lat[i] = 0.0f;
      lon[i] = 0.0f;
      elevation[i] = 0.0f;
    }
    return;
  }
  const int32_t* pb = blob + 3 * n;
  const int32_t* elev = wire_rows >= 5 ? blob + 4 * n : nullptr;
  for (int64_t i = 0; i < n; ++i) {
    int32_t h = head[i];
    int32_t et = (h >> 22) & 7;
    device_idx[i] = h & kWireDevMask;
    event_type[i] = et;
    alert_level[i] = (h >> 25) & 7;
    valid[i] = (h & kWireValidBit) ? 1 : 0;
    ts[i] = ts_row[i];
    if (et == kEtLocation) {
      lat[i] = bits_f32(pa[i]);
      lon[i] = bits_f32(pb[i]);
      value[i] = 0.0f;
      mm_idx[i] = 0;
      alert_type_idx[i] = 0;
    } else {
      lat[i] = 0.0f;
      lon[i] = 0.0f;
      value[i] = et == kEtMeasurement ? bits_f32(pa[i]) : 0.0f;
      mm_idx[i] = et == kEtMeasurement ? pb[i] : 0;
      alert_type_idx[i] = et == kEtAlert ? pb[i] : 0;
    }
    elevation[i] = elev ? bits_f32(elev[i]) : 0.0f;
  }
}

// Fused pack+route: EventBatch columns -> routed [S, kWireRows, B] blob in
// ONE pass (replaces swt_pack_blob + swt_route_blob back to back — two full
// passes over the batch plus a zeroed 5*S*B intermediate). `out` does NOT
// need to arrive zeroed: after routing, only the head-row tails (positions
// cursor[s]..B, whose valid bit must read 0) are cleared — the other rows
// of unfilled positions are never read because the device step masks on the
// head valid bit. Invalid input rows are skipped (padding). Returns the
// overflow count, -1 when overflow_cap is too small, or -2 when a valid
// row's device_idx is outside [0, 2^22) (caller raises the shared
// diagnostic).
int32_t swt_pack_route_blob(
    const int32_t* device_idx, const int32_t* event_type, const int32_t* ts,
    const int32_t* mm_idx, const float* value, const float* lat,
    const float* lon, const float* elevation, const int32_t* alert_type_idx,
    const int32_t* alert_level, const uint8_t* valid, int64_t n, int32_t S,
    int32_t B, int32_t wire_rows, int32_t ts_base, int32_t* out,
    int64_t* overflow_rows, int64_t overflow_cap) {
  std::vector<int32_t> cursor(static_cast<size_t>(S), 0);
  int64_t n_over = 0;
  const int64_t shard_stride = static_cast<int64_t>(wire_rows) * B;
  const bool with_elev = wire_rows >= 5;
  const bool packed = wire_rows == 3;
  for (int64_t i = 0; i < n; ++i) {
    if (!valid[i]) continue;
    int32_t dev = device_idx[i];
    if (dev < 0 || dev > kWireDevMask) return -2;
    int32_t s = dev % S;
    int32_t pos = cursor[s];
    if (pos >= B) {
      if (n_over >= overflow_cap) return -1;
      overflow_rows[n_over++] = i;
      continue;
    }
    cursor[s] = pos + 1;
    int32_t* dst = out + s * shard_stride + pos;
    int32_t et = event_type[i] & 7;
    dst[0] = (dev / S) | (et << 22) | ((alert_level[i] & 7) << 25) |
             kWireValidBit;
    if (packed) {
      int32_t delta = (ts[i] - ts_base) & kTsDeltaMask;
      int32_t idx =
          (et == kEtAlert ? alert_type_idx[i] : mm_idx[i]) & kIdxMask;
      dst[B] = delta | (idx << kPkIdxShift);
      dst[2 * B] = f32_bits(value[i]);
      continue;
    }
    dst[B] = ts[i];
    if (et == kEtLocation) {
      dst[2 * B] = f32_bits(lat[i]);
      dst[3 * B] = f32_bits(lon[i]);
    } else {
      dst[2 * B] = f32_bits(value[i]);
      dst[3 * B] = (et == kEtAlert ? alert_type_idx[i] : mm_idx[i]) & kIdxMask;
    }
    if (with_elev) dst[4 * B] = f32_bits(elevation[i]);
  }
  for (int32_t s = 0; s < S; ++s) {
    int32_t filled = cursor[s];
    if (filled < B)
      std::memset(out + s * shard_stride + filled, 0,
                  static_cast<size_t>(B - filled) * 4);
    if (packed) embed_ts_base(out + s * shard_stride, ts_base);
  }
  return static_cast<int32_t>(n_over);
}

int32_t swt_route_blob(const int32_t* blob, int64_t n, int32_t S, int32_t B,
                       int32_t wire_rows, int32_t* out,
                       int64_t* overflow_rows, int64_t overflow_cap) {
  std::vector<int32_t> cursor(static_cast<size_t>(S), 0);
  const int32_t* head_row = blob;
  int64_t n_over = 0;
  const int64_t shard_stride = static_cast<int64_t>(wire_rows) * B;
  // packed 3-row blobs carry the ts base in row 0's spare bits by LANE
  // POSITION: routing scatters lanes, so the base must be lifted out of
  // the flat head first and re-embedded per shard afterwards (spare bits
  // are stripped from every routed head; they are zero on 4/5-row blobs)
  const bool packed = wire_rows == 3;
  const int32_t base =
      packed && n >= kBaseLanes ? extract_ts_base(head_row) : 0;
  constexpr int32_t kSpareClear = (1 << kBaseShift) - 1;
  for (int64_t i = 0; i < n; ++i) {
    int32_t head = head_row[i];
    if ((head & kWireValidBit) == 0) continue;  // padding row
    int32_t dev = head & kWireDevMask;
    int32_t s = dev % S;
    int32_t pos = cursor[s];
    if (pos >= B) {
      if (n_over >= overflow_cap) return -1;
      overflow_rows[n_over++] = i;
      continue;
    }
    cursor[s] = pos + 1;
    int32_t* dst = out + s * shard_stride + pos;
    dst[0] = ((head & ~kWireDevMask) & kSpareClear) | (dev / S);
    for (int r = 1; r < wire_rows; ++r) dst[r * B] = blob[r * n + i];
  }
  if (packed)
    for (int32_t s = 0; s < S; ++s)
      embed_ts_base(out + s * shard_stride, base);
  return static_cast<int32_t>(n_over);
}

}  // extern "C"
