"""Device stream management: declare streams, append/read chunks, reassemble.

Reference: service-streaming-media — media/DeviceStreamManager.java handles
device requests to create streams and submit/request chunks, persisting
stream metadata via device management and chunk data via the event store
(chunked stream-data persistence across Mongo/Cassandra/InfluxDB). Here
stream metadata is a durable per-tenant collection (same store backends as
the registry) and chunks ride the same columnar event log as every other
event (DeviceStreamData events with `stream_id` + `sequence_number`), so
stream content is replayable and sharded exactly like the rest of the
event plane.
"""

from __future__ import annotations

import threading
from typing import Dict, Optional

from sitewhere_tpu.errors import ErrorCode, NotFoundError, SiteWhereError
from sitewhere_tpu.model.common import SearchCriteria, SearchResults, page
from sitewhere_tpu.model.device import DeviceStream
from sitewhere_tpu.model.event import DeviceStreamData
from sitewhere_tpu.persist.eventlog import EventFilter
from sitewhere_tpu.runtime.lifecycle import LifecycleComponent

_KIND = "device_stream"


class DeviceStreamManager(LifecycleComponent):
    """Per-tenant stream registry + chunk IO on top of event management."""

    def __init__(self, registry, event_management, store=None,
                 name: str = "device-stream-manager"):
        super().__init__(name)
        self.registry = registry
        self.events = event_management
        self.store = store
        self._streams: Dict[str, DeviceStream] = {}  # key: assignment|stream
        self._lock = threading.RLock()
        if store is not None:
            from sitewhere_tpu.registry.store import _entity_from_json
            for _entity_id, _token, payload in store.load_all(_KIND):
                stream = _entity_from_json(DeviceStream, payload)
                self._streams[self._key(stream.assignment_id,
                                        stream.token)] = stream

    @staticmethod
    def _key(assignment_id: str, stream_id: str) -> str:
        return f"{assignment_id}|{stream_id}"

    def _require_assignment(self, assignment_token: str):
        assignment = self.registry.get_device_assignment_by_token(
            assignment_token)
        if assignment is None:
            raise NotFoundError(f"unknown assignment: {assignment_token}",
                                ErrorCode.INVALID_ASSIGNMENT_TOKEN)
        return assignment

    # -- stream registry ---------------------------------------------------
    def create_device_stream(self, assignment_token: str, stream_id: str,
                             content_type: str = "application/octet-stream"
                             ) -> DeviceStream:
        """Declare a stream (DeviceStreamManager.handleDeviceStreamRequest):
        duplicate ids under one assignment are rejected."""
        assignment = self._require_assignment(assignment_token)
        with self._lock:
            key = self._key(assignment.id, stream_id)
            if key in self._streams:
                raise SiteWhereError(
                    f"duplicate stream id: {stream_id}",
                    ErrorCode.DUPLICATE_STREAM_ID, http_status=409)
            stream = DeviceStream(token=stream_id,
                                  assignment_id=assignment.id,
                                  content_type=content_type)
            self._streams[key] = stream
            if self.store is not None:
                from sitewhere_tpu.registry.store import _entity_to_json
                self.store.save(_KIND, stream.id, key,
                                _entity_to_json(stream))
        return stream

    def get_device_stream(self, assignment_token: str, stream_id: str
                          ) -> Optional[DeviceStream]:
        assignment = self.registry.get_device_assignment_by_token(
            assignment_token)
        if assignment is None:
            return None
        with self._lock:
            return self._streams.get(self._key(assignment.id, stream_id))

    def require_device_stream(self, assignment_token: str,
                              stream_id: str) -> DeviceStream:
        stream = self.get_device_stream(assignment_token, stream_id)
        if stream is None:
            raise NotFoundError(f"unknown stream: {stream_id}",
                                ErrorCode.INVALID_STREAM_ID)
        return stream

    def list_device_streams(self, assignment_token: str,
                            criteria: Optional[SearchCriteria] = None
                            ) -> SearchResults[DeviceStream]:
        assignment = self._require_assignment(assignment_token)
        with self._lock:
            streams = [s for s in self._streams.values()
                       if s.assignment_id == assignment.id]
        streams.sort(key=lambda s: s.created_date)
        return page(streams, criteria or SearchCriteria())

    # -- chunk IO ----------------------------------------------------------
    def add_stream_data(self, assignment_token: str, stream_id: str,
                        sequence_number: int, data: bytes
                        ) -> DeviceStreamData:
        """Persist one chunk (handleDeviceStreamDataRequest)."""
        self.require_device_stream(assignment_token, stream_id)
        event = DeviceStreamData(stream_id=stream_id,
                                 sequence_number=sequence_number, data=data)
        return self.events.add_stream_data(assignment_token, event)[0]

    def get_stream_data(self, assignment_token: str, stream_id: str,
                        sequence_number: int) -> Optional[DeviceStreamData]:
        """Exact columnar lookup; on redelivered duplicates the newest chunk
        wins (matching reassemble's last-write-wins)."""
        results = self.events.log.query(
            self.events.tenant,
            EventFilter(assignment_token=assignment_token,
                        stream_id=stream_id,
                        sequence_number=sequence_number),
            SearchCriteria(page_number=1, page_size=1))  # newest-first order
        return results.results[0] if results.results else None

    def list_stream_data(self, assignment_token: str, stream_id: str,
                         criteria: Optional[SearchCriteria] = None
                         ) -> SearchResults[DeviceStreamData]:
        return self.events.list_stream_data(assignment_token, stream_id,
                                            criteria)

    def reassemble(self, assignment_token: str, stream_id: str,
                   page_size: int = 10_000) -> bytes:
        """Concatenate all chunks in sequence order (no silent cap).

        Fetched as ONE page sized to the reported total, growing until a
        fetch returns everything it reported — fixed page boundaries over a
        live log would shift when a device appends mid-scan and silently
        skip a chunk. Redelivered duplicates: last write wins — equal
        sequence numbers keep append order under the stable sort, so a
        plain dict overwrite keeps the newest bytes."""
        self.require_device_stream(assignment_token, stream_id)
        want = max(page_size, 1)
        while True:
            results = self.events.list_stream_data(
                assignment_token, stream_id,
                SearchCriteria(page_number=1, page_size=want))
            if results.num_results <= want:
                break
            want = results.num_results
        by_seq: Dict[int, bytes] = {
            chunk.sequence_number: chunk.data for chunk in results.results}
        return b"".join(by_seq[seq] for seq in sorted(by_seq))
