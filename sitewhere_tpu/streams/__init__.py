"""Device binary streams (reference: service-streaming-media)."""

from sitewhere_tpu.streams.manager import DeviceStreamManager

__all__ = ["DeviceStreamManager"]
