"""Multi-host cluster assembly: the deployable N-process instance.

Reference deployment story: N OS processes (one per microservice replica)
joined by a Kafka broker — boot in Microservice.java:182-236, cross-process
consumption in kafka/MicroserviceKafkaConsumer.java:115-121, 20 s state
heartbeats aggregated into an instance topology (Microservice.java:734-753,
TopologyStateAggregator.java).

TPU-native redesign: the N processes are the HOSTS of one SPMD program — a
`jax.distributed` cluster whose devices form one global mesh running the
fused pipeline step in lockstep. This module supplies everything the SPMD
contract demands that a Kafka deployment gets for free:

- **ClusterStepLoop** — multi-controller jax requires every process to
  launch the same collective programs in the same order. A free-running
  loop on each host runs exactly one fused step per tick (empty batches
  when idle — the collective itself paces the cluster: fast hosts block in
  the psum until the slowest arrives), with presence sweeps on a
  deterministic tick cadence and a shutdown VOTE collective (a host wants
  to stop; everyone exits after the same tick once all shards voted) so no
  host ever hangs a peer's psum.
- **Foreign-row forwarding** — each host stages only its local shards'
  rows (the multi-host data contract); rows its ingest accepted for
  devices owned by another host hand back via `take_foreign()` and are
  forwarded over the peer's networked bus edge (busnet) keyed so the
  owner's consumer folds them — the reference's produce-to-the-partition-
  owner, at-least-once included (forward failures park on a local
  dead-letter topic, never drop).
- **Ownership-routed inbound** — decoded events for foreign-owned devices
  forward BEFORE persist (the owner persists + steps its own devices, so
  the event log and device state agree on ownership), exactly like keying
  a Kafka record by device token routes it to the owning consumer.
- **Heartbeats + topology** — every process publishes periodic state to
  every peer's `microservice-state-updates` topic; an aggregator folds
  them into the instance topology with staleness, and a watchdog turns a
  stale peer into a deliberate gang exit (see below).

**Failure model — gang restart.** A TPU pod slice is gang-scheduled: one
host dying breaks every collective, so the honest recovery story is the
whole cluster restarting and each host rebuilding from its durable state
(bus offsets + checkpoint + replay) — the reference's restarted-process
offset replay (DecodedEventsConsumer.java:194-199) applied per host. The
watchdog makes this deterministic instead of hang-forever: a peer stale
past `fail_after_s` exits the process with a distinct code for the
supervisor to restart the gang.

**Registry scope.** Control-plane writes replicate cluster-wide via
leaderless gossip (`RegistryGossip` below): every registry kind —
device types/commands/statuses, devices, assignments, area types/areas/
zones, customer types/customers, groups/elements, alarms — plus
deletions (tombstones) and fused-rule mutations, broadcast to every
peer's bus edge and applied idempotently with last-writer-wins ordering
(update stamp, host-independent content-digest tiebreak). References
travel by token; a multi-pass applier plus at-least-once redelivery
absorbs cross-entity reordering. User scripts and scripted-rule installs
replicate the same way (whole-state script payloads + stamped installs,
`register_scripts`) and persist in the scripted-rule store + instance
checkpoint. Tenant/user/authority provisioning replicates too
(`multitenant/replication.py` ProvisioningReplicator, wired below): a
tenant created over REST on any host boots its engine — and registers
its registry with this gossip — on every peer mid-flight; deletes drain
and retire engines cluster-wide, park in-flight rows on the dead-letter
topic, and tombstone the token; user mutations invalidate cached JWT
auth state. The provisioning set persists in the instance checkpoint, so
a gang restart rebuilds the same tenant world from durable state rather
than boot templates. Residual limit: events for devices whose gossip has
not yet arrived intern to UNKNOWN and surface on the unregistered path
during the convergence window rather than corrupting anything.

`ControlPlaneCluster` (below) is the mesh-free sibling composition: the
same replication stack over busnet edges for N INDEPENDENT single-host
instances — deployments (and CI environments) without multi-controller
collectives still converge their control plane.
"""

from __future__ import annotations

import json
import logging
import threading
import time
from collections import deque
from typing import Callable, Dict, List, Optional

import jax
import msgpack
import numpy as np
from jax.sharding import NamedSharding, PartitionSpec as P

try:  # jax >= 0.6
    from jax import shard_map as _shard_map
except ImportError:  # pragma: no cover
    from jax.experimental.shard_map import shard_map as _shard_map

from sitewhere_tpu.model.common import now_ms
# the LWW stamp + host-independent content digest are the shared
# replication core — ONE implementation (multitenant/replication.py)
# serves both the registry gossip and the provisioning replicator
from sitewhere_tpu.multitenant.replication import (
    ProvisioningReplicator, content_digest as _content_digest,
    lww_stamp as _gossip_stamp)
from sitewhere_tpu.ops.pack import EventBatch, empty_batch
from sitewhere_tpu.parallel.engine import ShardedPipelineEngine
from sitewhere_tpu.parallel.mesh import SHARD_AXIS
from sitewhere_tpu.runtime.bus import ConsumerHost, Record, TopicNaming
from sitewhere_tpu.runtime.busnet import BusClient, BusNetError
from sitewhere_tpu.runtime.metrics import GLOBAL_METRICS
from sitewhere_tpu.runtime.recovery import (
    EpochFence, LeaseTable, elect_successor)

LOGGER = logging.getLogger("sitewhere.cluster")

FOREIGN_ROWS_SUFFIX = "inbound-foreign-rows"
# consumer group folding forwarded rows; checkpoint.py captures its
# offsets so a gang restart replays only the gap — keep in one place
FOREIGN_ROWS_GROUP = "cluster-foreign-rows"


def foreign_rows_topic(naming: TopicNaming) -> str:
    """Global (cross-tenant) topic carrying forwarded foreign-owned rows;
    rows embed their device token, which implies the tenant."""
    return naming._global(FOREIGN_ROWS_SUFFIX)


# ---------------------------------------------------------------------------
# shutdown vote collective
# ---------------------------------------------------------------------------

class ClusterControl:
    """Tiny psum over the mesh: each shard contributes its host's stop
    flag; every host reads the identical total, so all hosts exit their
    step loop after the SAME tick — the lockstep-safe replacement for
    "just stop calling submit" (which would hang the peers' collectives).
    """

    def __init__(self, mesh):
        self.mesh = mesh
        self.n_shards = mesh.devices.size
        self._shard0 = NamedSharding(mesh, P(SHARD_AXIS))
        me = jax.process_index()
        self._local = [i for i, d in enumerate(mesh.devices.flat)
                       if d.process_index == me]
        self._multiprocess = len(self._local) < self.n_shards

        def tally(flags):  # per-shard block [1, 1]
            return jax.lax.psum(flags[0, 0], SHARD_AXIS)

        self._prog = jax.jit(_shard_map(
            tally, mesh=mesh, in_specs=P(SHARD_AXIS), out_specs=P()))

    def vote(self, flag: bool) -> int:
        """Collective; every host must call once per tick. Returns the
        number of shards whose host voted to stop."""
        value = np.int32(1 if flag else 0)
        if self._multiprocess:
            local = np.full((len(self._local), 1), value, np.int32)
            arr = jax.make_array_from_process_local_data(
                self._shard0, local, (self.n_shards, 1))
        else:
            arr = jax.device_put(
                np.full((self.n_shards, 1), value, np.int32), self._shard0)
        return int(self._prog(arr))


# ---------------------------------------------------------------------------
# foreign-row codec
# ---------------------------------------------------------------------------

def encode_rows(engine, batch: EventBatch, sel: np.ndarray) -> bytes:
    """Encode selected flat-batch rows as the self-describing msgpack
    blob. Rows travel by device TOKEN (and measurement/alert-type names),
    not interned indices — interning is per-process state that does not
    survive restarts or necessarily agree across hosts."""
    packer = engine.packer
    cols = {
        "tokens": [packer.devices.token_of(int(i)) or ""
                   for i in np.asarray(batch.device_idx)[sel]],
        "event_type": np.asarray(batch.event_type)[sel].tolist(),
        "ts_ms": (np.asarray(batch.ts, np.int64)[sel]
                  + np.int64(packer.epoch_base_ms)).tolist(),
        "value": np.asarray(batch.value)[sel].tolist(),
        "lat": np.asarray(batch.lat)[sel].tolist(),
        "lon": np.asarray(batch.lon)[sel].tolist(),
        "elevation": np.asarray(batch.elevation)[sel].tolist(),
        "alert_level": np.asarray(batch.alert_level)[sel].tolist(),
        "mm_names": [packer.measurements.token_of(int(m)) or ""
                     for m in np.asarray(batch.mm_idx)[sel]],
        "alert_types": [packer.alert_types.token_of(int(a)) or ""
                        for a in np.asarray(batch.alert_type_idx)[sel]],
    }
    return msgpack.packb(cols, use_bin_type=True)


def encode_foreign_rows(engine: ShardedPipelineEngine,
                        batch: EventBatch) -> Dict[int, tuple]:
    """Group a flat foreign batch (global device indices) by OWNER process:
    {pid: (payload bytes, row count)}."""
    valid = np.asarray(batch.valid)
    rows = np.nonzero(valid)[0]
    if rows.size == 0:
        return {}
    idx = np.asarray(batch.device_idx)[rows]
    shard = idx % engine.n_shards
    proc_of_shard = np.asarray(
        [d.process_index for d in engine.mesh.devices.flat], np.int32)
    owner = proc_of_shard[shard]
    out: Dict[int, tuple] = {}
    for pid in np.unique(owner):
        sel = rows[owner == np.int32(pid)]
        out[int(pid)] = (encode_rows(engine, batch, sel), int(sel.size))
    return out


def decode_foreign_rows(engine, payload: bytes) -> List[EventBatch]:
    """Inverse of encode_foreign_rows on the OWNER host: tokens and names
    re-intern against the local registry/packer; unknown device tokens
    intern to UNKNOWN (0) and surface as unregistered in the step. Returns
    one or more fixed-size batches (chunked to the packer's batch size)."""
    cols = msgpack.unpackb(payload, raw=False)
    packer = engine.packer
    n = len(cols["tokens"])
    if n == 0:
        return []
    device_idx = np.asarray(
        [packer.devices.lookup(t) for t in cols["tokens"]], np.int32)
    mm_idx = np.asarray(
        [packer.measurements.intern(m) if m else 0
         for m in cols["mm_names"]], np.int32)
    alert_type_idx = np.asarray(
        [packer.alert_types.intern(a) if a else 0
         for a in cols["alert_types"]], np.int32)
    batches = []
    B = packer.batch_size
    for start in range(0, n, B):
        end = min(n, start + B)
        sl = slice(start, end)
        batches.append(packer.pack_columns(
            device_idx[sl],
            np.asarray(cols["event_type"][start:end], np.int32),
            np.asarray(cols["ts_ms"][start:end], np.int64),
            mm_idx=mm_idx[sl],
            value=np.asarray(cols["value"][start:end], np.float32),
            lat=np.asarray(cols["lat"][start:end], np.float32),
            lon=np.asarray(cols["lon"][start:end], np.float32),
            elevation=np.asarray(cols["elevation"][start:end], np.float32),
            alert_type_idx=alert_type_idx[sl],
            alert_level=np.asarray(cols["alert_level"][start:end],
                                   np.int32)))
    return batches


# ---------------------------------------------------------------------------
# lockstep step loop
# ---------------------------------------------------------------------------

class FoldTicket:
    """Durability receipt for rows fed to the step loop. `wait()` returns
    True only when the rows genuinely folded (state advanced + foreign
    rows forwarded); a loop death FAILS the ticket so the waiter RAISES —
    the consumer's batch then redelivers instead of committing offsets for
    rows that only ever reached volatile memory."""

    __slots__ = ("_event", "_error")

    def __init__(self):
        self._event = threading.Event()
        self._error: Optional[BaseException] = None

    def resolve(self) -> None:
        self._event.set()

    def fail(self, exc: BaseException) -> None:
        self._error = exc
        self._event.set()

    def wait(self, timeout: Optional[float] = None) -> bool:
        if not self._event.wait(timeout):
            return False
        if self._error is not None:
            raise RuntimeError(
                f"step loop failed before folding: {self._error}")
        return True

class ClusterStepLoop:
    """Free-running collective step cadence for one host.

    Each tick: drain queued local batches (or an empty heartbeat batch),
    run ONE fused step, materialize this host's alerts, hand foreign rows
    to the forwarder, optionally sweep presence on a deterministic tick
    cadence, then run the shutdown-vote collective. Feeding is
    backpressured two ways: the bounded queue blocks producers, and when
    the engine's overflow backlog exceeds its bound the loop stops pulling
    new work so the backlog drains through the lockstep ticks (the
    multiprocess engine never runs extra drain steps — they would desync
    the collective program order across hosts).

    `feed()` returns a ticket (threading.Event) set once the rows are
    durably accounted for: folded into device state (overflow empty) and
    any foreign rows forwarded — consumers commit after the ticket fires
    (at-least-once end to end).
    """

    def __init__(self, engine: ShardedPipelineEngine,
                 control: Optional[ClusterControl] = None,
                 idle_interval_s: float = 0.005,
                 presence_every_ticks: int = 0,
                 max_batches_per_tick: int = 16,
                 queue_bound: int = 64,
                 on_alerts: Optional[Callable] = None,
                 on_presence_missing: Optional[Callable] = None,
                 forward_foreign: Optional[Callable] = None,
                 on_fatal: Optional[Callable] = None):
        self.engine = engine
        self.control = control or ClusterControl(engine.mesh)
        self.idle_interval_s = idle_interval_s
        self.presence_every_ticks = presence_every_ticks
        self.max_batches_per_tick = max_batches_per_tick
        self.queue_bound = queue_bound
        self.on_alerts = on_alerts
        self.on_presence_missing = on_presence_missing
        self.forward_foreign = forward_foreign
        self.on_fatal = on_fatal
        self.tick_count = 0
        self.fatal: Optional[BaseException] = None
        self._q: deque = deque()
        self._q_cond = threading.Condition()
        self._pending_tickets: List[FoldTicket] = []
        self._stop_requested = threading.Event()
        self._thread: Optional[threading.Thread] = None
        self._done = threading.Event()

    # -- producer side -----------------------------------------------------
    def feed(self, batch: EventBatch,
             timeout_s: float = 30.0) -> FoldTicket:
        """Queue a flat batch for the next tick; blocks while the queue is
        full (backpressure). Returns the fold ticket."""
        ticket = FoldTicket()
        deadline = time.monotonic() + timeout_s
        with self._q_cond:
            if self._done.is_set():
                raise RuntimeError("cluster step loop stopped")
            while len(self._q) >= self.queue_bound:
                if self._done.is_set():
                    raise RuntimeError("cluster step loop stopped")
                if time.monotonic() > deadline:
                    raise TimeoutError("cluster feed queue full")
                self._q_cond.wait(timeout=0.1)
            self._q.append((batch, ticket))
            self._q_cond.notify_all()
        return ticket

    # -- lifecycle ---------------------------------------------------------
    def start(self) -> None:
        if self._thread is not None:
            return
        self._done.clear()
        self._stop_requested.clear()
        self._thread = threading.Thread(target=self._run,
                                        name="cluster-step-loop",
                                        daemon=True)
        self._thread.start()

    def stop(self, timeout_s: float = 60.0) -> None:
        """Request a coordinated stop; returns once the loop exits (every
        host's loop exits after the same tick via the vote collective)."""
        self._stop_requested.set()
        with self._q_cond:
            self._q_cond.notify_all()
        if self._thread is not None:
            self._thread.join(timeout=timeout_s)
            self._thread = None

    # -- the loop ----------------------------------------------------------
    def _drain_for_tick(self) -> List:
        items: List = []
        if self.engine.pending_overflow > self.engine.max_overflow_events:
            return items  # backpressure: let lockstep ticks drain it
        with self._q_cond:
            while self._q and len(items) < self.max_batches_per_tick:
                items.append(self._q.popleft())
            if items:
                self._q_cond.notify_all()
        return items

    def _tick(self) -> int:
        from sitewhere_tpu.parallel.router import concat_flat_batches

        items = self._drain_for_tick()
        if items:
            batches = [b for b, _ in items]
            batch = (batches[0] if len(batches) == 1
                     else concat_flat_batches(batches))
        else:
            batch = empty_batch(1)
        routed, outputs = self.engine.submit(batch)
        alerts = self.engine.materialize_alerts(routed, outputs)
        if alerts and self.on_alerts is not None:
            self.on_alerts(alerts)
        foreign = self.engine.take_foreign()
        if foreign is not None and self.forward_foreign is not None:
            self.forward_foreign(foreign)
        self.tick_count += 1
        if (self.presence_every_ticks
                and self.tick_count % self.presence_every_ticks == 0):
            missing = self.engine.presence_sweep()
            if missing and self.on_presence_missing is not None:
                self.on_presence_missing(missing)
        self._pending_tickets.extend(t for _, t in items)
        if self._pending_tickets and self.engine.pending_overflow == 0:
            for ticket in self._pending_tickets:
                ticket.resolve()
            self._pending_tickets.clear()
        return len(items)

    def _run(self) -> None:
        try:
            while True:
                worked = self._tick()
                votes = self.control.vote(self._stop_requested.is_set())
                if votes >= self.control.n_shards:
                    break
                if worked == 0 and not self._stop_requested.is_set():
                    with self._q_cond:
                        if not self._q:
                            self._q_cond.wait(timeout=self.idle_interval_s)
        except BaseException as exc:  # noqa: BLE001 - a dead loop must be loud
            self.fatal = exc
            LOGGER.critical("cluster step loop died: %s", exc, exc_info=True)
            if self.on_fatal is not None:
                try:
                    self.on_fatal(exc)
                except Exception:
                    pass
        finally:
            self._done.set()
            with self._q_cond:
                self._q_cond.notify_all()
                queued = [t for _, t in self._q]
                self._q.clear()
            # tickets that will never fold FAIL (waiters raise -> their
            # consumer batches redeliver; committing them would lose rows
            # that only ever reached volatile memory)
            reason = self.fatal or RuntimeError("step loop stopped")
            for ticket in self._pending_tickets + queued:
                ticket.fail(reason)
            self._pending_tickets.clear()


# ---------------------------------------------------------------------------
# foreign-row forwarding over busnet
# ---------------------------------------------------------------------------

class ForeignRowForwarder:
    """Publish foreign-owned rows to the owner host's bus edge.

    At-least-once: a publish that fails after the client's retry budget
    parks the encoded group on the LOCAL dead-letter topic
    `<foreign-topic>.dead-letter` (durable when the bus has a data_dir)
    instead of dropping — the dead-letter surface can replay it later."""

    def __init__(self, process_id: int, peers: Dict[int, BusClient],
                 naming: TopicNaming, local_bus=None):
        self.process_id = process_id
        self.peers = peers
        self.topic = foreign_rows_topic(naming)
        self.local_bus = local_bus
        self.forwarded = 0
        self.dead_lettered = 0

    def forward(self, engine: ShardedPipelineEngine,
                batch: EventBatch) -> None:
        groups = encode_foreign_rows(engine, batch)
        for pid, (payload, n_rows) in groups.items():
            if pid == self.process_id:
                continue  # should not happen; local rows never stash
            client = self.peers.get(pid)
            key = str(pid).encode()
            try:
                if client is None:
                    raise BusNetError(f"no bus edge known for process {pid}")
                client.publish(self.topic, key, payload)
                self.forwarded += n_rows  # ROWS, comparable to the owner's
                #                           consumed_foreign counter
            except BusNetError as exc:
                LOGGER.error("foreign-row forward to process %d failed: %s",
                             pid, exc)
                if self.local_bus is not None:
                    self.local_bus.publish(f"{self.topic}.dead-letter",
                                           key, payload)
                    self.dead_lettered += n_rows


class ForeignRowsConsumer:
    """Owner-side consumer: decode forwarded rows and feed them to the
    step loop, committing only after the fold ticket fires (at-least-once
    across the host boundary). Rows this host does NOT own by its own
    registry's mapping (provisioning drift between hosts) park on the
    misroute dead-letter topic rather than ping-ponging back."""

    def __init__(self, bus, naming: TopicNaming, engine, loop: ClusterStepLoop,
                 owner_check: Optional[Callable[[str], bool]] = None,
                 group_id: str = FOREIGN_ROWS_GROUP):
        self.bus = bus
        self.engine = engine
        self.loop = loop
        self.owner_check = owner_check
        self.consumed_rows = 0
        self.misrouted_rows = 0
        self._misroute_topic = f"{foreign_rows_topic(naming)}.misrouted"
        self._host = ConsumerHost(
            bus, foreign_rows_topic(naming), group_id=group_id,
            handler=self._handle)

    def start(self) -> None:
        self._host.start()

    def stop(self) -> None:
        self._host.stop()

    def _handle(self, records: List[Record]) -> None:
        tickets = []
        for record in records:
            for batch in decode_foreign_rows(self.engine, record.value):
                batch = self._drop_misrouted(batch, record)
                if not np.asarray(batch.valid).any():
                    continue
                tickets.append(self.loop.feed(batch))
                self.consumed_rows += int(np.asarray(batch.valid).sum())
        for ticket in tickets:
            if not ticket.wait(timeout=60.0):
                raise TimeoutError("foreign rows not folded within 60s")

    def _drop_misrouted(self, batch: EventBatch, record: Record) -> EventBatch:
        if self.owner_check is None:
            return batch
        valid = np.asarray(batch.valid).copy()
        rows = np.nonzero(valid)[0]
        bad = []
        for row in rows:
            token = self.engine.packer.devices.token_of(
                int(np.asarray(batch.device_idx)[row]))
            # unknown tokens (idx 0) stay: they fold as unregistered
            if token is not None and not self.owner_check(token):
                bad.append(row)
        if bad:
            self.misrouted_rows += len(bad)
            valid[np.asarray(bad)] = False
            # park ONLY the misrouted rows (re-encoded): parking the whole
            # record would double-apply the owned rows — which fold now —
            # when an operator later replays the misroute topic
            self.bus.publish(self._misroute_topic, record.key,
                             encode_rows(self.engine, batch,
                                         np.asarray(bad)))
            LOGGER.warning("%d forwarded rows not owned here (registry "
                           "drift?) — parked on %s", len(bad),
                           self._misroute_topic)
            return batch.replace(valid=valid)
        return batch


# ---------------------------------------------------------------------------
# heartbeats + topology
# ---------------------------------------------------------------------------

class ProcessStateReporter:
    """Publish this process's state to the local AND every peer's
    `microservice-state-updates` topic on a fixed cadence (the reference's
    20 s heartbeat, Microservice.java:734-753). Peer publish failures are
    counted, not fatal — staleness detection on the other side is the
    real liveness signal."""

    def __init__(self, process_id, bus, naming: TopicNaming,
                 peers: Dict[int, BusClient],
                 build_state: Callable[[], Dict],
                 interval_s: float = 2.0):
        self.process_id = process_id
        self.bus = bus
        self.topic = naming.microservice_state_updates()
        self.peers = peers
        self.build_state = build_state
        self.interval_s = interval_s
        self.publish_errors = 0
        self._stop = threading.Event()
        self._thread: Optional[threading.Thread] = None

    def start(self) -> None:
        if self._thread is not None:
            return
        self._stop.clear()
        self._thread = threading.Thread(target=self._run,
                                        name="cluster-heartbeat",
                                        daemon=True)
        self._thread.start()

    def stop(self) -> None:
        self._stop.set()
        if self._thread is not None:
            self._thread.join(timeout=5.0)
            self._thread = None

    def beat_once(self) -> None:
        state = dict(self.build_state())
        state["process_id"] = self.process_id
        state["sent_at_ms"] = int(time.time() * 1000)
        payload = json.dumps(state).encode()
        key = str(self.process_id).encode()
        self.bus.publish(self.topic, key, payload)
        for pid, client in self.peers.items():
            try:
                client.publish(self.topic, key, payload)
            except BusNetError:
                self.publish_errors += 1

    def _run(self) -> None:
        while True:
            try:
                self.beat_once()
            except Exception:
                LOGGER.exception("heartbeat publish failed")
            if self._stop.wait(self.interval_s):
                return


class TopologyAggregator:
    """Fold state heartbeats from the local `microservice-state-updates`
    topic into a process map with liveness (TopologyStateAggregator.java's
    role). Remote processes appear/refresh via their forwarded heartbeats;
    staleness is computed against receive time so clock skew between
    hosts cannot fake liveness."""

    def __init__(self, bus, naming: TopicNaming,
                 stale_after_s: float = 10.0,
                 group_id: str = "topology-aggregator"):
        self.stale_after_s = stale_after_s
        self._states: Dict[str, Dict] = {}
        self._received_mono: Dict[str, float] = {}
        self._lock = threading.Lock()
        self._host = ConsumerHost(
            bus, naming.microservice_state_updates(), group_id=group_id,
            handler=self._handle)

    def start(self) -> None:
        self._host.start()

    def stop(self) -> None:
        self._host.stop()

    def _handle(self, records: List[Record]) -> None:
        now = time.monotonic()
        with self._lock:
            for record in records:
                try:
                    state = json.loads(record.value)
                except Exception:
                    continue
                pid = str(state.get("process_id", record.key.decode()))
                self._states[pid] = state
                self._received_mono[pid] = now

    def snapshot(self) -> Dict[str, Dict]:
        now = time.monotonic()
        with self._lock:
            out = {}
            for pid, state in self._states.items():
                age = now - self._received_mono[pid]
                entry = dict(state)
                entry["age_s"] = round(age, 3)
                entry["stale"] = age > self.stale_after_s
                out[pid] = entry
            return out

    def stale_processes(self, expected: List[str],
                        grace_s: float = 0.0) -> List[str]:
        """Expected process ids that are stale or were never seen. A
        never-seen process counts only after `grace_s` of observation
        (tracked from aggregator start)."""
        snap = self.snapshot()
        if not hasattr(self, "_started_mono"):
            self._started_mono = time.monotonic()
        out = []
        for pid in expected:
            entry = snap.get(str(pid))
            if entry is None:
                if time.monotonic() - self._started_mono > grace_s:
                    out.append(str(pid))
            elif entry["stale"]:
                out.append(str(pid))
        return out


class PeerWatchdog:
    """Turn a stale peer into a deliberate, loud gang exit instead of a
    hung collective (gang-restart failure model — module docstring)."""

    def __init__(self, aggregator: TopologyAggregator,
                 expected: List[str], fail_after_s: float = 15.0,
                 check_interval_s: float = 1.0,
                 on_peer_loss: Optional[Callable[[List[str]], None]] = None):
        self.aggregator = aggregator
        self.expected = [str(p) for p in expected]
        self.fail_after_s = fail_after_s
        self.check_interval_s = check_interval_s
        self.on_peer_loss = on_peer_loss
        self._stop = threading.Event()
        self._thread: Optional[threading.Thread] = None

    def start(self) -> None:
        if self._thread is not None or not self.expected:
            return
        self.aggregator._started_mono = time.monotonic()
        self._stop.clear()
        self._thread = threading.Thread(target=self._run,
                                        name="cluster-watchdog", daemon=True)
        self._thread.start()

    def stop(self) -> None:
        self._stop.set()
        if self._thread is not None:
            self._thread.join(timeout=5.0)
            self._thread = None

    def _run(self) -> None:
        while not self._stop.wait(self.check_interval_s):
            stale = self.aggregator.stale_processes(
                self.expected, grace_s=self.fail_after_s)
            hard = [p for p in stale
                    if self._stale_age(p) > self.fail_after_s]
            if hard:
                LOGGER.critical(
                    "peer process(es) %s unresponsive > %.1fs — gang "
                    "restart required", hard, self.fail_after_s)
                if self.on_peer_loss is not None:
                    self.on_peer_loss(hard)
                return

    def _stale_age(self, pid: str) -> float:
        with self.aggregator._lock:
            seen = self.aggregator._received_mono.get(pid)
        if seen is None:
            started = getattr(self.aggregator, "_started_mono",
                              time.monotonic())
            return time.monotonic() - started
        return time.monotonic() - seen


# ---------------------------------------------------------------------------
# gossip registry replication
# ---------------------------------------------------------------------------

REGISTRY_GOSSIP_SUFFIX = "registry-model-updates"

# Every registry kind replicates. Reference fields are resolved by TOKEN
# on the wire (entity ids are per-host UUIDs except when the creating
# host's id is adopted at create time): (id_field, collection). Fields
# NOT listed here (asset_id, triggering_event_id) travel verbatim — they
# reference managers outside the replicated registry.
_GOSSIP_REFS = {
    "device_type": [],
    "device_command": [("device_type_id", "device_types")],
    "device_status": [("device_type_id", "device_types")],
    "device": [("device_type_id", "device_types"),
               ("parent_device_id", "devices")],
    "assignment": [("device_id", "devices"),
                   ("device_type_id", "device_types"),
                   ("area_id", "areas"), ("customer_id", "customers")],
    "area_type": [],
    "area": [("area_type_id", "area_types"), ("parent_area_id", "areas")],
    "zone": [("area_id", "areas")],
    "customer_type": [],
    "customer": [("customer_type_id", "customer_types"),
                 ("parent_customer_id", "customers")],
    "device_group": [],
    "group_element": [("group_id", "device_groups"),
                      ("device_id", "devices"),
                      ("nested_group_id", "device_groups")],
    "alarm": [("device_id", "devices"),
              ("device_assignment_id", "assignments"),
              ("customer_id", "customers"), ("area_id", "areas")],
}
_GOSSIP_CLASSES = {}  # kind -> model class, resolved lazily


def _gossip_class(kind: str):
    if not _GOSSIP_CLASSES:
        from sitewhere_tpu.model import (
            Area, AreaType, Customer, CustomerType, Device, DeviceAlarm,
            DeviceAssignment, DeviceCommand, DeviceGroup, DeviceGroupElement,
            DeviceStatus, DeviceType, Zone)

        _GOSSIP_CLASSES.update({
            "device_type": DeviceType, "device_command": DeviceCommand,
            "device_status": DeviceStatus, "device": Device,
            "assignment": DeviceAssignment, "area_type": AreaType,
            "area": Area, "zone": Zone, "customer_type": CustomerType,
            "customer": Customer, "device_group": DeviceGroup,
            "group_element": DeviceGroupElement, "alarm": DeviceAlarm})
    return _GOSSIP_CLASSES.get(kind)


def _gossip_content_key(kind: str, data: Dict,
                        ref_tokens: Dict[str, str]) -> str:
    """Deterministic tiebreak for equal-stamp concurrent writes: the
    shared content digest with this kind's replicated-reference fields
    dropped (they appear by token in `_refs` instead — ids are per-host
    UUIDs). created_date is a per-host observation and updated_date
    normalizes to the LWW stamp, so an origin copy whose stamp rides
    created_date hashes identically to replicas carrying it explicitly."""
    ref_fields = tuple(field for field, _ in _GOSSIP_REFS.get(kind, ()))
    return _content_digest(data, ref_tokens=ref_tokens,
                           drop_fields=ref_fields)


def registry_gossip_topic(naming: TopicNaming) -> str:
    return naming._global(REGISTRY_GOSSIP_SUFFIX)


class RegistryGossip:
    """Leaderless cross-host registry replication.

    The reference gets cross-process registry consistency from a shared
    database; here every host broadcasts its registry mutations to its
    peers' bus edges and applies incoming ones idempotently. No
    sequencer is needed because shard OWNERSHIP no longer depends on
    creation order (shard-congruent interning, registry/interning.py) —
    hosts only need to converge on CONTENT, and the misroute guards
    cover the convergence window.

    Mechanics: EVERY registry kind replicates, including deletions.
    Entity references travel by TOKEN (ids are per-host UUIDs; a
    brand-new entity adopts the creating host's id, an existing one
    keeps its local id). An applier whose dependency has not arrived
    yet raises — the consumer's at-least-once redelivery retries until
    the dependency converges, and a genuine conflict parks on the
    dead-letter surface for the operator.

    Conflict order: last-writer-wins on the entity's updated/created
    stamp (local touch() is monotonic past any applied stamp), with a
    host-independent content digest breaking exact ties — every host
    compares the same pair of (stamp, digest) keys and picks the same
    winner, so concurrent updates converge identically everywhere.
    Deletes stamp past the entity's last write and leave a tombstone:
    a LATER write resurrects the entity (and the same comparison makes
    the delete a no-op on hosts that already applied that write), an
    EARLIER one stays dead. Same-token operations ride one partition in
    order; only cross-entity reordering needs the multi-pass applier.
    """

    def __init__(self, process_id: int, peers: Dict[int, BusClient],
                 instance, naming: TopicNaming):
        self.process_id = process_id
        self.peers = peers
        self.instance = instance
        self.topic = registry_gossip_topic(naming)
        self.published = 0
        self.applied = 0
        self.conflicts = 0
        self.publish_errors = 0
        # recovery-epoch fencing (runtime/recovery.py): outgoing gossip
        # carries this host's origin identity + epoch; the apply side
        # keeps per-origin floors so a fenced (taken-over) peer's stale
        # envelopes cannot resurrect pre-takeover registry state.
        # Unstamped envelopes (older peers) always admit.
        self.origin = f"proc:{process_id}"
        self.epoch = 0
        self._fence = EpochFence()
        self._applying = threading.local()
        self._registries: Dict[str, object] = {}
        # (tenant, kind, token) -> delete stamp; in-memory (a restarted
        # host re-learns deletions from the durable store, which the
        # delete already mutated)
        self._tombstones: Dict[tuple, int] = {}
        self._host = ConsumerHost(instance.bus, self.topic,
                                  group_id=f"registry-gossip-{process_id}",
                                  handler=self._handle)

    # -- publish side ------------------------------------------------------
    def register_tenant_registry(self, tenant_token: str, registry) -> None:
        """Called by TenantEngine construction: subscribe to this
        tenant's registry mutations (the complete collection-level feed —
        no wrapper can forget to replicate)."""
        self._registries[tenant_token] = registry
        registry.add_mutation_listener(
            lambda kind, op, entity, _t=tenant_token, _r=registry:
            self._on_mutation(_t, _r, kind, op, entity))

    def _on_mutation(self, tenant: str, registry, kind, op, entity) -> None:
        if getattr(self._applying, "active", False):
            return  # echo of an applied peer mutation
        if _gossip_class(kind) is None or not self.peers:
            return
        from sitewhere_tpu.web.marshal import to_jsonable

        if op != "delete":
            # A write to a token this host knows a tombstone for is a
            # RESURRECTION: its stamp must outrank the delete, or the
            # same-millisecond case diverges — receiving hosts keep the
            # token dead (ties favor the delete) while this host keeps
            # its local copy alive. Stamp the live entity past the
            # tombstone so every replica compares the same winning pair.
            key = (tenant, kind, getattr(entity, "token", ""))
            tomb = self._tombstones.get(key)
            if tomb is not None and \
                    _gossip_stamp(to_jsonable(entity)) <= tomb:
                entity.updated_date = tomb + 1
                # the row was already saved before this listener fired:
                # persist the bumped stamp too, or a restart rehydrates
                # the weaker one and a redelivered delete (same stamp)
                # kills the entity on this host alone
                try:
                    registry.collection_of(kind).persist_quietly(entity)
                except Exception:
                    LOGGER.exception("could not persist resurrection "
                                     "stamp for %s %r", kind, key[2])
            # A create's LWW stamp implicitly rides created_date — which
            # deliberately does NOT converge (a host that content-merges
            # this create keeps its own creation stamp). Make the stamp
            # EXPLICIT on the live entity so the payload replicates it and
            # every copy — origin included — compares the same stamp:
            # without this, a host that adopted the winning create's
            # content keeps a LOWER stamp (its own created_date) and an
            # in-flight older create re-wins there alone (observed
            # divergence in the 3-host storm test).
            if entity.updated_date is None:
                entity.updated_date = entity.created_date
        try:
            if op == "delete":
                # the delete is a write AFTER the entity's last one: stamp
                # past it so LWW orders it against concurrent updates
                data = to_jsonable(entity)
                stamp = max(now_ms(), _gossip_stamp(data) + 1)
                token = getattr(entity, "token", "")
                # the deleting host never consumes its own publish: record
                # the tombstone HERE too, or an in-flight concurrent peer
                # update would resurrect the entity on this host only
                key = (tenant, kind, token)
                self._tombstones[key] = max(self._tombstones.get(key, 0),
                                            stamp)
                payload = {"tenant": tenant, "kind": kind, "op": "delete",
                           "token": token, "stamp": stamp}
            else:
                refs = {}
                for field, coll_name in _GOSSIP_REFS.get(kind, []):
                    ref_id = getattr(entity, field, None)
                    if ref_id:
                        ref = getattr(registry, coll_name).get(ref_id)
                        if ref is not None:
                            refs[field] = ref.token
                payload = {"tenant": tenant, "kind": kind, "op": op,
                           "entity": to_jsonable(entity), "refs": refs}
        except Exception:
            LOGGER.exception("registry gossip encode failed (%s)", kind)
            return
        self._publish(getattr(entity, "token", "").encode(), payload)

    def set_epoch(self, epoch: int) -> None:
        """Adopt the instance's minted recovery epoch; outgoing gossip
        carries it from here on."""
        self.epoch = int(epoch)

    def fence(self, origin: str, epoch: int) -> int:
        """Raise the apply-side floor for `origin` (takeover broadcast)."""
        return self._fence.fence(str(origin), int(epoch))

    def _publish(self, key: bytes, data: Dict) -> None:
        data["origin"] = self.origin
        data["epoch"] = int(self.epoch)
        payload = msgpack.packb(data, use_bin_type=True)
        for pid, client in self.peers.items():
            try:
                client.publish(self.topic, key, payload)
                self.published += 1
            except BusNetError:
                self.publish_errors += 1
                # park for operator replay toward the peer
                self.instance.bus.publish(f"{self.topic}.dead-letter",
                                          key, payload)

    # -- fused-rule replication --------------------------------------------
    def register_rules_engine(self, engine) -> None:
        """Replicate fused-rule mutations (pipeline/engine.py rule feed):
        a rule added or removed on any host applies on every host, so the
        42M ev/s rule engine has ONE cluster-wide rule set (the reference
        re-configures every microservice instance from shared tenant
        config; here the mutation itself travels)."""
        engine.add_rules_listener(self._on_rule_mutation)

    def _on_rule_mutation(self, op: str, kind: str, payload) -> None:
        if getattr(self._applying, "active", False) or not self.peers:
            return
        from sitewhere_tpu.pipeline.engine import rule_to_dict

        if op == "remove":
            token = str(payload)
            data = {"kind": "_rule", "op": "remove", "token": token}
        else:
            token = payload.token
            data = {"kind": "_rule", "op": "add",
                    "rule": rule_to_dict(kind, payload)}
        self._publish(token.encode(), data)

    def _apply_rule(self, data: Dict) -> None:
        engine = self.instance.pipeline_engine
        if engine is None:
            return
        if data.get("op") == "remove":
            if engine.remove_rule(data.get("token", "")):
                self.applied += 1
            return
        from sitewhere_tpu.pipeline.engine import rule_from_dict

        kind, rule = rule_from_dict(dict(data.get("rule") or {}))
        # replace-on-add: idempotent under redelivery and under every
        # host applying the same boot config
        engine.upsert_rule(kind, rule)
        self.applied += 1

    # -- script + scripted-rule replication --------------------------------
    def register_scripts(self, instance) -> None:
        """Replicate the script store and scripted-rule installs
        (reference: ZK-backed ScriptSynchronizer.java:32 gives every node
        the same scripts; here the mutation itself travels). Script
        payloads are whole-state (metadata + every version's content) so
        the applier is idempotent and order-free; scripted-rule installs
        are (token -> script, stamp) with tombstoned removals. A rule
        install arriving before its script replays via the dependency-miss
        retry path, like any registry reference."""
        instance.script_manager.add_listener(self._on_script_mutation)
        instance.scripted_rules.add_listener(
            self._on_scripted_rule_mutation)
        rule_programs = getattr(instance, "rule_programs", None)
        if rule_programs is not None:
            # rule-program installs replicate the same way: LWW payloads
            # (the spec IS the identity) with tombstoned removals
            rule_programs.add_listener(self._on_rule_program_mutation)
        anomaly_models = getattr(instance, "anomaly_models", None)
        if anomaly_models is not None:
            # anomaly-model installs share the rule-program algebra
            anomaly_models.add_listener(self._on_anomaly_model_mutation)
        actuation_policies = getattr(instance, "actuation_policies", None)
        if actuation_policies is not None:
            # alert->command policies replicate the same way: a policy
            # installed on one peer fires on every peer's shard of the
            # fleet
            actuation_policies.add_listener(
                self._on_actuation_policy_mutation)

    def _on_script_mutation(self, op: str, scope: str, script_id: str,
                            payload) -> None:
        if getattr(self._applying, "active", False) or not self.peers:
            return
        data = {"kind": "_script", "op": op, "scope": scope,
                "scriptId": script_id, "payload": payload}
        self._publish(f"script:{scope}:{script_id}".encode(), data)

    def _on_scripted_rule_mutation(self, op: str, tenant: str, token: str,
                                   payload) -> None:
        if getattr(self._applying, "active", False) or not self.peers:
            return
        data = {"kind": "_scripted_rule", "op": op, "tenant": tenant,
                "token": token, "payload": payload}
        self._publish(token.encode(), data)

    def _apply_script(self, data: Dict) -> None:
        scripts = self.instance.script_manager
        if data.get("op") == "delete":
            if scripts.apply_delete(data.get("scope", ""),
                                    data.get("scriptId", ""),
                                    int(data.get("payload") or 0)):
                self.applied += 1
            return
        if scripts.apply_replicated(dict(data.get("payload") or {})):
            self.applied += 1

    def _apply_scripted_rule(self, data: Dict) -> None:
        if self.instance.apply_replicated_scripted_rule(
                data.get("op", ""), data.get("tenant", ""),
                data.get("token", ""), data.get("payload")):
            self.applied += 1

    def _on_rule_program_mutation(self, op: str, tenant: str, token: str,
                                  payload) -> None:
        if getattr(self._applying, "active", False) or not self.peers:
            return
        data = {"kind": "_rule_program", "op": op, "tenant": tenant,
                "token": token, "payload": payload}
        self._publish(token.encode(), data)

    def _apply_rule_program(self, data: Dict) -> None:
        # an invalid spec raises the structured RuleProgramError (409,
        # names the offending node) out of apply_replicated_rule_program
        # BEFORE any local mutation — _handle treats it as a
        # non-retryable conflict toward the retry budget / dead letter,
        # never a stack-trace crash of the applier
        if self.instance.apply_replicated_rule_program(
                data.get("op", ""), data.get("tenant", ""),
                data.get("token", ""), data.get("payload")):
            self.applied += 1

    def _on_anomaly_model_mutation(self, op: str, tenant: str, token: str,
                                   payload) -> None:
        if getattr(self._applying, "active", False) or not self.peers:
            return
        data = {"kind": "_model", "op": op, "tenant": tenant,
                "token": token, "payload": payload}
        self._publish(token.encode(), data)

    def _apply_anomaly_model(self, data: Dict) -> None:
        # invalid specs raise the structured AnomalyModelError (409,
        # names the offending field) BEFORE any local mutation — a
        # non-retryable conflict, same contract as _apply_rule_program
        if self.instance.apply_replicated_anomaly_model(
                data.get("op", ""), data.get("tenant", ""),
                data.get("token", ""), data.get("payload")):
            self.applied += 1

    def _on_actuation_policy_mutation(self, op: str, tenant: str,
                                      token: str, payload) -> None:
        if getattr(self._applying, "active", False) or not self.peers:
            return
        data = {"kind": "_actuation_policy", "op": op, "tenant": tenant,
                "token": token, "payload": payload}
        self._publish(token.encode(), data)

    def _apply_actuation_policy(self, data: Dict) -> None:
        # invalid specs raise the structured ActuationPolicyError (409,
        # names the offending field) BEFORE any local mutation — a
        # non-retryable conflict, same contract as _apply_anomaly_model
        if self.instance.apply_replicated_actuation_policy(
                data.get("op", ""), data.get("tenant", ""),
                data.get("token", ""), data.get("payload")):
            self.applied += 1

    # -- apply side --------------------------------------------------------
    def start(self) -> None:
        self._host.start()

    def stop(self) -> None:
        self._host.stop()

    def _handle(self, records: List[Record]) -> None:
        # The topic is partitioned by entity token, so a poll can hand us a
        # dependent entity BEFORE its dependency (a device ahead of its
        # device type). Multi-pass over the DEPENDENCY misses until a full
        # pass makes no progress: any topological order inside the batch
        # resolves without relying on redelivery (which would replay the
        # batch in the same order and fail deterministically). A dependency
        # in a LATER batch still resolves via the consumer's at-least-once
        # retry. Non-dependency failures (genuine conflicts) never succeed
        # on a later pass, so they are applied once and re-raised at the
        # end — toward the retry budget and the dead-letter surface.
        pending = [msgpack.unpackb(r.value, raw=False) for r in records]
        conflict: Optional[BaseException] = None
        self._applying.active = True
        try:
            while pending:
                missing: List[Dict] = []
                dep_error: Optional[BaseException] = None
                for data in pending:
                    try:
                        self._apply(data)
                    except Exception as exc:
                        if self._retryable(exc):
                            missing.append(data)
                            if dep_error is None:
                                dep_error = exc
                        elif conflict is None:
                            conflict = exc
                if len(missing) == len(pending):
                    raise dep_error  # no progress: retry budget applies
                pending = missing
            if conflict is not None:
                raise conflict
        finally:
            self._applying.active = False

    @staticmethod
    def _retryable(exc: BaseException) -> bool:
        """Failures that a LATER record in the same batch can clear:
        missing dependencies, plus referential-ordering refusals (a type
        delete ahead of its devices' deletes, an assignment create ahead
        of the prior assignment's release — cross-entity records ride
        different partitions, so order is not guaranteed)."""
        from sitewhere_tpu.errors import (
            ErrorCode, NotFoundError, SiteWhereError)

        if isinstance(exc, NotFoundError):
            return True
        return isinstance(exc, SiteWhereError) and exc.code in (
            ErrorCode.DEVICE_TYPE_IN_USE, ErrorCode.DEVICE_ALREADY_ASSIGNED)

    def _apply(self, data: Dict) -> None:
        from sitewhere_tpu.errors import (
            DuplicateTokenError, ErrorCode, NotFoundError, SiteWhereError)
        from sitewhere_tpu.web.marshal import entity_from_payload

        origin = data.get("origin")
        if origin is not None and not self._fence.admit(
                str(origin), int(data.get("epoch", 0))):
            # stale-epoch gossip from a fenced (taken-over) writer:
            # admit() already counted it on `fencing.rejected`
            LOGGER.warning(
                "rejected stale registry gossip from %s (epoch %s < "
                "floor %d)", origin, data.get("epoch"),
                self._fence.floor(str(origin)))
            return
        kind = data.get("kind")
        if kind == "_rule":
            self._apply_rule(data)
            return
        if kind == "_script":
            self._apply_script(data)
            return
        if kind == "_scripted_rule":
            self._apply_scripted_rule(data)
            return
        if kind == "_rule_program":
            self._apply_rule_program(data)
            return
        if kind == "_model":
            self._apply_anomaly_model(data)
            return
        if kind == "_actuation_policy":
            self._apply_actuation_policy(data)
            return
        cls = _gossip_class(kind)
        if cls is None:
            return
        engine = self.instance.get_tenant_engine(data.get("tenant", ""))
        if engine is None:
            raise NotFoundError(
                f"gossip for unknown tenant {data.get('tenant')!r}",
                ErrorCode.INVALID_TENANT_TOKEN)
        registry = engine.registry
        tenant = data.get("tenant", "")
        if data.get("op") == "delete":
            self._apply_delete(registry, tenant, kind, data)
            return
        entity_data = dict(data.get("entity") or {})
        token = entity_data.get("token", "")
        # a write that lost to an applied deletion stays dead; a NEWER
        # write resurrects the entity (the winning side of the LWW pair —
        # hosts that saw the write first make the delete a no-op instead)
        tomb = self._tombstones.get((tenant, kind, token))
        if tomb is not None and _gossip_stamp(entity_data) <= tomb:
            return
        # remap reference ids through tokens; a missing dependency raises
        # -> the batch redelivers until the dependency gossip arrives
        ref_tokens = dict(data.get("refs") or {})
        for field, coll_name in _GOSSIP_REFS.get(kind, []):
            ref_token = ref_tokens.get(field)
            if ref_token:
                local = getattr(registry, coll_name).get_by_token(ref_token)
                if local is None:
                    raise NotFoundError(
                        f"gossip dependency {coll_name}:{ref_token!r} not "
                        f"yet replicated", ErrorCode.GENERIC)
                entity_data[field] = local.id
        with registry.replication():
            # replication context: creates are idempotent get-or-create,
            # and stay claimable by a later identical local create
            # (registry/store.py _Collection) — the contract that lets
            # every host provision the same world in any order
            existing = registry.collection_of(kind).get_by_token(token)
            if existing is None:
                entity = entity_from_payload(cls, entity_data)
                try:
                    registry.create_by_kind(kind, entity)
                    self.applied += 1
                except DuplicateTokenError:
                    pass  # raced another replica of the same create
                except SiteWhereError:
                    # genuine conflict (e.g. device already actively
                    # assigned): re-raise -> retry budget -> dead-letter
                    self.conflicts += 1
                    raise
            else:
                self._update_existing(registry, kind, token, existing,
                                      entity_data, ref_tokens)

    def _apply_delete(self, registry, tenant: str, kind: str,
                      data: Dict) -> None:
        from sitewhere_tpu.web.marshal import to_jsonable

        token = data.get("token", "")
        stamp = int(data.get("stamp") or 0)
        key = (tenant, kind, token)
        self._tombstones[key] = max(self._tombstones.get(key, 0), stamp)
        existing = registry.collection_of(kind).get_by_token(token)
        if existing is None:
            return  # idempotent redelivery, or the entity never arrived
        if _gossip_stamp(to_jsonable(existing)) > stamp:
            return  # a concurrent write outranked the delete: keep it
        with registry.replication():
            registry.delete_by_kind(kind, token)
        self.applied += 1

    def _local_ref_tokens(self, registry, kind: str, entity) -> Dict[str, str]:
        """The entity's replicated references by token — the local half of
        the host-independent content digest."""
        out: Dict[str, str] = {}
        for field, coll_name in _GOSSIP_REFS.get(kind, []):
            ref_id = getattr(entity, field, None)
            if ref_id:
                ref = getattr(registry, coll_name).get(ref_id)
                if ref is not None:
                    out[field] = ref.token
        return out

    def _update_existing(self, registry, kind: str, token: str, existing,
                         entity_data: Dict, ref_tokens: Dict) -> None:
        import dataclasses as _dc

        from sitewhere_tpu.web.marshal import entity_from_payload, to_jsonable

        # created_date is a PER-HOST observation and deliberately does
        # not converge: it is excluded from the LWW diff (a later write
        # must not move it), so entities created concurrently on two
        # hosts keep each host's own creation stamp (differing by the
        # race window). Any mutation of it here would also mutate the
        # live LWW stamp of a never-updated entity (stamp == created
        # then), which two independent review passes showed lets
        # at-least-once redeliveries flip strict verdicts into digest
        # ties and diverge CONTENT — the actual contract. Content
        # convergence is what the storm test pins; creation stamps are
        # like per-replica writetimes.
        current = to_jsonable(existing)
        # last-writer-wins: stamps first, host-independent digest on exact
        # ties — every host compares the same (stamp, digest) pair, so
        # concurrent updates converge to the same winner everywhere. The
        # digests (json + sha1 over the full entity) are only computed on
        # a tie, the rare case.
        inc_ts, loc_ts = _gossip_stamp(entity_data), _gossip_stamp(current)
        if inc_ts < loc_ts:
            return  # stale: the local copy already won
        if inc_ts == loc_ts:
            inc_key = _gossip_content_key(kind, entity_data, ref_tokens)
            loc_key = _gossip_content_key(
                kind, current,
                self._local_ref_tokens(registry, kind, existing))
            if inc_key <= loc_key:
                return  # identical, or the local copy wins the tiebreak
        # coerce through the marshal layer so enum/location fields apply
        # with model types, not raw wire values
        coerced = entity_from_payload(type(existing), entity_data)
        inc_json = to_jsonable(coerced)
        # the writer's updated_date is part of the diff: adopting the
        # winning stamp is what keeps later comparisons consistent
        fields = {f.name for f in _dc.fields(type(existing))} \
            - {"id", "token", "created_date"}
        diff = {name: getattr(coerced, name) for name in fields
                if current.get(name) != inc_json.get(name)}
        if not diff:
            return
        try:
            with registry.replication():
                result = registry.update_by_kind(kind, token, diff)
                if kind == "assignment":
                    # status may have moved through the generic diff path:
                    # re-derive the active-assignment index entry
                    registry.reconcile_active_assignment(result)
            self.applied += 1
        except Exception:
            self.conflicts += 1
            LOGGER.exception("gossip update of %s %r failed", kind, token)


# ---------------------------------------------------------------------------
# leased ownership + automated takeover
# ---------------------------------------------------------------------------

class TakeoverMonitor:
    """Leased ownership + automated takeover (runtime/recovery.py).

    Every host leases its own shard group and renews it through the
    existing heartbeat edges — each ProcessStateReporter state carries
    `{"leases": {resource: epoch}}`, so the lease protocol adds no new
    transport. Every host mirrors the leases it hears into a local
    LeaseTable (a stale heartbeat does NOT refresh, so the mirrored TTL
    lapses exactly when the heartbeats stop).

    When a peer's lease lapses — or its heartbeat reports a `failed`
    health ladder — every surviving host computes the same deterministic
    successor (lowest healthy rank, elect_successor); ONLY the successor
    acts. It fences the failed owner's epoch (local appliers via
    `fence_hooks`, cluster-wide via the busnet `fence` broadcast — from
    then on the zombie's stale-epoch writes are rejected and counted),
    steals the lease at the fenced epoch, runs `on_takeover` (checkpoint
    restore + retained-log replay on the wired instance), and counts
    `takeover.count`. No operator in the loop.

    When the fenced owner comes back (a restart mints epoch = floor, so
    its traffic re-admits automatically), the successor releases the
    stolen lease and the owner's own renewal takes over again.

    `check_once()` is the whole state machine; the background thread
    just calls it on a cadence. Deterministic tests drive it directly
    with an injectable clock and peer-state snapshots."""

    def __init__(self, process_id: int,
                 peer_states: Callable[[], Dict[str, Dict]],
                 epoch_of: Callable[[], int],
                 on_takeover: Optional[Callable[[str, Dict], None]] = None,
                 fence_hooks: Optional[List[Callable[[str, int], None]]]
                 = None,
                 fence_broadcast: Optional[Callable[[str, int], None]]
                 = None,
                 leases: Optional[LeaseTable] = None,
                 ttl_s: float = 6.0, check_interval_s: float = 1.0,
                 clock: Callable[[], float] = time.monotonic):
        self.process_id = int(process_id)
        self.owner = f"proc:{process_id}"
        self.resource = f"shard-group:{process_id}"
        self.peer_states = peer_states
        self.epoch_of = epoch_of
        self.on_takeover = on_takeover
        self.fence_hooks = list(fence_hooks or [])
        self.fence_broadcast = fence_broadcast
        self.ttl_s = float(ttl_s)
        self.check_interval_s = float(check_interval_s)
        self._clock = clock
        self.leases = leases if leases is not None else LeaseTable(
            clock=clock)
        self.taken: set = set()  # resources this host took over and holds
        self.events: deque = deque(maxlen=32)
        self._takeovers = GLOBAL_METRICS.counter("takeover.count")
        self._local_takeovers = 0  # this monitor's share of the counter
        self._stop = threading.Event()
        self._thread: Optional[threading.Thread] = None

    # -- lifecycle ---------------------------------------------------------
    def start(self) -> None:
        if self._thread is not None:
            return
        self._stop.clear()
        self._thread = threading.Thread(target=self._run,
                                        name="takeover-monitor",
                                        daemon=True)
        self._thread.start()

    def stop(self) -> None:
        self._stop.set()
        if self._thread is not None:
            self._thread.join(timeout=5.0)
            self._thread = None

    def _run(self) -> None:
        while not self._stop.wait(self.check_interval_s):
            try:
                self.check_once()
            except Exception:
                LOGGER.exception("takeover check failed")

    # -- heartbeat ride-along ---------------------------------------------
    def lease_advertisement(self) -> Dict[str, int]:
        """The `leases` block this host's heartbeat carries: its own
        shard group plus anything it took over, at its current epoch."""
        epoch = int(self.epoch_of())
        out = {self.resource: epoch}
        for resource in list(self.taken):
            out[resource] = epoch
        return out

    # -- the state machine -------------------------------------------------
    def check_once(self) -> List[Dict]:
        """One tick: renew own lease, mirror peers' leases, detect lapses
        and failed-health owners, take over as the deterministic
        successor. Returns the takeover events performed this tick."""
        now = self._clock()
        epoch = int(self.epoch_of())
        if not self.leases.renew(self.resource, self.owner, epoch,
                                 now=now):
            self.leases.acquire(self.resource, self.owner, epoch,
                                self.ttl_s, now=now)
        states = dict(self.peer_states() or {})
        healthy: Dict[int, bool] = {self.process_id: True}
        owner_failed: Dict[str, bool] = {}
        for pid, state in states.items():
            try:
                rank = int(state.get("process_id", pid))
            except (TypeError, ValueError):
                continue
            if rank == self.process_id:
                continue
            stale = bool(state.get("stale"))
            failed = state.get("health") == "failed"
            healthy[rank] = not stale and not failed
            owner_failed[f"proc:{rank}"] = failed
            if stale or failed:
                # a stale heartbeat must not refresh leases, and a host
                # reporting `failed` gets no mirror/handback either — a
                # zombie advertising its old lease would otherwise flap
                # ownership back and forth every tick
                continue
            advertised = state.get("leases") or {}
            for resource, lease_epoch in advertised.items():
                owner = f"proc:{rank}"
                if resource in self.taken:
                    # the fenced owner is back and advertising again:
                    # hand the lease back (its restart minted an epoch
                    # at the fenced floor, so its writes already
                    # re-admit) and let its renewal take over
                    self.leases.release(resource, self.owner)
                    self.taken.discard(resource)
                    self.events.append({
                        "resource": resource, "op": "handback",
                        "to": owner, "at_ms": int(time.time() * 1000)})
                    LOGGER.info("lease %s handed back to %s", resource,
                                owner)
                if not self.leases.renew(resource, owner,
                                         int(lease_epoch), now=now):
                    self.leases.acquire(resource, owner, int(lease_epoch),
                                        self.ttl_s, now=now)
        performed: List[Dict] = []
        for resource, info in self.leases.snapshot(now=now).items():
            owner = info["owner"]
            if owner == self.owner:
                continue
            lapsed = info["expired"] or owner_failed.get(owner, False)
            if not lapsed:
                continue
            try:
                owner_rank = int(owner.rpartition(":")[2])
            except ValueError:
                owner_rank = None
            successor = elect_successor(healthy, exclude=owner_rank)
            if successor != self.process_id:
                continue
            performed.append(
                self._take_over(resource, owner, int(info["epoch"]),
                                now=now))
        return performed

    def _take_over(self, resource: str, owner: str, last_epoch: int,
                   now: float) -> Dict:
        fence_epoch = last_epoch + 1
        for hook in self.fence_hooks:
            try:
                hook(owner, fence_epoch)
            except Exception:
                LOGGER.exception("fence hook failed for %s", owner)
        if self.fence_broadcast is not None:
            try:
                self.fence_broadcast(owner, fence_epoch)
            except Exception:
                LOGGER.exception("fence broadcast failed for %s", owner)
        # the steal and the fence are one decision: the lease is taken
        # at the FENCED epoch, so even a still-live lease record yields
        # (LeaseTable.acquire's strictly-higher-epoch rule)
        self.leases.acquire(resource, self.owner, fence_epoch, self.ttl_s,
                            now=now)
        self.taken.add(resource)
        self._takeovers.inc()
        self._local_takeovers += 1
        event = {"resource": resource, "op": "takeover", "from": owner,
                 "to": self.owner, "fenced_epoch": fence_epoch,
                 "at_ms": int(time.time() * 1000)}
        self.events.append(event)
        LOGGER.warning("took over %s from %s (fenced at epoch %d)",
                       resource, owner, fence_epoch)
        if self.on_takeover is not None:
            try:
                self.on_takeover(resource, event)
            except Exception:
                LOGGER.exception("takeover callback failed for %s",
                                 resource)
        return event

    def snapshot(self) -> Dict:
        return {
            "leases": self.leases.snapshot(),
            "taken_over": sorted(self.taken),
            "takeovers": self._local_takeovers,
            "takeover_events": list(self.events),
        }


def _annotate_recovery_state(cluster, state: Dict) -> None:
    """Failover fields every heartbeat carries (runtime/recovery.py):
    the host's recovery epoch + fence-key origin, its lease
    advertisement (peers mirror these into their lease tables), and the
    engine health-ladder state (a `failed` report triggers takeover
    without waiting for the heartbeat TTL to lapse)."""
    epoch = int(getattr(cluster.instance, "recovery_epoch", 0))
    state["epoch"] = epoch
    state["origin"] = f"proc:{cluster.process_id}"
    monitor = getattr(cluster, "takeover_monitor", None)
    if monitor is not None:
        state["leases"] = monitor.lease_advertisement()
    else:
        state["leases"] = {f"shard-group:{cluster.process_id}": epoch}
    health = getattr(cluster.instance.pipeline_engine, "health", None)
    if health is not None:
        state["health"] = health.state


# ---------------------------------------------------------------------------
# cluster telemetry fan-in (busnet `telemetry` op + /api/cluster/telemetry)
# ---------------------------------------------------------------------------

def _telemetry_snapshot(instance, process_id: int) -> Dict:
    """One process's telemetry payload: metrics report + full Prometheus
    exposition (instance.extra_gauges families included), the flight
    recorder's window rollups, and the event-age waterfall when the
    window saw stamped batches. This is what the busnet `telemetry` op
    serves to peers — all host-side reads, no device sync, so a peer's
    scrape never perturbs this host's step loop."""
    from sitewhere_tpu.runtime.flight import GLOBAL_FLIGHT

    rollups = GLOBAL_FLIGHT.export(last_n=64).get("rollups", {})
    out = {
        "process_id": int(process_id),
        "instance_id": instance.instance_id,
        "status": instance.status.name,
        "metrics": instance.metrics.report(),
        "prometheus_text": instance.prometheus_text(),
        "flight_rollups": rollups,
    }
    age = rollups.get("event_age")
    if age:
        out["event_age"] = age
    return out


def _inject_peer_label(line: str, pid: str) -> str:
    """`name{edge="x"} 1` -> `name{edge="x",peer="<pid>"} 1` (and bare
    `name 1` grows a label block). Label VALUES in this codebase are
    tokens (engine names, table names, edges) — never contain spaces —
    so splitting on the first space is safe."""
    name_part, _, rest = line.partition(" ")
    if not rest:
        return line
    if name_part.endswith("}") and "{" in name_part:
        base, _, labels = name_part.partition("{")
        labels = labels[:-1]
        name_part = (f'{base}{{{labels},peer="{pid}"}}' if labels
                     else f'{base}{{peer="{pid}"}}')
    else:
        name_part = f'{name_part}{{peer="{pid}"}}'
    return f"{name_part} {rest}"


def _cluster_telemetry(cluster) -> Dict:
    """Fan out over busnet and merge: local snapshot + every reachable
    peer's, keyed by process id, plus one merged Prometheus exposition
    with a peer="<pid>" label injected into every sample (header lines
    deduplicated across peers). Unreachable peers land in `stale_peers`
    instead of failing the whole view — during an incident a partial
    waterfall is exactly what the operator needs."""
    processes: Dict[str, Dict] = {
        str(cluster.process_id): _telemetry_snapshot(cluster.instance,
                                                     cluster.process_id)}
    stale: List[str] = []
    for pid, client in sorted(cluster.peers.items()):
        try:
            processes[str(pid)] = client.telemetry()
        except (BusNetError, OSError) as exc:
            LOGGER.warning("telemetry fan-in: peer %d unreachable (%s)",
                           pid, exc)
            stale.append(str(pid))
    merged: List[str] = []
    seen_headers = set()
    for pid in sorted(processes, key=int):
        for line in (processes[pid].get("prometheus_text") or
                     "").splitlines():
            if line.startswith("#"):
                if line not in seen_headers:
                    seen_headers.add(line)
                    merged.append(line)
            elif line:
                merged.append(_inject_peer_label(line, pid))
    return {
        "process_id": cluster.process_id,
        "num_processes": cluster.num_processes,
        "processes": processes,
        "stale_peers": stale,
        "prometheus_text": "\n".join(merged) + ("\n" if merged else ""),
    }


# ---------------------------------------------------------------------------
# composition root: one cluster host
# ---------------------------------------------------------------------------

class ClusterService:
    """Everything one host of an N-process instance runs, composed.

    Wire-up (the Microservice.java:182-236 boot sequence, TPU-shaped):
    busnet server over the instance's bus (so peers and edge processes can
    produce/consume), BusClients to every peer's edge, the lockstep step
    loop with alert/presence persistence callbacks, foreign-row
    forwarding + consumption, state heartbeats, the topology aggregator,
    and the peer watchdog. Install on a SiteWhereInstance BEFORE
    instance.start() — tenant engines created afterwards pick up the
    cluster hooks in their inbound processors (ownership routing +
    lockstep feeding).

    Also serves as the `cluster` hooks object InboundProcessingService
    consumes: owner_process / forward_decoded / feed_hot.
    """

    def __init__(self, instance, process_id: int, num_processes: int,
                 peer_bus_addrs: Optional[Dict[int, tuple]] = None,
                 bus_host: str = "127.0.0.1", bus_port: int = 0,
                 heartbeat_s: float = 1.0, stale_after_s: float = 5.0,
                 fail_after_s: float = 15.0,
                 presence_every_ticks: int = 0,
                 idle_interval_s: float = 0.005,
                 exit_on_peer_loss: bool = False,
                 peer_loss_exit_code: int = 13,
                 registry_gossip: bool = True):
        from sitewhere_tpu.runtime.busnet import BusServer

        engine = instance.pipeline_engine
        if not isinstance(engine, ShardedPipelineEngine):
            raise TypeError(
                "ClusterService requires a ShardedPipelineEngine instance "
                "(enable_pipeline with a mesh/shards configuration)")
        self.instance = instance
        self.engine = engine
        self.process_id = process_id
        self.num_processes = num_processes
        self.exit_on_peer_loss = exit_on_peer_loss
        self.peer_loss_exit_code = peer_loss_exit_code
        self.degraded: List[str] = []
        self._proc_of_shard = np.asarray(
            [d.process_index for d in engine.mesh.devices.flat], np.int32)

        naming = instance.naming
        self.bus_server = BusServer(instance.bus, host=bus_host,
                                    port=bus_port)
        # serve this host's telemetry snapshot to peers (the fan-in for
        # GET /api/cluster/telemetry rides the existing bus edge)
        self.bus_server.telemetry_provider = (
            lambda: _telemetry_snapshot(instance, process_id))
        self.peers: Dict[int, BusClient] = {}
        for pid, addr in (peer_bus_addrs or {}).items():
            if int(pid) != process_id:
                self.peers[int(pid)] = BusClient(addr[0], int(addr[1]))

        self.forwarder = ForeignRowForwarder(
            process_id, self.peers, naming, local_bus=instance.bus)
        self.control = ClusterControl(engine.mesh)
        self.loop = ClusterStepLoop(
            engine, control=self.control,
            idle_interval_s=idle_interval_s,
            presence_every_ticks=presence_every_ticks,
            on_alerts=self._persist_alerts,
            on_presence_missing=self._persist_presence_missing,
            forward_foreign=lambda batch: self.forwarder.forward(
                engine, batch),
            on_fatal=self._on_fatal)
        self.foreign_consumer = ForeignRowsConsumer(
            instance.bus, naming, engine, self.loop,
            owner_check=lambda token: (self.owner_process(token)
                                       == self.process_id))
        self.reporter = ProcessStateReporter(
            process_id, instance.bus, naming, self.peers,
            build_state=self._build_state, interval_s=heartbeat_s)
        self.gossip = (RegistryGossip(process_id, self.peers, instance,
                                      naming) if registry_gossip else None)
        if self.gossip is not None:
            self.gossip.register_rules_engine(engine)
            self.gossip.register_scripts(instance)
        # tenant/user/authority provisioning replication with reactive
        # engine lifecycle (multitenant/replication.py) — same flag as
        # the registry gossip: both are the control plane
        self.provisioning = (ProvisioningReplicator(
            process_id, self.peers, instance, naming)
            if registry_gossip else None)
        # epoch stamping (runtime/recovery.py): the SPMD gang restarts as
        # a unit, so there is no takeover monitor here — but stamping
        # gossip/provisioning envelopes and busnet RPCs means a zombie
        # from BEFORE the gang restart (a host the supervisor failed to
        # kill) is fenced out once any peer raises its floor.
        epoch = int(getattr(instance, "recovery_epoch", 0))
        if self.gossip is not None:
            self.gossip.set_epoch(epoch)
        if self.provisioning is not None:
            self.provisioning.set_epoch(epoch)
        for client in self.peers.values():
            client.set_epoch(f"proc:{process_id}", epoch)
        self.aggregator = TopologyAggregator(
            instance.bus, naming, stale_after_s=stale_after_s)
        expected_peers = [p for p in range(num_processes)
                          if p != process_id]
        self.watchdog = PeerWatchdog(
            self.aggregator, expected_peers, fail_after_s=fail_after_s,
            on_peer_loss=self._on_peer_loss)
        instance.cluster_hooks = self

    # -- hooks consumed by InboundProcessingService ------------------------
    def owner_process(self, token: str) -> int:
        """Process owning a device token's shard; unknown tokens are
        handled locally (they surface on the unregistered path)."""
        idx = self.engine.registry.devices.lookup(token)
        if idx <= 0:
            return self.process_id
        return int(self._proc_of_shard[idx % self.engine.n_shards])

    def forward_decoded(self, groups: Dict[int, List[Record]],
                        tenant: str) -> None:
        """Republish decoded-event records to their owner hosts' decoded
        topics (pre-persist ownership routing). Raises on delivery failure
        so the consumer's batch redelivers (at-least-once). Each record is
        stamped `fwdFrom` — if the receiving host's registry DISAGREES on
        ownership (provisioning drift), the stamp lets it dead-letter the
        record instead of forwarding it back forever."""
        topic = self.instance.naming.event_source_decoded_events(tenant)
        for pid, records in groups.items():
            client = self.peers.get(int(pid))
            if client is None:
                raise BusNetError(f"no bus edge known for process {pid}")
            stamped = []
            for record in records:
                try:
                    data = msgpack.unpackb(record.value, raw=False)
                    data["fwdFrom"] = self.process_id
                    stamped.append((record.key,
                                    msgpack.packb(data, use_bin_type=True)))
                except Exception:
                    stamped.append((record.key, record.value))
            client.publish_batch(topic, stamped)

    def feed_hot(self, events, tokens) -> List[FoldTicket]:
        """Queue locally-owned persisted events for the lockstep step;
        returns fold tickets (wait before committing offsets)."""
        return [self.loop.feed(batch)
                for batch in self.engine.packer.pack_events(events, tokens)]

    # -- step-loop callbacks ----------------------------------------------
    def _resolve_assignment(self, device_token: str):
        tensors = self.instance.registry_tensors
        if tensors is None:
            return None, None
        tenant_token = tensors.tenant_of_device(device_token)
        if tenant_token is None:
            return None, None
        tenant_engine = self.instance.get_tenant_engine(tenant_token)
        if tenant_engine is None:
            return None, None
        device = tenant_engine.registry.get_device_by_token(device_token)
        if device is None:
            return tenant_engine, None
        return (tenant_engine,
                tenant_engine.registry.get_active_assignment(device.id))

    def _persist_alerts(self, alerts) -> None:
        for alert in alerts:
            try:
                tenant_engine, assignment = self._resolve_assignment(
                    alert.device_id)
                if tenant_engine is None or assignment is None:
                    continue
                tenant_engine.event_management.add_alerts(
                    assignment.token, alert)
            except Exception:
                LOGGER.exception("cluster alert persist failed for %s",
                                 alert.device_id)

    def _persist_presence_missing(self, tokens: List[str]) -> None:
        from sitewhere_tpu.model.event import DeviceStateChange
        from sitewhere_tpu.model.state import PresenceState

        for token in tokens:
            try:
                tenant_engine, assignment = self._resolve_assignment(token)
                if tenant_engine is None or assignment is None:
                    continue
                tenant_engine.event_management.add_state_changes(
                    assignment.token, DeviceStateChange(
                        device_id=token, attribute="presence",
                        type="presence",
                        previous_state=PresenceState.PRESENT.name,
                        new_state=PresenceState.NOT_PRESENT.name))
            except Exception:
                LOGGER.exception("presence state-change persist failed "
                                 "for %s", token)

    def _build_state(self) -> Dict:
        state = {
            "instance_id": self.instance.instance_id,
            "status": self.instance.status.name,
            "tick": self.loop.tick_count,
            "forwarded_rows": self.forwarder.forwarded,
            "consumed_foreign": self.foreign_consumer.consumed_rows,
        }
        if self.gossip is not None:
            state["gossip_published"] = self.gossip.published
            state["gossip_applied"] = self.gossip.applied
        if self.provisioning is not None:
            state["provisioning_published"] = self.provisioning.published
            state["provisioning_applied"] = self.provisioning.applied
        _annotate_recovery_state(self, state)
        return state

    def _on_fatal(self, exc: BaseException) -> None:
        LOGGER.critical("cluster host %d step loop fatal: %s",
                        self.process_id, exc)
        if self.exit_on_peer_loss:
            import os

            os._exit(self.peer_loss_exit_code)
        # exit_on_peer_loss=False (examples/tests): the process survives
        # with a dead loop — STOP heartbeating so peers' staleness
        # watchdogs see the failure instead of a live-looking host whose
        # vote/step collectives hang forever
        self.reporter.stop()

    def _on_peer_loss(self, stale: List[str]) -> None:
        self.degraded = stale
        if self.exit_on_peer_loss:
            import os

            LOGGER.critical("exiting for gang restart (peers lost: %s)",
                            stale)
            os._exit(self.peer_loss_exit_code)

    # -- composite lifecycle ----------------------------------------------
    @property
    def bus_port(self) -> int:
        return self.bus_server.port

    def start(self) -> None:
        """Boot order matters: the bus edge first (peers may already be
        publishing), then the instance — which fully initializes the
        engine BEFORE the lockstep loop's first submit (a lazy init racing
        instance.start() left _sharded_step half-built) — then the loop
        and its consumers, then heartbeats and the watchdog. Feeds that
        tenant-engine consumers enqueue before the loop starts simply wait
        in its queue."""
        self.bus_server.start()
        self.aggregator.start()
        self.instance.start()
        self.loop.start()
        self.foreign_consumer.start()
        if self.gossip is not None:
            self.gossip.start()
        if self.provisioning is not None:
            self.provisioning.start()
        self.reporter.start()
        self.watchdog.start()

    def stop(self) -> None:
        self.watchdog.stop()
        self.reporter.stop()
        if self.provisioning is not None:
            self.provisioning.stop()
        if self.gossip is not None:
            self.gossip.stop()
        self.instance.stop()
        self.foreign_consumer.stop()
        self.loop.stop()
        self.aggregator.stop()
        for client in self.peers.values():
            client.close()
        self.bus_server.stop()

    def processes(self) -> Dict[str, Dict]:
        """Cluster process map for instance topology (/admin): every
        heartbeat-known process plus self, with liveness."""
        out = self.aggregator.snapshot()
        me = str(self.process_id)
        if me not in out:
            state = self._build_state()
            state["process_id"] = self.process_id
            state["age_s"] = 0.0
            state["stale"] = False
            out[me] = state
        return out

    def cluster_telemetry(self) -> Dict:
        """Cluster-wide telemetry fan-in (GET /api/cluster/telemetry)."""
        return _cluster_telemetry(self)


# ---------------------------------------------------------------------------
# control-plane-only cluster (no SPMD mesh)
# ---------------------------------------------------------------------------

class ControlPlaneCluster:
    """N INDEPENDENT single-host instances joined by busnet edges: the
    control plane — registry gossip, tenant/user/authority provisioning
    with reactive engine lifecycle, script + scripted-rule replication,
    heartbeats/topology — converges cluster-wide while each host runs its
    OWN pipeline engine and owns every device it ingests locally.

    This is the deployable shape for environments without
    multi-controller collectives (and the composition the provisioning
    drill runs at N=3): no jax.distributed gang, no lockstep loop, no
    foreign-row forwarding — `data_plane = False` tells TenantEngine to
    keep the direct single-host submit path. A killed host restarts alone
    (its supervisor) and rebuilds from its durable state; survivors keep
    serving — there are no collectives to hang.

    Install on a SiteWhereInstance BEFORE `instance.start()` (the
    constructor sets `instance.cluster_hooks`, which tenant engines read
    to register their registries with the gossip), then `start()`.
    """

    data_plane = False

    def __init__(self, instance, process_id: int, num_processes: int,
                 peer_bus_addrs: Optional[Dict[int, tuple]] = None,
                 bus_host: str = "127.0.0.1", bus_port: int = 0,
                 heartbeat_s: float = 1.0, stale_after_s: float = 5.0):
        from sitewhere_tpu.runtime.busnet import BusServer

        self.instance = instance
        self.process_id = process_id
        self.num_processes = num_processes
        self.degraded: List[str] = []
        naming = instance.naming
        self.bus_server = BusServer(instance.bus, host=bus_host,
                                    port=bus_port)
        # peer telemetry for GET /api/cluster/telemetry (same fan-in as
        # the SPMD cluster — the control plane has a bus edge too)
        self.bus_server.telemetry_provider = (
            lambda: _telemetry_snapshot(instance, process_id))
        self.peers: Dict[int, BusClient] = {}
        for pid, addr in (peer_bus_addrs or {}).items():
            if int(pid) != process_id:
                self.peers[int(pid)] = BusClient(addr[0], int(addr[1]))
        self.gossip = RegistryGossip(process_id, self.peers, instance,
                                     naming)
        self.gossip.register_scripts(instance)
        if instance.pipeline_engine is not None:
            self.gossip.register_rules_engine(instance.pipeline_engine)
        self.provisioning = ProvisioningReplicator(
            process_id, self.peers, instance, naming)
        self.reporter = ProcessStateReporter(
            process_id, instance.bus, naming, self.peers,
            build_state=self._build_state, interval_s=heartbeat_s)
        self.aggregator = TopologyAggregator(
            instance.bus, naming, stale_after_s=stale_after_s)
        # epoch-fenced failover (runtime/recovery.py): stamp this host's
        # recovery epoch into every gossip/provisioning envelope and
        # busnet RPC, and run the lease/takeover state machine over the
        # heartbeat topology. The lease TTL tracks the staleness window
        # so a lapse and a stale heartbeat mean the same thing.
        epoch = int(getattr(instance, "recovery_epoch", 0))
        self.gossip.set_epoch(epoch)
        self.provisioning.set_epoch(epoch)
        for client in self.peers.values():
            client.set_epoch(f"proc:{process_id}", epoch)
        self.takeover_monitor = TakeoverMonitor(
            process_id,
            peer_states=self.aggregator.snapshot,
            epoch_of=lambda: int(getattr(self.instance,
                                         "recovery_epoch", 0)),
            on_takeover=self._perform_takeover,
            fence_hooks=[self.gossip.fence, self.provisioning.fence],
            fence_broadcast=self._broadcast_fence,
            ttl_s=stale_after_s, check_interval_s=heartbeat_s)
        instance.cluster_hooks = self

    def _build_state(self) -> Dict:
        state = {
            "instance_id": self.instance.instance_id,
            "status": self.instance.status.name,
            "mode": "control-plane",
            "gossip_published": self.gossip.published,
            "gossip_applied": self.gossip.applied,
            "provisioning_published": self.provisioning.published,
            "provisioning_applied": self.provisioning.applied,
        }
        _annotate_recovery_state(self, state)
        return state

    def _broadcast_fence(self, origin: str, epoch: int) -> None:
        """Raise the fence floor for `origin` on every reachable peer —
        the cluster-wide half of a takeover (local appliers are fenced
        via fence_hooks). Unreachable peers are skipped: they learn the
        floor from admitted successor traffic (EpochFence.observe)."""
        for pid, client in self.peers.items():
            try:
                client.fence(origin, epoch)
            except BusNetError:
                LOGGER.warning("fence broadcast to process %d failed "
                               "(will learn floor from traffic)", pid)

    def _perform_takeover(self, resource: str, event: Dict) -> None:
        """Successor-side recovery: restore the last-good checkpoint and
        replay the retained log past its saved offsets (the replay
        barrier keeps the replayed records' effects suppressed). Traffic
        admits as soon as this returns — no operator action."""
        manager = getattr(self.instance, "checkpoint_manager", None)
        if manager is None:
            return
        try:
            manager.restore_on_boot()
        except Exception:
            LOGGER.exception("takeover restore failed for %s", resource)

    @property
    def bus_port(self) -> int:
        return self.bus_server.port

    def start(self) -> None:
        self.bus_server.start()
        self.aggregator.start()
        self.instance.start()
        self.gossip.start()
        self.provisioning.start()
        self.reporter.start()
        self.takeover_monitor.start()

    def stop(self) -> None:
        self.takeover_monitor.stop()
        self.reporter.stop()
        self.provisioning.stop()
        self.gossip.stop()
        self.instance.stop()
        self.aggregator.stop()
        for client in self.peers.values():
            client.close()
        self.bus_server.stop()

    def processes(self) -> Dict[str, Dict]:
        out = self.aggregator.snapshot()
        me = str(self.process_id)
        if me not in out:
            state = self._build_state()
            state["process_id"] = self.process_id
            state["age_s"] = 0.0
            state["stale"] = False
            out[me] = state
        return out

    def cluster_telemetry(self) -> Dict:
        """Cluster-wide telemetry fan-in (GET /api/cluster/telemetry)."""
        return _cluster_telemetry(self)
